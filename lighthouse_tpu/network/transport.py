"""TCP transport: Noise-encrypted, framed, snappy-compressed messages.

Reference: ``beacon_node/lighthouse_network`` — libp2p over TCP with a
Noise session layer, gossipsub (snappy-compressed SSZ payloads) and
SSZ-snappy req/resp (``src/rpc/protocol.rs:143-220``, codec
``rpc/codec/ssz_snappy.rs``).

This transport keeps the reference's WIRE SEMANTICS (topic strings,
SSZ-snappy payloads, request/response protocol names) over a simple
length-prefixed TCP framing instead of libp2p's multistream negotiation.
Every connection starts with a **Noise XX handshake** (``noise.py``):
mutual static-key authentication, after which each frame is sealed
end-to-end:

    wire  := u32-le ct_len | AEAD(frame)
    frame := u8 kind | u16-le name_len | u32-le req_id | name | payload

kind: 0 = gossip publish (name = topic, req_id = 0), 1 = rpc request,
2 = rpc response (req_id echoes the request so late responses can never
be mis-delivered to a newer request). Payloads are snappy raw blocks.
``Peer.node_id`` (hash of the remote static key) is the identity peer
scoring and bans key on — spoofing it requires the private key.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils import snappy
from . import noise

KIND_GOSSIP = 0
KIND_REQUEST = 1
KIND_RESPONSE = 2

_HDR = struct.Struct("<BHI")
_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 24  # 16 MiB ceiling, like the reference's max_chunk_size
MAX_INFLIGHT_HANDLERS = 4  # concurrent request handlers per peer
HANDSHAKE_TIMEOUT_S = 5.0


class Peer:
    """One authenticated remote; owns the socket + reader thread. Created
    only AFTER the Noise handshake succeeded (``session``)."""

    def __init__(self, sock: socket.socket, addr, on_frame, on_close,
                 session: noise.Session):
        self.sock = sock
        self.addr = addr
        self.session = session
        self.node_id = session.remote_node_id
        self.remote_listen_port: Optional[int] = None
        self._on_frame = on_frame
        self._on_close = on_close
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._req_counter = 0
        # rid -> [event, response]: any number of outstanding requests
        # (reference multiplexes substreams, rpc/protocol.rs:143-220)
        self._pending: dict[int, list] = {}
        self._inflight_handlers = 0  # server-side, capped per peer
        self._closed = False
        self._thread = threading.Thread(target=self._read_loop, daemon=True)
        self._thread.start()

    # -- sending ---------------------------------------------------------

    def send(self, kind: int, name: bytes, payload: bytes, req_id: int = 0) -> bool:
        comp = snappy.compress_raw(payload)
        frame = _HDR.pack(kind, len(name), req_id) + name + comp
        try:
            # encrypt INSIDE the lock: the AEAD nonce is a strict counter,
            # so ciphertexts must hit the socket in encryption order
            with self._send_lock:
                ct = self.session.send.encrypt(frame)
                self.sock.sendall(_LEN.pack(len(ct)) + ct)
            return True
        except (OSError, noise.HandshakeError):
            self.close()
            return False

    @property
    def closed(self) -> bool:
        return self._closed

    # -- server-side handler accounting ----------------------------------

    def try_begin_handler(self) -> bool:
        """Reserve a request-handler slot; False when the per-peer cap is
        reached (caller should answer busy rather than queue unboundedly)."""
        with self._state_lock:
            if self._inflight_handlers >= MAX_INFLIGHT_HANDLERS:
                return False
            self._inflight_handlers += 1
            return True

    def end_handler(self) -> None:
        with self._state_lock:
            self._inflight_handlers -= 1

    def request(self, protocol: bytes, payload: bytes, timeout: float = 10.0) -> Optional[bytes]:
        """Any number of concurrent in-flight requests per peer, matched
        by request id (the reference multiplexes substreams the same way;
        single-flight serialization head-of-line-blocked range sync vs
        backfill vs lookups — VERDICT r3 weak #6). A late answer to a
        timed-out request is dropped instead of satisfying a newer one."""
        ev = threading.Event()
        entry = [ev, None]
        with self._state_lock:
            self._req_counter += 1
            rid = self._req_counter
            self._pending[rid] = entry
        if not self.send(KIND_REQUEST, protocol, payload, req_id=rid):
            with self._state_lock:
                self._pending.pop(rid, None)
            return None
        ok = ev.wait(timeout)
        with self._state_lock:
            self._pending.pop(rid, None)
        # read from the LOCAL entry: a response recorded just before the
        # peer closed must still be delivered (close() swaps the dict)
        return entry[1] if ok else None

    # -- receiving -------------------------------------------------------

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        try:
            while True:
                ln_raw = self._read_exact(_LEN.size)
                if ln_raw is None:
                    break
                (ct_len,) = _LEN.unpack(ln_raw)
                if ct_len > MAX_FRAME or ct_len < _HDR.size + noise.TAGLEN:
                    break
                ct = self._read_exact(ct_len)
                if ct is None:
                    break
                try:
                    frame = self.session.recv.decrypt(ct)
                except noise.HandshakeError:
                    break  # tampered/replayed ciphertext: kill the session
                kind, name_len, req_id = _HDR.unpack(frame[: _HDR.size])
                body = frame[_HDR.size:]
                if name_len > len(body):
                    break
                name = body[:name_len]
                try:
                    payload = snappy.decompress_raw(body[name_len:])
                except snappy.SnappyError:
                    continue
                if kind == KIND_RESPONSE:
                    with self._state_lock:
                        entry = self._pending.get(req_id)
                        if entry is not None:
                            entry[1] = payload
                            entry[0].set()
                        # else: stale response for a timed-out request — drop
                else:
                    self._on_frame(self, kind, name, payload, req_id)
        except OSError:
            pass
        finally:
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass
        # wake every waiter immediately (response stays None) instead of
        # letting each ride out its full timeout on a dead peer
        with self._state_lock:
            pending, self._pending = self._pending, {}
        for ev, _ in pending.values():
            ev.set()
        self._on_close(self)


class Transport:
    """Listener + authenticated peer set. ``on_gossip(peer, topic,
    payload)``, ``on_request(peer, protocol, payload) -> bytes``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 identity: noise.Identity | None = None):
        self.identity = identity or noise.Identity()
        self.node_id = self.identity.node_id
        self.on_gossip: Callable = lambda *a: None
        self.on_request: Callable = lambda *a: b""
        self.on_peer_connected: Callable = lambda peer: None
        self.on_peer_removed: Callable = lambda peer: None
        self._server = socket.create_server((host, port))
        self.host = host
        self.port = self._server.getsockname()[1]
        self.peers: list[Peer] = []
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._running = True
        self._accept_thread.start()

    # -- peer management -------------------------------------------------

    def dial(self, host: str, port: int) -> Optional[Peer]:
        if not self._running:
            return None  # a closed transport must not open new sockets
        with self._lock:
            for p in self.peers:
                if p.remote_listen_port == port and p.addr[0] == host:
                    return p
        try:
            sock = socket.create_connection((host, port), timeout=5)
        except OSError:
            return None
        try:
            sock.settimeout(HANDSHAKE_TIMEOUT_S)
            session = noise.handshake_initiator(sock, self.identity)
            # the handshake timeout must not linger: it would turn any 5s
            # idle period into a recv timeout that kills the connection
            sock.settimeout(None)
        except (OSError, noise.HandshakeError):
            try:
                sock.close()  # a failed handshake must not leak the fd
            except OSError:
                pass
            return None
        if session.remote_node_id == self.node_id:
            sock.close()  # self-dial (or key reuse): refuse
            return None
        peer = self._add_peer(sock, (host, port), session)
        peer.remote_listen_port = port
        self.on_peer_connected(peer)
        return peer

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, addr = self._server.accept()
            except OSError:
                if not self._running:
                    return
                # transient accept error (e.g. ECONNABORTED from a reset
                # queued connection) must not kill the listener; back off
                # so persistent errors (fd exhaustion) cannot busy-spin
                time.sleep(0.05)
                continue
            # handshake runs off the accept loop: a stalling dialer must
            # not block further accepts (libp2p upgrades concurrently too)
            threading.Thread(
                target=self._handshake_inbound, args=(sock, addr), daemon=True
            ).start()

    def _handshake_inbound(self, sock: socket.socket, addr) -> None:
        try:
            sock.settimeout(HANDSHAKE_TIMEOUT_S)
            session = noise.handshake_responder(sock, self.identity)
            sock.settimeout(None)
        except (OSError, noise.HandshakeError):
            try:
                sock.close()
            except OSError:
                pass
            return
        if not self._running or session.remote_node_id == self.node_id:
            sock.close()
            return
        peer = self._add_peer(sock, addr, session)
        try:
            self.on_peer_connected(peer)
        except Exception:
            peer.close()  # a handler bug must not kill the accept path

    def _add_peer(self, sock: socket.socket, addr, session: noise.Session) -> Peer:
        peer = Peer(sock, addr, self._dispatch, self._remove_peer, session)
        with self._lock:
            self.peers.append(peer)
        return peer

    def _remove_peer(self, peer: Peer) -> None:
        with self._lock:
            if peer in self.peers:
                self.peers.remove(peer)
        try:
            self.on_peer_removed(peer)
        except Exception:
            pass  # a cleanup-hook bug must not break peer teardown

    def peer_count(self) -> int:
        with self._lock:
            return len(self.peers)

    def peers_snapshot(self) -> list:
        """Consistent copy of the peer list for out-of-loop consumers
        (discovery walk, metrics) — no reaching into ``_lock``."""
        with self._lock:
            return list(self.peers)

    # -- dispatch --------------------------------------------------------

    def _dispatch(self, peer: Peer, kind: int, name: bytes, payload: bytes, req_id: int) -> None:
        if kind == KIND_GOSSIP:
            self.on_gossip(peer, name.decode(), payload)
        elif kind == KIND_REQUEST:
            # handle off the read loop so concurrent requests from one
            # peer execute concurrently and never stall its gossip —
            # bounded PER PEER so (a) a request flood cannot queue
            # unbounded payloads (the old inline path's TCP backpressure
            # analogue) and (b) slow handlers for one peer never starve
            # another peer's requests (per-peer isolation, as when the
            # read loop itself served them)
            if not peer.try_begin_handler():
                # busy: answer empty immediately (the reference returns an
                # RPC error) so the requester fails fast instead of riding
                # out its timeout
                peer.send(KIND_RESPONSE, name, b"", req_id=req_id)
                return
            threading.Thread(
                target=self._handle_request,
                args=(peer, name, payload, req_id),
                daemon=True,
            ).start()

    def _handle_request(self, peer: Peer, name: bytes, payload: bytes, req_id: int) -> None:
        try:
            try:
                resp = self.on_request(peer, name.decode(), payload)
            except Exception:
                resp = b""
            peer.send(KIND_RESPONSE, name, resp or b"", req_id=req_id)
        finally:
            peer.end_handler()

    # -- broadcast -------------------------------------------------------

    def publish(self, topic: str, payload: bytes, exclude: Peer | None = None) -> int:
        n = 0
        with self._lock:
            targets = list(self.peers)
        for p in targets:
            if p is exclude:
                continue
            if p.send(KIND_GOSSIP, topic.encode(), payload):
                n += 1
        return n

    def close(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            peers = list(self.peers)
        for p in peers:
            p.close()
