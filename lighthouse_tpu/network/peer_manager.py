"""Peer scoring + req/resp rate limiting (reference:
``lighthouse_network/src/service/gossipsub_scoring_parameters.rs:56-83``
for the score shape — decaying penalties with greylist/disconnect
thresholds — and ``rpc/rate_limiter.rs:59`` for the per-protocol token
buckets).

The transport trusts nobody: every inbound gossip frame and RPC request
passes through the PeerManager first; verification failures reported by
the BeaconProcessor feed back as penalties. A peer whose score sinks
below ``BAN_THRESHOLD`` is disconnected and its address refused on
re-dial until the ban decays.
"""

from __future__ import annotations

import threading
import time

from ..utils import flight_recorder, logging, metrics

_PENALTIES = metrics.counter(
    "network_peer_penalties_total", "scoring penalties applied"
)
_BANS = metrics.counter("network_peer_bans_total", "peers banned")
_RATE_LIMITED = metrics.counter(
    "network_rate_limited_total", "requests dropped by rate limiting"
)

# Offence weights (shape follows the reference's P4 invalid-message
# penalty dominating the score).
OFFENCES = {
    "invalid_message": -10.0,   # signature/structural verification failed
    "undecodable": -4.0,        # bytes that do not decode at all
    "rate_limit": -2.0,         # token bucket exceeded
    "protocol": -6.0,           # malformed RPC / unknown protocol abuse
}

DISCONNECT_THRESHOLD = -20.0   # peer gets disconnected
BAN_THRESHOLD = -40.0          # address refused on re-dial
SCORE_HALFLIFE_S = 60.0        # exponential decay toward 0
BAN_DURATION_S = 300.0


class TokenBucket:
    """Leaky token bucket: ``rate`` tokens/s, burst up to ``capacity``."""

    __slots__ = ("capacity", "rate", "tokens", "_last")

    def __init__(self, capacity: float, rate: float):
        self.capacity = float(capacity)
        self.rate = float(rate)
        self.tokens = float(capacity)
        self._last = time.monotonic()

    def allow(self, cost: float = 1.0) -> bool:
        now = time.monotonic()
        self.tokens = min(
            self.capacity, self.tokens + (now - self._last) * self.rate
        )
        self._last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


# Per-protocol-class request budgets (reference rate_limiter.rs quotas:
# expensive by-range requests get small budgets, pings large ones).
DEFAULT_RPC_QUOTAS = {
    "blocks_by_range": (16, 2.0),
    "blocks_by_root": (32, 4.0),
    "status": (8, 1.0),
    "ping": (16, 2.0),
    "default": (64, 8.0),
}
GOSSIP_QUOTA = (512, 128.0)  # frames (burst, per-second)


class _PeerState:
    __slots__ = ("score", "buckets", "gossip_bucket", "_last_decay")

    def __init__(self):
        self.score = 0.0
        self.buckets: dict[str, TokenBucket] = {}
        self.gossip_bucket = TokenBucket(*GOSSIP_QUOTA)
        self._last_decay = time.monotonic()

    def decay(self) -> None:
        now = time.monotonic()
        dt = now - self._last_decay
        if dt > 0.0:
            self.score *= 0.5 ** (dt / SCORE_HALFLIFE_S)
            self._last_decay = now


def _rpc_class(protocol: str) -> str:
    for key in DEFAULT_RPC_QUOTAS:
        if key in protocol:
            return key
    return "default"


class PeerManager:
    MAX_TRACKED = 4096

    def __init__(self, quotas: dict | None = None):
        # merge so a partial override cannot KeyError an unnamed class
        self.quotas = {**DEFAULT_RPC_QUOTAS, **(quotas or {})}
        self._lock = threading.Lock()
        # Scores are keyed by the peer's NOISE IDENTITY (hash of its
        # static key, Peer.node_id) — unforgeable without the private key,
        # so a misbehaving peer that reconnects resumes its decayed score
        # under the same identity, like the reference peerdb's
        # PeerId-keyed records. Minting a fresh keypair buys a fresh
        # score (sybil), which the reference accepts too; the IP is kept
        # as fallback for identity-less callers (unit tests).
        self._peers: dict[str, _PeerState] = {}
        self._banned: dict[str, float] = {}          # ban key -> expiry
        self.on_disconnect = lambda peer: None       # set by the service
        self.ban_key = (
            lambda peer: getattr(peer, "node_id", None) or peer.addr[0]
        )

    # -- lifecycle -------------------------------------------------------

    def _state(self, peer) -> _PeerState:
        key = self.ban_key(peer)
        st = self._peers.get(key)
        if st is None:
            if len(self._peers) >= self.MAX_TRACKED:
                # evict decayed/benign entries; tracked state is bounded
                stale = []
                for k, s in self._peers.items():
                    s.decay()
                    if s.score > -1.0:
                        stale.append(k)
                for k in stale:
                    del self._peers[k]
            st = self._peers[key] = _PeerState()
        return st

    def is_banned(self, key: str) -> bool:
        with self._lock:
            expiry = self._banned.get(key)
            if expiry is None:
                return False
            if time.monotonic() > expiry:
                del self._banned[key]
                return False
            return True

    def score(self, peer) -> float:
        with self._lock:
            st = self._state(peer)
            st.decay()
            return st.score

    # -- admission -------------------------------------------------------

    def allow_gossip(self, peer) -> bool:
        with self._lock:
            st = self._state(peer)
            if not st.gossip_bucket.allow():
                _RATE_LIMITED.inc()
                self._penalize_locked(peer, st, "rate_limit")
                return False
            return True

    def allow_request(self, peer, protocol: str) -> bool:
        cls = _rpc_class(protocol)
        with self._lock:
            st = self._state(peer)
            bucket = st.buckets.get(cls)
            if bucket is None:
                bucket = st.buckets[cls] = TokenBucket(*self.quotas[cls])
            if not bucket.allow():
                _RATE_LIMITED.inc()
                self._penalize_locked(peer, st, "rate_limit")
                return False
            return True

    # -- scoring ---------------------------------------------------------

    def report(self, peer, offence: str) -> None:
        """Apply a penalty; disconnect/ban when thresholds are crossed."""
        with self._lock:
            st = self._state(peer)
            self._penalize_locked(peer, st, offence)

    def _penalize_locked(self, peer, st: _PeerState, offence: str) -> None:
        st.decay()
        st.score += OFFENCES[offence]
        _PENALTIES.inc()
        flight_recorder.record(
            "peer_penalty", peer=self.ban_key(peer), offence=offence,
            score=round(st.score, 3),
        )
        if st.score <= BAN_THRESHOLD:
            key = self.ban_key(peer)
            if key and key not in self._banned:
                self._banned[key] = time.monotonic() + BAN_DURATION_S
                _BANS.inc()
                flight_recorder.record(
                    "peer_ban", peer=key, score=round(st.score, 3),
                    offence=offence, duration_s=BAN_DURATION_S,
                )
                logging.log("warn", "peer banned", peer=key,
                            score=st.score, offence=offence)
        if st.score <= DISCONNECT_THRESHOLD:
            # callback outside the lock would be cleaner, but peer.close()
            # only flags + closes a socket — no re-entry into the manager
            self.on_disconnect(peer)
