"""L5 networking: gossip pub/sub, req/resp RPC, peer exchange, range
sync over TCP with SSZ-snappy payloads.

Reference: ``beacon_node/lighthouse_network`` (libp2p behaviour) +
``beacon_node/network`` (router, sync) — SURVEY.md §2.4 rows 18-19.
"""

from .service import (
    ATTESTATION_SUBNET_COUNT,
    NetworkService,
    RangeSync,
    Topics,
)
from .transport import Peer, Transport

__all__ = [
    "ATTESTATION_SUBNET_COUNT",
    "NetworkService",
    "Peer",
    "RangeSync",
    "Topics",
    "Transport",
]
