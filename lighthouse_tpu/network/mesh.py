"""Gossipsub-style mesh control (reference:
``lighthouse_network/src/service/`` gossipsub behaviour + the degree
parameters in ``gossipsub_scoring_parameters.rs``).

Per-topic overlay meshes with degree targets: a heartbeat GRAFTs the
highest-scoring peers into under-full meshes and PRUNEs the
lowest-scoring out of over-full ones; relayed messages are forwarded to
mesh members only. Originated messages are flood-published (the
reference enables flood-publish for its latency-critical topics), so
mesh state bounds RELAY fan-out without ever gating first-hop delivery.

Control wire: a direct (non-flooded) gossip frame on the reserved topic
``_ctl`` with payload ``b"G"``/``b"P"`` + topic bytes — the
multistream-free analogue of gossipsub's GRAFT/PRUNE control messages.

IHAVE/IWANT repair (gossipsub's lazy-pull leg): each heartbeat sends a
digest of recently relayed message ids per topic to a few NON-mesh
peers (``b"H"`` + topic-length + topic + 20-byte ids); a peer missing
any of them pulls with ``b"W"`` + ids and receives the full frames.
Without this, a peer whose GRAFTs were all refused (remote meshes at
D_HIGH) would only ever see first-hop flood-published messages.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

from .transport import KIND_GOSSIP

CTL_TOPIC = "_ctl"
GRAFT = b"G"
PRUNE = b"P"
IHAVE = b"H"
IWANT = b"W"

MSG_ID_LEN = 20


class MeshRouter:
    # degree targets sized for the in-process simulators (reference
    # mainnet: D=8, D_low=6, D_high=12)
    D_LOW = 2
    D = 4
    D_HIGH = 8
    MAX_TOPICS = 256          # locally-tracked topics (subnets x forks fit)
    PRUNE_BACKOFF_S = 30.0    # gossipsub prune backoff analogue
    GOSSIP_LAZY = 3           # non-mesh peers receiving IHAVE per heartbeat
    MCACHE_CAP = 512          # retained full messages for IWANT service
    MCACHE_MAX_BYTES = 8 << 20  # byte budget (block frames can be large)
    IHAVE_MAX_IDS = 16        # digest size bound (also caps IWANT replies)
    IHAVE_WINDOW_S = 30.0     # only advertise recent ids (gossipsub's
    #                           ~3-heartbeat history window analogue)

    def __init__(self, service):
        self.service = service
        self._lock = threading.Lock()
        # topic -> set of grafted peers; keys created ONLY by track()
        # (recognized local topics), never by remote control frames
        self.mesh: dict[str, set] = {}
        # (id(peer), topic) -> monotonic time until which GRAFT is banned
        self._backoff: dict[tuple[int, str], float] = {}
        # message cache for the IHAVE/IWANT pull leg: id -> (topic,
        # payload, monotonic); bounded by count AND bytes
        self._mcache: OrderedDict[bytes, tuple[str, bytes, float]] = OrderedDict()
        self._mcache_bytes = 0
        # topic -> recent (message id, monotonic) for IHAVE digests
        self._recent: dict[str, deque] = {}

    # -- routing ---------------------------------------------------------

    def relay_peers(self, topic: str, exclude=None) -> list | None:
        """Peers to forward a RELAYED message on ``topic`` to (sender
        already removed), or None to flood (mesh too thin — the sender
        does not count toward the delivery-trust threshold)."""
        with self._lock:
            members = [
                p for p in self.mesh.get(topic, ())
                if not p.closed and p is not exclude
            ]
        if len(members) < self.D_LOW:
            return None
        return members

    # -- control ---------------------------------------------------------

    def remember(self, topic: str, msg_id: bytes, payload: bytes) -> None:
        """Cache a published/relayed message so IWANT can serve it and the
        next heartbeat's IHAVE digests advertise it."""
        import time as _time

        if len(topic.encode()) > 255:
            return  # digest frames carry a 1-byte topic length
        now = _time.monotonic()
        with self._lock:
            old = self._mcache.pop(msg_id, None)
            if old is not None:
                self._mcache_bytes -= len(old[1])
            self._mcache[msg_id] = (topic, payload, now)
            self._mcache_bytes += len(payload)
            while self._mcache and (
                len(self._mcache) > self.MCACHE_CAP
                or self._mcache_bytes > self.MCACHE_MAX_BYTES
            ):
                _, (_, old_payload, _) = self._mcache.popitem(last=False)
                self._mcache_bytes -= len(old_payload)
            dq = self._recent.get(topic)
            if dq is None:
                if len(self._recent) >= self.MAX_TOPICS:
                    return
                dq = self._recent[topic] = deque(maxlen=self.IHAVE_MAX_IDS)
            dq.append((msg_id, now))

    def on_control(self, peer, payload: bytes) -> None:
        if not payload:
            return
        import time as _time

        action = payload[:1]
        if action == IHAVE:
            return self._on_ihave(peer, payload[1:])
        if action == IWANT:
            return self._on_iwant(peer, payload[1:])
        topic = payload[1:].decode(errors="replace")
        send_refusal = False
        with self._lock:
            members = self.mesh.get(topic)
            if members is None:
                # unknown topic: refuse — remote control frames must not
                # create mesh state (junk-topic contamination would
                # propagate via heartbeats otherwise)
                if action == GRAFT:
                    send_refusal = True
            elif action == GRAFT:
                if len(members) >= self.D_HIGH and peer not in members:
                    send_refusal = True  # full: refuse symmetrically
                else:
                    members.add(peer)
            elif action == PRUNE:
                members.discard(peer)
                self._backoff[(id(peer), topic)] = (
                    _time.monotonic() + self.PRUNE_BACKOFF_S
                )
        if send_refusal:
            self._send_ctl(peer, PRUNE, topic)

    def _send_ctl(self, peer, action: bytes, topic: str) -> None:
        try:
            peer.send(KIND_GOSSIP, CTL_TOPIC.encode(), action + topic.encode())
        except Exception:
            pass

    # -- IHAVE / IWANT ---------------------------------------------------

    def _on_ihave(self, peer, body: bytes) -> None:
        """b"H" + tlen(1) + topic + ids: pull any ids we have not seen."""
        if not body:
            return
        tlen = body[0]
        ids_raw = body[1 + tlen:]
        ids = [
            ids_raw[i : i + MSG_ID_LEN]
            for i in range(0, len(ids_raw), MSG_ID_LEN)
        ][: self.IHAVE_MAX_IDS]
        missing = [m for m in ids if len(m) == MSG_ID_LEN
                   and not self.service.has_seen(m)]
        if missing:
            try:
                peer.send(
                    KIND_GOSSIP, CTL_TOPIC.encode(), IWANT + b"".join(missing)
                )
            except Exception:
                pass

    def _on_iwant(self, peer, body: bytes) -> None:
        """b"W" + ids: serve cached full messages as normal gossip frames
        (the receiver dedups through its seen-cache like any gossip)."""
        ids = [
            body[i : i + MSG_ID_LEN] for i in range(0, len(body), MSG_ID_LEN)
        ][: self.IHAVE_MAX_IDS]
        with self._lock:
            hits = [self._mcache.get(m) for m in ids]
        for hit in hits:
            if hit is None:
                continue
            topic, payload, _ts = hit
            try:
                peer.send(KIND_GOSSIP, topic.encode(), payload)
            except Exception:
                pass

    def track(self, topic: str) -> None:
        """Make ``topic`` mesh-managed (called on first publish or first
        RECOGNIZED receive — callers validate the topic)."""
        if topic == CTL_TOPIC:
            return
        with self._lock:
            if topic not in self.mesh and len(self.mesh) >= self.MAX_TOPICS:
                return  # bounded; overflow topics just flood
            self.mesh.setdefault(topic, set())

    # -- maintenance -----------------------------------------------------

    def heartbeat(self) -> None:
        """Degree maintenance (gossipsub heartbeat analogue): drop closed
        peers, GRAFT the best-scoring non-members up to D, PRUNE the
        worst-scoring members down to D when above D_HIGH."""
        transport = self.service.transport
        pm = self.service.peer_manager
        all_peers = transport.peers_snapshot()
        with self._lock:
            topics = list(self.mesh.keys())
        for topic in topics:
            with self._lock:
                members = {p for p in self.mesh.get(topic, ()) if not p.closed}
                self.mesh[topic] = members
                current = set(members)
            if len(current) < self.D:
                import time as _time

                now = _time.monotonic()
                with self._lock:
                    self._backoff = {
                        k: t for k, t in self._backoff.items() if t > now
                    }
                    backoff = dict(self._backoff)
                candidates = sorted(
                    (
                        p for p in all_peers
                        if p not in current
                        and not p.closed
                        and backoff.get((id(p), topic), 0) <= now
                    ),
                    key=lambda p: pm.score(p),
                    reverse=True,
                )
                for p in candidates[: self.D - len(current)]:
                    with self._lock:
                        self.mesh[topic].add(p)
                    self._send_ctl(p, GRAFT, topic)
            elif len(current) > self.D_HIGH:
                victims = sorted(current, key=lambda p: pm.score(p))
                for p in victims[: len(current) - self.D]:
                    with self._lock:
                        self.mesh[topic].discard(p)
                    self._send_ctl(p, PRUNE, topic)
            # lazy-pull leg: advertise recent ids to a few NON-mesh peers
            # so a peer kept out of every mesh (all GRAFTs refused) still
            # learns of — and can pull — relayed messages
            tb = topic.encode()
            if len(tb) > 255:
                continue  # remember() filters these too; belt-and-braces
            import time as _time2

            cutoff = _time2.monotonic() - self.IHAVE_WINDOW_S
            with self._lock:
                dq = self._recent.get(topic)
                ids = [m for m, ts in dq if ts > cutoff] if dq else []
            if not ids:
                continue
            import random as _random

            outsiders = [
                p for p in all_peers if p not in current and not p.closed
            ]
            digest = (
                IHAVE + bytes([len(tb)]) + tb
                + b"".join(ids[-self.IHAVE_MAX_IDS:])
            )
            for p in _random.sample(
                outsiders, min(self.GOSSIP_LAZY, len(outsiders))
            ):
                try:
                    p.send(KIND_GOSSIP, CTL_TOPIC.encode(), digest)
                except Exception:
                    pass

    def remove_peer(self, peer) -> None:
        with self._lock:
            for members in self.mesh.values():
                members.discard(peer)
