"""Gossipsub-style mesh control (reference:
``lighthouse_network/src/service/`` gossipsub behaviour + the degree
parameters in ``gossipsub_scoring_parameters.rs``).

Per-topic overlay meshes with degree targets: a heartbeat GRAFTs the
highest-scoring peers into under-full meshes and PRUNEs the
lowest-scoring out of over-full ones; relayed messages are forwarded to
mesh members only. Originated messages are flood-published (the
reference enables flood-publish for its latency-critical topics), so
mesh state bounds RELAY fan-out without ever gating first-hop delivery.

Control wire: a direct (non-flooded) gossip frame on the reserved topic
``_ctl`` with payload ``b"G"``/``b"P"`` + topic bytes — the
multistream-free analogue of gossipsub's GRAFT/PRUNE control messages.
"""

from __future__ import annotations

import threading

from .transport import KIND_GOSSIP

CTL_TOPIC = "_ctl"
GRAFT = b"G"
PRUNE = b"P"


class MeshRouter:
    # degree targets sized for the in-process simulators (reference
    # mainnet: D=8, D_low=6, D_high=12)
    D_LOW = 2
    D = 4
    D_HIGH = 8
    MAX_TOPICS = 256          # locally-tracked topics (subnets x forks fit)
    PRUNE_BACKOFF_S = 30.0    # gossipsub prune backoff analogue

    def __init__(self, service):
        self.service = service
        self._lock = threading.Lock()
        # topic -> set of grafted peers; keys created ONLY by track()
        # (recognized local topics), never by remote control frames
        self.mesh: dict[str, set] = {}
        # (id(peer), topic) -> monotonic time until which GRAFT is banned
        self._backoff: dict[tuple[int, str], float] = {}

    # -- routing ---------------------------------------------------------

    def relay_peers(self, topic: str, exclude=None) -> list | None:
        """Peers to forward a RELAYED message on ``topic`` to (sender
        already removed), or None to flood (mesh too thin — the sender
        does not count toward the delivery-trust threshold)."""
        with self._lock:
            members = [
                p for p in self.mesh.get(topic, ())
                if not p.closed and p is not exclude
            ]
        if len(members) < self.D_LOW:
            return None
        return members

    # -- control ---------------------------------------------------------

    def on_control(self, peer, payload: bytes) -> None:
        if not payload:
            return
        import time as _time

        action, topic = payload[:1], payload[1:].decode(errors="replace")
        send_refusal = False
        with self._lock:
            members = self.mesh.get(topic)
            if members is None:
                # unknown topic: refuse — remote control frames must not
                # create mesh state (junk-topic contamination would
                # propagate via heartbeats otherwise)
                if action == GRAFT:
                    send_refusal = True
            elif action == GRAFT:
                if len(members) >= self.D_HIGH and peer not in members:
                    send_refusal = True  # full: refuse symmetrically
                else:
                    members.add(peer)
            elif action == PRUNE:
                members.discard(peer)
                self._backoff[(id(peer), topic)] = (
                    _time.monotonic() + self.PRUNE_BACKOFF_S
                )
        if send_refusal:
            self._send_ctl(peer, PRUNE, topic)

    def _send_ctl(self, peer, action: bytes, topic: str) -> None:
        try:
            peer.send(KIND_GOSSIP, CTL_TOPIC.encode(), action + topic.encode())
        except Exception:
            pass

    def track(self, topic: str) -> None:
        """Make ``topic`` mesh-managed (called on first publish or first
        RECOGNIZED receive — callers validate the topic)."""
        if topic == CTL_TOPIC:
            return
        with self._lock:
            if topic not in self.mesh and len(self.mesh) >= self.MAX_TOPICS:
                return  # bounded; overflow topics just flood
            self.mesh.setdefault(topic, set())

    # -- maintenance -----------------------------------------------------

    def heartbeat(self) -> None:
        """Degree maintenance (gossipsub heartbeat analogue): drop closed
        peers, GRAFT the best-scoring non-members up to D, PRUNE the
        worst-scoring members down to D when above D_HIGH."""
        transport = self.service.transport
        pm = self.service.peer_manager
        with transport._lock:
            all_peers = list(transport.peers)
        with self._lock:
            topics = list(self.mesh.keys())
        for topic in topics:
            with self._lock:
                members = {p for p in self.mesh.get(topic, ()) if not p.closed}
                self.mesh[topic] = members
                current = set(members)
            if len(current) < self.D:
                import time as _time

                now = _time.monotonic()
                with self._lock:
                    self._backoff = {
                        k: t for k, t in self._backoff.items() if t > now
                    }
                    backoff = dict(self._backoff)
                candidates = sorted(
                    (
                        p for p in all_peers
                        if p not in current
                        and not p.closed
                        and backoff.get((id(p), topic), 0) <= now
                    ),
                    key=lambda p: pm.score(p),
                    reverse=True,
                )
                for p in candidates[: self.D - len(current)]:
                    with self._lock:
                        self.mesh[topic].add(p)
                    self._send_ctl(p, GRAFT, topic)
            elif len(current) > self.D_HIGH:
                victims = sorted(current, key=lambda p: pm.score(p))
                for p in victims[: len(current) - self.D]:
                    with self._lock:
                        self.mesh[topic].discard(p)
                    self._send_ctl(p, PRUNE, topic)

    def remove_peer(self, peer) -> None:
        with self._lock:
            for members in self.mesh.values():
                members.discard(peer)
