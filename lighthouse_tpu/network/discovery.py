"""Peer discovery: random-walk address learning + target-count
maintenance — the transport-native replacement for the reference's
discv5 service (``lighthouse_network/src/discovery/``; same role:
keep the node at its target peer count by continuously learning and
dialing new addresses, not just the boot nodes).

The walk piggybacks on the peer-exchange RPC: every round below target,
one random connected peer is asked for its peer list; unknown addresses
enter the table and get dialed until the target is met. The address
table is exportable/importable so a restarting node can re-bootstrap
from the peers it knew (the analogue of persisted ENRs).
"""

from __future__ import annotations

import json
import random
import threading
import time


class Discovery:
    TARGET_PEERS = 16
    MAX_TABLE = 512
    WALK_INTERVAL_S = 10.0

    def __init__(self, service):
        self.service = service
        self._lock = threading.Lock()
        # (host, port) -> monotonic last-seen
        self.table: dict[tuple[str, int], float] = {}
        # (host, port) -> consecutive dial failures
        self._fails: dict[tuple[str, int], int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "Discovery":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    # -- address table ---------------------------------------------------

    FAIL_EVICT = 3  # consecutive dial failures before an address is dropped

    def learn(self, host: str, port: int) -> None:
        # self-filter on (host, port): a REMOTE node on the same port
        # number must still be learnable
        if port == self.service.port and host in (
            "127.0.0.1", "localhost", self.service.transport.host,
        ):
            return
        with self._lock:
            if (host, port) not in self.table and len(self.table) >= self.MAX_TABLE:
                # evict the stalest entry
                oldest = min(self.table, key=self.table.get)
                del self.table[oldest]
            self.table[(host, int(port))] = time.monotonic()
            self._fails.pop((host, int(port)), None)

    def learn_from_px(self, raw: bytes) -> None:
        """Parse one peer-exchange response (the single copy of the wire
        format both the handshake and the walk use)."""
        try:
            for host, port in json.loads(raw):
                if port:
                    self.learn(str(host), int(port))
        except (ValueError, TypeError):
            pass

    def addresses(self) -> list[list]:
        with self._lock:
            return [[h, p] for (h, p) in self.table]

    def import_addresses(self, addrs) -> None:
        for h, p in addrs:
            self.learn(str(h), int(p))

    # -- the walk --------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.WALK_INTERVAL_S):
            try:
                self.round()
            except Exception:
                pass

    def round(self) -> int:
        """One maintenance round; returns the number of dials made."""
        transport = self.service.transport
        need = self.TARGET_PEERS - transport.peer_count()
        if need <= 0:
            return 0
        from .service import PROTO_PEER_EXCHANGE

        peers = transport.peers_snapshot()
        if peers:
            # the PX walk runs off the round's critical path: a slow peer
            # (timeout 2s) must not delay the maintenance dials below —
            # its addresses simply feed the NEXT round
            target = random.choice(peers)

            def _walk():
                raw = target.request(
                    PROTO_PEER_EXCHANGE.encode(), b"[]", timeout=2
                )
                if raw:
                    self.learn_from_px(raw)

            threading.Thread(target=_walk, daemon=True).start()
        connected = {
            (p.addr[0], p.remote_listen_port)
            for p in peers
            if p.remote_listen_port
        }
        dials = 0
        attempts = 0
        candidates = [a for a in self.addresses() if tuple(a) not in connected]
        random.shuffle(candidates)
        for host, port in candidates:
            # bound the round: failed dials block up to the connect
            # timeout each, so they count toward the attempt budget
            if dials >= need or attempts >= need + 3 or self._stop.is_set():
                break
            attempts += 1
            if self.service.connect(host, port) is not None:
                dials += 1
                continue
            with self._lock:
                key = (host, int(port))
                self._fails[key] = self._fails.get(key, 0) + 1
                if self._fails[key] >= self.FAIL_EVICT:
                    self.table.pop(key, None)
                    self._fails.pop(key, None)
        return dials
