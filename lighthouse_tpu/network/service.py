"""Network service: gossip topics, req/resp protocols, peer exchange,
router into the BeaconProcessor, and range sync.

Reference mapping (SURVEY.md §2.4):

* topics mirror ``lighthouse_network/src/types/topics.rs:47-72``:
  ``/eth2/{fork_digest}/beacon_block/ssz_snappy``,
  ``.../beacon_aggregate_and_proof/...``,
  ``.../beacon_attestation_{subnet}/...``, voluntary_exit, slashings;
* req/resp protocols mirror ``rpc/protocol.rs:143-155``: status, goodbye,
  ping, metadata, beacon_blocks_by_range, beacon_blocks_by_root;
* the Router + work queues mirror ``network/src/router`` +
  ``beacon_processor`` (gossip items become Work batches);
* discovery is peer-exchange over an extra ``peers`` protocol (discv5's
  niche: learning listen addresses of more peers) + static bootnodes;
* range sync mirrors ``network/src/sync/range_sync``: on a Status showing
  a peer ahead, batches of blocks_by_range feed CHAIN_SEGMENT work.
"""

from __future__ import annotations

import json
import struct
import threading
import time
from typing import Optional

from ..beacon_processor import BeaconProcessor, Work, WorkKind
from ..ssz import hash_tree_root
from ..state_transition.epoch import fork_of
from ..types.domains import compute_fork_digest
from ..utils import metrics
from .peer_manager import PeerManager
from .transport import KIND_GOSSIP, Peer, Transport

_GOSSIP_RX = metrics.counter("network_gossip_received_total")
_GOSSIP_TX = metrics.counter("network_gossip_published_total")
_SYNC_BATCHES = metrics.counter(
    "sync_range_batches_total", "range-sync batches fetched"
)
_SYNC_BLOCKS = metrics.counter(
    "sync_range_blocks_total", "blocks imported by range sync"
)
_BACKFILL_BLOCKS = metrics.counter(
    "sync_backfill_blocks_total", "blocks stored by backfill sync"
)
_LOOKUPS = metrics.counter(
    "sync_block_lookups_total", "parent-chain lookups started"
)
_LOOKUP_BLOCKS = metrics.counter(
    "sync_block_lookup_blocks_fetched_total", "blocks fetched by root"
)

ATTESTATION_SUBNET_COUNT = 64


class Topics:
    def __init__(self, fork_digest: bytes):
        self.prefix = f"/eth2/{fork_digest.hex()}"

    def block(self) -> str:
        return f"{self.prefix}/beacon_block/ssz_snappy"

    def aggregate(self) -> str:
        return f"{self.prefix}/beacon_aggregate_and_proof/ssz_snappy"

    def attestation(self, subnet: int) -> str:
        return f"{self.prefix}/beacon_attestation_{subnet}/ssz_snappy"

    def sync_committee(self, subnet: int) -> str:
        return f"{self.prefix}/sync_committee_{subnet}/ssz_snappy"

    def sync_contribution(self) -> str:
        return (
            f"{self.prefix}/sync_committee_contribution_and_proof/ssz_snappy"
        )

    def voluntary_exit(self) -> str:
        return f"{self.prefix}/voluntary_exit/ssz_snappy"

    def attester_slashing(self) -> str:
        return f"{self.prefix}/attester_slashing/ssz_snappy"

    def proposer_slashing(self) -> str:
        return f"{self.prefix}/proposer_slashing/ssz_snappy"


PROTO_STATUS = "/eth2/beacon_chain/req/status/1"
PROTO_GOODBYE = "/eth2/beacon_chain/req/goodbye/1"
PROTO_PING = "/eth2/beacon_chain/req/ping/1"
PROTO_METADATA = "/eth2/beacon_chain/req/metadata/1"
PROTO_BLOCKS_BY_RANGE = "/eth2/beacon_chain/req/beacon_blocks_by_range/1"
PROTO_BLOCKS_BY_ROOT = "/eth2/beacon_chain/req/beacon_blocks_by_root/1"
PROTO_PEER_EXCHANGE = "/eth2/beacon_chain/req/peers/1"


class NetworkService:
    """Wires a BeaconChain + BeaconProcessor to the transport."""

    def __init__(
        self,
        chain,
        processor: BeaconProcessor,
        host: str = "127.0.0.1",
        port: int = 0,
        subnets: Optional[set[int]] = None,
    ):
        self.chain = chain
        self.processor = processor
        self.subnets = subnets if subnets is not None else set(range(ATTESTATION_SUBNET_COUNT))
        gvr = bytes(chain.head_state.genesis_validators_root)
        # One Topics per scheduled fork: gossip is ACCEPTED for any of
        # them, PUBLISHED on the wall-clock epoch's digest, so nodes on
        # either side of a fork transition still exchange messages.
        self._topics_by_fork = {
            fork: Topics(compute_fork_digest(
                chain.spec, chain.spec.fork_version_for(fork), gvr
            ))
            for fork in ("phase0", "altair", "bellatrix")
        }
        self.transport = Transport(host, port)
        self.peer_manager = PeerManager()
        self.peer_manager.on_disconnect = lambda p: p.close()
        self._seen: dict[bytes, float] = {}  # gossip message-id dedup
        self._seen_lock = threading.Lock()
        from .mesh import MeshRouter

        self.mesh_router = MeshRouter(self)
        self._mesh_stop = threading.Event()
        self._mesh_thread = threading.Thread(
            target=self._mesh_heartbeat_loop, daemon=True
        )
        self._mesh_thread.start()
        self.sync = RangeSync(self)
        self.backfill = BackfillSync(self)
        self.lookups = BlockLookups(self)
        from .discovery import Discovery

        self.discovery = Discovery(self).start()
        # callbacks are wired LAST: the accept thread is live from the
        # Transport constructor, and an early inbound handshake must not
        # race attributes (sync/discovery/mesh) into AttributeErrors —
        # until here such peers just get the transport's no-op handlers
        self.transport.on_gossip = self._on_gossip
        self.transport.on_request = self._on_request
        self.transport.on_peer_connected = self._on_peer_connected
        self.transport.on_peer_removed = (
            lambda peer: self.mesh_router.remove_peer(peer)
        )
        # the HTTP API's /node/identity + /node/peers read this
        chain.network = self

    @property
    def topics(self) -> Topics:
        """Topics for the current wall-clock epoch's fork digest."""
        return self._topics_by_fork[
            self.chain.spec.fork_name_at_epoch(self.chain.epoch())
        ]

    # -- lifecycle -------------------------------------------------------

    @property
    def port(self) -> int:
        return self.transport.port

    def connect(self, host: str, port: int) -> Optional[Peer]:
        if self.peer_manager.is_banned(host):
            return None
        peer = self.transport.dial(host, port)
        if peer is not None:
            self.discovery.learn(host, port)
        return peer

    def close(self) -> None:
        self.discovery.stop()
        self._mesh_stop.set()
        self.transport.close()

    def _mesh_heartbeat_loop(self) -> None:
        # gossipsub heartbeat analogue (reference heartbeat_interval ~0.7s)
        while not self._mesh_stop.wait(1.0):
            try:
                self.mesh_router.heartbeat()
            except Exception:
                pass

    # -- gossip out ------------------------------------------------------

    def publish_block(self, signed_block) -> None:
        self._publish(self.topics.block(), type(signed_block).encode(signed_block))

    def publish_attestation(self, attestation, subnet: int) -> None:
        self._publish(
            self.topics.attestation(subnet % ATTESTATION_SUBNET_COUNT),
            type(attestation).encode(attestation),
        )

    def publish_aggregate(self, signed_aggregate) -> None:
        self._publish(
            self.topics.aggregate(), type(signed_aggregate).encode(signed_aggregate)
        )

    def publish_sync_committee_message(self, msg, subnet: int) -> None:
        self._publish(
            self.topics.sync_committee(
                subnet % self.chain.preset.SYNC_COMMITTEE_SUBNET_COUNT
            ),
            type(msg).encode(msg),
        )

    def publish_sync_contribution(self, signed_contribution) -> None:
        self._publish(
            self.topics.sync_contribution(),
            type(signed_contribution).encode(signed_contribution),
        )

    def publish_voluntary_exit(self, signed_exit) -> None:
        self._publish(
            self.topics.voluntary_exit(), type(signed_exit).encode(signed_exit)
        )

    def publish_attester_slashing(self, slashing) -> None:
        self._publish(
            self.topics.attester_slashing(), type(slashing).encode(slashing)
        )

    def publish_proposer_slashing(self, slashing) -> None:
        self._publish(
            self.topics.proposer_slashing(), type(slashing).encode(slashing)
        )

    def _publish(self, topic: str, payload: bytes) -> None:
        mid = self._msg_id(topic, payload)
        self._mark_seen(topic, payload, mid)
        _GOSSIP_TX.inc()
        # originated messages flood-publish (reference flood_publish for
        # latency-critical topics); the mesh bounds RELAY fan-out only
        self.mesh_router.track(topic)
        self.mesh_router.remember(topic, mid, payload)
        self.transport.publish(topic, payload)

    # -- gossip in -------------------------------------------------------

    def _msg_id(self, topic: str, payload: bytes) -> bytes:
        from ..ssz.sha256 import hash_bytes

        return hash_bytes(topic.encode() + payload)[:20]

    def _mark_seen(self, topic: str, payload: bytes, mid: bytes | None = None) -> bool:
        """True if already seen. Prunes entries older than 10 minutes.
        ``mid`` lets hot paths reuse an already-computed message id (the
        sha256 runs over the full payload — blocks are large)."""
        if mid is None:
            mid = self._msg_id(topic, payload)
        now = time.monotonic()
        with self._seen_lock:
            if mid in self._seen:
                return True
            self._seen[mid] = now
            if len(self._seen) > 1 << 16:
                cutoff = now - 600
                self._seen = {
                    k: ts for k, ts in self._seen.items() if ts > cutoff
                }
            return False

    def has_seen(self, msg_id: bytes) -> bool:
        """IHAVE digest check (mesh router): seen-cache membership by id."""
        with self._seen_lock:
            return msg_id in self._seen

    # Verification-failure kinds that are NOT the sender's fault (clock
    # skew, duplicates seen first from another peer, not-yet-synced heads)
    _BENIGN_KINDS = frozenset(
        {
            "PriorAttestationKnown",
            "AttestationAlreadyKnown",
            "AggregatorAlreadyKnown",
            "ContributionAlreadyKnown",
            "PriorMessageKnown",
            "OutsideSlotRange",
            "OutsideSlotWindow",
            "UnknownHeadBlock",
            "UnknownTargetRoot",
            "UnknownSyncCommittee",
            "ParentUnknown",
            "BlockIsAlreadyKnown",
            "RepeatProposal",
            "FutureSlot",
        }
    )

    def _feedback(self, peer: Peer):
        """Done-callback reporting invalid gossip back to the scorer
        (reference: the processor's invalid-message penalties feeding
        gossipsub peer scores)."""

        def done(result):
            kind = getattr(result, "kind", None)
            if (
                isinstance(result, Exception)
                and kind is not None
                and kind not in self._BENIGN_KINDS
            ):
                self.peer_manager.report(peer, "invalid_message")

        return done

    def _on_gossip(self, peer: Peer, topic: str, payload: bytes) -> None:
        from .mesh import CTL_TOPIC

        # rate limiting applies to control frames too: GRAFT/PRUNE spam
        # must hit the same token bucket + penalties as any gossip
        if not self.peer_manager.allow_gossip(peer):
            return  # rate-limited: dropped, not forwarded
        if topic == CTL_TOPIC:  # GRAFT/PRUNE control: per-link, not flooded
            self.mesh_router.on_control(peer, payload)
            return
        mid = self._msg_id(topic, payload)
        if self._mark_seen(topic, payload, mid):
            return
        _GOSSIP_RX.inc()
        t = self.chain.types
        # match against every scheduled fork's topic set
        kinds = {}
        for tp in self._topics_by_fork.values():
            kinds[tp.block()] = "block"
            kinds[tp.aggregate()] = "aggregate"
            kinds[tp.sync_contribution()] = "sync_contribution"
            kinds[tp.voluntary_exit()] = "voluntary_exit"
            kinds[tp.attester_slashing()] = "attester_slashing"
            kinds[tp.proposer_slashing()] = "proposer_slashing"
        kind = kinds.get(topic)
        if kind is None and "/beacon_attestation_" in topic:
            kind = "attestation"
        if kind is None and "/sync_committee_" in topic:
            kind = "sync_message"
        if kind is not None:
            # only RECOGNIZED topics become mesh-managed: junk topics from
            # a hostile peer must never enter (or propagate through) the
            # mesh control plane
            self.mesh_router.track(topic)
        fb = self._feedback(peer)
        try:
            if kind == "block":
                fork = fork_of(self.chain.head_state)
                sb = t.signed_block[fork].decode(payload)

                def block_done(result, _fb=fb, _sb=sb):
                    _fb(result)
                    self._after_block(result, _sb)

                self.processor.submit(
                    Work(WorkKind.GOSSIP_BLOCK, sb, done=block_done)
                )
            elif kind == "aggregate":
                sa = t.SignedAggregateAndProof.decode(payload)
                self.processor.submit(
                    Work(WorkKind.GOSSIP_AGGREGATE, sa, done=fb)
                )
            elif kind == "attestation":
                att = t.Attestation.decode(payload)
                self.processor.submit(
                    Work(WorkKind.GOSSIP_ATTESTATION, att, done=fb)
                )
            elif kind == "sync_message":
                sm = t.SyncCommitteeMessage.decode(payload)
                self.processor.submit(
                    Work(WorkKind.GOSSIP_SYNC_MESSAGE, sm, done=fb)
                )
            elif kind == "sync_contribution":
                sc = t.SignedContributionAndProof.decode(payload)
                self.processor.submit(
                    Work(WorkKind.GOSSIP_SYNC_CONTRIBUTION, sc, done=fb)
                )
            elif kind == "voluntary_exit":
                ex = t.SignedVoluntaryExit.decode(payload)
                if self.chain.op_pool is not None:
                    self.chain.op_pool.insert_voluntary_exit(ex)
            elif kind == "attester_slashing":
                sl = t.AttesterSlashing.decode(payload)
                if self.chain.op_pool is not None:
                    self.chain.op_pool.insert_attester_slashing(sl)
            elif kind == "proposer_slashing":
                sl = t.ProposerSlashing.decode(payload)
                if self.chain.op_pool is not None:
                    self.chain.op_pool.insert_proposer_slashing(sl)
            else:
                return
        except Exception:
            self.peer_manager.report(peer, "undecodable")
            return
        # relay to the topic mesh (flood fallback while the mesh is
        # thinner than D_low), minus the sender; remember the message so
        # heartbeat IHAVE digests let non-mesh peers pull it
        self.mesh_router.remember(topic, mid, payload)
        members = self.mesh_router.relay_peers(topic, exclude=peer)
        if members is None:
            self.transport.publish(topic, payload, exclude=peer)
        else:
            for p in members:
                p.send(KIND_GOSSIP, topic.encode(), payload)

    def _after_block(self, result, sb=None) -> None:
        """Unknown-parent blocks trigger an active parent lookup (and
        range sync as the catch-up fallback); others are done."""
        from ..beacon_chain import BlockError

        if isinstance(result, BlockError) and result.kind == "ParentUnknown":
            if sb is not None:
                self.lookups.search(bytes(sb.message.parent_root), orphan=sb)
            self.sync.trigger()

    # -- req/resp --------------------------------------------------------

    def _on_peer_connected(self, peer: Peer) -> None:
        if self.peer_manager.is_banned(self.peer_manager.ban_key(peer)):
            peer.close()
            return
        # handshake: status + peer exchange, off-thread (dial returns fast)
        threading.Thread(
            target=self._handshake, args=(peer,), daemon=True
        ).start()

    def _handshake(self, peer: Peer) -> None:
        status = peer.request(
            PROTO_STATUS.encode(), json.dumps(self.local_status()).encode()
        )
        if status:
            try:
                self.sync.on_status(peer, json.loads(status))
            except (ValueError, KeyError):
                pass
        px = peer.request(PROTO_PEER_EXCHANGE.encode(), b"[]")
        if px:
            self.discovery.learn_from_px(px)
            try:
                for host, port in json.loads(px):
                    if port != self.port and self.transport.peer_count() < 32:
                        self.transport.dial(host, port)
            except (ValueError, TypeError):
                pass

    def local_status(self) -> dict:
        """Status payload (reference StatusMessage)."""
        chain = self.chain
        fin = chain.fork_choice.store.finalized_checkpoint
        return {
            "fork_digest": self.topics.prefix.split("/")[-1],
            "finalized_epoch": fin[0],
            "finalized_root": fin[1].hex(),
            "head_slot": chain.head_state.slot,
            "head_root": chain.head_block_root.hex(),
            "listen_port": self.port,
        }

    def _on_request(self, peer: Peer, protocol: str, payload: bytes) -> bytes:
        if not self.peer_manager.allow_request(peer, protocol):
            return b""  # rate-limited (reference rpc/rate_limiter.rs)
        chain = self.chain
        if protocol == PROTO_STATUS:
            try:
                theirs = json.loads(payload)
                peer.remote_listen_port = theirs.get("listen_port")
                self.sync.on_status(peer, theirs)
            except (ValueError, KeyError):
                pass
            return json.dumps(self.local_status()).encode()
        if protocol == PROTO_PING or protocol == PROTO_GOODBYE:
            return b"pong"
        if protocol == PROTO_METADATA:
            return json.dumps(
                {"attnets": sorted(self.subnets), "seq_number": 0}
            ).encode()
        if protocol == PROTO_PEER_EXCHANGE:
            peers = [
                [p.addr[0], p.remote_listen_port]
                for p in self.transport.peers_snapshot()
                if p.remote_listen_port
            ]
            return json.dumps(peers).encode()
        if protocol == PROTO_BLOCKS_BY_RANGE:
            start, count = struct.unpack("<QQ", payload[:16])
            out = []
            from ..store.iter import block_roots_iter

            wanted = range(start, start + min(count, 64))
            roots = {}
            for slot, root in block_roots_iter(chain.store, chain.head_block_root):
                if slot < start:
                    break
                if slot in wanted:
                    roots[slot] = root
            for slot in sorted(roots):
                block = chain.store.get_block(roots[slot])
                if block is not None:
                    enc = type(block).encode(block)
                    out.append(struct.pack("<I", len(enc)) + enc)
            return b"".join(out)
        if protocol == PROTO_BLOCKS_BY_ROOT:
            out = []
            for i in range(0, len(payload), 32):
                block = chain.store.get_block(payload[i:i + 32])
                if block is not None:
                    enc = type(block).encode(block)
                    out.append(struct.pack("<I", len(enc)) + enc)
            return b"".join(out)
        return b""


class BlockLookups:
    """Active unknown-parent block lookups (reference
    ``network/src/sync/block_lookups``): when a gossip block references an
    unknown parent, fetch the parent chain by root from the best-scored
    peers (retry across peers, downscore bad responders), import the
    recovered segment oldest-first, then replay the orphan. Range sync
    only helps when a peer's STATUS shows it ahead; a same-height fork or
    a missed gossip block needs this root-addressed path."""

    MAX_CHAIN = 16   # parent-depth bound (reference PARENT_DEPTH_TOLERANCE)
    PEER_TRIES = 3   # distinct peers asked per root before giving up
    MAX_INFLIGHT = 8  # concurrent lookup threads (reference bounds these
    #                   too: cheap ParentUnknown gossip must not fan out
    #                   unbounded threads or by-root request storms)
    NEG_CACHE_S = 30.0  # roots that failed recently are not re-searched

    def __init__(self, service: NetworkService):
        self.service = service
        self._lock = threading.Lock()
        self._inflight: set[bytes] = set()
        self._neg_cache: dict[bytes, float] = {}
        self._metric = _LOOKUPS
        self._fetched = _LOOKUP_BLOCKS

    def search(self, root: bytes, orphan=None) -> None:
        """Fire-and-forget lookup of ``root`` and its unknown ancestors;
        ``orphan`` (the block whose parent is missing) is replayed after
        the segment imports."""
        chain = self.service.chain
        now = time.monotonic()
        with self._lock:
            if root in self._inflight or len(self._inflight) >= self.MAX_INFLIGHT:
                return
            if self._neg_cache.get(root, 0.0) > now:
                return
            if len(self._neg_cache) > 1024:
                self._neg_cache = {
                    k: t for k, t in self._neg_cache.items() if t > now
                }
            self._inflight.add(root)
        if chain.store.get_block(root) is not None:
            with self._lock:
                self._inflight.discard(root)
            return
        self._metric.inc()
        threading.Thread(
            target=self._run, args=(root, orphan), daemon=True
        ).start()

    # -- internals -------------------------------------------------------

    def _best_peers(self) -> list[Peer]:
        pm = self.service.peer_manager
        peers = [
            p for p in self.service.transport.peers_snapshot() if not p.closed
        ]
        return sorted(peers, key=pm.score, reverse=True)

    def _request_block(self, root: bytes):
        """Ask up to PEER_TRIES best peers for one block by root; verify
        the response IS the requested block (hash_tree_root) and
        downscore peers that answer with garbage."""
        for peer in self._best_peers()[: self.PEER_TRIES]:
            raw = peer.request(PROTO_BLOCKS_BY_ROOT.encode(), root, timeout=10)
            if not raw:
                continue  # empty/timeout: try the next peer, no penalty
            try:
                (n,) = struct.unpack_from("<I", raw, 0)
                chunk = raw[4:4 + n]
                t = self.service.chain.types
                sb = None
                for fork in ("bellatrix", "altair", "phase0"):
                    try:
                        sb = t.signed_block[fork].decode(chunk)
                        break
                    except Exception:
                        continue
                if sb is None or hash_tree_root(sb.message) != root:
                    raise ValueError("wrong or undecodable block")
            except Exception:
                self.service.peer_manager.report(peer, "protocol")
                continue
            self._fetched.inc()
            return sb
        return None

    def _run(self, root: bytes, orphan) -> None:
        try:
            chain = self.service.chain
            segment = []  # newest -> oldest
            want = root
            for _ in range(self.MAX_CHAIN):
                if want == bytes(32) or chain.store.get_block(want) is not None:
                    break
                sb = self._request_block(want)
                if sb is None:
                    # nobody could serve it: negative-cache so repeat
                    # ParentUnknown gossip cannot re-trigger immediately
                    with self._lock:
                        self._neg_cache[root] = (
                            time.monotonic() + self.NEG_CACHE_S
                        )
                    return
                segment.append(sb)
                want = bytes(sb.message.parent_root)
            else:
                # chain deeper than the bound: that is range sync's job
                self.service.sync.trigger()
                return
            if not segment:
                return
            segment.reverse()  # oldest first for CHAIN_SEGMENT
            done = threading.Event()
            result = {}

            def _done(r, _ev=done, _res=result):
                _res["r"] = r
                _ev.set()

            self.service.processor.submit(
                Work(WorkKind.CHAIN_SEGMENT, segment, done=_done)
            )
            if not done.wait(timeout=60) or isinstance(result.get("r"), Exception):
                return
            if orphan is not None:
                # replay the orphan now that its ancestry is in the store
                self.service.processor.submit(
                    Work(WorkKind.GOSSIP_BLOCK, orphan, done=lambda r: None)
                )
        finally:
            with self._lock:
                self._inflight.discard(root)


class BackfillSync:
    """Reverse sync below a checkpoint anchor (reference
    ``network/src/sync/backfill_sync``): pull descending batches with
    blocks_by_range, check hash-linkage to the known anchor chain, batch
    proposal-signature verification with per-epoch fork domains (correct
    across any number of fork boundaries), then store."""

    BATCH = 32

    def __init__(self, service: NetworkService):
        self.service = service
        self.complete = False

    def _proposal_set(self, chain, anchor_state, sb, block_root):
        """Proposal signature set with the domain computed from the
        block's OWN epoch's fork version (get_domain on a state only
        knows one fork back; historical blocks need the schedule)."""
        from ..crypto import bls
        from ..types.chain_spec import DOMAIN_BEACON_PROPOSER
        from ..types.domains import compute_domain, compute_signing_root

        epoch = sb.message.slot // chain.preset.SLOTS_PER_EPOCH
        domain = compute_domain(
            chain.spec,
            DOMAIN_BEACON_PROPOSER,
            chain.spec.fork_version_at_epoch(epoch),
            bytes(anchor_state.genesis_validators_root),
        )
        root = compute_signing_root(None, block_root, domain)
        pk = chain.pubkey_cache.get(sb.message.proposer_index)
        return bls.SignatureSet.single_pubkey(
            bls.Signature.deserialize(bytes(sb.signature)), pk, root
        )

    def run(self, peer: Peer) -> int:
        """Blocking backfill from the oldest stored block downwards.
        Returns the number of blocks stored."""
        from ..store.iter import block_roots_iter

        chain = self.service.chain
        stored = 0
        oldest_root = None
        oldest_slot = None
        for slot, root in block_roots_iter(chain.store, chain.head_block_root):
            oldest_root, oldest_slot = root, slot
        if oldest_root is None or oldest_slot == 0:
            self.complete = True
            return 0
        block = chain.store.get_block(oldest_root)
        want = bytes(block.message.parent_root)
        anchor_state = chain.head_state
        next_below = oldest_slot  # request strictly below this slot
        while want != bytes(32):
            start = max(0, next_below - self.BATCH)
            count = next_below - start
            if count <= 0:
                break
            raw = peer.request(
                PROTO_BLOCKS_BY_RANGE.encode(),
                struct.pack("<QQ", start, count),
                timeout=30,
            )
            if not raw:
                return stored
            blocks = self._decode_blocks_any_fork(raw)
            if not blocks:
                return stored
            # walk the batch backwards, checking hash linkage to `want`
            verified = []
            sets = []
            for sb in reversed(blocks):
                root = hash_tree_root(sb.message)
                if root != want:
                    continue  # forked/extra block in response
                if sb.message.slot > 0:
                    sets.append(
                        self._proposal_set(chain, anchor_state, sb, root)
                    )
                verified.append((root, sb))
                want = bytes(sb.message.parent_root)
            if not verified:
                return stored
            # historical proposal signatures are the textbook bulk-class
            # workload (ISSUE 15): deadline-insensitive, contiguous,
            # self-paced — the scheduler fuses them onto the big warm
            # rungs at gossip idle; without a scheduler this is the same
            # direct call as before
            from ..verification_service import backend_verify_bulk

            if sets and not backend_verify_bulk(chain, sets, kind="backfill"):
                return stored
            for root, sb in verified:
                chain.store.put_block(root, sb)
                stored += 1
            _BACKFILL_BLOCKS.inc(len(verified))
            next_below = verified[-1][1].message.slot
            if verified[-1][1].message.slot == 0:
                break
        self.complete = True
        return stored

    def _decode_blocks_any_fork(self, raw: bytes) -> list:
        """Length-prefixed blocks; each tried against every scheduled
        fork's type (historical batches span fork boundaries)."""
        t = self.service.chain.types
        out = []
        i = 0
        while i + 4 <= len(raw):
            (n,) = struct.unpack_from("<I", raw, i)
            i += 4
            if i + n > len(raw):
                break
            chunk = raw[i:i + n]
            i += n
            for fork in ("bellatrix", "altair", "phase0"):
                try:
                    out.append(t.signed_block[fork].decode(chunk))
                    break
                except Exception:
                    continue
        return out


class RangeSync:
    """Forward range sync (reference ``network/src/sync/range_sync``):
    when a peer's status is ahead, pull batches of blocks_by_range and
    feed them as CHAIN_SEGMENT work until caught up."""

    BATCH = 32

    def __init__(self, service: NetworkService):
        self.service = service
        self._lock = threading.Lock()
        self._active = False
        self._best: Optional[tuple[int, Peer]] = None  # (head_slot, peer)

    def on_status(self, peer: Peer, status: dict) -> None:
        their_head = int(status.get("head_slot", 0))
        with self._lock:
            best = self._best
            if (
                best is None
                or their_head > best[0]
                or best[1].closed  # a dead best peer must never wedge sync
            ):
                self._best = (their_head, peer)
        if their_head > self.service.chain.head_state.slot:
            self.trigger()

    def trigger(self) -> None:
        with self._lock:
            if self._active:
                return
            self._active = True
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self) -> None:
        try:
            chain = self.service.chain
            while True:
                with self._lock:
                    best = self._best
                if best is None or best[0] <= chain.head_state.slot:
                    return
                target_slot, peer = best
                if peer.closed:
                    with self._lock:
                        if self._best is best:
                            self._best = None
                    return
                start = chain.head_state.slot + 1
                payload = struct.pack("<QQ", start, self.BATCH)
                raw = peer.request(PROTO_BLOCKS_BY_RANGE.encode(), payload, timeout=30)
                if not raw:
                    with self._lock:
                        if self._best is best:
                            self._best = None  # failed peer: re-learn from statuses
                    return
                blocks = self._decode_blocks(raw)
                if not blocks:
                    return
                _SYNC_BATCHES.inc()
                _SYNC_BLOCKS.inc(len(blocks))
                done = threading.Event()
                result = {}

                def _done(r, _ev=done, _res=result):
                    _res["r"] = r
                    _ev.set()

                self.service.processor.submit(
                    Work(WorkKind.CHAIN_SEGMENT, blocks, done=_done)
                )
                if not done.wait(timeout=60):
                    return
                if isinstance(result.get("r"), Exception):
                    return
        finally:
            with self._lock:
                self._active = False

    def _decode_blocks(self, raw: bytes) -> list:
        t = self.service.chain.types
        fork = fork_of(self.service.chain.head_state)
        out = []
        i = 0
        while i + 4 <= len(raw):
            (n,) = struct.unpack_from("<I", raw, i)
            i += 4
            if i + n > len(raw):
                break
            out.append(t.signed_block[fork].decode(raw[i:i + n]))
            i += n
        return out
