"""Noise-XX authenticated key exchange + AEAD framing for the transport.

The reference's libp2p layer authenticates and encrypts every connection
with the Noise protocol before any application bytes flow
(``beacon_node/lighthouse_network/src/service/behaviour.rs:17-30`` wires
the transport; libp2p-noise is the session layer), and peer scoring is
keyed by the cryptographic peer id, not the socket address
(``src/peer_manager/peerdb.rs``). This module gives the TCP transport the
same properties:

* **Noise XX** handshake (3 messages) over X25519 + HKDF-SHA256 +
  ChaCha20-Poly1305: mutual authentication of *static* keys, forward
  secrecy from ephemerals, and a transcript hash binding every message.
* **Identity**: a node's id is ``sha256(static_pub)`` — unforgeable
  without the private key; scores/bans key on it (``Peer.node_id``).
* **Transport phase**: every frame is AEAD-sealed with a per-direction
  key and a strictly-increasing counter nonce — on-path tampering,
  reflection, and replay (within or across sessions — ephemerals differ)
  all fail authentication and kill the connection.

The state machine follows the Noise spec's SymmetricState/CipherState
objects (MixHash / MixKey / EncryptAndHash / Split) so each step is
checkable against the spec; only the XX pattern is implemented.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives import serialization
from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"
DHLEN = 32
TAGLEN = 16
MAX_NOISE_MSG = 1 << 16


class HandshakeError(Exception):
    pass


def _hkdf2(ck: bytes, ikm: bytes) -> tuple[bytes, bytes]:
    """Noise HKDF with two outputs (spec §4.3)."""
    temp = hmac.new(ck, ikm, hashlib.sha256).digest()
    out1 = hmac.new(temp, b"\x01", hashlib.sha256).digest()
    out2 = hmac.new(temp, out1 + b"\x02", hashlib.sha256).digest()
    return out1, out2


def _pub_bytes(priv: X25519PrivateKey) -> bytes:
    return priv.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )


def _dh(priv: X25519PrivateKey, pub: bytes) -> bytes:
    return priv.exchange(X25519PublicKey.from_public_bytes(pub))


class Identity:
    """A node's static X25519 keypair; ``node_id`` is the wire identity."""

    def __init__(self, priv: X25519PrivateKey | None = None):
        self._priv = priv or X25519PrivateKey.generate()
        self.public = _pub_bytes(self._priv)
        self.node_id = node_id(self.public)

    @classmethod
    def from_seed(cls, seed: bytes) -> "Identity":
        """Deterministic identity (tests / stable node keys on disk)."""
        raw = hashlib.sha256(b"lighthouse-tpu-node-key" + seed).digest()
        return cls(X25519PrivateKey.from_private_bytes(raw))


def node_id(static_pub: bytes) -> str:
    return hashlib.sha256(static_pub).hexdigest()[:40]


class CipherState:
    """One direction of the transport: AEAD key + counter nonce."""

    __slots__ = ("_aead", "_n")

    def __init__(self, key: bytes):
        self._aead = ChaCha20Poly1305(key)
        self._n = 0

    def _nonce(self) -> bytes:
        n = struct.pack("<4xQ", self._n)
        self._n += 1
        if self._n >= 2**64 - 1:
            raise HandshakeError("nonce exhausted")
        return n

    def encrypt(self, plaintext: bytes, ad: bytes = b"") -> bytes:
        return self._aead.encrypt(self._nonce(), plaintext, ad)

    def decrypt(self, ciphertext: bytes, ad: bytes = b"") -> bytes:
        try:
            return self._aead.decrypt(self._nonce(), ciphertext, ad)
        except InvalidTag as e:
            raise HandshakeError("AEAD authentication failed") from e


class _Symmetric:
    """Noise SymmetricState (spec §5.2), SHA-256 / ChaChaPoly."""

    def __init__(self):
        self.h = hashlib.sha256(PROTOCOL_NAME).digest()
        self.ck = self.h
        self._cipher: CipherState | None = None

    def mix_hash(self, data: bytes) -> None:
        self.h = hashlib.sha256(self.h + data).digest()

    def mix_key(self, ikm: bytes) -> None:
        self.ck, k = _hkdf2(self.ck, ikm)
        self._cipher = CipherState(k)

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        if self._cipher is None:
            ct = plaintext
        else:
            ct = self._cipher.encrypt(plaintext, ad=self.h)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ct: bytes) -> bytes:
        if self._cipher is None:
            pt = ct
        else:
            pt = self._cipher.decrypt(ct, ad=self.h)
        self.mix_hash(ct)
        return pt

    def split(self) -> tuple[CipherState, CipherState]:
        k1, k2 = _hkdf2(self.ck, b"")
        return CipherState(k1), CipherState(k2)


def _send_msg(sock, payload: bytes) -> None:
    sock.sendall(struct.pack("<H", len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise HandshakeError("connection closed during handshake")
        buf += chunk
    return buf


def _recv_msg(sock) -> bytes:
    (ln,) = struct.unpack("<H", _recv_exact(sock, 2))
    return _recv_exact(sock, ln)


class Session:
    """Completed handshake: per-direction cipher states + remote identity."""

    __slots__ = ("send", "recv", "remote_static", "remote_node_id")

    def __init__(self, send: CipherState, recv: CipherState, remote_static: bytes):
        self.send = send
        self.recv = recv
        self.remote_static = remote_static
        self.remote_node_id = node_id(remote_static)


def handshake_initiator(sock, identity: Identity) -> Session:
    """XX initiator: -> e ; <- e, ee, s, es ; -> s, se."""
    sym = _Symmetric()
    sym.mix_hash(b"")  # empty prologue
    e = X25519PrivateKey.generate()
    e_pub = _pub_bytes(e)

    # -> e
    sym.mix_hash(e_pub)
    _send_msg(sock, e_pub)

    # <- e, ee, s, es
    msg2 = _recv_msg(sock)
    if len(msg2) != DHLEN + DHLEN + TAGLEN:
        raise HandshakeError("bad handshake message 2")
    re_pub, ct_s = msg2[:DHLEN], msg2[DHLEN:]
    sym.mix_hash(re_pub)
    sym.mix_key(_dh(e, re_pub))                    # ee
    rs_pub = sym.decrypt_and_hash(ct_s)            # s
    sym.mix_key(_dh(e, rs_pub))                    # es (initiator: DH(e, rs))

    # -> s, se
    ct_si = sym.encrypt_and_hash(identity.public)  # s
    sym.mix_key(_dh(identity._priv, re_pub))       # se (initiator: DH(s, re))
    _send_msg(sock, ct_si)

    send, recv = sym.split()
    return Session(send, recv, rs_pub)


def handshake_responder(sock, identity: Identity) -> Session:
    sym = _Symmetric()
    sym.mix_hash(b"")
    e = X25519PrivateKey.generate()
    e_pub = _pub_bytes(e)

    # <- e
    msg1 = _recv_msg(sock)
    if len(msg1) != DHLEN:
        raise HandshakeError("bad handshake message 1")
    re_pub = msg1
    sym.mix_hash(re_pub)

    # -> e, ee, s, es
    sym.mix_hash(e_pub)
    sym.mix_key(_dh(e, re_pub))                    # ee
    ct_s = sym.encrypt_and_hash(identity.public)   # s
    sym.mix_key(_dh(identity._priv, re_pub))       # es (responder: DH(s, re))
    _send_msg(sock, e_pub + ct_s)

    # <- s, se
    msg3 = _recv_msg(sock)
    if len(msg3) != DHLEN + TAGLEN:
        raise HandshakeError("bad handshake message 3")
    rs_pub = sym.decrypt_and_hash(msg3)            # s
    sym.mix_key(_dh(e, rs_pub))                    # se (responder: DH(e, rs))

    recv_c, send_c = sym.split()  # initiator's send is our recv
    return Session(send_c, recv_c, rs_pub)
