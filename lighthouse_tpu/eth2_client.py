"""Typed Beacon-API HTTP client (reference: ``common/eth2/src/lib.rs:140``
— the SDK the validator client and checkpoint sync use, with
``beacon_node_fallback``-style multi-node redundancy in
``validator_client/``).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .ssz.json import from_json, to_json


class BeaconNodeError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class BeaconNodeClient:
    def __init__(self, base_url: str, types, timeout: float = 10.0):
        self.base = base_url.rstrip("/")
        self.t = types
        self.timeout = timeout

    # -- raw -------------------------------------------------------------

    def _get(self, path: str, params: dict | None = None):
        url = self.base + path
        if params:
            from urllib.parse import urlencode

            url += "?" + urlencode(params)
        return self._req(urllib.request.Request(url))

    def _post(self, path: str, body) -> object:
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            self.base + path, data=data,
            headers={"Content-Type": "application/json"},
        )
        return self._req(req)

    def _req(self, req):
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                if not raw:
                    return None
                ctype = r.headers.get("Content-Type", "")
                return json.loads(raw) if "json" in ctype else raw
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("message", "")
            except Exception:
                msg = ""
            raise BeaconNodeError(e.code, msg) from None
        except urllib.error.URLError as e:
            raise BeaconNodeError(0, str(e.reason)) from None

    # -- node ------------------------------------------------------------

    def health(self) -> bool:
        try:
            self._get("/eth/v1/node/health")
            return True
        except BeaconNodeError:
            return False

    def syncing(self) -> dict:
        return self._get("/eth/v1/node/syncing")["data"]

    def genesis(self) -> dict:
        return self._get("/eth/v1/beacon/genesis")["data"]

    def spec(self) -> dict:
        return self._get("/eth/v1/config/spec")["data"]

    # -- beacon ----------------------------------------------------------

    def state_finality_checkpoints(self, state_id: str = "head") -> dict:
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/finality_checkpoints"
        )["data"]

    def validators(self, state_id: str = "head", id: str | None = None) -> list:
        params = {"id": id} if id else None
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/validators", params
        )["data"]

    def state_ssz(self, state_id: str = "finalized"):
        """Fork byte + SSZ state (checkpoint-sync bootstrap)."""
        from .types.containers import FORK_NAMES

        raw = self._get(f"/eth/v2/debug/beacon/states/{state_id}")
        fork = FORK_NAMES[raw[0]]
        return self.t.state[fork].decode(bytes(raw[1:]))

    def block(self, block_id: str = "head"):
        out = self._get(f"/eth/v2/beacon/blocks/{block_id}")
        return from_json(self.t.signed_block[out["version"]], out["data"])

    def header(self, block_id: str = "head") -> dict:
        return self._get(f"/eth/v1/beacon/headers/{block_id}")["data"]

    def publish_block(self, signed_block) -> None:
        fork = next(
            f for f, cls in self.t.signed_block.items()
            if isinstance(signed_block, cls)
        )
        self._post(
            "/eth/v1/beacon/blocks",
            {"version": fork, "data": to_json(type(signed_block), signed_block)},
        )

    def publish_attestations(self, attestations) -> None:
        self._post(
            "/eth/v1/beacon/pool/attestations",
            [to_json(type(a), a) for a in attestations],
        )

    def publish_voluntary_exit(self, signed_exit) -> None:
        self._post(
            "/eth/v1/beacon/pool/voluntary_exits",
            to_json(type(signed_exit), signed_exit),
        )

    # -- validator -------------------------------------------------------

    def proposer_duties(self, epoch: int) -> dict:
        return self._get(f"/eth/v1/validator/duties/proposer/{epoch}")

    def attester_duties(self, epoch: int, validator_indices) -> dict:
        return self._post(
            f"/eth/v1/validator/duties/attester/{epoch}",
            [str(i) for i in validator_indices],
        )

    def produce_block(self, slot: int, randao_reveal: bytes, graffiti: bytes = bytes(32)):
        out = self._get(
            f"/eth/v2/validator/blocks/{slot}",
            {
                "randao_reveal": "0x" + randao_reveal.hex(),
                "graffiti": "0x" + graffiti.hex(),
            },
        )
        return from_json(self.t.block[out["version"]], out["data"])

    def attestation_data(self, slot: int, committee_index: int):
        out = self._get(
            "/eth/v1/validator/attestation_data",
            {"slot": slot, "committee_index": committee_index},
        )
        return from_json(self.t.AttestationData, out["data"])

    def aggregate_attestation(self, slot: int, attestation_data_root: bytes):
        out = self._get(
            "/eth/v1/validator/aggregate_attestation",
            {
                "slot": slot,
                "attestation_data_root": "0x" + attestation_data_root.hex(),
            },
        )
        return from_json(self.t.Attestation, out["data"])

    def sync_duties(self, epoch: int, validator_indices) -> dict:
        return self._post(
            f"/eth/v1/validator/duties/sync/{epoch}",
            [str(i) for i in validator_indices],
        )

    def publish_sync_committee_messages(self, messages) -> None:
        """messages: [{slot, beacon_block_root, validator_index, signature}]"""
        self._post("/eth/v1/beacon/pool/sync_committees", messages)

    def sync_committee_contribution(
        self, slot: int, subcommittee_index: int, beacon_block_root: bytes
    ):
        out = self._get(
            "/eth/v1/validator/sync_committee_contribution",
            {
                "slot": slot,
                "subcommittee_index": subcommittee_index,
                "beacon_block_root": "0x" + bytes(beacon_block_root).hex(),
            },
        )
        return from_json(self.t.SyncCommitteeContribution, out["data"])

    def publish_contribution_and_proofs(self, signed_contributions) -> None:
        self._post(
            "/eth/v1/validator/contribution_and_proofs",
            [
                to_json(self.t.SignedContributionAndProof, sc)
                for sc in signed_contributions
            ],
        )

    def beacon_committee_subscriptions(self, subscriptions) -> None:
        self._post("/eth/v1/validator/beacon_committee_subscriptions", subscriptions)

    def sync_committee_subscriptions(self, subscriptions) -> None:
        self._post("/eth/v1/validator/sync_committee_subscriptions", subscriptions)

    def prepare_beacon_proposer(self, preparations) -> None:
        """preparations: [{validator_index, fee_recipient}]"""
        self._post("/eth/v1/validator/prepare_beacon_proposer", preparations)

    def register_validator(self, registrations) -> None:
        self._post("/eth/v1/validator/register_validator", registrations)

    def publish_aggregate_and_proofs(self, signed_aggregates) -> None:
        self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            [to_json(type(s), s) for s in signed_aggregates],
        )
