"""SSZ type descriptors: encode/decode + structural metadata.

Spec semantics follow the consensus-spec SimpleSerialize rules the
reference implements with derive macros (``consensus/ssz/src/``,
``consensus/ssz_derive``): little-endian uints, 4-byte offsets for
variable-size members, Bitlist delimiter bits, strict decode (every byte
consumed, offsets monotone).

Descriptors are lightweight objects; ``Container`` subclasses are both the
descriptor and the value class (fields declared in an ordered ``fields``
list, instances get attribute storage + zeroed defaults — the analogue of
the reference's ``#[derive(Encode, Decode, TreeHash)]`` structs).
"""

from __future__ import annotations

from typing import Any, Sequence

BYTES_PER_LENGTH_OFFSET = 4


class SSZError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Basic types
# ---------------------------------------------------------------------------

class _Uint:
    def __init__(self, bits: int):
        self.bits = bits
        self.size = bits // 8

    def __repr__(self):
        return f"Uint{self.bits}"

    def is_fixed(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return self.size

    def default(self) -> int:
        return 0

    def encode(self, v: int) -> bytes:
        if not 0 <= v < (1 << self.bits):
            raise SSZError(f"uint{self.bits} out of range: {v}")
        return int(v).to_bytes(self.size, "little")

    def decode(self, data: bytes) -> int:
        if len(data) != self.size:
            raise SSZError(f"uint{self.bits}: expected {self.size} bytes, got {len(data)}")
        return int.from_bytes(data, "little")


class _Boolean:
    size = 1

    def __repr__(self):
        return "Boolean"

    def is_fixed(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return 1

    def default(self) -> bool:
        return False

    def encode(self, v: bool) -> bytes:
        return b"\x01" if v else b"\x00"

    def decode(self, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise SSZError(f"invalid boolean byte {data!r}")


Uint8 = _Uint(8)
Uint16 = _Uint(16)
Uint32 = _Uint(32)
Uint64 = _Uint(64)
Uint128 = _Uint(128)
Uint256 = _Uint(256)
Boolean = _Boolean()


# ---------------------------------------------------------------------------
# Byte vectors / lists (special-cased for compactness: values are `bytes`)
# ---------------------------------------------------------------------------

class ByteVector:
    def __init__(self, length: int):
        self.length = length

    def __repr__(self):
        return f"ByteVector({self.length})"

    def is_fixed(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return self.length

    def default(self) -> bytes:
        return bytes(self.length)

    def encode(self, v: bytes) -> bytes:
        v = bytes(v)
        if len(v) != self.length:
            raise SSZError(f"ByteVector({self.length}): got {len(v)} bytes")
        return v

    def decode(self, data: bytes) -> bytes:
        if len(data) != self.length:
            raise SSZError(f"ByteVector({self.length}): got {len(data)} bytes")
        return bytes(data)


class ByteList:
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self):
        return f"ByteList({self.limit})"

    def is_fixed(self) -> bool:
        return False

    def default(self) -> bytes:
        return b""

    def encode(self, v: bytes) -> bytes:
        v = bytes(v)
        if len(v) > self.limit:
            raise SSZError(f"ByteList limit {self.limit} exceeded: {len(v)}")
        return v

    def decode(self, data: bytes) -> bytes:
        if len(data) > self.limit:
            raise SSZError(f"ByteList limit {self.limit} exceeded: {len(data)}")
        return bytes(data)


Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)


# ---------------------------------------------------------------------------
# Bit types (values are lists of bools)
# ---------------------------------------------------------------------------

def _pack_bits(bits: Sequence[bool], extra_bit_at: int | None = None) -> bytes:
    n = len(bits) + (1 if extra_bit_at is not None else 0)
    out = bytearray((n + 7) // 8)
    for i, b in enumerate(bits):
        if b:
            out[i // 8] |= 1 << (i % 8)
    if extra_bit_at is not None:
        out[extra_bit_at // 8] |= 1 << (extra_bit_at % 8)
    return bytes(out)


class Bitvector:
    def __init__(self, length: int):
        if length <= 0:
            raise SSZError("Bitvector length must be positive")
        self.length = length

    def __repr__(self):
        return f"Bitvector({self.length})"

    def is_fixed(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return (self.length + 7) // 8

    def default(self) -> list:
        return [False] * self.length

    def encode(self, v: Sequence[bool]) -> bytes:
        if len(v) != self.length:
            raise SSZError(f"Bitvector({self.length}): got {len(v)} bits")
        return _pack_bits(v)

    def decode(self, data: bytes) -> list:
        if len(data) != self.fixed_size():
            raise SSZError(f"Bitvector({self.length}): got {len(data)} bytes")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(self.length)]
        # trailing padding bits must be zero
        for i in range(self.length, len(data) * 8):
            if (data[i // 8] >> (i % 8)) & 1:
                raise SSZError("Bitvector: nonzero padding bits")
        return bits


class Bitlist:
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self):
        return f"Bitlist({self.limit})"

    def is_fixed(self) -> bool:
        return False

    def default(self) -> list:
        return []

    def encode(self, v: Sequence[bool]) -> bytes:
        if len(v) > self.limit:
            raise SSZError(f"Bitlist limit {self.limit} exceeded: {len(v)}")
        return _pack_bits(v, extra_bit_at=len(v))

    def decode(self, data: bytes) -> list:
        if not data:
            raise SSZError("Bitlist: empty encoding (delimiter bit required)")
        last = data[-1]
        if last == 0:
            raise SSZError("Bitlist: missing delimiter bit")
        # position of the highest set bit in the last byte
        top = last.bit_length() - 1
        n = (len(data) - 1) * 8 + top
        if n > self.limit:
            raise SSZError(f"Bitlist limit {self.limit} exceeded: {n}")
        return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(n)]


# ---------------------------------------------------------------------------
# Homogeneous collections
# ---------------------------------------------------------------------------

def _encode_sequence(elem, values) -> bytes:
    if elem.is_fixed():
        return b"".join(elem.encode(v) for v in values)
    parts = [elem.encode(v) for v in values]
    offset = BYTES_PER_LENGTH_OFFSET * len(parts)
    out = bytearray()
    for p in parts:
        out += offset.to_bytes(BYTES_PER_LENGTH_OFFSET, "little")
        offset += len(p)
    for p in parts:
        out += p
    return bytes(out)


def _decode_sequence(elem, data: bytes, count: int | None) -> list:
    """count=None: infer from data (list); else exact count (vector)."""
    if elem.is_fixed():
        size = elem.fixed_size()
        if count is None:
            if len(data) % size:
                raise SSZError("sequence length not a multiple of element size")
            count = len(data) // size
        elif len(data) != size * count:
            raise SSZError("vector byte length mismatch")
        return [elem.decode(data[i * size:(i + 1) * size]) for i in range(count)]
    # variable-size elements: offset table
    if not data:
        if count not in (None, 0):
            raise SSZError("empty data for non-empty vector")
        return []
    first = int.from_bytes(data[:BYTES_PER_LENGTH_OFFSET], "little")
    if first % BYTES_PER_LENGTH_OFFSET or first == 0:
        raise SSZError("malformed first offset")
    n = first // BYTES_PER_LENGTH_OFFSET
    if count is not None and n != count:
        raise SSZError("vector element count mismatch")
    offsets = []
    for i in range(n):
        o = int.from_bytes(
            data[i * BYTES_PER_LENGTH_OFFSET:(i + 1) * BYTES_PER_LENGTH_OFFSET],
            "little",
        )
        offsets.append(o)
    offsets.append(len(data))
    if offsets[0] != n * BYTES_PER_LENGTH_OFFSET:
        raise SSZError("first offset does not point past the offset table")
    out = []
    for i in range(n):
        if offsets[i + 1] < offsets[i]:
            raise SSZError("offsets not monotone")
        out.append(elem.decode(data[offsets[i]:offsets[i + 1]]))
    return out


class Vector:
    def __init__(self, elem, length: int):
        if length <= 0:
            raise SSZError("Vector length must be positive")
        self.elem = elem
        self.length = length

    def __repr__(self):
        return f"Vector({self.elem!r}, {self.length})"

    def is_fixed(self) -> bool:
        return self.elem.is_fixed()

    def fixed_size(self) -> int:
        return self.elem.fixed_size() * self.length

    def default(self) -> list:
        return [self.elem.default() for _ in range(self.length)]

    def encode(self, v) -> bytes:
        if len(v) != self.length:
            raise SSZError(f"Vector({self.length}): got {len(v)} elements")
        return _encode_sequence(self.elem, v)

    def decode(self, data: bytes) -> list:
        return _decode_sequence(self.elem, data, self.length)


class List:
    def __init__(self, elem, limit: int):
        self.elem = elem
        self.limit = limit

    def __repr__(self):
        return f"List({self.elem!r}, {self.limit})"

    def is_fixed(self) -> bool:
        return False

    def default(self) -> list:
        return []

    def encode(self, v) -> bytes:
        if len(v) > self.limit:
            raise SSZError(f"List limit {self.limit} exceeded: {len(v)}")
        return _encode_sequence(self.elem, v)

    def decode(self, data: bytes) -> list:
        out = _decode_sequence(self.elem, data, None)
        if len(out) > self.limit:
            raise SSZError(f"List limit {self.limit} exceeded: {len(out)}")
        return out


class Union:
    """SSZ union: 1-byte selector + encoded value. ``None`` option must be
    selector 0 with empty body (per spec)."""

    def __init__(self, options):
        self.options = list(options)  # descriptors; options[0] may be None

    def is_fixed(self) -> bool:
        return False

    def default(self):
        return (0, None if self.options[0] is None else self.options[0].default())

    def encode(self, v) -> bytes:
        sel, val = v
        if not 0 <= sel < len(self.options):
            raise SSZError(f"Union selector {sel} out of range")
        opt = self.options[sel]
        if opt is None:
            if val is not None:
                raise SSZError("Union None option carries no value")
            return bytes([sel])
        return bytes([sel]) + opt.encode(val)

    def decode(self, data: bytes):
        if not data:
            raise SSZError("Union: empty encoding")
        sel = data[0]
        if sel >= len(self.options):
            raise SSZError(f"Union selector {sel} out of range")
        opt = self.options[sel]
        if opt is None:
            if len(data) != 1:
                raise SSZError("Union None option carries no value")
            return (0, None)
        return (sel, opt.decode(data[1:]))


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------

def field(name: str, tpe) -> tuple:
    return (name, tpe)


class _ContainerMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        fields = ns.get("fields")
        if fields is None:
            # inherit
            for b in bases:
                if hasattr(b, "fields"):
                    cls.fields = b.fields
                    break
        if getattr(cls, "fields", None):
            cls._field_names = [n for n, _ in cls.fields]
            cls._field_types = dict(cls.fields)
        return cls


class Container(metaclass=_ContainerMeta):
    """Base for SSZ containers; subclasses set ``fields = [(name, type), ...]``.

    The class doubles as its own descriptor: ``cls.encode(instance)``,
    ``cls.decode(bytes)``, ``cls.is_fixed()``...
    """

    fields: list = []

    def __init__(self, **kwargs):
        for n, t in self.fields:
            if n in kwargs:
                setattr(self, n, kwargs.pop(n))
            else:
                setattr(self, n, t.default())
        if kwargs:
            raise SSZError(f"{type(self).__name__}: unknown fields {sorted(kwargs)}")

    def __eq__(self, o):
        if type(o) is not type(self):
            return NotImplemented
        return all(getattr(self, n) == getattr(o, n) for n in self._field_names)

    def __hash__(self):
        return hash(type(self).encode(self))

    def __repr__(self):
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n in self._field_names[:4])
        more = "..." if len(self._field_names) > 4 else ""
        return f"{type(self).__name__}({inner}{more})"

    def copy(self):
        import copy as _copy

        return _copy.deepcopy(self)

    # -- descriptor protocol (classmethods) ------------------------------

    @classmethod
    def is_fixed(cls) -> bool:
        return all(t.is_fixed() for _, t in cls.fields)

    @classmethod
    def fixed_size(cls) -> int:
        return sum(t.fixed_size() for _, t in cls.fields)

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def encode(cls, v) -> bytes:
        fixed_parts = []
        var_parts = []
        for n, t in cls.fields:
            val = getattr(v, n)
            if t.is_fixed():
                fixed_parts.append(t.encode(val))
            else:
                fixed_parts.append(None)
                var_parts.append(t.encode(val))
        fixed_len = sum(
            len(p) if p is not None else BYTES_PER_LENGTH_OFFSET for p in fixed_parts
        )
        out = bytearray()
        offset = fixed_len
        vi = 0
        for p in fixed_parts:
            if p is not None:
                out += p
            else:
                out += offset.to_bytes(BYTES_PER_LENGTH_OFFSET, "little")
                offset += len(var_parts[vi])
                vi += 1
        for p in var_parts:
            out += p
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes):
        values = {}
        var_fields = []
        offsets = []
        pos = 0
        for n, t in cls.fields:
            if t.is_fixed():
                size = t.fixed_size()
                if pos + size > len(data):
                    raise SSZError(f"{cls.__name__}: truncated at field {n}")
                values[n] = t.decode(data[pos:pos + size])
                pos += size
            else:
                if pos + BYTES_PER_LENGTH_OFFSET > len(data):
                    raise SSZError(f"{cls.__name__}: truncated offset at {n}")
                offsets.append(
                    int.from_bytes(data[pos:pos + BYTES_PER_LENGTH_OFFSET], "little")
                )
                var_fields.append((n, t))
                pos += BYTES_PER_LENGTH_OFFSET
        if var_fields:
            if offsets[0] != pos:
                raise SSZError(f"{cls.__name__}: first offset mismatch")
            offsets.append(len(data))
            for (n, t), start, end in zip(var_fields, offsets, offsets[1:]):
                if end < start or start > len(data):
                    raise SSZError(f"{cls.__name__}: bad offsets for {n}")
                values[n] = t.decode(data[start:end])
        elif pos != len(data):
            raise SSZError(f"{cls.__name__}: {len(data) - pos} trailing bytes")
        return cls(**values)
