"""Generic container <-> JSON in the Beacon-API wire shape (reference:
``consensus/serde_utils`` — quoted ints, 0x-hex bytes — as used by every
``/eth/v1`` route and by the spec test ``value.yaml`` files)."""

from __future__ import annotations

from .core import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    List,
    SSZError,
    Union,
    Vector,
    _Boolean,
    _ContainerMeta,
    _Uint,
    _pack_bits,
)


def to_json(tpe, value):
    if isinstance(tpe, _Uint):
        return str(value)
    if isinstance(tpe, _Boolean):
        return bool(value)
    if isinstance(tpe, (ByteVector, ByteList)):
        return "0x" + bytes(value).hex()
    if isinstance(tpe, (Bitvector, Bitlist)):
        extra = len(value) if isinstance(tpe, Bitlist) else None
        return "0x" + _pack_bits(list(value), extra_bit_at=extra).hex()
    if isinstance(tpe, (Vector, List)):
        return [to_json(tpe.elem, v) for v in value]
    if isinstance(tpe, Union):
        sel, val = value
        opt = tpe.options[sel]
        return {
            "selector": str(sel),
            "value": None if opt is None else to_json(opt, val),
        }
    if isinstance(tpe, _ContainerMeta):
        return {n: to_json(t, getattr(value, n)) for n, t in tpe.fields}
    raise SSZError(f"to_json: unsupported type {tpe!r}")


def _unpack_bits(data: bytes, length: int | None) -> list[bool]:
    bits = []
    for byte in data:
        for i in range(8):
            bits.append(bool((byte >> i) & 1))
    if length is None:
        return bits
    # Bitlist: strip up to the delimiter bit
    while bits and not bits[-1]:
        bits.pop()
    if not bits:
        raise SSZError("bitlist missing delimiter")
    bits.pop()  # the delimiter itself
    return bits


def from_json(tpe, obj):
    if isinstance(tpe, _Uint):
        return int(obj)
    if isinstance(tpe, _Boolean):
        return bool(obj)
    if isinstance(tpe, (ByteVector, ByteList)):
        return bytes.fromhex(obj[2:] if obj.startswith("0x") else obj)
    if isinstance(tpe, Bitvector):
        data = bytes.fromhex(obj[2:])
        return _unpack_bits(data, None)[: tpe.length]
    if isinstance(tpe, Bitlist):
        data = bytes.fromhex(obj[2:])
        return _unpack_bits(data, -1)
    if isinstance(tpe, (Vector, List)):
        return [from_json(tpe.elem, v) for v in obj]
    if isinstance(tpe, Union):
        sel = int(obj["selector"])
        opt = tpe.options[sel]
        return (sel, None if opt is None else from_json(opt, obj["value"]))
    if isinstance(tpe, _ContainerMeta):
        return tpe(**{n: from_json(t, obj[n]) for n, t in tpe.fields})
    raise SSZError(f"from_json: unsupported type {tpe!r}")
