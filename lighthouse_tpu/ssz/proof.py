"""Merkle proof GENERATION over SSZ container trees (reference:
``consensus/merkle_proof`` + ``BeaconState::compute_merkle_proof`` in
``consensus/types/src/beacon_state.rs`` — the light-client seam).

A container's hash-tree-root is the Merkle root of its field roots padded
to the next power of two; a field's generalized index is
``next_pow2(n_fields) + field_index``; nested paths multiply:
``gi(parent_path) * next_pow2(n_child) + child_index``.
"""

from __future__ import annotations

from .core import Container, _ContainerMeta
from .hash import _next_pow2, hash_tree_root
from .sha256 import ZERO_HASHES, hash_pairs

import numpy as np


def _field_roots(tpe, value) -> list[bytes]:
    return [hash_tree_root(t, getattr(value, n)) for n, t in tpe.fields]


def _tree_levels(leaves: list[bytes], width: int) -> list[list[bytes]]:
    """All levels bottom-up over ``width`` (pow2) leaves, zero-padded."""
    level = list(leaves) + [ZERO_HASHES[0]] * (width - len(leaves))
    # leaves of a container are real roots; padding uses zero chunks
    level = [bytes(x) for x in level]
    levels = [level]
    d = 0
    while len(level) > 1:
        pairs = np.frombuffer(b"".join(level), np.uint8).reshape(-1, 64)
        hashed = hash_pairs(pairs)
        level = [hashed[i].tobytes() for i in range(hashed.shape[0])]
        levels.append(level)
        d += 1
    return levels


def compute_merkle_proof(value: Container, path: list[str]) -> tuple[bytes, list[bytes], int]:
    """Branch for the field at ``path`` (e.g. ``["finalized_checkpoint",
    "root"]``) against ``hash_tree_root(value)``.

    -> (leaf_root, branch bottom-up, generalized_index). Only all-fixed
    container hops are supported (the light-client paths are)."""
    tpe = type(value)
    if not isinstance(tpe, _ContainerMeta):
        raise TypeError("proofs start at a container")
    name = path[0]
    fields = tpe.fields
    names = [n for n, _ in fields]
    idx = names.index(name)
    sub_tpe = dict(fields)[name]
    sub_val = getattr(value, name)

    width = _next_pow2(len(fields))
    depth = (width - 1).bit_length()
    leaves = _field_roots(tpe, value)
    levels = _tree_levels(leaves, width)

    branch = []
    i = idx
    for d in range(depth):
        branch.append(levels[d][i ^ 1])
        i //= 2

    gi = width + idx
    if len(path) == 1:
        return leaves[idx], branch, gi

    # recurse into the sub-container; its branch sits BELOW ours
    sub_leaf, sub_branch, sub_gi = compute_merkle_proof(sub_val, path[1:])
    sub_width = 1 << (sub_gi.bit_length() - 1)
    return sub_leaf, sub_branch + branch, gi * sub_width + (sub_gi - sub_width)


def verify_merkle_proof(
    leaf: bytes, branch: list[bytes], generalized_index: int, root: bytes
) -> bool:
    """Spec ``is_valid_merkle_branch`` driven by a generalized index."""
    from ..state_transition.merkle import is_valid_merkle_branch

    depth = generalized_index.bit_length() - 1
    index = generalized_index - (1 << depth)
    return is_valid_merkle_branch(leaf, branch, depth, index, root)
