"""Hashing layer: batched SHA-256 over 64-byte blocks.

The analogue of the reference's ``crypto/eth2_hashing`` (runtime dispatch
between ring and SHA-NI — ``src/lib.rs:87-177``): one seam,
``hash_pairs``, through which ALL merkleization flows. Backends:

* native C (``_native/sha256.c``): SHA-NI when the CPU has it, portable
  scalar otherwise; batch-first export so Python pays one FFI transition
  per merkle tree level instead of one interpreter round-trip per node;
* hashlib (OpenSSL) fallback when no C compiler is available — slower per
  row purely from per-call interpreter overhead, same results.
"""

from __future__ import annotations

import hashlib

import numpy as np

from .._native import build_and_load as _build_and_load

_lib = _build_and_load("sha256")
if _lib is not None:
    import ctypes as _ct

    try:
        _lib.sha256_hash_pairs.argtypes = [
            _ct.c_char_p, _ct.c_char_p, _ct.c_size_t
        ]
        _lib.sha256_oneshot.argtypes = [_ct.c_char_p, _ct.c_size_t, _ct.c_char_p]
    except AttributeError:  # symbols missing (unexpected toolchain) -> fallback
        _lib = None


def hash_bytes(data: bytes) -> bytes:
    if _lib is not None:
        out = _ct.create_string_buffer(32)
        _lib.sha256_oneshot(data, len(data), out)
        return out.raw
    return hashlib.sha256(data).digest()


def hash32_concat(a: bytes, b: bytes) -> bytes:
    return hash_bytes(a + b)


def _hash_pairs_hashlib(pairs: np.ndarray) -> np.ndarray:
    out = np.empty((pairs.shape[0], 32), np.uint8)
    mv = memoryview(np.ascontiguousarray(pairs)).cast("B")
    for i in range(pairs.shape[0]):
        out[i] = np.frombuffer(
            hashlib.sha256(mv[i * 64:(i + 1) * 64]).digest(), np.uint8
        )
    return out


def hash_pairs(pairs: np.ndarray) -> np.ndarray:
    """uint8[n, 64] -> uint8[n, 32]: SHA-256 of each 64-byte row.

    The merkleization hot loop: one native batch call when available.
    """
    if _lib is not None:
        n = pairs.shape[0]
        pairs = np.ascontiguousarray(pairs)
        out = np.empty((n, 32), np.uint8)
        _lib.sha256_hash_pairs(
            pairs.ctypes.data_as(_ct.c_char_p),
            out.ctypes.data_as(_ct.c_char_p),
            n,
        )
        return out
    return _hash_pairs_hashlib(pairs)


# Zero-subtree hashes: ZERO_HASHES[d] = root of an all-zero depth-d tree.
ZERO_HASHES = [bytes(32)]
for _ in range(64):
    ZERO_HASHES.append(hash32_concat(ZERO_HASHES[-1], ZERO_HASHES[-1]))
