"""Hashing layer: batched SHA-256 over 64-byte blocks.

The analogue of the reference's ``crypto/eth2_hashing`` (runtime dispatch
between ring and SHA-NI — ``src/lib.rs:87-177``): one seam,
``hash_pairs``, through which ALL merkleization flows, so the backend can
be swapped (hashlib loop now; C++ batched SHA-NI or a device kernel later)
without touching tree-hash logic.
"""

from __future__ import annotations

import hashlib

import numpy as np


def hash_bytes(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def hash32_concat(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def hash_pairs(pairs: np.ndarray) -> np.ndarray:
    """uint8[n, 64] -> uint8[n, 32]: SHA-256 of each 64-byte row.

    The merkleization hot loop. Current backend: hashlib (OpenSSL SHA-NI)
    per row — already native speed per hash; the batch interface is what
    lets a vectorized backend slot in.
    """
    out = np.empty((pairs.shape[0], 32), np.uint8)
    for i in range(pairs.shape[0]):
        out[i] = np.frombuffer(hashlib.sha256(pairs[i].tobytes()).digest(), np.uint8)
    return out


# Zero-subtree hashes: ZERO_HASHES[d] = root of an all-zero depth-d tree.
ZERO_HASHES = [bytes(32)]
for _ in range(64):
    ZERO_HASHES.append(hash32_concat(ZERO_HASHES[-1], ZERO_HASHES[-1]))
