"""SimpleSerialize (SSZ) + Merkleization.

Re-design of the reference's SSZ stack (``consensus/ssz``,
``consensus/ssz_types``, ``consensus/tree_hash`` — Rust trait/derive
macros) as a declarative schema system: every wire type is a *descriptor
object* (``Uint64``, ``Vector(t, n)``, ``List(t, n)``, ``Container``
subclasses, ...) that knows how to encode, decode, and hash-tree-root
values.

The TPU-first angle: SSZ fixed-length types are the one place the
reference is already statically shaped (``FixedVector``/``VariableList``
with typenum bounds — ``consensus/ssz_types/src/lib.rs``); descriptors
here expose ``np.ndarray``-backed columnar views so state fields
(balances, validators, ...) can move to device without re-marshalling
(see ``state/``). Hashing is the batched SHA-256 in ``.sha256`` (numpy
lane-parallel, the host analogue of ``crypto/eth2_hashing``'s SHA-NI
dispatch).
"""

from .core import (
    Bitlist,
    Bitvector,
    Boolean,
    ByteList,
    ByteVector,
    Container,
    List,
    SSZError,
    Uint8,
    Uint16,
    Uint32,
    Uint64,
    Uint128,
    Uint256,
    Union,
    Vector,
    field,
)
from .hash import hash_tree_root
from .core import Bytes4, Bytes20, Bytes32, Bytes48, Bytes96

__all__ = [
    "Bitlist",
    "Bitvector",
    "Boolean",
    "ByteList",
    "ByteVector",
    "Bytes4",
    "Bytes20",
    "Bytes32",
    "Bytes48",
    "Bytes96",
    "Container",
    "List",
    "SSZError",
    "Uint8",
    "Uint16",
    "Uint32",
    "Uint64",
    "Uint128",
    "Uint256",
    "Union",
    "Vector",
    "field",
    "hash_tree_root",
]
