"""hash-tree-root (Merkleization) over the SSZ descriptors.

Spec rules as in the reference's ``consensus/tree_hash``: basic values are
packed into 32-byte chunks; collections merkleize to their *limit* depth
using virtual zero subtrees (so a ``List[Validator, 2**40]`` does not
materialize 2^40 chunks); lists/bitlists mix in their length; unions mix
in their selector.
"""

from __future__ import annotations

import numpy as np

from .core import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    SSZError,
    Union,
    Vector,
    _Boolean,
    _ContainerMeta,
    _Uint,
    _pack_bits,
)
from .sha256 import ZERO_HASHES, hash32_concat, hash_pairs

BYTES_PER_CHUNK = 32


def _next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def _pad_chunks(data: bytes) -> list[bytes]:
    if not data:
        return []
    if len(data) % BYTES_PER_CHUNK:
        data = data + bytes(BYTES_PER_CHUNK - len(data) % BYTES_PER_CHUNK)
    return [data[i:i + BYTES_PER_CHUNK] for i in range(0, len(data), BYTES_PER_CHUNK)]


def merkleize(chunks: list[bytes], limit: int | None = None) -> bytes:
    """Merkle root of chunks padded (virtually) to ``limit`` leaves."""
    count = len(chunks)
    if limit is None:
        limit = count
    if count > limit:
        raise SSZError(f"merkleize: {count} chunks exceed limit {limit}")
    width = _next_pow2(limit)
    depth = (width - 1).bit_length()
    if count == 0:
        return ZERO_HASHES[depth]
    layer = chunks
    for d in range(depth):
        if len(layer) % 2:
            layer = layer + [ZERO_HASHES[d]]
        if len(layer) == 0:
            break
        arr = np.frombuffer(b"".join(layer), np.uint8).reshape(-1, 64)
        hashed = hash_pairs(arr)
        layer = [hashed[i].tobytes() for i in range(hashed.shape[0])]
    root = layer[0]
    return root


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash32_concat(root, length.to_bytes(32, "little"))


def _chunk_count(tpe) -> int:
    """Leaf-limit of a collection type (spec chunk_count)."""
    if isinstance(tpe, (_Uint, _Boolean)):
        return 1
    if isinstance(tpe, ByteVector):
        return (tpe.length + 31) // 32
    if isinstance(tpe, ByteList):
        return (tpe.limit + 31) // 32
    if isinstance(tpe, Bitvector):
        return (tpe.length + 255) // 256
    if isinstance(tpe, Bitlist):
        return (tpe.limit + 255) // 256
    if isinstance(tpe, Vector):
        if _is_basic(tpe.elem):
            return (tpe.length * tpe.elem.fixed_size() + 31) // 32
        return tpe.length
    if isinstance(tpe, List):
        if _is_basic(tpe.elem):
            return (tpe.limit * tpe.elem.fixed_size() + 31) // 32
        return tpe.limit
    raise SSZError(f"chunk_count: unsupported type {tpe!r}")


def _is_basic(tpe) -> bool:
    return isinstance(tpe, (_Uint, _Boolean))


def hash_tree_root(tpe, value=None) -> bytes:
    """Root of ``value`` under descriptor ``tpe``. For containers the value
    may be omitted (``hash_tree_root(instance)``)."""
    if value is None and isinstance(tpe, Container):
        value = tpe
        tpe = type(tpe)

    if _is_basic(tpe):
        return tpe.encode(value).ljust(32, b"\x00")
    if isinstance(tpe, ByteVector):
        return merkleize(_pad_chunks(tpe.encode(value)), _chunk_count(tpe))
    if isinstance(tpe, ByteList):
        data = tpe.encode(value)
        return mix_in_length(
            merkleize(_pad_chunks(data), _chunk_count(tpe)), len(data)
        )
    if isinstance(tpe, Bitvector):
        return merkleize(_pad_chunks(_pack_bits(value)), _chunk_count(tpe))
    if isinstance(tpe, Bitlist):
        if len(value) > tpe.limit:
            raise SSZError("Bitlist over limit")
        return mix_in_length(
            merkleize(_pad_chunks(_pack_bits(value)), _chunk_count(tpe)), len(value)
        )
    if isinstance(tpe, Vector):
        if _is_basic(tpe.elem):
            if len(value) != tpe.length:
                raise SSZError("Vector length mismatch")
            packed = b"".join(tpe.elem.encode(v) for v in value)
            return merkleize(_pad_chunks(packed), _chunk_count(tpe))
        return merkleize([hash_tree_root(tpe.elem, v) for v in value], tpe.length)
    if isinstance(tpe, List):
        if len(value) > tpe.limit:
            raise SSZError("List over limit")
        if _is_basic(tpe.elem):
            packed = b"".join(tpe.elem.encode(v) for v in value)
            root = merkleize(_pad_chunks(packed), _chunk_count(tpe))
        else:
            root = merkleize(
                [hash_tree_root(tpe.elem, v) for v in value], tpe.limit
            )
        return mix_in_length(root, len(value))
    if isinstance(tpe, Union):
        sel, val = value
        opt = tpe.options[sel]
        root = bytes(32) if opt is None else hash_tree_root(opt, val)
        return hash32_concat(root, sel.to_bytes(32, "little"))
    if isinstance(tpe, _ContainerMeta):
        leaves = [hash_tree_root(t, getattr(value, n)) for n, t in tpe.fields]
        return merkleize(leaves, len(leaves))
    raise SSZError(f"hash_tree_root: unsupported type {tpe!r}")
