"""Incremental hash-tree-root caching (reference: ``consensus/cached_tree_hash``).

The reference caches the internal Merkle layers of the big ``BeaconState``
fields and re-hashes only the paths touched since the last root. Same idea
here, arranged around the batched hashing seam:

* :class:`MerkleTreeCache` — stores every layer of one field's tree as a
  contiguous ``uint8[width, 32]`` matrix. ``update(leaves)`` vectorially
  diffs the new leaf matrix against the cached one and re-hashes only the
  changed pair-paths (one batched ``hash_pairs`` call per level). The diff
  doubles as the correctness guarantee: a cache fed a *different* state's
  leaves just does more work, never returns a wrong root.
* per-element root memo — container roots (validators) keyed by their
  field-value tuple (flat types) or SSZ encoding, with generational
  eviction, so unchanged elements skip merkleization between slots.
* :class:`CachedRootComputer` — drives both for a ``BeaconState``-shaped
  container: heavy list/vector fields go through tree caches, everything
  else recomputes via the plain path.
"""

from __future__ import annotations

import threading

import numpy as np

from .core import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    _Boolean,
    _ContainerMeta,
    _Uint,
)
from .hash import _chunk_count, _is_basic, hash_tree_root, merkleize, mix_in_length
from .sha256 import ZERO_HASHES, hash_pairs

_ZERO_ROWS = [np.frombuffer(z, np.uint8) for z in ZERO_HASHES]


def _depth_for_limit(limit: int) -> int:
    if limit <= 1:
        return 0
    return (limit - 1).bit_length()


class MerkleTreeCache:
    """Layered Merkle tree over up to ``2**depth`` virtual leaves with
    incremental (diff-based) updates."""

    def __init__(self, depth: int):
        self.depth = depth
        self._leaves: np.ndarray | None = None
        self._layers: list[np.ndarray] = []
        self._root: bytes = ZERO_HASHES[depth]

    # -- full rebuild ----------------------------------------------------

    def _rebuild(self, leaves: np.ndarray) -> bytes:
        self._leaves = leaves.copy()
        self._layers = []
        layer = self._leaves
        d = 0
        while layer.shape[0] > 1:
            n = layer.shape[0]
            if n % 2:
                layer = np.concatenate([layer, _ZERO_ROWS[d][None]], axis=0)
                n += 1
            nxt = hash_pairs(layer.reshape(n // 2, 64))
            self._layers.append(nxt)
            layer = nxt
            d += 1
        self._root = self._fold_zero(layer, d)
        return self._root

    def _fold_zero(self, top: np.ndarray, d: int) -> bytes:
        """Fold the single real node up through the remaining virtual
        all-zero right subtrees."""
        if self._leaves is None or self._leaves.shape[0] == 0:
            return ZERO_HASHES[self.depth]
        node = top[0].tobytes()
        pair = np.empty((1, 64), np.uint8)
        for lvl in range(d, self.depth):
            pair[0, :32] = np.frombuffer(node, np.uint8)
            pair[0, 32:] = _ZERO_ROWS[lvl]
            node = hash_pairs(pair)[0].tobytes()
        return node

    # -- incremental update ----------------------------------------------

    def update(self, leaves: np.ndarray) -> bytes:
        """``leaves`` is uint8[n, 32]; returns the depth-``self.depth``
        virtual-zero-padded root."""
        if leaves.shape[0] == 0:
            self._leaves = leaves.copy()
            self._layers = []
            self._root = ZERO_HASHES[self.depth]
            return self._root
        if (
            self._leaves is None
            or self._leaves.shape[0] != leaves.shape[0]
            # >1/4 changed: a full batched rebuild is cheaper than the
            # per-level gather/scatter bookkeeping
        ):
            return self._rebuild(leaves)
        changed = np.nonzero(np.any(self._leaves != leaves, axis=1))[0]
        if changed.size == 0:
            return self._root
        if changed.size > leaves.shape[0] // 4:
            return self._rebuild(leaves)

        np.copyto(self._leaves, leaves)
        layer = self._leaves
        idx = np.unique(changed >> 1)
        for d, nxt in enumerate(self._layers):
            n = layer.shape[0]
            pairs = np.empty((idx.size, 64), np.uint8)
            pairs[:, :32] = layer[2 * idx]
            right = 2 * idx + 1
            in_range = right < n
            pairs[in_range, 32:] = layer[right[in_range]]
            pairs[~in_range, 32:] = _ZERO_ROWS[d]
            nxt[idx] = hash_pairs(pairs)
            layer = nxt
            idx = np.unique(idx >> 1)
        self._root = self._fold_zero(layer, len(self._layers))
        return self._root


def _flat_fields(tpe) -> bool:
    """True when every field is a basic/bytes value — then the field
    tuple is an immutable, cheap memo key. Types with nested containers
    or lists fall back to the encoding key (a nested mutable object in a
    dict key could be mutated after insertion and poison the table)."""
    return all(
        isinstance(t, (_Uint, _Boolean, ByteVector)) for _, t in tpe.fields
    )


class _ElemRootMemo:
    """Container-root memo with generational eviction.

    Key = the tuple of field VALUES for flat (all-basic-field) types —
    one attribute read per field, ~20x cheaper than SSZ-encoding the
    element just to look it up (the encode cost dominated the incremental
    state root at mainnet registry sizes); other types key by encoding."""

    def __init__(self, cap: int = 1 << 21):
        self.cap = cap
        self._new: dict = {}
        self._old: dict = {}
        self._flat: dict = {}

    def get(self, tpe, value) -> bytes:
        flat = self._flat.get(tpe)
        if flat is None:
            flat = self._flat[tpe] = _flat_fields(tpe)
        if flat:
            key = (tpe, *(getattr(value, n) for n, _ in tpe.fields))
        else:
            key = tpe.encode(value)
        try:
            root = self._new.get(key)
        except TypeError:  # a flat field held an unhashable value
            key = tpe.encode(value)
            root = self._new.get(key)
        if root is None:
            root = self._old.get(key)
            if root is None:
                root = hash_tree_root(tpe, value)
            self._new[key] = root
            if len(self._new) > self.cap:
                self._old = self._new
                self._new = {}
        return root


class CachedRootComputer:
    """hash_tree_root for a container with incremental caching of its
    list/vector fields. One computer per chain (or one global default) —
    feeding it unrelated states is safe, only slower."""

    def __init__(self):
        self._trees: dict[str, MerkleTreeCache] = {}
        self._memo = _ElemRootMemo()
        # The BeaconProcessor runs >1 worker thread; a computer shared
        # across threads (e.g. a per-chain instance reached from HTTP and
        # worker threads) must serialize — the diff-then-rehash in
        # MerkleTreeCache.update is not atomic, so interleaved updates
        # would permanently corrupt cached layers.
        self._lock = threading.Lock()

    def _tree(self, key: str, depth: int) -> MerkleTreeCache:
        t = self._trees.get(key)
        if t is None or t.depth != depth:
            t = self._trees[key] = MerkleTreeCache(depth)
        return t

    # -- leaf-matrix builders -------------------------------------------

    def _container_list_leaves(self, tpe, values) -> np.ndarray:
        out = np.empty((len(values), 32), np.uint8)
        memo = self._memo
        elem = tpe.elem
        for i, v in enumerate(values):
            out[i] = np.frombuffer(memo.get(elem, v), np.uint8)
        return out

    @staticmethod
    def _packed_basic_leaves(elem, values) -> np.ndarray:
        size = elem.fixed_size()
        per_chunk = 32 // size
        n_chunks = (len(values) + per_chunk - 1) // per_chunk
        if isinstance(elem, _Uint) and elem.bits in (8, 16, 32, 64):
            arr = np.asarray(values, dtype=f"<u{size}")
        elif isinstance(elem, _Boolean):
            arr = np.asarray(values, dtype=np.uint8)
        else:
            data = b"".join(elem.encode(v) for v in values)
            arr = np.frombuffer(data, np.uint8)
        raw = arr.view(np.uint8).reshape(-1)
        out = np.zeros((n_chunks, 32), np.uint8)
        out.reshape(-1)[: raw.size] = raw
        return out

    @staticmethod
    def _bytes32_vector_leaves(values) -> np.ndarray:
        return np.frombuffer(b"".join(values), np.uint8).reshape(-1, 32)

    # -- the public entry ------------------------------------------------

    def hash_tree_root(self, value: Container) -> bytes:
        with self._lock:
            tpe = type(value)
            leaves = []
            for name, t in tpe.fields:
                v = getattr(value, name)
                leaves.append(self._field_root(name, t, v))
            return merkleize(leaves, len(leaves))

    def _field_root(self, name: str, t, v) -> bytes:
        if isinstance(t, List):
            depth = _depth_for_limit(_chunk_count(t))
            if isinstance(t.elem, _ContainerMeta):
                lv = self._container_list_leaves(t, v)
            elif _is_basic(t.elem):
                lv = self._packed_basic_leaves(t.elem, v)
            elif isinstance(t.elem, ByteVector) and t.elem.length == 32:
                lv = (
                    self._bytes32_vector_leaves(v)
                    if v
                    else np.empty((0, 32), np.uint8)
                )
            else:
                return hash_tree_root(t, v)
            root = self._tree(name, depth).update(lv)
            return mix_in_length(root, len(v))
        if isinstance(t, Vector):
            depth = _depth_for_limit(_chunk_count(t))
            if _is_basic(t.elem):
                lv = self._packed_basic_leaves(t.elem, v)
            elif isinstance(t.elem, ByteVector) and t.elem.length == 32:
                lv = self._bytes32_vector_leaves(v)
            else:
                return hash_tree_root(t, v)
            return self._tree(name, depth).update(lv)
        return hash_tree_root(t, v)


# Default computers for the state transition's per-slot root refresh — a
# small LIFO POOL, not thread-local: per-thread computers would start cold
# on every ThreadingHTTPServer request thread (a full re-merkleization per
# request), while a single shared computer would serialize concurrent
# state transitions AND thrash its diff trees between unrelated state
# lineages (trees are keyed by field name). LIFO checkout keeps the
# warmest computer with the active lineage; concurrent transitions get
# their own.
_POOL: list[CachedRootComputer] = []
_POOL_CAP = 4
_POOL_LOCK = threading.Lock()


def cached_state_root(state) -> bytes:
    with _POOL_LOCK:
        computer = _POOL.pop() if _POOL else CachedRootComputer()
    try:
        return computer.hash_tree_root(state)
    finally:
        with _POOL_LOCK:
            if len(_POOL) < _POOL_CAP:
                _POOL.append(computer)
