"""Flight recorder: a bounded, thread-safe event journal for failure
forensics (reference: Lighthouse pairs its metric families with
structured slog events — ``common/logging`` — so a counter tick never
loses its context; committee-BLS measurement work shows per-batch
context, not aggregates, explains verifier tail latency).

The metrics registry answers "how much, how often"; trace spans answer
"where did the wall-clock go"; this module answers "what exactly
happened around THIS failure": every staged device verify, gossip
rejection, queue shed and peer ban appends one structured event to a
fixed-capacity ring, and on a verify failure or crit-level log the
whole ring can be snapshotted to a JSON artifact that
``tools/forensics_report.py`` renders into a timeline.

Design constraints (same discipline as :mod:`utils.tracing`):

* DISABLED recording must cost well under 1 microsecond per call —
  ``record()`` returns after one global check, no allocation
  (``tests/test_flight_recorder.py`` pins this).
* Enabled recording is O(1): one ring-slot write under one lock, no
  I/O. Capacity is fixed; old events are overwritten, never reallocated.
* Every event kind is declared in :data:`EVENT_KINDS` and documented in
  ``docs/OBSERVABILITY.md`` (linted by ``tests/test_zgate4_metrics_lint``);
  ``record()`` rejects unknown kinds so a typo cannot silently fork the
  catalogue.
* Dump-on-failure is opt-in (``LIGHTHOUSE_TPU_FLIGHT_DUMP=1``) and
  rate-limited: test suites induce failures constantly, and forensics
  must never become an I/O amplifier on the hot path.

Env knobs (all read at import; :func:`configure` overrides at runtime):

    LIGHTHOUSE_TPU_FLIGHT_RECORDER          1|0   record events (default 1)
    LIGHTHOUSE_TPU_FLIGHT_CAPACITY          int   ring capacity (default 4096)
    LIGHTHOUSE_TPU_FLIGHT_DUMP              1|0   dump_on_failure writes (default 0)
    LIGHTHOUSE_TPU_FLIGHT_DIR               path  dump directory
    LIGHTHOUSE_TPU_FLIGHT_RETAIN            int   dump files kept (default 8)
    LIGHTHOUSE_TPU_FLIGHT_DUMP_INTERVAL_S   float min seconds between dumps (default 30)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from typing import Callable, Iterable, List, Optional

from . import metrics

SCHEMA = "lighthouse_tpu.flight_recorder/1"
DUMP_PREFIX = "lighthouse_tpu_flight_"

# The event-kind catalogue: one entry per producer call site family,
# snake_case, each documented in docs/OBSERVABILITY.md (linted).
EVENT_KINDS = (
    "attestation_rejected",   # beacon_chain/attestation_verification.py
    "block_rejected",         # beacon_chain/block_verification.py
    "bls_stage_verify",       # crypto/device/bls.py, one per staged verify
    "bulk_resume",            # verification_service/admission.py, excursion end
    "bulk_throttle",          # verification_service/admission.py, bulk paused
    "cold_route",             # compile_service/service.py, cold-bucket flush
    "compile_failed",         # compile_service/service.py, per failed rung
    "compile_ready",          # compile_service/service.py, rung now warm
    "compile_retry",          # compile_service/service.py, failed rung re-queued
    "compile_started",        # compile_service/service.py, per AOT rung
    "deadline_miss",          # verification_service/batcher.py, SLO miss
    "fault_injected",         # utils/fault_injection.py, one per injected fault
    "incident_opened",        # utils/watchtower.py, detector latched an incident
    "incident_resolved",      # utils/watchtower.py, breach cleared + duration
    "key_table_reset",        # crypto/device/key_table.py, agg region recycle
    "key_table_sync",         # crypto/device/key_table.py, startup/delta rows
    "log",                    # utils/logging.py, warn/error/crit lines
    "lookahead_epoch_warmed",  # duty_lookahead/, one per warmed epoch
    "lookahead_insert_failed",  # duty_lookahead/, per failed pre-insert
    "op_pool_device_agg",     # operation_pool/device_agg.py, per device merge
    "peer_ban",               # network/peer_manager.py
    "peer_penalty",           # network/peer_manager.py
    "pipeline_flush",         # utils/pipeline_profiler.py, one per flush
    "queue_shed",             # beacon_processor/processor.py
    "scheduler_bisection",    # verification_service/batcher.py, per split
    "scheduler_flush",        # verification_service/batcher.py, per batch
    "scheduler_plan",         # verification_service/batcher.py, per flush plan
    "scheduler_shed",         # verification_service/batcher.py, backpressure
    "shard_dispatch",         # verification_service/batcher.py, dp sub-batch
    "shard_lost",             # crypto/device/mesh.py, chip dropped from axis
    "shard_probation",        # crypto/device/mesh.py, probation entry/failed probe
    "shard_recovered",        # crypto/device/mesh.py, chip re-admitted to axis
    "slo_burn",               # verification_service/slo.py, budget burn alert
    "sync_rejected",          # beacon_chain/sync_committee_verification.py
    "transfer_ledger",        # utils/transfer_ledger.py, one per verify
    "watchdog_reaped",        # verification_service/batcher.py, hung dispatch
)
_KINDS = frozenset(EVENT_KINDS)

_EVENTS_TOTAL = metrics.counter_vec(
    "flight_recorder_events_total",
    "journal events recorded, by event kind (see docs/OBSERVABILITY.md)",
    ("kind",),
)
_DUMPS_TOTAL = metrics.counter_vec(
    "flight_recorder_dumps_total",
    "journal snapshots written to disk, by trigger",
    ("trigger",),
)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


_enabled = os.environ.get("LIGHTHOUSE_TPU_FLIGHT_RECORDER", "1") not in ("", "0")
_capacity = max(1, _env_int("LIGHTHOUSE_TPU_FLIGHT_CAPACITY", 4096))
_dump_on_failure = os.environ.get("LIGHTHOUSE_TPU_FLIGHT_DUMP", "0") not in ("", "0")
_dump_dir = os.environ.get("LIGHTHOUSE_TPU_FLIGHT_DIR") or os.path.join(
    tempfile.gettempdir(), "lighthouse_tpu_flight"
)
_retain = max(1, _env_int("LIGHTHOUSE_TPU_FLIGHT_RETAIN", 8))
_min_dump_interval_s = _env_float("LIGHTHOUSE_TPU_FLIGHT_DUMP_INTERVAL_S", 30.0)

_lock = threading.Lock()
_ring: List[Optional[dict]] = [None] * _capacity
_seq = 0  # total events ever recorded; ring slot = seq % capacity

_dump_lock = threading.Lock()
_last_dump = -float("inf")

_subscribers: List[Callable[[dict], None]] = []
_tls = threading.local()


def _jsonable(v):
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    if isinstance(v, (bytes, bytearray)):
        return "0x" + bytes(v).hex()
    return str(v)


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------


def record(kind: str, /, **fields) -> None:
    """Append one structured event to the ring. O(1); when disabled this
    is a single global check (< 1 µs, pinned by the gate test)."""
    if not _enabled:
        return
    if kind not in _KINDS:
        raise ValueError(
            f"unknown flight-recorder event kind {kind!r}; declare it in "
            f"EVENT_KINDS and document it in docs/OBSERVABILITY.md"
        )
    ev = {
        "t": time.time(),
        "thread": threading.current_thread().name,
        "kind": kind,
        "fields": {k: _jsonable(v) for k, v in fields.items()},
    }
    global _seq
    with _lock:
        ev["seq"] = _seq
        _ring[_seq % _capacity] = ev
        _seq += 1
    _EVENTS_TOTAL.with_labels(kind).inc()
    if kind.endswith("_rejected"):
        # chain-time attribution: every journal rejection lands on its
        # slot's report card (utils.slot_ledger imports neither this
        # module nor anything jax-shaped — no cycle)
        from . import slot_ledger

        slot_ledger.note_rejection(kind)
    if _subscribers:
        _notify(ev)


def _notify(ev: dict) -> None:
    """Invoke subscribers outside the ring lock. Re-entrant records (a
    subscriber that logs, and logging that journals) append normally but
    do NOT re-notify — bounds any record->subscriber->record loop."""
    if getattr(_tls, "notifying", False):
        return
    _tls.notifying = True
    try:
        for fn in list(_subscribers):
            try:
                fn(ev)
            except Exception:
                pass  # a broken subscriber must never break the producer
    finally:
        _tls.notifying = False


def subscribe(fn: Callable[[dict], None]) -> None:
    """Register a callback invoked (outside the ring lock) for every
    recorded event — the wiring surface for e.g. the validator monitor.
    NOTE: disabling the recorder (``LIGHTHOUSE_TPU_FLIGHT_RECORDER=0``)
    silences subscribers too — validator-monitor failure tracking rides
    on the journal, so that knob trades it away along with the ring."""
    if fn not in _subscribers:
        _subscribers.append(fn)


def unsubscribe(fn: Callable[[dict], None]) -> None:
    try:
        _subscribers.remove(fn)
    except ValueError:
        pass


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------


def events(kinds: Iterable[str] | None = None, limit: int | None = None) -> List[dict]:
    """Journal contents, oldest first; optionally filtered to ``kinds``
    and truncated to the newest ``limit`` (after filtering)."""
    with _lock:
        n = min(_seq, _capacity)
        start = _seq - n
        evs = [_ring[i % _capacity] for i in range(start, _seq)]
    if kinds is not None:
        kindset = set(kinds)
        evs = [e for e in evs if e["kind"] in kindset]
    if limit is not None:
        # -0: would mean "everything" — a 0/negative limit means none
        evs = evs[-limit:] if limit > 0 else []
    return evs


def status() -> dict:
    """One-line health of the recorder itself (the /lighthouse surfaces)."""
    with _lock:
        seq, cap = _seq, _capacity
    return {
        "enabled": _enabled,
        "capacity": cap,
        "recorded_total": seq,
        "dropped": max(0, seq - cap),
        "dump_on_failure": _dump_on_failure,
        "dump_dir": _dump_dir,
    }


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def clear() -> None:
    """Drop every recorded event (capacity unchanged) and reset the
    dump rate-limit clock."""
    global _seq, _last_dump
    with _lock:
        for i in range(_capacity):
            _ring[i] = None
        _seq = 0
    with _dump_lock:
        _last_dump = -float("inf")


def configure(
    capacity: int | None = None,
    enabled: bool | None = None,
    dump: bool | None = None,
    dump_dir: str | None = None,
    retain: int | None = None,
    min_dump_interval_s: float | None = None,
) -> dict:
    """Override settings at runtime; returns the PREVIOUS values of every
    settable knob so callers (tests) can restore with ``configure(**prev)``.
    Changing ``capacity`` reallocates and clears the ring."""
    global _capacity, _ring, _seq, _enabled, _dump_on_failure
    global _dump_dir, _retain, _min_dump_interval_s
    prev = {
        "capacity": _capacity,
        "enabled": _enabled,
        "dump": _dump_on_failure,
        "dump_dir": _dump_dir,
        "retain": _retain,
        "min_dump_interval_s": _min_dump_interval_s,
    }
    if capacity is not None and capacity != _capacity:
        with _lock:
            _capacity = max(1, int(capacity))
            _ring = [None] * _capacity
            _seq = 0
    if enabled is not None:
        _enabled = bool(enabled)
    if dump is not None:
        _dump_on_failure = bool(dump)
    if dump_dir is not None:
        _dump_dir = dump_dir
    if retain is not None:
        _retain = max(1, int(retain))
    if min_dump_interval_s is not None:
        _min_dump_interval_s = float(min_dump_interval_s)
    return prev


# ---------------------------------------------------------------------------
# Dumping
# ---------------------------------------------------------------------------


def snapshot(trigger: str | None = None, context: dict | None = None) -> dict:
    """The dump document: recorder state + every journal event, plus the
    triggering context. Stable schema (``SCHEMA``) so
    ``tools/forensics_report.py`` and external tooling can rely on it."""
    evs = events()
    with _lock:
        seq, cap = _seq, _capacity
    now = time.time()  # one clock read: seconds and ms must agree
    return {
        "schema": SCHEMA,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(now))
        + f".{int(now * 1000) % 1000:03d}Z",
        "pid": os.getpid(),
        "trigger": trigger,
        "context": {k: _jsonable(v) for k, v in (context or {}).items()},
        "capacity": cap,
        "recorded_total": seq,
        "dropped": max(0, seq - cap),
        "events": evs,
    }


def dump(trigger: str, /, path: str | None = None, **context) -> str:
    """Write the journal snapshot to ``path`` (default: a fresh file in
    the dump directory) and apply retention. Returns the path written."""
    doc = snapshot(trigger, context)
    if path is None:
        os.makedirs(_dump_dir, exist_ok=True)
        path = os.path.join(
            _dump_dir,
            f"{DUMP_PREFIX}{int(time.time() * 1000):013d}_{doc['recorded_total']:08d}_{trigger}.json",
        )
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    _DUMPS_TOTAL.with_labels(trigger).inc()
    _apply_retention()
    return path


def dump_on_failure(trigger: str, /, **context) -> str | None:
    """Snapshot the journal on a failure signal (staged verify returned
    False, block signature batch failed, crit-level log). No-op unless
    dumping is enabled; rate-limited to one dump per
    ``min_dump_interval_s`` so induced-failure storms (test suites,
    attack traffic) cannot turn forensics into an I/O amplifier."""
    global _last_dump
    if not (_enabled and _dump_on_failure):
        return None
    with _dump_lock:
        if time.monotonic() - _last_dump < _min_dump_interval_s:
            return None
        try:
            path = dump(trigger, **context)
        except OSError as e:
            # no logging here: utils.logging journals into this module.
            # The window is NOT consumed: a failed write (full disk, bad
            # dir) must not suppress the next genuine failure's dump.
            print(f"flight_recorder: dump failed: {e!r}", file=sys.stderr)
            return None
        _last_dump = time.monotonic()
        return path


def _apply_retention() -> None:
    """Keep only the newest ``retain`` dump files in the dump directory
    (names embed a ms timestamp, so lexicographic order is age order)."""
    try:
        names = sorted(
            n for n in os.listdir(_dump_dir) if n.startswith(DUMP_PREFIX)
        )
        for n in names[: max(0, len(names) - _retain)]:
            os.remove(os.path.join(_dump_dir, n))
    except OSError:
        pass
