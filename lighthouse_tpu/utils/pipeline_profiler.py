"""Pipeline-occupancy profiler: per-shard device idle-gap (bubble)
attribution and flush critical-path timelines (ISSUE 12).

ROADMAP item 5 (overlap host pack with device compute) is the refactor
that lets the multi-chip throughput from the served dp mesh actually
reach the devices — but before this module nothing could *see* a
pipeline bubble: the data-movement ledger (ISSUE 8) prices pack time
and bytes, the SLO layer (ISSUE 7) prices verdict latency, yet no
instrument attributed *device idle time* to its cause. The committee
batch-verification cost model (PAPERS.md, arxiv 2302.00418) and the
FPGA verification-engine pipeline (arxiv 2112.02229) agree on the
bound: verifier throughput is limited by keeping the verify engine FED,
not by the engine itself — exactly the quantity this profiler measures.
Same evidence-first pattern that made the ledger the base for the
device key table: measure the bubble before building the double-
buffered pack pipeline.

Three instruments, one module:

* **Per-shard busy/idle interval tracking** — every staged dispatch
  (``crypto/device/bls._run_stage``, dispatch-to-sync wall) reports a
  busy interval on its dp shard; the gap between a shard's
  sync-complete and its next dispatch is a BUBBLE, attributed to its
  cause by overlap with the recorded host-activity timeline:
  ``pack`` (the host was packing), ``plan`` (the flush planner was
  deciding), ``compile`` (an XLA compile was in flight / the flush was
  shed to the CPU fallback while its rung compiles), ``queue_empty``
  (the flush thread was waiting on an empty queue — no work existed),
  ``other`` (uncovered remainder). Lands in
  ``bls_device_bubble_seconds_total{shard,cause}`` (per-cause seconds
  sum EXACTLY to measured idle, pinned by test) and
  ``bls_device_shard_busy_seconds_total{shard}``.
* **Flush lifecycle timelines** — the scheduler wraps each flush in a
  :class:`FlushRecord`: submit → queue-wait → plan → pack (the
  ledger's phase clocks feed the same wall) → dispatch → device-wait →
  resolve. One ``pipeline_flush`` flight-recorder event per flush
  (bisection and shed sub-batches included — exactly-once, pinned by
  test) carries the per-phase seconds and the critical-path phase; a
  flush-thread saturation gauge
  (``verification_scheduler_flush_thread_saturation``) says what
  fraction of the flush wall went to host pack vs waiting on device.
* **Overlap-potential estimate** — the go/no-go number for ROADMAP
  item 5: per flush, the projected wall if pack for flush N+1
  overlapped flush N's device time is the busiest dispatch LANE's
  ``max(pack, device) + fallback`` plus the serial remainder, against
  the measured wall (per-lane, because concurrent dp workers already
  overlap each other — phase sums would pin the projection at 1.0 on
  multi-chip flushes); cumulative projected sets/s and the speedup
  ratio are served in :func:`summary` and
  ``verification_scheduler_overlap_potential_ratio``.

jax-free at import (tools read it offline); thread-safe (dp shard
workers, verify_now callers and the flush thread all record
concurrently); with the profiler disabled
(``LIGHTHOUSE_TPU_PIPELINE_PROFILER=0``) every hook returns in well
under 1 µs (pinned like disabled spans and the disabled ledger).

Attribution contract: a gap's per-cause seconds are EXACT interval
arithmetic — overlapping host activities are assigned in priority
order (pack > plan > compile > queue_empty) over the still-uncovered
sub-intervals, so no second is double-counted and the cause split
always sums to the gap. The activity timeline is a bounded ring
(default 4096 intervals, ``LIGHTHOUSE_TPU_PIPELINE_ACTIVITY``); an
idle period nothing recorded an activity for attributes to ``other``
— the profiler never fabricates a cause.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import flight_recorder, metrics, slot_ledger

# flush lifecycle phases, in timeline order (docs/OBSERVABILITY.md)
FLUSH_PHASES = ("queue_wait", "plan", "pack", "device", "fallback", "resolve")
# bubble causes; attribution priority is the order below minus "other"
BUBBLE_CAUSES = ("pack", "plan", "compile", "queue_empty", "other")
_PRIORITY = ("pack", "plan", "compile", "queue_empty")

# ---------------------------------------------------------------------------
# Metric families
# ---------------------------------------------------------------------------

_BUBBLE_SECONDS = metrics.counter_vec(
    "bls_device_bubble_seconds_total",
    "device idle-gap (bubble) seconds per dp mesh shard, attributed to "
    "cause by overlap with the recorded host-activity timeline: pack "
    "(host was packing), plan (flush planner deciding), compile (XLA "
    "compile in flight / flush shed to the CPU fallback while its rung "
    "compiles), queue_empty (flush thread waiting on an empty queue), "
    "other (uncovered remainder) — per-cause seconds sum exactly to "
    "measured idle (pinned by test). The evidence base for ROADMAP "
    "item 5's double-buffered pack pipeline",
    ("shard", "cause"),
)
_BUSY_SECONDS = metrics.counter_vec(
    "bls_device_shard_busy_seconds_total",
    "device busy seconds per dp mesh shard (staged dispatch-to-sync "
    "walls, overlap-clipped so concurrent dispatches on one shard are "
    "not double-counted); bubble_ratio = bubble / (busy + bubble)",
    ("shard",),
)
_FLUSH_PHASE_SECONDS = metrics.counter_vec(
    "verification_scheduler_flush_phase_seconds_total",
    "cumulative flush-lifecycle seconds by phase: queue_wait (oldest "
    "submission's wait before drain), plan (flush planner), pack (host "
    "pack inside the flush), device (staged dispatch-to-sync), "
    "fallback (CPU fallback verifies of shed sub-batches), resolve "
    "(flush wall not covered by the other phases — future delivery, "
    "bookkeeping). Summed phase seconds can exceed summed flush walls "
    "when dp shard workers pack/dispatch concurrently",
    ("phase",),
)
_SATURATION = metrics.gauge(
    "verification_scheduler_flush_thread_saturation",
    "host-pack share of the most recent flush's active wall: pack / "
    "(pack + device + fallback). 1.0 = the flush thread spent its "
    "whole active time packing (the device starved behind the host); "
    "0.0 = all waiting on device (pack is free) — the single number "
    "that says which side of the pipeline to widen (ROADMAP item 5)",
)
_OVERLAP_RATIO = metrics.gauge(
    "verification_scheduler_overlap_potential_ratio",
    "projected speedup if host pack for flush N+1 overlapped flush N's "
    "device time (cumulative measured flush wall / projected "
    "overlapped wall, >= 1.0): the go/no-go sizing number for ROADMAP "
    "item 5's double-buffered pack pipeline",
)


# ---------------------------------------------------------------------------
# Enable / configure
# ---------------------------------------------------------------------------

# one env-parsing convention across the observability knobs
_env_int = flight_recorder._env_int
_env_float = flight_recorder._env_float

_enabled = os.environ.get(
    "LIGHTHOUSE_TPU_PIPELINE_PROFILER", "1"
) not in ("", "0")
_max_activity = max(16, _env_int("LIGHTHOUSE_TPU_PIPELINE_ACTIVITY", 4096))
# activity intervals older than this never explain a live gap (gaps end
# "now"); pruned on append so a long-lived node's ring stays relevant
_activity_retention_s = _env_float(
    "LIGHTHOUSE_TPU_PIPELINE_RETENTION_S", 300.0
)


def enabled() -> bool:
    return _enabled


def configure(
    enabled: Optional[bool] = None,
    max_activity: Optional[int] = None,
    retention_s: Optional[float] = None,
) -> dict:
    """Override knobs at runtime; returns the PREVIOUS values so tests
    can restore them (flight_recorder.configure's contract)."""
    global _enabled, _max_activity, _activity_retention_s, _activity
    prev = {
        "enabled": _enabled,
        "max_activity": _max_activity,
        "retention_s": _activity_retention_s,
    }
    if enabled is not None:
        _enabled = bool(enabled)
    if max_activity is not None and int(max_activity) != _max_activity:
        _max_activity = max(16, int(max_activity))
        with _lock:
            _activity = deque(_activity, maxlen=_max_activity)
    if retention_s is not None:
        _activity_retention_s = float(retention_s)
    return prev


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


class _ShardState:
    __slots__ = (
        "last_sync", "busy_s", "idle_s", "dispatches", "gaps",
        "causes", "cause_counts",
    )

    def __init__(self):
        self.last_sync: Optional[float] = None
        self.busy_s = 0.0
        self.idle_s = 0.0
        self.dispatches = 0
        self.gaps = 0
        self.causes: Dict[str, float] = {}
        self.cause_counts: Dict[str, int] = {}


def _fresh_totals() -> dict:
    return {
        "flushes": 0,
        "sets": 0,
        "wall_s": 0.0,
        "projected_wall_s": 0.0,
        **{f"{p}_s": 0.0 for p in FLUSH_PHASES},
    }


_lock = threading.Lock()
_activity: deque = deque(maxlen=_max_activity)  # (cause, t0, t1)
# still-open empty-queue waits by flush-thread id: a verify_now gap
# closing while the flush thread is STILL parked must attribute to
# queue_empty, not wait for the interval to complete at wake
_open_idle: Dict[int, float] = {}
_shards: Dict[int, _ShardState] = {}
_totals = _fresh_totals()

_tls = threading.local()


def reset() -> None:
    """Drop every recorded interval, gap and flush total (knobs keep
    their values) — the bench pipeline_leg and tests start clean."""
    global _totals
    with _lock:
        _activity.clear()
        _open_idle.clear()
        _shards.clear()
        _totals = _fresh_totals()


# ---------------------------------------------------------------------------
# Interval arithmetic (pure helpers; exact, no double counting)
# ---------------------------------------------------------------------------


def _merge(ivs: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    ivs = sorted(ivs)
    out: List[Tuple[float, float]] = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _attribute_gap(
    g0: float, g1: float, activity: List[Tuple[str, float, float]]
) -> Dict[str, float]:
    """Split the gap [g0, g1) across BUBBLE_CAUSES: each priority cause
    claims its recorded activity's overlap with the still-uncovered
    sub-intervals; the remainder is ``other``. The returned seconds sum
    to exactly ``g1 - g0``."""
    per_cause: Dict[str, List[Tuple[float, float]]] = {
        c: [] for c in _PRIORITY
    }
    for cause, a0, a1 in activity:
        if a1 <= g0 or a0 >= g1:
            continue
        per_cause[cause].append((max(a0, g0), min(a1, g1)))
    remaining = [(g0, g1)]
    out: Dict[str, float] = {}
    for cause in _PRIORITY:
        ivs = _merge(per_cause[cause])
        if not ivs:
            continue
        got = 0.0
        new_remaining: List[Tuple[float, float]] = []
        for rs, re_ in remaining:
            cur = rs
            for s, e in ivs:
                if e <= cur or s >= re_:
                    continue
                s2, e2 = max(s, cur), min(e, re_)
                if s2 > cur:
                    new_remaining.append((cur, s2))
                got += e2 - s2
                cur = e2
            if cur < re_:
                new_remaining.append((cur, re_))
        remaining = new_remaining
        if got > 0.0:
            out[cause] = got
    rest = sum(e - s for s, e in remaining)
    if rest > 0.0:
        out["other"] = rest
    return out


def _note_activity_locked(cause: str, t0: float, t1: float) -> None:
    _activity.append((cause, t0, t1))
    cutoff = t1 - _activity_retention_s
    while _activity and _activity[0][2] < cutoff:
        _activity.popleft()


# ---------------------------------------------------------------------------
# Flush lifecycle records
# ---------------------------------------------------------------------------


class FlushRecord:
    """One flush's lifecycle aggregate: phase seconds accumulate from
    the flush thread AND its dp sub-batch workers (the scheduler enters
    :func:`flush_scope` on each); :func:`flush_end` closes the record,
    journals ONE ``pipeline_flush`` event and feeds the gauges."""

    __slots__ = (
        "trigger", "kinds", "n_submissions", "n_sets", "queue_wait_s",
        "t0", "phases", "shards", "by_thread", "_lock",
    )

    def __init__(self, trigger: str, kinds: str, n_submissions: int,
                 n_sets: int, queue_wait_s: float):
        self.trigger = trigger
        self.kinds = kinds
        self.n_submissions = int(n_submissions)
        self.n_sets = int(n_sets)
        self.queue_wait_s = max(0.0, float(queue_wait_s))
        self.t0 = time.perf_counter()
        self.phases = {"plan": 0.0, "pack": 0.0, "device": 0.0,
                       "fallback": 0.0}
        self.shards: set = set()
        # per-dispatching-thread (pack, device, fallback) walls: dp
        # sub-batch workers run CONCURRENTLY, so the overlap projection
        # must reason about the busiest LANE, not phase sums — summed
        # device seconds across 2 shards exceed the wall and would pin
        # the projection at 1.0 on exactly the multi-chip nodes it
        # exists to size
        self.by_thread: Dict[int, Dict[str, float]] = {}
        self._lock = threading.Lock()

    def add(self, phase: str, seconds: float,
            shard: Optional[int] = None) -> None:
        with self._lock:
            self.phases[phase] = self.phases.get(phase, 0.0) + seconds
            if shard is not None:
                self.shards.add(int(shard))
            if phase in ("pack", "device", "fallback"):
                lane = self.by_thread.setdefault(
                    threading.get_ident(),
                    {"pack": 0.0, "device": 0.0, "fallback": 0.0},
                )
                lane[phase] += seconds


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopScope()


class _FlushScope:
    """Thread-local current-flush frame: hooks fired on this thread
    (pack walls, stage walls, fallback walls) attribute to the record
    without plumbing a handle through the backend."""

    __slots__ = ("record", "_prev")

    def __init__(self, record: FlushRecord):
        self.record = record

    def __enter__(self):
        self._prev = getattr(_tls, "flush", None)
        _tls.flush = self.record
        return self

    def __exit__(self, *exc):
        _tls.flush = self._prev
        return False


def flush_scope(record: Optional[FlushRecord]):
    """Scope this thread's profiler hooks to ``record`` (the scheduler
    enters it on the flush thread and on every dp sub-batch worker);
    None (profiler disabled) is a shared no-op."""
    if record is None:
        return _NOOP
    return _FlushScope(record)


def current_flush() -> Optional[FlushRecord]:
    return getattr(_tls, "flush", None)


def flush_begin(
    trigger: str, kinds: str, n_submissions: int, n_sets: int,
    queue_wait_s: float,
) -> Optional[FlushRecord]:
    """Open one flush's lifecycle record (None when disabled — every
    later hook and :func:`flush_end` then no-op for free)."""
    if not _enabled:
        return None
    return FlushRecord(trigger, kinds, n_submissions, n_sets, queue_wait_s)


def flush_end(
    record: Optional[FlushRecord],
    verdict: Optional[bool] = None,
    mode: Optional[str] = None,
    n_sub_batches: int = 0,
    dp_shards=(),
) -> Optional[dict]:
    """Close the record: derive the residual ``resolve`` phase and the
    critical path, project the overlapped wall (ROADMAP item 5), update
    the cumulative totals + gauges, and journal ONE ``pipeline_flush``
    event. Returns the journaled row (tests read it back)."""
    if record is None or not _enabled:
        return None
    wall = max(0.0, time.perf_counter() - record.t0)
    with record._lock:
        phases = dict(record.phases)
        shards = sorted(record.shards)
        lanes = [dict(v) for v in record.by_thread.values()]
    plan_s = phases.get("plan", 0.0)
    pack_s = phases.get("pack", 0.0)
    device_s = phases.get("device", 0.0)
    fallback_s = phases.get("fallback", 0.0)
    # residual: the flush wall no phase explains (future delivery,
    # bookkeeping, thread handoff). Concurrent dp workers can make the
    # phase sum exceed the wall — the residual floors at 0 rather than
    # going negative (phase seconds stay the truth; the wall is the
    # wall)
    resolve_s = max(
        0.0, wall - plan_s - pack_s - device_s - fallback_s
    )
    # overlap projection per LANE (dispatching thread): pack for flush
    # N+1 over flush N's device time hides the smaller of the lane's
    # (pack, device) behind the larger; concurrent lanes already
    # overlap each other, so the projection reasons about the busiest
    # lane — phase SUMS across dp workers exceed the wall and would
    # pin the projection at 1.0 on exactly the multi-chip flushes it
    # sizes. Clamped to the wall — concurrency already achieved cannot
    # be re-claimed as potential.
    if lanes:
        busiest_serial = max(
            ln["pack"] + ln["device"] + ln["fallback"] for ln in lanes
        )
        busiest_overlapped = max(
            max(ln["pack"], ln["device"]) + ln["fallback"] for ln in lanes
        )
    else:
        busiest_serial = busiest_overlapped = 0.0
    lane_residual = max(0.0, wall - plan_s - busiest_serial)
    projected = min(
        wall, busiest_overlapped + plan_s + lane_residual
    )
    busy = pack_s + device_s + fallback_s
    saturation = (pack_s / busy) if busy > 0 else 0.0
    critical = max(
        (
            ("pack", pack_s), ("device", device_s),
            ("fallback", fallback_s), ("plan", plan_s),
            ("resolve", resolve_s),
        ),
        key=lambda kv: kv[1],
    )[0]
    phase_seconds = {
        "queue_wait": record.queue_wait_s,
        "plan": plan_s, "pack": pack_s, "device": device_s,
        "fallback": fallback_s, "resolve": resolve_s,
    }
    global _totals
    with _lock:
        _totals["flushes"] += 1
        _totals["sets"] += record.n_sets
        _totals["wall_s"] += wall
        _totals["projected_wall_s"] += projected
        for p, s in phase_seconds.items():
            _totals[f"{p}_s"] += s
        total_wall = _totals["wall_s"]
        total_projected = _totals["projected_wall_s"]
    for p, s in phase_seconds.items():
        if s > 0:
            _FLUSH_PHASE_SECONDS.with_labels(p).inc(s)
    _SATURATION.set(round(saturation, 4))
    _OVERLAP_RATIO.set(
        round(total_wall / total_projected, 4) if total_projected else 0.0
    )
    row = {
        "trigger": record.trigger,
        "kinds": record.kinds,
        "n_submissions": record.n_submissions,
        "n_sets": record.n_sets,
        "mode": mode,
        "n_sub_batches": int(n_sub_batches),
        "dp_shards": list(dp_shards) if dp_shards else shards,
        "queue_wait_s": round(record.queue_wait_s, 6),
        "plan_s": round(plan_s, 6),
        "pack_s": round(pack_s, 6),
        "device_s": round(device_s, 6),
        "fallback_s": round(fallback_s, 6),
        "resolve_s": round(resolve_s, 6),
        "wall_s": round(wall, 6),
        "critical_path": critical,
        "saturation": round(saturation, 4),
        "projected_wall_s": round(projected, 6),
        "overlap_speedup": round(wall / projected, 4) if projected else None,
        "verdict": verdict,
    }
    flight_recorder.record("pipeline_flush", **row)
    return row


# ---------------------------------------------------------------------------
# Hooks (the hot path; < 1 µs disabled)
# ---------------------------------------------------------------------------


def note_pack_wall(t0: float, t1: float) -> None:
    """One host pack completed on THIS thread (the packers in
    crypto/device/bls.py call this with their own perf_counter wall):
    host-activity interval for bubble attribution + the current flush
    record's ``pack`` phase."""
    if not _enabled or t1 <= t0:
        return
    rec = getattr(_tls, "flush", None)
    if rec is not None:
        rec.add("pack", t1 - t0)
    with _lock:
        _note_activity_locked("pack", t0, t1)


def note_plan_wall(
    t0: float, t1: float, record: Optional[FlushRecord] = None
) -> None:
    """The flush planner's decision wall (scheduler flush thread).
    ``record`` attributes the phase explicitly — the scheduler plans
    BEFORE entering the dispatch scope; hooks fired inside the scope
    fall back to the thread-local record."""
    if not _enabled or t1 <= t0:
        return
    rec = record if record is not None else getattr(_tls, "flush", None)
    if rec is not None:
        rec.add("plan", t1 - t0)
    with _lock:
        _note_activity_locked("plan", t0, t1)


def note_fallback_wall(t0: float, t1: float) -> None:
    """One CPU fallback verify completed (compile_service — the flush
    was shed because its rung is cold): the device idled for a
    compile-caused reason, so the activity lands under ``compile``."""
    if not _enabled or t1 <= t0:
        return
    rec = getattr(_tls, "flush", None)
    if rec is not None:
        rec.add("fallback", t1 - t0)
    with _lock:
        _note_activity_locked("compile", t0, t1)

def note_idle_begin(t0: float) -> None:
    """The scheduler's flush thread is ENTERING an empty-queue wait:
    mark the interval open NOW, so a ``verify_now`` dispatch landing
    while the thread is still parked attributes its gap to
    ``queue_empty`` instead of ``other`` (the completed interval only
    reaches the ring at wake — too late for gaps that close mid-wait)."""
    if not _enabled:
        return
    with _lock:
        _open_idle[threading.get_ident()] = t0


def note_idle_end(t0: float, t1: float) -> None:
    """The empty-queue wait ended: close the open marker and record the
    completed ``queue_empty`` activity interval (no work existed — a
    device gap overlapping it is traffic's fault, not the
    pipeline's)."""
    if not _enabled:
        # marker cleared even when disabled — a knob flip mid-wait must
        # not leave a stale open marker claiming queue_empty forever
        if _open_idle:
            with _lock:
                _open_idle.pop(threading.get_ident(), None)
        return
    # pop + record under ONE lock hold: a gap closing between the two
    # would see neither the open marker nor the completed interval and
    # misattribute the wait to `other`
    with _lock:
        _open_idle.pop(threading.get_ident(), None)
        if t1 > t0:
            _note_activity_locked("queue_empty", t0, t1)


def note_stage_wall(
    stage: str, shard, t0: float, t1: float, fresh: bool = False
) -> None:
    """One staged device dispatch synced (``bls._run_stage``): a busy
    interval on ``shard``. The gap since the shard's previous
    sync-complete is a BUBBLE — attributed by overlap with the
    host-activity timeline and landed in
    ``bls_device_bubble_seconds_total{shard,cause}``. ``fresh`` marks a
    first-shape dispatch whose wall includes the XLA compile: the
    interval is also recorded as ``compile`` activity so OTHER shards'
    gaps behind it attribute honestly. Overlapping dispatches on one
    shard (verify_now racing a flush) are busy-clipped, never
    double-counted, and never produce a negative gap."""
    if not _enabled:
        return
    if t1 <= t0:
        return
    shard = int(shard) if shard is not None else 0
    rec = getattr(_tls, "flush", None)
    if rec is not None:
        rec.add("device", t1 - t0, shard=shard)
    gap_attr = None
    with _lock:
        if fresh:
            _note_activity_locked("compile", t0, t1)
        st = _shards.get(shard)
        if st is None:
            st = _shards[shard] = _ShardState()
        if st.last_sync is not None and t0 > st.last_sync:
            g0, g1 = st.last_sync, t0
            # scan the ring from the TAIL and stop at the first entry
            # ending before the gap: activities are appended at their
            # end time, so per-dispatch work is bounded by the
            # intervals near the gap, not the ring capacity (a full
            # 4096-entry copy under this lock would serialize the very
            # packers the profiler measures). Thread-scheduling jitter
            # can in rare cases hide an older overlapping entry behind
            # the break; its seconds then fall to `other` —
            # conservative, and the cause split still sums exactly.
            overlapping: List[Tuple[str, float, float]] = []
            for entry in reversed(_activity):
                if entry[2] <= g0:
                    break
                overlapping.append(entry)
            # still-open empty-queue waits cover the gap's tail even
            # though their completed interval has not reached the ring
            # yet (they close at wake; this gap closes NOW)
            for start in _open_idle.values():
                if start < g1:
                    overlapping.append(("queue_empty", start, g1))
            gap_attr = _attribute_gap(g0, g1, overlapping)
            st.idle_s += g1 - g0
            st.gaps += 1
            for cause, s in gap_attr.items():
                st.causes[cause] = st.causes.get(cause, 0.0) + s
                st.cause_counts[cause] = st.cause_counts.get(cause, 0) + 1
        busy0 = t0 if st.last_sync is None else max(t0, st.last_sync)
        busy = max(0.0, t1 - busy0)
        st.busy_s += busy
        st.dispatches += 1
        st.last_sync = t1 if st.last_sync is None else max(st.last_sync, t1)
    if busy > 0:
        _BUSY_SECONDS.with_labels(str(shard)).inc(busy)
    if gap_attr:
        for cause, s in gap_attr.items():
            _BUBBLE_SECONDS.with_labels(str(shard), cause).inc(s)
        # chain-time attribution: the bubble lands on the slot the gap
        # CLOSED in (cause split stays in the counter family)
        slot_ledger.note_bubble(sum(gap_attr.values()))
    if fresh:
        slot_ledger.note_fresh_compile(stage)


# ---------------------------------------------------------------------------
# Reading (jax-free: the /lighthouse/health `pipeline` block, the bench
# pipeline_leg, tools/pipeline_report.py and bls.stage_latency_summary)
# ---------------------------------------------------------------------------


def shard_bubble_ratio(shard) -> Optional[float]:
    """idle / (busy + idle) for one shard; None before its first
    dispatch (no interval exists — never a fabricated 0.0)."""
    with _lock:
        st = _shards.get(int(shard) if shard is not None else 0)
        if st is None or (st.busy_s + st.idle_s) <= 0:
            return None
        return round(st.idle_s / (st.busy_s + st.idle_s), 4)


def bubble_rows() -> Dict[str, dict]:
    """Aggregated per-cause bubble rows across every shard — the
    ``bubble:<cause>`` rows ``bls.stage_latency_summary()`` reports
    next to the stage and pack splits."""
    with _lock:
        agg: Dict[str, List[float]] = {}
        for st in _shards.values():
            for cause, s in st.causes.items():
                rec = agg.setdefault(cause, [0.0, 0])
                rec[0] += s
                rec[1] += st.cause_counts.get(cause, 0)
    return {
        cause: {
            "sum_s": round(s, 6),
            "count": n,
            "mean_s": round(s / n, 6) if n else 0.0,
        }
        for cause, (s, n) in sorted(agg.items())
    }


def summary() -> dict:
    """One document for ``/lighthouse/health``'s ``pipeline`` block and
    the bench ``pipeline_leg``: per-shard busy/idle/bubble attribution,
    cumulative flush-phase seconds, flush-thread saturation, and the
    overlap-potential projection (ROADMAP item 5's sizing input)."""
    with _lock:
        shards_doc = {}
        for i in sorted(_shards):
            st = _shards[i]
            span = st.busy_s + st.idle_s
            causes = {
                c: round(s, 6) for c, s in sorted(st.causes.items())
            }
            dominant = (
                max(st.causes.items(), key=lambda kv: kv[1])[0]
                if st.causes else None
            )
            shards_doc[str(i)] = {
                "dispatches": st.dispatches,
                "gaps": st.gaps,
                "busy_s": round(st.busy_s, 6),
                "idle_s": round(st.idle_s, 6),
                "bubble_ratio": (
                    round(st.idle_s / span, 4) if span > 0 else None
                ),
                "causes": causes,
                "dominant_cause": dominant,
            }
        totals = dict(_totals)
    flushes = totals["flushes"]
    wall = totals["wall_s"]
    projected = totals["projected_wall_s"]
    pack = totals["pack_s"]
    device = totals["device_s"]
    fallback = totals["fallback_s"]
    busy = pack + device + fallback
    return {
        "enabled": _enabled,
        "shards": shards_doc,
        "flushes": {
            "count": flushes,
            "sets": totals["sets"],
            "wall_s": round(wall, 6),
            **{
                f"{p}_s": round(totals[f"{p}_s"], 6)
                for p in FLUSH_PHASES
            },
        },
        # cumulative counterpart of the per-flush gauge: what fraction
        # of ALL flush active time went to host pack
        "flush_thread_saturation": (
            round(pack / busy, 4) if busy > 0 else None
        ),
        "overlap_potential": {
            "basis": (
                "projected wall per flush = busiest dispatch lane's "
                "max(pack, device) + fallback, plus plan and the "
                "residual (pack for flush N+1 overlapping flush N's "
                "device time hides the smaller of each lane's two "
                "walls; concurrent dp lanes already overlap each "
                "other); PROJECTED, not measured — the measured "
                "counterpart arrives with ROADMAP item 5"
            ),
            "pack_s": round(pack, 6),
            "device_s": round(device, 6),
            "measured_wall_s": round(wall, 6),
            "projected_wall_s": round(projected, 6),
            "measured_sets_per_sec": (
                round(totals["sets"] / wall, 2) if wall > 0 else None
            ),
            "projected_sets_per_sec": (
                round(totals["sets"] / projected, 2)
                if projected > 0 else None
            ),
            "projected_speedup": (
                round(wall / projected, 4) if projected > 0 else None
            ),
        },
    }
