"""Cross-cutting utilities (reference: ``common/`` crates — slot_clock,
lighthouse_metrics, task_executor, logging)."""

from .lockfile import Lockfile, LockfileError
from .slot_clock import ManualSlotClock, SlotClock, SystemTimeSlotClock
from . import metrics

__all__ = [
    "Lockfile",
    "LockfileError",
    "ManualSlotClock",
    "SlotClock",
    "SystemTimeSlotClock",
    "metrics",
]
