"""Lightweight nested trace spans for the verification hot path.

The metrics registry answers "how much, how often"; this module answers
"where did THIS batch's wall-clock go": nested, attributed, thread-aware
timed spans exportable as chrome://tracing JSON (load the file at
``chrome://tracing`` or https://ui.perfetto.dev). ``tools/trace_report.py``
drives a staged device BLS verify under tracing and writes the file.

Design constraints (the hot path keeps its instrumentation always-on):

* DISABLED is the default and must cost well under 1 microsecond per
  enter/exit — ``span()`` returns a shared no-op context manager without
  allocating a span object (the zgate4 micro-check pins this).
* Enabled recording is thread-safe: spans nest per-thread via a
  thread-local stack; completed spans append to a bounded global buffer
  under one lock (two appends per span, no per-event I/O).
* Export emits chrome trace "X" (complete) events with microsecond
  timestamps relative to the trace epoch, plus thread-name metadata.

Enable with ``LIGHTHOUSE_TPU_TRACE=1`` in the environment or
:func:`enable` at runtime; :func:`clear` resets the buffer and epoch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List

_MAX_EVENTS = 200_000

_enabled = False
_lock = threading.Lock()
_events: List[dict] = []
_dropped = 0
_thread_names: Dict[int, str] = {}
_t0 = time.perf_counter()

_tls = threading.local()


class _NoopSpan:
    """Shared disabled-path singleton: enter/exit do nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:  # parity with _Span.set
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs) -> None:
        """Attach attributes discovered mid-span (e.g. the verdict)."""
        self.attrs.update(attrs)

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        stack = _tls.stack
        stack.pop()
        tid = threading.get_ident()
        args: Dict[str, Any] = {k: _jsonable(v) for k, v in self.attrs.items()}
        if stack:
            args["parent"] = stack[-1]
        if exc_type is not None:
            args["error"] = exc_type.__name__
        ev = {
            "name": self.name,
            "ph": "X",
            # clamp: a span straddling clear()'s epoch reset must not
            # emit a negative timestamp (chrome rejects them)
            "ts": max(0.0, round((self.t0 - _t0) * 1e6, 3)),
            "dur": round((t1 - self.t0) * 1e6, 3),
            "pid": os.getpid(),
            "tid": tid,
            "args": args,
        }
        global _dropped
        with _lock:
            if len(_events) < _MAX_EVENTS:
                _events.append(ev)
                if tid not in _thread_names:
                    _thread_names[tid] = threading.current_thread().name
            else:
                _dropped += 1
        return False


def _jsonable(v):
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


def span(name: str, **attrs):
    """Context manager timing one named region; nests within the
    enclosing span of the same thread. ``attrs`` become chrome-trace
    ``args``. When tracing is disabled this is a shared no-op."""
    if not _enabled:
        return _NOOP
    return _Span(name, attrs)


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def clear() -> None:
    """Drop recorded events and restart the trace epoch."""
    global _dropped, _t0
    with _lock:
        _events.clear()
        _thread_names.clear()
        _dropped = 0
        _t0 = time.perf_counter()


def events() -> List[dict]:
    with _lock:
        return list(_events)


def dropped() -> int:
    with _lock:
        return _dropped


def chrome_trace() -> dict:
    """The chrome://tracing JSON object for everything recorded so far."""
    with _lock:
        evs = list(_events)
        names = dict(_thread_names)
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": os.getpid(),
            "tid": tid,
            "args": {"name": tname},
        }
        for tid, tname in sorted(names.items())
    ]
    return {
        "traceEvents": meta + evs,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "lighthouse_tpu.utils.tracing"},
    }


def export_chrome(path: str) -> int:
    """Write the chrome trace JSON to ``path``; returns the event count."""
    trace = chrome_trace()
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])


if os.environ.get("LIGHTHOUSE_TPU_TRACE", "") not in ("", "0"):
    enable()
