"""Structured logging with rate limiting (reference:
``common/logging/src/lib.rs`` — slog decorators + ``TimeLatch`` at
``:196`` suppressing repeat warnings inside a window).

``log(level, msg, **fields)`` emits one ``key=value``-structured line to
stderr; hot paths guard repeated messages with a :class:`TimeLatch` so a
flood (e.g. queue shedding, repeated peer bans) costs one line per
window instead of one per event."""

from __future__ import annotations

import sys
import threading
import time

from . import metrics

_LINES = metrics.counter("log_lines_total", "structured log lines emitted")
_SUPPRESSED = metrics.counter(
    "log_lines_suppressed_total", "log lines dropped by TimeLatch windows"
)

LEVELS = ("debug", "info", "warn", "error", "crit")
_MIN_LEVEL = "info"


def set_level(level: str) -> None:
    global _MIN_LEVEL
    assert level in LEVELS
    _MIN_LEVEL = level


def log(level: str, msg: str, **fields) -> None:
    if LEVELS.index(level) < LEVELS.index(_MIN_LEVEL):
        return
    _LINES.inc()
    ts = time.strftime("%b %d %H:%M:%S")
    kv = " ".join(f"{k}={_fmt(v)}" for k, v in fields.items())
    print(f"{ts} {level.upper():5s} {msg}{' ' + kv if kv else ''}",
          file=sys.stderr, flush=True)


def _fmt(v) -> str:
    if isinstance(v, bytes):
        return "0x" + v.hex()[:16]
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


class TimeLatch:
    """One ``fire()`` per ``window`` seconds (reference TimeLatch):
    returns True when the caller should emit, False (counted) otherwise."""

    def __init__(self, window: float = 30.0):
        self.window = window
        self._last = 0.0
        self._lock = threading.Lock()

    def fire(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if now - self._last >= self.window:
                self._last = now
                return True
        _SUPPRESSED.inc()
        return False


def rate_limited(latch: TimeLatch, level: str, msg: str, **fields) -> None:
    """Emit through a latch; suppressed lines are counted, not printed."""
    if latch.fire():
        log(level, msg, **fields)
