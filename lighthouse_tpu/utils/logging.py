"""Structured logging with rate limiting (reference:
``common/logging/src/lib.rs`` — slog decorators + ``TimeLatch`` at
``:196`` suppressing repeat warnings inside a window).

``log(level, msg, **fields)`` emits one structured line to stderr —
``key=value`` text by default, one JSON object per line with
``LIGHTHOUSE_TPU_LOG_FORMAT=json`` (or :func:`set_format`). The minimum
level honors ``LIGHTHOUSE_TPU_LOG_LEVEL`` at import and
:func:`set_level` at runtime (both thread-safe). Hot paths guard
repeated messages with a :class:`TimeLatch` so a flood (e.g. queue
shedding, repeated peer bans) costs one line per window instead of one
per event.

Every emitted line ticks ``log_messages_total{level}`` (Lighthouse-style
— error/crit rates are scrapeable), warn-and-above lines feed the
flight-recorder journal, and a crit line triggers
``flight_recorder.dump_on_failure`` so the context that led up to it is
preserved.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from . import flight_recorder, metrics

_MESSAGES = metrics.counter_vec(
    "log_messages_total", "structured log messages emitted, by level",
    ("level",),
)
_SUPPRESSED = metrics.counter(
    "log_lines_suppressed_total", "log lines dropped by TimeLatch windows"
)

LEVELS = ("debug", "info", "warn", "error", "crit")
FORMATS = ("text", "json")

# warn-and-above lines are journaled: below that the ring would be all
# chatter and the forensics window would shrink to nothing
_JOURNAL_MIN_IDX = LEVELS.index("warn")

_state_lock = threading.Lock()
_min_idx = LEVELS.index("info")
_format = "text"


def set_level(level: str) -> None:
    global _min_idx
    assert level in LEVELS
    with _state_lock:
        _min_idx = LEVELS.index(level)


def get_level() -> str:
    with _state_lock:
        return LEVELS[_min_idx]


def set_format(fmt: str) -> None:
    global _format
    assert fmt in FORMATS
    with _state_lock:
        _format = fmt


def log(level: str, msg: str, **fields) -> None:
    idx = LEVELS.index(level)
    # one locked read of both knobs: a concurrent set_level/set_format
    # can never interleave a half-updated view into this emission
    with _state_lock:
        min_idx, fmt = _min_idx, _format
    if idx < min_idx:
        return
    _MESSAGES.with_labels(level).inc()
    if idx >= _JOURNAL_MIN_IDX:
        flight_recorder.record("log", level=level, msg=msg, **fields)
    if fmt == "json":
        doc = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "level": level,
            "msg": msg,
        }
        for k, v in fields.items():
            # a caller field named ts/level/msg must survive, not be
            # silently shadowed by the envelope (text mode prints it)
            doc[k if k not in doc else f"field_{k}"] = _json_val(v)
        line = json.dumps(doc)
    else:
        ts = time.strftime("%b %d %H:%M:%S")
        kv = " ".join(f"{k}={_fmt(v)}" for k, v in fields.items())
        line = f"{ts} {level.upper():5s} {msg}{' ' + kv if kv else ''}"
    print(line, file=sys.stderr, flush=True)
    if level == "crit":
        # crit = the node is in trouble: preserve the journal that led
        # here (no-op unless dumping is enabled; rate-limited inside)
        flight_recorder.dump_on_failure("crit_log", msg=msg)


def _fmt(v) -> str:
    if isinstance(v, bytes):
        return "0x" + v.hex()[:16]
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def _json_val(v):
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    if isinstance(v, bytes):
        return "0x" + v.hex()[:16]
    return str(v)


class TimeLatch:
    """One ``fire()`` per ``window`` seconds (reference TimeLatch):
    returns True when the caller should emit, False (counted) otherwise."""

    def __init__(self, window: float = 30.0):
        self.window = window
        self._last = 0.0
        self._lock = threading.Lock()

    def fire(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if now - self._last >= self.window:
                self._last = now
                return True
        _SUPPRESSED.inc()
        return False


def rate_limited(latch: TimeLatch, level: str, msg: str, **fields) -> None:
    """Emit through a latch; suppressed lines are counted, not printed."""
    if latch.fire():
        log(level, msg, **fields)


# env knobs honored at import (unknown values are ignored, not fatal:
# a typo in an env var must never take the node down)
_env_level = os.environ.get("LIGHTHOUSE_TPU_LOG_LEVEL", "").lower()
if _env_level in LEVELS:
    set_level(_env_level)
_env_format = os.environ.get("LIGHTHOUSE_TPU_LOG_FORMAT", "").lower()
if _env_format in FORMATS:
    set_format(_env_format)
