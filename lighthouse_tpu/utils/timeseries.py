"""On-node metrics history: bounded time-series rings, a background
sampler over a declared family allowlist, and the capacity/headroom
estimator (ISSUE 14).

Every observability surface the node had before this module —
``/lighthouse/health``, the SLO window, the transfer ledger, the
pipeline profiler — is an instantaneous snapshot; nothing on the node
could answer "how close to saturation are we, and is it getting
worse?". ROADMAP item 2's bulk-QoS admission control needs exactly that
signal, and the committee batch-verification cost model (PAPERS.md,
arxiv 2302.00418) shows throughput-vs-load goes nonlinear near the top
of the rung ladder — the regime a 1M-validator firehose lives in. This
module is the time axis:

* **Bounded per-series rings with downsampling tiers.** Every sample of
  a series lands in the ``raw`` ring; completed time buckets fold into
  the ``1m`` and ``10m`` tiers as ``(t, min, max, mean, count)`` points,
  so an operator can read an hour at sample resolution and a day at
  10-minute resolution from a store whose memory is STRICTLY bounded:
  ring capacities are fixed (old points overwritten, never reallocated)
  and the series count is capped (``max_series``; overflow series are
  counted, not stored). Retention math at the defaults (10 s sampling):
  ``raw`` 360 points = 1 h, ``1m`` 180 points = 3 h, ``10m`` 144 points
  = 24 h.
* **A declared sampler allowlist** (:data:`SAMPLE_FAMILIES`): the
  background sampler snapshots EXISTING registry families — scheduler
  occupancy/queue depth, per-kind arrival and verdict rates, per-shard
  sets/s and bubble ratio, deadline misses, device memory, H2D bytes —
  into ``capacity_*`` series. Counter families become per-second RATES
  (delta / dt against the previous sample); gauges are stored as read.
  Each allowlist family is documented in ``docs/OBSERVABILITY.md``
  (linted by ``tests/test_zgate4_metrics_lint.py``) — an undeclared
  series cannot silently appear.
* **The capacity/headroom estimator** (:func:`estimate_capacity`):
  measured serving cost per signature set (preference order:
  per-shard dispatch walls from the mesh families over sampling-
  interval deltas → the compile service's organic rung-cost feed →
  the pipeline profiler's flush walls; the source is always reported,
  never fabricated) × the
  healthy-shard count → ``capacity_estimated_sets_per_sec``; held
  against the measured arrival rate →  ``capacity_utilization`` and
  ``capacity_headroom_ratio`` — the go/no-go dial ROADMAP item 2's
  admission control will read. ``headroom = max(0, 1 − arrival/capacity)``
  (the formula lives in docs/COST_MODEL.md with its measured inputs).

Served at ``GET /lighthouse/timeseries`` (``?family=&window=&tier=``)
and summarized in the ``capacity`` block of ``/lighthouse/health``;
rendered as sparkline tables by ``tools/capacity_report.py``, which can
also lockstep-replay a trace through the estimator to predict where a
ramp saturates (the ``saturation_ramp`` acceptance trace).

Design constraints (the house observability discipline):

* jax-free at import (tools read it offline; subprocess-pinned).
* DISABLED sampling costs well under 1 µs per :func:`sample` call —
  one global check, no allocation (pinned like disabled spans).
* Enabled :meth:`TimeseriesStore.record` is O(1) amortized: ring
  appends + bucket accumulation under one lock; readers snapshot under
  the same lock, so a scrape never observes a torn point.

Env knobs (read at import; :func:`configure` overrides at runtime):

    LIGHTHOUSE_TPU_TIMESERIES        1|0   sampling enabled (default 1)
    LIGHTHOUSE_TPU_TS_INTERVAL_S     float sampler period (default 10)
    LIGHTHOUSE_TPU_TS_RAW_POINTS     int   raw ring capacity (default 360)
    LIGHTHOUSE_TPU_TS_1M_POINTS     int   1m ring capacity (default 180)
    LIGHTHOUSE_TPU_TS_10M_POINTS    int   10m ring capacity (default 144)
    LIGHTHOUSE_TPU_TS_MAX_SERIES     int   series cap (default 256)
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import flight_recorder, metrics, slot_ledger

SCHEMA = "lighthouse_tpu.timeseries/1"

# downsampling tiers: (name, bucket seconds); "raw" stores every sample
TIERS = (("raw", 0.0), ("1m", 60.0), ("10m", 600.0))
TIER_NAMES = tuple(name for name, _ in TIERS)

# one env-parsing convention across the observability knobs
_env_int = flight_recorder._env_int
_env_float = flight_recorder._env_float

# ---------------------------------------------------------------------------
# Sampler allowlist: every series family the background sampler may
# produce, sorted, snake_case, capacity_-prefixed, each documented in
# docs/OBSERVABILITY.md (linted by tests/test_zgate4_metrics_lint.py).
#
# mode:
#   gauge   — store the source gauge's value as read
#   rate    — store (cum − prev_cum) / dt of the source counter family
#   ratio   — bubble/(bubble+busy) over the sampling interval's deltas
#   derived — produced by the capacity estimator, not read from a source
# label: the source label NAME each series is split by (children whose
# other labels differ are summed under it); None = sum every child (or
# the source is unlabeled).
# ---------------------------------------------------------------------------


class FamilySpec:
    __slots__ = ("family", "mode", "source", "label")

    def __init__(self, family: str, mode: str, source: Optional[str],
                 label: Optional[str]):
        self.family = family
        self.mode = mode
        self.source = source
        self.label = label


SAMPLE_FAMILIES: Tuple[FamilySpec, ...] = (
    FamilySpec("capacity_arrival_sets_per_sec", "rate",
               "verification_scheduler_arrival_sets_total", "kind"),
    # bulk QoS class (ISSUE 15): queue depth + served rate + the
    # admission throttle state — the three series an operator reads to
    # see the degradation order doing its job (bulk sheds FIRST as
    # headroom vanishes; gossip's series above stay flat)
    FamilySpec("capacity_bulk_queue_depth", "gauge",
               "verification_scheduler_bulk_queue_depth", None),
    FamilySpec("capacity_bulk_sets_per_sec", "rate",
               "verification_scheduler_bulk_sets_total", "kind"),
    FamilySpec("capacity_bulk_throttled", "gauge",
               "verification_scheduler_bulk_throttled", None),
    FamilySpec("capacity_deadline_miss_per_sec", "rate",
               "verification_scheduler_deadline_misses_total", "kind"),
    FamilySpec("capacity_device_memory_bytes", "gauge",
               "device_memory_bytes", "kind"),
    FamilySpec("capacity_dp_shards", "gauge",
               "verification_scheduler_dp_shards", None),
    FamilySpec("capacity_estimated_sets_per_sec", "derived", None, None),
    FamilySpec("capacity_h2d_bytes_per_sec", "rate",
               "bls_device_h2d_bytes_total", None),
    FamilySpec("capacity_headroom_ratio", "derived", None, None),
    FamilySpec("capacity_occupancy_ratio", "gauge",
               "verification_scheduler_batch_occupancy_ratio", None),
    # watchtower inputs (ISSUE 18): the key-table reupload ratio, the
    # recompile rate and the SLO burn rate as HISTORY, so the drift /
    # burst / rate-of-change detectors have a window to stand on (the
    # live gauges alone have no time axis)
    FamilySpec("capacity_pubkey_reupload_ratio", "gauge",
               "bls_device_pubkey_reupload_ratio", "kind"),
    FamilySpec("capacity_queue_depth", "gauge",
               "verification_scheduler_queue_depth", None),
    FamilySpec("capacity_recompiles_per_sec", "rate",
               "bls_device_recompiles_total", None),
    FamilySpec("capacity_shard_bubble_ratio", "ratio",
               "bls_device_bubble_seconds_total", "shard"),
    FamilySpec("capacity_shard_sets_per_sec", "rate",
               "bls_device_shard_sets_total", "shard"),
    FamilySpec("capacity_slo_burn_rate", "gauge",
               "verification_scheduler_slo_burn_rate", "kind"),
    FamilySpec("capacity_utilization", "derived", None, None),
    # sets_total, NOT submissions_total: a backfill submission carries
    # 48-128 sets, so a per-submission rate would read ~100x under the
    # true serving rate and its units would not match the arrival
    # series it is held against
    FamilySpec("capacity_verdict_sets_per_sec", "rate",
               "verification_scheduler_sets_total", "kind"),
    # chain-time slot ledger (ISSUE 17): the per-epoch first-sighting
    # hit ratio (ROADMAP item 3's go/no-go dial) as history, plus the
    # ledger's own event throughput so a dashboard can see attribution
    # coverage move with load
    FamilySpec("slot_first_sighting_hit_ratio", "gauge",
               "key_table_first_sighting_hit_ratio", "epoch"),
    FamilySpec("slot_ledger_events_per_sec", "rate",
               "slot_ledger_events_total", "event"),
)

# ---------------------------------------------------------------------------
# Metric families (the estimator's live gauges + the sampler's own
# accounting; prefix `capacity_` is declared in the zgate4 lint)
# ---------------------------------------------------------------------------

_EST_CAPACITY = metrics.gauge(
    "capacity_estimated_sets_per_sec",
    "estimated serving capacity of the node in signature sets/s: "
    "healthy dp shards x 1 / measured cost-per-set (cost preference "
    "order: per-shard dispatch walls -> compile-service organic rung "
    "cost -> pipeline flush walls; see docs/OBSERVABILITY.md capacity "
    "section and the headroom formula in docs/COST_MODEL.md). 0 until "
    "a cost has been measured — never fabricated",
)
_UTILIZATION = metrics.gauge(
    "capacity_utilization",
    "measured demand (deadline-class arrival rate + ADMITTED bulk "
    "service rate — parked bulk demand is excluded so the admission "
    "valve never throttles on demand it itself controls, ISSUE 15) / "
    "estimated capacity: < 1 means headroom exists, > 1 means the "
    "queue is growing and deadline misses are a matter of time — the "
    "nonlinear-regime dial of the committee batch-verification cost "
    "model (arxiv 2302.00418)",
)
_HEADROOM = metrics.gauge(
    "capacity_headroom_ratio",
    "max(0, 1 - utilization): the live headroom dial ROADMAP item 2's "
    "bulk-QoS admission control reads. Crossing below 0.2 PRECEDES the "
    "first deadline-miss burst on a saturation ramp (the predictive "
    "property tests/test_timeseries_capacity.py certifies)",
)
_SAMPLES_TOTAL = metrics.counter(
    "capacity_sampler_samples_total",
    "sampling passes the capacity timeseries sampler has run "
    "(background thread ticks + explicit sample() calls)",
)
_SAMPLER_ERRORS = metrics.counter(
    "capacity_sampler_errors_total",
    "background sampling passes that raised (the pass is dropped, the "
    "thread survives) — a climbing rate with a stalled "
    "capacity_sampler_samples_total means the time axis is silently "
    "empty and one of the allowlisted source families changed shape",
)
_SAMPLER_MEMORY = metrics.gauge(
    "capacity_sampler_memory_bytes",
    "estimated bytes held by the timeseries store (series rings + "
    "rate state) — stays under the configured bound "
    "(max_series x full-tier cost), pinned by test",
)

# ---------------------------------------------------------------------------
# Enable / configure
# ---------------------------------------------------------------------------

_enabled = os.environ.get(
    "LIGHTHOUSE_TPU_TIMESERIES", "1"
) not in ("", "0")
_interval_s = max(0.01, _env_float("LIGHTHOUSE_TPU_TS_INTERVAL_S", 10.0))
_raw_points = max(8, _env_int("LIGHTHOUSE_TPU_TS_RAW_POINTS", 360))
_m1_points = max(4, _env_int("LIGHTHOUSE_TPU_TS_1M_POINTS", 180))
_m10_points = max(4, _env_int("LIGHTHOUSE_TPU_TS_10M_POINTS", 144))
_max_series = max(8, _env_int("LIGHTHOUSE_TPU_TS_MAX_SERIES", 256))

# conservative per-point cost constants for the memory bound (CPython
# tuple of floats + deque slot, rounded up; the bound test holds the
# ESTIMATE under the configured bound, and sys.getsizeof spot-checks
# keep the constants honest)
_RAW_POINT_BYTES = 120
_AGG_POINT_BYTES = 180
_SERIES_OVERHEAD_BYTES = 1024


def enabled() -> bool:
    return _enabled


def configure(
    enabled: Optional[bool] = None,
    interval_s: Optional[float] = None,
    raw_points: Optional[int] = None,
    m1_points: Optional[int] = None,
    m10_points: Optional[int] = None,
    max_series: Optional[int] = None,
) -> dict:
    """Override knobs at runtime; returns the PREVIOUS values so tests
    can restore with ``configure(**prev)`` (flight_recorder's contract).
    Changing a ring capacity applies to the NEXT :func:`reset`'s store —
    live rings keep their geometry (bounded either way)."""
    global _enabled, _interval_s, _raw_points, _m1_points, _m10_points
    global _max_series
    prev = {
        "enabled": _enabled,
        "interval_s": _interval_s,
        "raw_points": _raw_points,
        "m1_points": _m1_points,
        "m10_points": _m10_points,
        "max_series": _max_series,
    }
    if enabled is not None:
        _enabled = bool(enabled)
    if interval_s is not None:
        _interval_s = max(0.01, float(interval_s))
    if raw_points is not None:
        _raw_points = max(8, int(raw_points))
    if m1_points is not None:
        _m1_points = max(4, int(m1_points))
    if m10_points is not None:
        _m10_points = max(4, int(m10_points))
    if max_series is not None:
        _max_series = max(8, int(max_series))
    return prev


# ---------------------------------------------------------------------------
# The store
# ---------------------------------------------------------------------------


class _Series:
    __slots__ = ("raw", "tiers", "open_buckets")

    def __init__(self, raw_points: int, m1_points: int, m10_points: int):
        self.raw: deque = deque(maxlen=raw_points)  # (t, v)
        # tier name -> ring of (t_bucket, min, max, mean, count)
        self.tiers: Dict[str, deque] = {
            "1m": deque(maxlen=m1_points),
            "10m": deque(maxlen=m10_points),
        }
        # tier name -> open accumulator [bucket_start, min, max, sum, n]
        self.open_buckets: Dict[str, Optional[list]] = {
            "1m": None, "10m": None,
        }


class TimeseriesStore:
    """Bounded, thread-safe store of named series (see module
    docstring). ``record`` is the single write path (sampler thread,
    tests, any number of writer threads); every read snapshots under
    the same lock."""

    def __init__(
        self,
        raw_points: Optional[int] = None,
        m1_points: Optional[int] = None,
        m10_points: Optional[int] = None,
        max_series: Optional[int] = None,
    ):
        self.raw_points = int(raw_points if raw_points is not None
                              else _raw_points)
        self.m1_points = int(m1_points if m1_points is not None
                             else _m1_points)
        self.m10_points = int(m10_points if m10_points is not None
                              else _m10_points)
        self.max_series = int(max_series if max_series is not None
                              else _max_series)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, str], _Series] = {}
        self._recorded_total = 0
        self._dropped_series = 0

    # -- writing ----------------------------------------------------------

    def record(
        self, family: str, value: float, t: Optional[float] = None,
        label: str = "",
    ) -> None:
        """Append one sample of ``(family, label)`` at time ``t``
        (default: now, wall clock — the endpoint serves operator-facing
        timestamps). A series beyond the ``max_series`` bound is
        COUNTED as dropped, never stored — the memory bound is strict."""
        if t is None:
            t = time.time()
        v = float(value)
        key = (family, label)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self._dropped_series += 1
                    return
                s = self._series[key] = _Series(
                    self.raw_points, self.m1_points, self.m10_points
                )
            s.raw.append((t, v))
            self._recorded_total += 1
            for tier, bucket_s in TIERS:
                if bucket_s <= 0:
                    continue
                start = (t // bucket_s) * bucket_s
                ob = s.open_buckets[tier]
                if ob is not None and start > ob[0]:
                    # bucket complete: fold into the tier ring
                    s.tiers[tier].append((
                        ob[0], ob[1], ob[2], ob[3] / ob[4], ob[4],
                    ))
                    ob = None
                if ob is None or start < ob[0]:
                    # fresh bucket; a timestamp OLDER than the open
                    # bucket (synthetic test time running backwards)
                    # stays in the raw ring but cannot join a closed
                    # aggregation window
                    if ob is None:
                        s.open_buckets[tier] = [start, v, v, v, 1]
                    continue
                ob[1] = min(ob[1], v)
                ob[2] = max(ob[2], v)
                ob[3] += v
                ob[4] += 1

    # -- reading ----------------------------------------------------------

    def families(self) -> List[str]:
        with self._lock:
            return sorted({fam for fam, _ in self._series})

    def points(
        self, family: str, label: str = "", tier: str = "raw",
        window_s: Optional[float] = None, now: Optional[float] = None,
    ) -> List[tuple]:
        """One series' points, oldest first. ``raw`` points are
        ``(t, value)``; downsampled tiers serve ``(t_bucket, min, max,
        mean, count)`` including the still-open bucket (freshness wins;
        its count says how partial it is). ``window_s`` keeps points
        newer than ``now − window_s``."""
        if tier not in TIER_NAMES:
            raise ValueError(
                f"unknown tier {tier!r} (expected one of {TIER_NAMES})"
            )
        with self._lock:
            s = self._series.get((family, label))
            if s is None:
                return []
            if tier == "raw":
                pts = list(s.raw)
            else:
                pts = list(s.tiers[tier])
                ob = s.open_buckets[tier]
                if ob is not None:
                    pts.append((ob[0], ob[1], ob[2], ob[3] / ob[4], ob[4]))
        if window_s is not None:
            cutoff = (time.time() if now is None else now) - window_s
            pts = [p for p in pts if p[0] >= cutoff]
        return pts

    def doc(
        self, families: Optional[List[str]] = None, tier: str = "raw",
        window_s: Optional[float] = None,
    ) -> dict:
        """The ``/lighthouse/timeseries`` reply body: schema, tier,
        filters, and every selected series' points keyed family →
        label ("" for unlabeled)."""
        if tier not in TIER_NAMES:
            raise ValueError(
                f"unknown tier {tier!r} (expected one of {TIER_NAMES})"
            )
        with self._lock:
            keys = sorted(self._series)
        if families is not None:
            want = set(families)
            keys = [k for k in keys if k[0] in want]
        fams: Dict[str, Dict[str, list]] = {}
        for fam, label in keys:
            pts = self.points(fam, label, tier=tier, window_s=window_s)
            fams.setdefault(fam, {})[label] = [list(p) for p in pts]
        return {
            "schema": SCHEMA,
            "tier": tier,
            "window_s": window_s,
            "families": fams,
        }

    def stats(self) -> dict:
        """Store accounting incl. the memory estimate vs its bound —
        the ``store`` half of the ``capacity`` health block."""
        with self._lock:
            n_series = len(self._series)
            n_raw = sum(len(s.raw) for s in self._series.values())
            n_agg = sum(
                len(ring) + (1 if s.open_buckets[t] is not None else 0)
                for s in self._series.values()
                for t, ring in s.tiers.items()
            )
            recorded = self._recorded_total
            dropped = self._dropped_series
        est = (
            n_raw * _RAW_POINT_BYTES
            + n_agg * _AGG_POINT_BYTES
            + n_series * _SERIES_OVERHEAD_BYTES
        )
        bound = self.max_series * (
            self.raw_points * _RAW_POINT_BYTES
            + (self.m1_points + self.m10_points + 2) * _AGG_POINT_BYTES
            + _SERIES_OVERHEAD_BYTES
        )
        return {
            "series": n_series,
            "max_series": self.max_series,
            "recorded_total": recorded,
            "dropped_series": dropped,
            "raw_points": n_raw,
            "agg_points": n_agg,
            "capacity": {
                "raw": self.raw_points,
                "1m": self.m1_points,
                "10m": self.m10_points,
            },
            "memory_bytes_est": est,
            "memory_bound_bytes": bound,
        }


# ---------------------------------------------------------------------------
# Module-level store + sampler state
# ---------------------------------------------------------------------------

_state_lock = threading.Lock()
_store: Optional[TimeseriesStore] = None
# (family, label) -> (t, cumulative value): the rate baseline. For the
# ratio mode the value is the (numerator, denominator) pair.
_rate_state: Dict[Tuple[str, str], Tuple[float, float]] = {}
_ratio_state: Dict[Tuple[str, str], Tuple[float, float, float]] = {}
_last_estimate: Optional[dict] = None
# interval-delta shard cost: (cum seconds, cum sets) at the previous
# pass, and the last interval-measured cost (sticky — see
# measured_cost_per_set)
_cost_prev: Optional[Tuple[float, float]] = None
_cost_last: Optional[float] = None


def get_store() -> TimeseriesStore:
    global _store
    with _state_lock:
        if _store is None:
            _store = TimeseriesStore()
        return _store


def reset() -> None:
    """Fresh store + rate baselines + last estimate (knobs keep their
    values) — tests and the bench capacity leg start clean."""
    global _store, _last_estimate, _cost_prev, _cost_last
    with _state_lock:
        _store = TimeseriesStore()
        _rate_state.clear()
        _ratio_state.clear()
        _last_estimate = None
        _cost_prev = None
        _cost_last = None


# ---------------------------------------------------------------------------
# Reading the registry (one sampling pass)
# ---------------------------------------------------------------------------


def _source_values(source: str, label: Optional[str]) -> Optional[dict]:
    """{label value ("" when unlabeled/summed): numeric value} for one
    registry family, summing children across the non-kept labels; None
    when the family is not registered yet."""
    m = metrics.get(source)
    if m is None:
        return None
    if not hasattr(m, "labelnames"):
        return {"": float(m.value)}
    out: Dict[str, float] = {}
    try:
        keep_idx = m.labelnames.index(label) if label is not None else None
    except ValueError:
        keep_idx = None
    for values, child in m.children().items():
        key = values[keep_idx] if keep_idx is not None else ""
        out[key] = out.get(key, 0.0) + float(child.value)
    return out


def _sample_rates(spec: FamilySpec, store: TimeseriesStore,
                  now: float) -> Dict[str, float]:
    """Counter family → per-second rates against the previous pass's
    cumulative values. The first sighting of a label records nothing
    (there is no interval to rate over — never a fabricated 0)."""
    cur = _source_values(spec.source, spec.label)
    rates: Dict[str, float] = {}
    if cur is None:
        return rates
    for label, value in cur.items():
        key = (spec.family, label)
        prev = _rate_state.get(key)
        _rate_state[key] = (now, value)
        if prev is None:
            continue
        t0, v0 = prev
        dt = now - t0
        if dt <= 0:
            continue
        rate = max(0.0, value - v0) / dt
        rates[label] = rate
        store.record(spec.family, rate, t=now, label=label)
    return rates


def _sample_bubble_ratio(spec: FamilySpec, store: TimeseriesStore,
                         now: float) -> None:
    """bubble / (bubble + busy) per shard over the sampling interval's
    deltas — the live counterpart of the profiler's lifetime ratio."""
    bubble = _source_values("bls_device_bubble_seconds_total", "shard")
    busy = _source_values("bls_device_shard_busy_seconds_total", "shard")
    if bubble is None or busy is None:
        return
    for shard in sorted(set(bubble) | set(busy)):
        nb = bubble.get(shard, 0.0)
        ns = busy.get(shard, 0.0)
        key = (spec.family, shard)
        prev = _ratio_state.get(key)
        _ratio_state[key] = (now, nb, ns)
        if prev is None:
            continue
        _t0, pb, ps = prev
        d_bubble = max(0.0, nb - pb)
        d_busy = max(0.0, ns - ps)
        span = d_bubble + d_busy
        if span <= 0:
            continue  # idle interval: no dispatch, no honest ratio
        store.record(spec.family, d_bubble / span, t=now, label=shard)


# ---------------------------------------------------------------------------
# The capacity / headroom estimator
# ---------------------------------------------------------------------------


def _shard_cost_cumulative() -> Optional[Tuple[float, float]]:
    """(Σ shard verify seconds, Σ shard sets) from the mesh families;
    None until both exist."""
    secs_m = metrics.get("bls_device_shard_verify_seconds")
    sets_m = metrics.get("bls_device_shard_sets_total")
    if secs_m is None or sets_m is None:
        return None
    secs = sum(
        float(c.sum) for c in secs_m.children().values()
    ) if hasattr(secs_m, "children") else 0.0
    sets = sum(
        float(c.value) for c in sets_m.children().values()
    ) if hasattr(sets_m, "children") else 0.0
    return secs, sets


def _update_interval_shard_cost() -> None:
    """One pass of the mesh cost feed: the per-set cost over THIS
    sampling interval's dispatch deltas (sticky — kept until a later
    interval measures again). Interval deltas, NEVER lifetime
    cumulative values: a process-lifetime average would let hours of
    warm history (or another workload entirely) mask what serving
    costs RIGHT NOW — and the capacity dial exists to answer right
    now. Called under _state_lock."""
    global _cost_prev, _cost_last
    cur = _shard_cost_cumulative()
    if cur is None:
        return
    prev, _cost_prev = _cost_prev, cur
    if prev is None:
        return
    d_secs = cur[0] - prev[0]
    d_sets = cur[1] - prev[1]
    if d_secs > 0 and d_sets > 0:
        _cost_last = d_secs / d_sets


def measured_cost_per_set() -> Tuple[Optional[float], Optional[str]]:
    """Measured serving cost per signature set, with its source —
    preference order (most device-truthful first):

    1. ``shard_verify``  — the mesh feed: per-shard dispatch walls over
       recent SAMPLING-INTERVAL deltas (sticky once measured), so the
       per-set cost is per-chip, current, and capacity scales with the
       healthy-shard count;
    2. ``compile_service`` — the service's organic rung-cost gauge
       (``compile_service_measured_cost_seconds_per_set``, fed by
       ``note_rung_verified`` on every staged dispatch);
    3. ``flush_wall`` — the pipeline profiler's cumulative flush
       accounting: device+fallback seconds per fused set, or (for a
       stub/cpu-native backend that never fires a stage hook) the
       flush wall minus planning per set.

    Returns (None, None) when nothing has been measured — the estimator
    never invents a capacity."""
    if _cost_last is not None and _cost_last > 0:
        return _cost_last, "shard_verify"
    g = metrics.get("compile_service_measured_cost_seconds_per_set")
    if g is not None and float(getattr(g, "value", 0.0)) > 0:
        return float(g.value), "compile_service"
    from . import pipeline_profiler

    flushes = pipeline_profiler.summary().get("flushes", {})
    sets = flushes.get("sets", 0)
    if sets:
        busy = flushes.get("device_s", 0.0) + flushes.get("fallback_s", 0.0)
        if busy > 0:
            return busy / sets, "flush_wall"
        serving = flushes.get("wall_s", 0.0) - flushes.get("plan_s", 0.0)
        if serving > 0:
            return serving / sets, "flush_wall"
    return None, None


def _healthy_shard_count() -> int:
    """The mesh feed: live healthy-shard count when a mesh is attached
    (read directly — the dp gauge only updates at flush time, so it
    would lag a chip loss), else 1 (single-device serving). A mesh
    with EVERY chip lost is a true 0 — capacity is genuinely zero and
    the dial must say so, not fall back to a stale gauge."""
    try:
        from ..crypto.device import mesh as mesh_mod

        if mesh_mod.get_active_mesh() is not None:
            return mesh_mod.healthy_shard_count()
    except Exception:
        pass
    g = metrics.get("verification_scheduler_dp_shards")
    if g is not None and float(getattr(g, "value", 0.0)) > 0:
        return int(g.value)
    return 1


def estimate_capacity(
    arrival_sets_per_sec: Optional[float] = None,
    cost_s_per_set: Optional[float] = None,
    shards: Optional[int] = None,
    publish: bool = True,
) -> dict:
    """One estimator pass: combine measured cost, healthy shards and
    the arrival rate into the capacity/utilization/headroom triple.
    Every input is overridable, and the lockstep replay in
    ``tools/capacity_report.py`` drives THIS function per step with
    modeled inputs and ``publish=False`` (the formula has exactly one
    home; a model run must not write the live gauges); anything
    unmeasured stays ``None`` and the corresponding gauge is left
    untouched — the dial never lies."""
    source = "override"
    if cost_s_per_set is None:
        cost_s_per_set, source = measured_cost_per_set()
    if shards is None:
        shards = _healthy_shard_count()
    est = None
    if cost_s_per_set and cost_s_per_set > 0:
        est = shards / cost_s_per_set
    utilization = headroom = None
    if est is not None and arrival_sets_per_sec is not None:
        if est > 0:
            utilization = arrival_sets_per_sec / est
            headroom = max(0.0, 1.0 - utilization)
        else:
            # measured ZERO capacity (a mesh with every chip lost):
            # utilization is undefined (x/0) but the headroom dial
            # must read empty, not unknown
            headroom = 0.0
    doc = {
        "cost_s_per_set": (
            round(cost_s_per_set, 9) if cost_s_per_set else None
        ),
        "cost_source": source if cost_s_per_set else None,
        "shards": shards,
        "estimated_sets_per_sec": (
            round(est, 3) if est is not None else None
        ),
        "arrival_sets_per_sec": (
            round(arrival_sets_per_sec, 3)
            if arrival_sets_per_sec is not None else None
        ),
        "utilization": (
            round(utilization, 4) if utilization is not None else None
        ),
        "headroom_ratio": (
            round(headroom, 4) if headroom is not None else None
        ),
    }
    if publish:
        if est is not None:
            _EST_CAPACITY.set(est)
        if utilization is not None:
            _UTILIZATION.set(utilization)
        if headroom is not None:
            _HEADROOM.set(headroom)
            # chain-time: the slot's report card keeps its MINIMUM
            # headroom — the worst moment inside the slot, the per-slot
            # resolution ROADMAP item 1's "throughout" claims need
            slot_ledger.note_headroom(headroom)
    return doc


# ---------------------------------------------------------------------------
# The sampling pass (the hot-path seam; < 1 µs disabled)
# ---------------------------------------------------------------------------


def _bulk_arrival_rate(now: float) -> float:
    """Bulk-PATH arrival rate (sets/s) off the same counter the arrival
    series samples, grouped by the path label instead of kind. NOT
    stored as a series — it exists only to be subtracted from the
    estimator's utilization numerator (see ``sample()``). First
    sighting rates 0.0 (no interval yet): the numerator momentarily
    includes bulk demand rather than fabricating a subtraction.
    Called under ``_state_lock`` like every `_rate_state` user."""
    vals = _source_values(
        "verification_scheduler_arrival_sets_total", "path"
    )
    value = (vals or {}).get("bulk")
    if value is None:
        return 0.0
    key = ("_util_bulk_arrivals", "bulk")
    prev = _rate_state.get(key)
    _rate_state[key] = (now, value)
    if prev is None:
        return 0.0
    t0, v0 = prev
    dt = now - t0
    if dt <= 0:
        return 0.0
    return max(0.0, value - v0) / dt


def sample(now: Optional[float] = None) -> Optional[dict]:
    """Run ONE sampling pass: snapshot every allowlisted family into
    the store, then run the capacity estimator on the rates just
    measured and record its outputs as series too. Returns the
    estimator document (None when disabled — a single global check,
    pinned < 1 µs like disabled spans)."""
    if not _enabled:
        return None
    global _last_estimate
    if now is None:
        now = time.time()
    store = get_store()
    arrival_total: Optional[float] = None
    bulk_served = 0.0
    with _state_lock:
        for spec in SAMPLE_FAMILIES:
            if spec.mode == "gauge":
                vals = _source_values(spec.source, spec.label)
                if vals is None:
                    continue
                for label, v in vals.items():
                    store.record(spec.family, v, t=now, label=label)
            elif spec.mode == "rate":
                rates = _sample_rates(spec, store, now)
                if spec.family == "capacity_arrival_sets_per_sec" and rates:
                    arrival_total = sum(rates.values())
                elif spec.family == "capacity_bulk_sets_per_sec" and rates:
                    bulk_served = sum(rates.values())
            elif spec.mode == "ratio":
                _sample_bubble_ratio(spec, store, now)
            # "derived" families are recorded below by the estimator
        # primed EVERY pass (not only when the arrival series already
        # rated) so its own first sighting lines up with the arrival
        # family's — a lazily-primed read would miss the first real
        # interval's bulk demand
        bulk_demand = _bulk_arrival_rate(now)
        if arrival_total is not None:
            # the utilization NUMERATOR counts deadline-class demand
            # plus ADMITTED bulk service — not raw bulk offered demand
            # (ISSUE 15): bulk arrivals the admission valve has parked
            # would otherwise hold headroom below the resume threshold
            # on demand the valve itself controls, a self-referential
            # feedback loop that could never un-throttle under a
            # persistent bulk submitter. The per-kind arrival SERIES
            # keeps the full demand picture (bulk included).
            arrival_total = max(0.0, arrival_total - bulk_demand) + bulk_served
        _update_interval_shard_cost()
    est = estimate_capacity(arrival_sets_per_sec=arrival_total)
    if est["estimated_sets_per_sec"] is not None:
        store.record(
            "capacity_estimated_sets_per_sec",
            est["estimated_sets_per_sec"], t=now,
        )
    if est["utilization"] is not None:
        store.record("capacity_utilization", est["utilization"], t=now)
    if est["headroom_ratio"] is not None:
        store.record("capacity_headroom_ratio", est["headroom_ratio"], t=now)
    with _state_lock:
        _last_estimate = {**est, "t": now}
    _SAMPLES_TOTAL.inc()
    _SAMPLER_MEMORY.set(store.stats()["memory_bytes_est"])
    return est


def last_estimate() -> Optional[dict]:
    with _state_lock:
        return dict(_last_estimate) if _last_estimate else None


# ---------------------------------------------------------------------------
# Background sampler
# ---------------------------------------------------------------------------


class Sampler:
    """Background thread calling :func:`sample` every ``interval_s``.
    Started by the node runner / tools / tests — the store serves
    whatever history exists either way."""

    def __init__(self, interval_s: Optional[float] = None):
        self.interval_s = float(
            interval_s if interval_s is not None else _interval_s
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Sampler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="capacity-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                sample()
            except Exception:
                # a sampling crash must never kill the thread — but a
                # silent swallow would serve an empty time axis with
                # nothing pointing at why (the monitoring.py
                # {outcome}-counter convention)
                _SAMPLER_ERRORS.inc()
            self._stop.wait(self.interval_s)


_sampler: Optional[Sampler] = None


def start_sampler(interval_s: Optional[float] = None) -> Sampler:
    global _sampler
    with _state_lock:
        if _sampler is None or not _sampler.running():
            _sampler = Sampler(interval_s=interval_s)
        s = _sampler
        # started INSIDE the lock: a concurrent stop_sampler() must
        # either see the running thread (and stop it) or take the
        # handle before start — never interleave into an orphaned,
        # unstoppable sampler (start never joins, so no deadlock with
        # the new thread's own _state_lock acquisition)
        s.start()
    return s


def stop_sampler() -> None:
    global _sampler
    with _state_lock:
        s = _sampler
        _sampler = None
    # join OUTSIDE the lock: the sampler thread may be mid-sample()
    # waiting on _state_lock
    if s is not None:
        s.stop()


def sampler_running() -> bool:
    s = _sampler
    return s is not None and s.running()


# ---------------------------------------------------------------------------
# The `capacity` health block
# ---------------------------------------------------------------------------


def capacity_summary() -> dict:
    """One document for ``/lighthouse/health``'s ``capacity`` block:
    sampler state, store accounting (memory estimate vs bound), the
    family catalogue, and the latest estimator output."""
    store = get_store()
    s = _sampler
    return {
        "enabled": _enabled,
        "sampler": {
            "running": sampler_running(),
            # the RUNNING sampler's actual period — start_sampler may
            # have overridden the module default
            "interval_s": s.interval_s if s is not None else _interval_s,
            "samples_total": int(_SAMPLES_TOTAL.value),
        },
        "store": store.stats(),
        "families": [s.family for s in SAMPLE_FAMILIES],
        "estimate": last_estimate(),
    }
