"""PID lockfile guarding a datadir against concurrent processes
(reference: ``common/lockfile`` — the BN/VC refuse to start on a
locked datadir)."""

from __future__ import annotations

import os


class LockfileError(RuntimeError):
    pass


class Lockfile:
    def __init__(self, path: str):
        self.path = path
        self._held = False

    def acquire(self) -> "Lockfile":
        """O_EXCL creation decides ownership; a stale (dead-pid) lock is
        removed only if its content is unchanged since we read it, so a
        concurrent fresh acquirer's file is never deleted."""
        for _ in range(5):
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                try:
                    with open(self.path) as f:
                        content = f.read()
                except OSError:
                    continue  # holder vanished between open attempts
                try:
                    pid = int(content.strip() or 0)
                except ValueError:
                    pid = 0
                if pid and _pid_alive(pid):
                    raise LockfileError(
                        f"datadir locked by running process {pid} ({self.path})"
                    )
                # stale: remove only if still the same stale content
                try:
                    with open(self.path) as f:
                        if f.read() == content:
                            os.unlink(self.path)
                except OSError:
                    pass
                continue
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            self._held = True
            return self
        raise LockfileError(f"could not acquire {self.path} (contended)")

    def release(self) -> None:
        if self._held:
            try:
                os.unlink(self.path)
            except OSError:
                pass
            self._held = False

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
