"""Slot clocks (reference: ``common/slot_clock`` — trait at
``src/lib.rs:20``, ``SystemTimeSlotClock``, ``ManualSlotClock`` for
tests).

Chain-time axis (ISSUE 17): every instrument in the measurement stack
is keyed on wall-clock, but the workload that matters is keyed on the
beacon chain's slot clock — committee batch-verification cost peaks at
slot and epoch boundaries. This module is the jax-free resolution seam:
genesis-anchored slot AND epoch math, plus a settable process-global
clock (:func:`set_clock`) so replays can map trace-time → slot
deterministically and every ``slot_ledger`` producer attributes to the
same chain time without threading a clock through each call site.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

# Mainnet constants — the defaults a clock gets when the caller does
# not say otherwise. Replays install clocks scaled to their traces.
DEFAULT_SECONDS_PER_SLOT = 12
DEFAULT_SLOTS_PER_EPOCH = 32


class SlotClock:
    def __init__(
        self,
        genesis_time: float,
        seconds_per_slot: float,
        slots_per_epoch: int = DEFAULT_SLOTS_PER_EPOCH,
    ):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self.slots_per_epoch = max(1, int(slots_per_epoch))

    def now(self) -> int:
        """Current slot (0 before genesis)."""
        return self.slot_at(self._unix_time())

    def slot_at(self, t: float) -> int:
        """Slot containing unix time ``t`` (0 before genesis) — the
        genesis-anchored resolution replays use to map a trace
        timestamp onto chain time."""
        if t < self.genesis_time:
            return 0
        return int(t - self.genesis_time) // int(self.seconds_per_slot) \
            if float(self.seconds_per_slot).is_integer() \
            else int((t - self.genesis_time) / self.seconds_per_slot)

    def epoch_of(self, slot: int) -> int:
        """Epoch containing ``slot``."""
        return int(slot) // self.slots_per_epoch

    def epoch_at(self, t: float) -> int:
        return self.epoch_of(self.slot_at(t))

    def current_epoch(self) -> int:
        return self.epoch_of(self.now())

    def first_slot_of_epoch(self, epoch: int) -> int:
        return int(epoch) * self.slots_per_epoch

    def seconds_into_slot(self) -> float:
        t = self._unix_time()
        if t < self.genesis_time:
            return 0.0
        return (t - self.genesis_time) % self.seconds_per_slot

    def start_of(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def duration_to_next_slot(self) -> float:
        return self.start_of(self.now() + 1) - self._unix_time()

    def _unix_time(self) -> float:
        return time.time()


class SystemTimeSlotClock(SlotClock):
    pass


class ManualSlotClock(SlotClock):
    """Test clock: advanced explicitly (reference ManualSlotClock)."""

    def __init__(
        self,
        genesis_time: float = 0,
        seconds_per_slot: float = 12,
        slots_per_epoch: int = DEFAULT_SLOTS_PER_EPOCH,
    ):
        super().__init__(genesis_time, seconds_per_slot, slots_per_epoch)
        self._now = float(genesis_time)

    def set_slot(self, slot: int) -> None:
        self._now = self.start_of(slot)

    def advance_slots(self, n: int = 1) -> None:
        self._now += n * self.seconds_per_slot

    def advance_seconds(self, s: float) -> None:
        self._now += s

    def _unix_time(self) -> float:
        return self._now


# ---------------------------------------------------------------------------
# Process-global clock seam (ISSUE 17)
# ---------------------------------------------------------------------------
#
# The slot ledger's producers (scheduler, transfer ledger, pipeline
# profiler, key table, …) attribute events to "the current slot" — ONE
# clock per process, replaceable for replays. The default is a
# mainnet-parameter system clock anchored at unix epoch 0, so slots are
# globally meaningful absolute numbers until something more specific is
# installed.

_clock_lock = threading.Lock()
_global_clock: Optional[SlotClock] = None


def get_clock() -> SlotClock:
    """The process-global slot clock (created lazily with mainnet
    parameters when nothing was installed)."""
    global _global_clock
    with _clock_lock:
        if _global_clock is None:
            _global_clock = SystemTimeSlotClock(
                genesis_time=0,
                seconds_per_slot=DEFAULT_SECONDS_PER_SLOT,
                slots_per_epoch=DEFAULT_SLOTS_PER_EPOCH,
            )
        return _global_clock


def set_clock(clock: Optional[SlotClock]) -> Optional[SlotClock]:
    """Install ``clock`` as the process-global slot clock (None resets
    to the lazy default); returns the previous clock so callers can
    restore it — the replay drivers' install/restore discipline."""
    global _global_clock
    with _clock_lock:
        prev = _global_clock
        _global_clock = clock
        return prev
