"""Slot clocks (reference: ``common/slot_clock`` — trait at
``src/lib.rs:20``, ``SystemTimeSlotClock``, ``ManualSlotClock`` for
tests)."""

from __future__ import annotations

import time


class SlotClock:
    def __init__(self, genesis_time: int, seconds_per_slot: int):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot

    def now(self) -> int:
        """Current slot (0 before genesis)."""
        t = self._unix_time()
        if t < self.genesis_time:
            return 0
        return int(t - self.genesis_time) // self.seconds_per_slot

    def seconds_into_slot(self) -> float:
        t = self._unix_time()
        if t < self.genesis_time:
            return 0.0
        return (t - self.genesis_time) % self.seconds_per_slot

    def start_of(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def duration_to_next_slot(self) -> float:
        return self.start_of(self.now() + 1) - self._unix_time()

    def _unix_time(self) -> float:
        return time.time()


class SystemTimeSlotClock(SlotClock):
    pass


class ManualSlotClock(SlotClock):
    """Test clock: advanced explicitly (reference ManualSlotClock)."""

    def __init__(self, genesis_time: int = 0, seconds_per_slot: int = 12):
        super().__init__(genesis_time, seconds_per_slot)
        self._now = float(genesis_time)

    def set_slot(self, slot: int) -> None:
        self._now = self.start_of(slot)

    def advance_slots(self, n: int = 1) -> None:
        self._now += n * self.seconds_per_slot

    def advance_seconds(self, s: float) -> None:
        self._now += s

    def _unix_time(self) -> float:
        return self._now
