"""Remote monitoring push (reference ``common/monitoring_api``
``src/lib.rs:63,105``: periodic POST of process + beacon-node health to
a remote monitoring endpoint).

One JSON document per interval::

    {"general": {"version", "timestamp"},
     "process": {"pid", "cpu_process_seconds_total", "memory_process_bytes"},
     "beacon_node": {"head_slot", "finalized_epoch", "peers", "sync_state"}}

A failed push retries with bounded exponential backoff plus jitter
(``base_backoff_s`` doubling up to ``max_backoff_s``) instead of waiting
the full interval — a briefly-down collector misses one document, not
several — and every attempt ticks ``monitoring_push_total{outcome}`` so
a silent push drought is scrapeable.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.request

from . import metrics

VERSION = "lighthouse_tpu/0.4.0"

_PUSH_TOTAL = metrics.counter_vec(
    "monitoring_push_total",
    "remote monitoring push attempts, by outcome (ok/error)",
    ("outcome",),
)


def collect(chain) -> dict:
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        cpu_s = ru.ru_utime + ru.ru_stime
        rss = ru.ru_maxrss * 1024  # linux reports KiB
    except Exception:
        cpu_s, rss = 0.0, 0
    net = getattr(chain, "network", None)
    return {
        "general": {"version": VERSION, "timestamp": int(time.time() * 1000)},
        "process": {
            "pid": os.getpid(),
            "cpu_process_seconds_total": round(cpu_s, 2),
            "memory_process_bytes": rss,
        },
        "beacon_node": {
            "head_slot": int(chain.head_state.slot),
            "finalized_epoch": int(
                chain.fork_choice.store.finalized_checkpoint[0]
            ),
            "peers": net.transport.peer_count() if net is not None else 0,
            "sync_state": "Synced",
        },
    }


class MonitoringService:
    def __init__(
        self,
        chain,
        endpoint: str,
        interval_s: float = 60.0,
        base_backoff_s: float = 1.0,
        max_backoff_s: float | None = None,
    ):
        self.chain = chain
        self.endpoint = endpoint
        self.interval_s = interval_s
        self.base_backoff_s = base_backoff_s
        # retries never wait longer than the regular cadence
        self.max_backoff_s = (
            min(max_backoff_s, interval_s)
            if max_backoff_s is not None
            else interval_s
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self.sent = 0
        self.errors = 0
        self._consecutive_failures = 0

    def start(self) -> "MonitoringService":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def push_once(self) -> bool:
        doc = collect(self.chain)
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                ok = 200 <= r.status < 300
        except Exception:
            ok = False
        if ok:
            self.sent += 1
            _PUSH_TOTAL.with_labels("ok").inc()
        else:
            self.errors += 1
            _PUSH_TOTAL.with_labels("error").inc()
        return ok

    def next_wait(self, consecutive_failures: int) -> float:
        """Seconds until the next push attempt: the regular interval
        after a success, bounded exponential backoff with jitter after
        ``consecutive_failures`` straight failures. Jitter multiplies by
        U[0.5, 1.0] so a fleet of nodes losing one collector does not
        retry in lockstep; the result never exceeds ``max_backoff_s``."""
        if consecutive_failures <= 0:
            return self.interval_s
        backoff = min(
            self.max_backoff_s,
            self.base_backoff_s * (2.0 ** (consecutive_failures - 1)),
        )
        return backoff * random.uniform(0.5, 1.0)

    def _loop(self) -> None:
        wait = self.interval_s
        while not self._stop.wait(wait):
            try:
                ok = self.push_once()
            except Exception:
                # a transient collect/push failure must never kill the
                # monitoring thread for the life of the process
                self.errors += 1
                _PUSH_TOTAL.with_labels("error").inc()
                ok = False
            self._consecutive_failures = (
                0 if ok else self._consecutive_failures + 1
            )
            wait = self.next_wait(self._consecutive_failures)
