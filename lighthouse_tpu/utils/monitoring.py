"""Remote monitoring push (reference ``common/monitoring_api``
``src/lib.rs:63,105``: periodic POST of process + beacon-node health to
a remote monitoring endpoint).

One JSON document per interval::

    {"general": {"version", "timestamp"},
     "process": {"pid", "cpu_process_seconds_total", "memory_process_bytes"},
     "beacon_node": {"head_slot", "finalized_epoch", "peers", "sync_state"}}
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

VERSION = "lighthouse_tpu/0.4.0"


def collect(chain) -> dict:
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        cpu_s = ru.ru_utime + ru.ru_stime
        rss = ru.ru_maxrss * 1024  # linux reports KiB
    except Exception:
        cpu_s, rss = 0.0, 0
    net = getattr(chain, "network", None)
    return {
        "general": {"version": VERSION, "timestamp": int(time.time() * 1000)},
        "process": {
            "pid": os.getpid(),
            "cpu_process_seconds_total": round(cpu_s, 2),
            "memory_process_bytes": rss,
        },
        "beacon_node": {
            "head_slot": int(chain.head_state.slot),
            "finalized_epoch": int(
                chain.fork_choice.store.finalized_checkpoint[0]
            ),
            "peers": net.transport.peer_count() if net is not None else 0,
            "sync_state": "Synced",
        },
    }


class MonitoringService:
    def __init__(self, chain, endpoint: str, interval_s: float = 60.0):
        self.chain = chain
        self.endpoint = endpoint
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self.sent = 0
        self.errors = 0

    def start(self) -> "MonitoringService":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def push_once(self) -> bool:
        doc = collect(self.chain)
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                ok = 200 <= r.status < 300
        except Exception:
            ok = False
        if ok:
            self.sent += 1
        else:
            self.errors += 1
        return ok

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.push_once()
            except Exception:
                # a transient collect/push failure must never kill the
                # monitoring thread for the life of the process
                self.errors += 1
