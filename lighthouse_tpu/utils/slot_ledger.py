"""Slot ledger: a bounded, thread-safe per-slot rollup store — the
chain-time axis of the measurement stack (reference: Lighthouse's
``validator_monitor`` attributes per-epoch summaries to registered
validators; committee-consensus measurement work shows batch-verification
cost peaks exactly at slot and epoch boundaries, so wall-clock windows
smear the signal the operator needs).

Every instrument the node has — SLO windows, transfer ledger, pipeline
profiler, capacity timeseries — answers "how is the node doing *lately*";
this module answers "how did the node do in *slot N*": every scheduler
resolution, deadline miss, journal rejection, H2D byte total, bubble
interval and headroom sample lands in its slot's **report card** (per-kind
sets/verdicts/misses, in-slot p99, min headroom, bytes moved, fresh
compiles, bulk admitted/parked), with epoch-level aggregation on top that
tracks per-committee aggregate-cache behavior — a committee seen for the
first time (host EC sum paid) vs a collapsed K=1 hit — minting the
``key_table_first_sighting_hit_ratio{epoch}`` gauge, ROADMAP item 3's
go/no-go dial.

Design constraints (same discipline as :mod:`utils.tracing`,
:mod:`utils.flight_recorder`, :mod:`utils.transfer_ledger`):

* jax-free import: tools and the HTTP surface render report cards on
  hosts with no accelerator stack.
* DISABLED attribution must cost well under 1 microsecond per call —
  every ``note_*`` returns after one global check, no allocation
  (``tests/test_slot_ledger.py`` pins this).
* Enabled attribution is O(1) amortized: one dict update under one lock.
  Retention is bounded (``max_slots`` cards, ``max_epochs`` epoch rows);
  evicted cards fold into eviction totals so **lifetime conservation
  holds**: for every counter, sum(retained cards) + evicted == lifetime
  (the exactness tests pin this, including under 8 writer threads).
* Attribution is exactly-once by construction: each producer hooks the
  single point its event is finalized (e.g. the batcher's
  ``_observe_latency``), never the per-path branches above it.

Chain time comes from :mod:`utils.slot_clock`'s process-global clock
unless the caller passes ``slot=`` explicitly (replays resolve slots
from virtual trace time and pass them in).

Env knobs (read at import; :func:`configure` overrides at runtime):

    LIGHTHOUSE_TPU_SLOT_LEDGER        1|0   attribute events (default 1)
    LIGHTHOUSE_TPU_SLOT_LEDGER_SLOTS  int   report cards retained (default 64)
    LIGHTHOUSE_TPU_SLOT_LEDGER_EPOCHS int   epoch rows retained (default 64)
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from . import metrics
from . import slot_clock

SCHEMA = "lighthouse_tpu.slot_ledger/1"

# In-slot latency reservoir cap: enough for exact p99 at any realistic
# per-slot arrival rate; beyond it the card keeps counting but stops
# sampling (sampled count is reported so a truncated p99 is visible).
LATENCY_SAMPLE_CAP = 4096

# The event catalogue for slot_ledger_events_total — one label value per
# note_* family, documented in docs/OBSERVABILITY.md (linted).
EVENTS = (
    "bubble",
    "bulk",
    "fresh_compile",
    "h2d",
    "headroom",
    "lookahead",
    "rejection",
    "resolution",
    "sighting",
)

_SLOTS_RETAINED = metrics.gauge(
    "slot_ledger_slots",
    "per-slot report cards currently retained by the slot ledger",
)
_EVICTED_TOTAL = metrics.counter(
    "slot_ledger_evicted_total",
    "report cards evicted by slot-ledger retention (folded into "
    "eviction totals, so lifetime conservation still holds)",
)
_EVENTS_TOTAL = metrics.counter_vec(
    "slot_ledger_events_total",
    "events attributed to a slot report card, by event family "
    "(see docs/OBSERVABILITY.md)",
    ("event",),
)
_FIRST_SIGHTING_RATIO = metrics.gauge_vec(
    "key_table_first_sighting_hit_ratio",
    "per-epoch committee aggregate-cache collapse ratio: collapsed K=1 "
    "hits / (first sightings + hits). ROADMAP item 3's go/no-go dial",
    ("epoch",),
)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


_enabled = os.environ.get("LIGHTHOUSE_TPU_SLOT_LEDGER", "1") not in ("", "0")
_max_slots = max(1, _env_int("LIGHTHOUSE_TPU_SLOT_LEDGER_SLOTS", 64))
_max_epochs = max(1, _env_int("LIGHTHOUSE_TPU_SLOT_LEDGER_EPOCHS", 64))

_lock = threading.RLock()

# slot -> report card dict (see _new_card for the schema)
_cards: Dict[int, dict] = {}
# epoch -> {"first": int, "hits": int}
_epochs: Dict[int, dict] = {}

# Names of the card counters that must conserve: for each,
# sum over retained cards + _evicted[name] == _lifetime[name].
_COUNTERS = (
    "sets",
    "verdicts",
    "misses",
    "rejections",
    "h2d_bytes",
    "fresh_compiles",
    "bulk_admitted_sets",
    "bulk_parked_sets",
    "sightings_first",
    "sightings_hit",
    "lookahead_committees",
    "lookahead_host_sums",
    "lookahead_device_sums",
)


def _zero_totals() -> Dict[str, float]:
    t: Dict[str, float] = {k: 0 for k in _COUNTERS}
    t["bubble_s"] = 0.0
    return t


_lifetime = _zero_totals()
_evicted = _zero_totals()
_evicted_cards = 0


def _new_card(slot: int, epoch: int) -> dict:
    return {
        "slot": slot,
        "epoch": epoch,
        # kind -> {"sets", "verdicts", "misses"}
        "kinds": {},
        "sets": 0,
        "verdicts": 0,
        "misses": 0,
        # kind -> count (journal *_rejected events)
        "rejected": {},
        "rejections": 0,
        "h2d_bytes": 0,
        "bubble_s": 0.0,
        "fresh_compiles": 0,
        "bulk_admitted_sets": 0,
        "bulk_parked_sets": 0,
        "sightings_first": 0,
        "sightings_hit": 0,
        "lookahead_committees": 0,
        "lookahead_host_sums": 0,
        "lookahead_device_sums": 0,
        "headroom_min": None,
        "headroom_samples": 0,
        "_lat_ms": [],  # capped reservoir, exact until the cap
        "lat_samples": 0,
    }


def _resolve(slot: Optional[int]) -> Tuple[int, int]:
    """(slot, epoch) for an attribution: explicit slot, else the
    process-global clock's current slot."""
    clock = slot_clock.get_clock()
    s = clock.now() if slot is None else int(slot)
    return s, clock.epoch_of(s)


def _card(slot: int, epoch: int) -> dict:
    """Card for ``slot``, creating + applying retention. Caller holds
    the lock."""
    card = _cards.get(slot)
    if card is None:
        card = _new_card(slot, epoch)
        _cards[slot] = card
        while len(_cards) > _max_slots:
            _evict(min(_cards))
        _SLOTS_RETAINED.set(len(_cards))
    return card


def _evict(slot: int) -> None:
    """Fold the evicted card's counters into the eviction totals so
    lifetime conservation survives retention. Caller holds the lock."""
    global _evicted_cards
    card = _cards.pop(slot)
    for k in _COUNTERS:
        _evicted[k] += card[k]
    _evicted["bubble_s"] += card["bubble_s"]
    _evicted_cards += 1
    _EVICTED_TOTAL.inc()


# ---------------------------------------------------------------------------
# Producers (one note_* per attribution point)
# ---------------------------------------------------------------------------


def note_resolution(
    kind: str,
    path: str,
    n_sets: int,
    latency_s: float,
    missed: bool = False,
    qos: str = "deadline",
    slot: Optional[int] = None,
) -> None:
    """One scheduler resolution — hooked at the batcher's single
    accounting point (``_observe_latency``) so bisection/shed/bulk paths
    cannot double-count."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        _update_resolution(_card(s, e), kind, n_sets, latency_s, missed)
        _lifetime["sets"] += n_sets
        _lifetime["verdicts"] += 1
        if missed:
            _lifetime["misses"] += 1
    _EVENTS_TOTAL.with_labels("resolution").inc()


def _update_resolution(
    card: dict, kind: str, n_sets: int, latency_s: float, missed: bool
) -> None:
    per = card["kinds"].get(kind)
    if per is None:
        per = {"sets": 0, "verdicts": 0, "misses": 0}
        card["kinds"][kind] = per
    per["sets"] += n_sets
    per["verdicts"] += 1
    card["sets"] += n_sets
    card["verdicts"] += 1
    if missed:
        per["misses"] += 1
        card["misses"] += 1
    card["lat_samples"] += 1
    if len(card["_lat_ms"]) < LATENCY_SAMPLE_CAP:
        card["_lat_ms"].append(latency_s * 1000.0)


def note_rejection(kind: str, slot: Optional[int] = None) -> None:
    """One journal rejection (``*_rejected`` flight-recorder kinds)."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        card = _card(s, e)
        card["rejected"][kind] = card["rejected"].get(kind, 0) + 1
        card["rejections"] += 1
        _lifetime["rejections"] += 1
    _EVENTS_TOTAL.with_labels("rejection").inc()


def note_h2d_bytes(n: int, slot: Optional[int] = None) -> None:
    """Host-to-device bytes committed by the transfer ledger."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        _card(s, e)["h2d_bytes"] += n
        _lifetime["h2d_bytes"] += n
    _EVENTS_TOTAL.with_labels("h2d").inc()


def note_bubble(seconds: float, slot: Optional[int] = None) -> None:
    """One pipeline bubble interval (profiler idle-gap attribution)."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        _card(s, e)["bubble_s"] += seconds
        _lifetime["bubble_s"] += seconds
    _EVENTS_TOTAL.with_labels("bubble").inc()


def note_headroom(ratio: float, slot: Optional[int] = None) -> None:
    """One headroom estimate sample; the card keeps the slot minimum —
    the worst moment inside the slot, not an average over it."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        card = _card(s, e)
        if card["headroom_min"] is None or ratio < card["headroom_min"]:
            card["headroom_min"] = float(ratio)
        card["headroom_samples"] += 1
    _EVENTS_TOTAL.with_labels("headroom").inc()


def note_fresh_compile(stage: Optional[str] = None, slot: Optional[int] = None) -> None:
    """One fresh XLA compile observed inside the slot (stage wall-time
    attributed with ``fresh=True``)."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        _card(s, e)["fresh_compiles"] += 1
        _lifetime["fresh_compiles"] += 1
    _EVENTS_TOTAL.with_labels("fresh_compile").inc()


def note_bulk(
    admitted_sets: int = 0, parked_sets: int = 0, slot: Optional[int] = None
) -> None:
    """Bulk-class admission outcome: sets admitted through the governor
    vs parked (throttled) by a headroom excursion."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        card = _card(s, e)
        card["bulk_admitted_sets"] += admitted_sets
        card["bulk_parked_sets"] += parked_sets
        _lifetime["bulk_admitted_sets"] += admitted_sets
        _lifetime["bulk_parked_sets"] += parked_sets
    _EVENTS_TOTAL.with_labels("bulk").inc()


def note_lookahead(
    committees: int = 0,
    host_sums: int = 0,
    device_sums: int = 0,
    slot: Optional[int] = None,
) -> None:
    """Duty-lookahead precompute work attributed to the slot it ran in
    (ISSUE 19) — committees warmed for a FUTURE epoch, split by the sum
    path that produced each aggregate row (device MSM vs host EC fold).
    The point of the attribution: precompute cost lands visibly in the
    quiet mid-epoch slots that paid it, and stays OUT of the verify-span
    accounting — an epoch row whose sightings are all hits while its
    slots carry ``lookahead_committees`` is the zero-host-sums-in-verify
    acceptance shape, pinned by the replay gate."""
    if not _enabled:
        return
    s, e = _resolve(slot)
    with _lock:
        card = _card(s, e)
        card["lookahead_committees"] += committees
        card["lookahead_host_sums"] += host_sums
        card["lookahead_device_sums"] += device_sums
        _lifetime["lookahead_committees"] += committees
        _lifetime["lookahead_host_sums"] += host_sums
        _lifetime["lookahead_device_sums"] += device_sums
    _EVENTS_TOTAL.with_labels("lookahead").inc()


def note_committee_sighting(outcome: str, slot: Optional[int] = None) -> None:
    """One committee-aggregate consult: ``"first"`` (host EC sum paid —
    the key table had no collapsed row) or ``"hit"`` (collapsed K=1 row
    served). Conservation: first + hits == committee sightings, and the
    per-epoch ``key_table_first_sighting_hit_ratio`` gauge is minted from
    exactly these two counters — an honest denominator by construction."""
    if not _enabled:
        return
    if outcome not in ("first", "hit"):
        raise ValueError(f"sighting outcome must be 'first' or 'hit', got {outcome!r}")
    s, e = _resolve(slot)
    with _lock:
        card = _card(s, e)
        row = _epochs.get(e)
        if row is None:
            row = {"first": 0, "hits": 0}
            _epochs[e] = row
            while len(_epochs) > _max_epochs:
                del _epochs[min(_epochs)]
        if outcome == "first":
            card["sightings_first"] += 1
            _lifetime["sightings_first"] += 1
            row["first"] += 1
        else:
            card["sightings_hit"] += 1
            _lifetime["sightings_hit"] += 1
            row["hits"] += 1
        total = row["first"] + row["hits"]
        ratio = row["hits"] / total if total else 0.0
    _FIRST_SIGHTING_RATIO.with_labels(str(e)).set(ratio)
    _EVENTS_TOTAL.with_labels("sighting").inc()


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------


def _quantile_ms(samples: List[float], q: float) -> float:
    """Nearest-rank quantile over raw ms samples (local copy of the SLO
    window's rule — the ledger must stay importable without the
    verification service)."""
    if not samples:
        return 0.0
    xs = sorted(samples)
    idx = max(0, min(len(xs) - 1, int(q * len(xs) + 0.999999) - 1))
    return xs[idx]


def _render_card(card: dict) -> dict:
    """Public report-card view: raw reservoir replaced by its quantiles."""
    out = {k: v for k, v in card.items() if not k.startswith("_")}
    out["kinds"] = {k: dict(v) for k, v in card["kinds"].items()}
    out["rejected"] = dict(card["rejected"])
    lat = card["_lat_ms"]
    out["p50_ms"] = round(_quantile_ms(lat, 0.50), 3)
    out["p99_ms"] = round(_quantile_ms(lat, 0.99), 3)
    out["lat_sampled"] = len(lat)
    return out


def slot_cards(last: Optional[int] = None) -> List[dict]:
    """Retained report cards, ascending by slot; ``last`` keeps only the
    newest N."""
    with _lock:
        slots = sorted(_cards)
        if last is not None:
            slots = slots[-max(0, int(last)):] if last > 0 else []
        return [_render_card(_cards[s]) for s in slots]


def epoch_cards(last: Optional[int] = None) -> List[dict]:
    """Epoch rows (first sightings / hits / ratio), ascending by epoch."""
    with _lock:
        epochs = sorted(_epochs)
        if last is not None:
            epochs = epochs[-max(0, int(last)):] if last > 0 else []
        out = []
        for e in epochs:
            row = _epochs[e]
            total = row["first"] + row["hits"]
            out.append(
                {
                    "epoch": e,
                    "first_sightings": row["first"],
                    "hits": row["hits"],
                    "sightings": total,
                    "hit_ratio": round(row["hits"] / total, 4) if total else 0.0,
                }
            )
        return out


def lifetime_totals() -> dict:
    """Lifetime counters (conservation: retained + evicted == these)."""
    with _lock:
        return dict(_lifetime)


def evicted_totals() -> dict:
    with _lock:
        return dict(_evicted)


def summary() -> dict:
    """The health endpoint's ``chain_time`` block: clock parameters,
    retention state, lifetime totals and the newest epoch's dial."""
    clock = slot_clock.get_clock()
    with _lock:
        retained = len(_cards)
        evicted_cards = _evicted_cards
        lifetime = dict(_lifetime)
        newest = max(_epochs) if _epochs else None
        row = dict(_epochs[newest]) if newest is not None else None
    doc = {
        "enabled": _enabled,
        "current_slot": clock.now(),
        "current_epoch": clock.current_epoch(),
        "seconds_per_slot": clock.seconds_per_slot,
        "slots_per_epoch": clock.slots_per_epoch,
        "slots_retained": retained,
        "max_slots": _max_slots,
        "cards_evicted": evicted_cards,
        "lifetime": lifetime,
    }
    if row is not None:
        total = row["first"] + row["hits"]
        doc["latest_epoch"] = {
            "epoch": newest,
            "first_sightings": row["first"],
            "hits": row["hits"],
            "hit_ratio": round(row["hits"] / total, 4) if total else 0.0,
        }
    return doc


# ---------------------------------------------------------------------------
# Control
# ---------------------------------------------------------------------------


def enabled() -> bool:
    return _enabled


def configure(
    enabled: Optional[bool] = None,
    max_slots: Optional[int] = None,
    max_epochs: Optional[int] = None,
) -> dict:
    """Override settings at runtime; returns the PREVIOUS values so
    callers (tests, replay drivers) restore with ``configure(**prev)``.
    Shrinking ``max_slots`` applies retention immediately."""
    global _enabled, _max_slots, _max_epochs
    prev = {
        "enabled": _enabled,
        "max_slots": _max_slots,
        "max_epochs": _max_epochs,
    }
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if max_slots is not None:
            _max_slots = max(1, int(max_slots))
            while len(_cards) > _max_slots:
                _evict(min(_cards))
            _SLOTS_RETAINED.set(len(_cards))
        if max_epochs is not None:
            _max_epochs = max(1, int(max_epochs))
            while len(_epochs) > _max_epochs:
                del _epochs[min(_epochs)]
    return prev


def reset() -> None:
    """Drop every card, epoch row and total (retention knobs unchanged)."""
    global _lifetime, _evicted, _evicted_cards
    with _lock:
        _cards.clear()
        _epochs.clear()
        _lifetime = _zero_totals()
        _evicted = _zero_totals()
        _evicted_cards = 0
        _SLOTS_RETAINED.set(0)


# ---------------------------------------------------------------------------
# Committee sighting model (replay-side)
# ---------------------------------------------------------------------------


class CommitteeSightingModel:
    """jax-free mirror of the key table's aggregate-cache admission
    policy, for replays where no device key table exists (stub /
    cpu-native backends never call ``resolve_sets``): a committee
    validator-index tuple is a collapsed **hit** only once it has been
    seen ``min_repeats`` times before (the table inserts a candidate at
    its ``min_repeats``-th miss — sighting 1 is a first, sighting 2 is
    the first+insert, sighting 3+ are hits, matching
    ``DEFAULT_AGG_MIN_REPEATS = 2``). Feeds the same
    :func:`note_committee_sighting` dial as the real table."""

    def __init__(self, min_repeats: int = 2):
        self.min_repeats = max(1, int(min_repeats))
        self._seen: Dict[Tuple[int, ...], int] = {}
        self.first = 0
        self.hits = 0
        self.prewarmed = 0

    def prewarm(self, committees) -> int:
        """Duty-lookahead admission (ISSUE 19): mark each committee
        tuple as already satisfying the repeat threshold — the model
        mirror of ``DeviceKeyTable.insert_precomputed``, which bypasses
        ``agg_min_repeats`` for lookahead-sourced tuples. A prewarmed
        tuple's FIRST observe is a hit (K=1 shipped, no host EC sum in
        any verify span). Warming is not a sighting: nothing is noted to
        the ledger here — the lookahead worker attributes its own work
        via :func:`note_lookahead`. Returns tuples newly warmed."""
        n = 0
        for c in committees:
            key = tuple(int(v) for v in c)
            if self._seen.get(key, 0) < self.min_repeats:
                self._seen[key] = self.min_repeats
                n += 1
        self.prewarmed += n
        return n

    def observe(self, committee, slot: Optional[int] = None) -> str:
        key = tuple(int(v) for v in committee)
        prior = self._seen.get(key, 0)
        self._seen[key] = prior + 1
        outcome = "hit" if prior >= self.min_repeats else "first"
        if outcome == "hit":
            self.hits += 1
        else:
            self.first += 1
        note_committee_sighting(outcome, slot=slot)
        return outcome

    def hit_ratio(self) -> float:
        total = self.first + self.hits
        return self.hits / total if total else 0.0
