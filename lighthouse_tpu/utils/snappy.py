"""Snappy codec: raw block format + framing format.

Used by the ef_tests harness (``.ssz_snappy`` vector files) and the
networking layer's SSZ-snappy encodings (reference: gossip payloads use
raw snappy blocks; req/resp streams use the framing format —
``lighthouse_network/src/rpc/codec/ssz_snappy.rs``).

The raw-block hot path (every gossip frame) prefers the NATIVE C codec
(``_native/snappy.c`` — real hash-match compression, the algorithm the
reference gets from the Rust ``snap`` crate); the pure-Python
implementation remains as fallback and as the framing-format layer.
"""

from __future__ import annotations

import struct

_FRAME_MAGIC = b"\xff\x06\x00\x00sNaPpY"


class SnappyError(ValueError):
    pass


# ---------------------------------------------------------------------------
# varint
# ---------------------------------------------------------------------------

def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise SnappyError("varint too long")


def _write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


# ---------------------------------------------------------------------------
# raw block format
# ---------------------------------------------------------------------------

def _native_lib():
    global _NATIVE
    if _NATIVE is _UNSET:
        import ctypes

        from .._native import build_and_load

        lib = build_and_load("snappy")
        if lib is not None:
            lib.lt_snappy_max_compressed.restype = ctypes.c_size_t
            lib.lt_snappy_max_compressed.argtypes = [ctypes.c_size_t]
            lib.lt_snappy_compress.restype = ctypes.c_size_t
            lib.lt_snappy_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ]
            lib.lt_snappy_uncompressed_length.restype = ctypes.c_long
            lib.lt_snappy_uncompressed_length.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.lt_snappy_decompress.restype = ctypes.c_long
            lib.lt_snappy_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
        _NATIVE = lib
    return _NATIVE


_UNSET = object()
_NATIVE = _UNSET


def decompress_raw(data: bytes) -> bytes:
    """Snappy raw (frame-less) block (native fast path)."""
    lib = _native_lib()
    if lib is not None:
        import ctypes

        want = lib.lt_snappy_uncompressed_length(data, len(data))
        # An attacker controls the length header: allocate only what a
        # VALID stream of this size could produce (a 3-byte copy element
        # emits <= 64 bytes, so expansion is < 64x + slack) — a 5-byte
        # frame claiming 2 GiB must fail before any big allocation.
        if want < 0 or want > 64 * len(data) + 64:
            raise SnappyError("bad uncompressed length")
        buf = ctypes.create_string_buffer(max(int(want), 1))
        got = lib.lt_snappy_decompress(data, len(data), buf, want)
        if got < 0:
            raise SnappyError("malformed snappy block")
        return ctypes.string_at(buf, got)
    return _decompress_raw_py(data)


def _decompress_raw_py(data: bytes) -> bytes:
    """Snappy raw (frame-less) block, pure Python."""
    expected, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            length += 1
            if pos + length > n:
                raise SnappyError("truncated literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            if pos >= n:
                raise SnappyError("truncated copy-1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("truncated copy-2")
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("truncated copy-4")
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("invalid copy offset")
        # overlapping copies are the point (RLE-style); copy byte-wise
        start = len(out) - offset
        for i in range(length):
            out.append(out[start + i])
    if len(out) != expected:
        raise SnappyError(
            f"decompressed length {len(out)} != header {expected}"
        )
    return bytes(out)


def compress_raw(data: bytes) -> bytes:
    """Raw block (native hash-match compression when available)."""
    lib = _native_lib()
    if lib is not None:
        import ctypes

        cap = lib.lt_snappy_max_compressed(len(data))
        buf = ctypes.create_string_buffer(int(cap))
        n = lib.lt_snappy_compress(data, len(data), buf)
        return ctypes.string_at(buf, n)
    return _compress_raw_py(data)


def _compress_raw_py(data: bytes) -> bytes:
    """Literal-only raw block (valid per the format spec)."""
    out = bytearray(_write_uvarint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + 65536]
        pos += len(chunk)
        L = len(chunk) - 1
        if L < 60:
            out.append(L << 2)
        elif L < 1 << 8:
            out.append(60 << 2)
            out.append(L)
        elif L < 1 << 16:
            out.append(61 << 2)
            out += L.to_bytes(2, "little")
        else:
            out.append(62 << 2)
            out += L.to_bytes(3, "little")
        out += chunk
    return bytes(out)


# ---------------------------------------------------------------------------
# framing format
# ---------------------------------------------------------------------------

_CRC32C_TABLE = None


def _crc32c(data: bytes) -> int:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    crc ^= 0xFFFFFFFF
    # snappy frame "masked" crc
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def decompress_frames(data: bytes) -> bytes:
    """Snappy framing format stream."""
    if not data.startswith(_FRAME_MAGIC):
        raise SnappyError("missing stream identifier")
    pos = len(_FRAME_MAGIC)
    out = bytearray()
    n = len(data)
    while pos < n:
        if pos + 4 > n:
            raise SnappyError("truncated chunk header")
        kind = data[pos]
        length = int.from_bytes(data[pos + 1:pos + 4], "little")
        pos += 4
        chunk = data[pos:pos + length]
        if len(chunk) != length:
            raise SnappyError("truncated chunk body")
        pos += length
        if kind == 0x00:  # compressed data
            body = decompress_raw(chunk[4:])
            _check_crc(chunk[:4], body)
            out += body
        elif kind == 0x01:  # uncompressed data
            body = chunk[4:]
            _check_crc(chunk[:4], body)
            out += body
        elif kind == 0xFF:  # stream identifier (repeated)
            continue
        elif 0x80 <= kind <= 0xFE:  # skippable padding (0xFE = spec padding chunk)
            continue
        else:
            raise SnappyError(f"unknown chunk type 0x{kind:02x}")
    return bytes(out)


def _check_crc(crc_bytes: bytes, body: bytes) -> None:
    want = int.from_bytes(crc_bytes, "little")
    got = _crc32c(body)
    if want != got:
        raise SnappyError("frame CRC mismatch")


def compress_frames(data: bytes) -> bytes:
    out = bytearray(_FRAME_MAGIC)
    pos = 0
    while pos < len(data):
        body = data[pos:pos + 65536]
        pos += len(body)
        comp = compress_raw(body)
        payload = struct.pack("<I", _crc32c(body)) + comp
        out.append(0x00)
        out += len(payload).to_bytes(3, "little")
        out += payload
    return bytes(out)


def decompress(data: bytes) -> bytes:
    """Sniff frame magic vs raw block."""
    if data.startswith(_FRAME_MAGIC):
        return decompress_frames(data)
    return decompress_raw(data)
