"""The watchtower: online anomaly detection over the node's own
timeseries, with latched incidents and correlated forensic bundles
(ISSUE 18).

Every instrument the node grew so far — metrics/spans, the flight
recorder, the timeseries store + capacity estimator (ISSUE 14), the
pipeline profiler, the slot ledger (ISSUE 17) — is a dial a HUMAN
reads after the fact. This module is the thing that watches them: a
background evaluator walks a declared **detector catalogue**
(:data:`DETECTORS`, linted like ``EVENT_KINDS``) over the timeseries
store and the slot ledger, and a firing detector latches an
**incident** plus one correlated forensic capture, turning PR 14's
one-off "headroom crossed its floor 2.5 s before the first miss
burst" reading into a standing, self-certifying alarm (the always-on
verification posture of the FPGA verification-engine monitor plane,
PAPERS.md arxiv 2112.02229).

* **Detector catalogue** — each detector declares name, input series
  (a ``series:<family>`` read from the timeseries store, or a
  ``probe:<name>`` computed from the registry / slot ledger), window,
  threshold, and severity (``info``/``warn``/``page``). Algorithms:
  ``zscore`` (rolling-window drift baseline: deviation must clear BOTH
  ``threshold`` standard deviations and an absolute ``min_delta`` —
  a flat baseline cannot page on noise), ``floor``/``ceil`` (level
  crossing with a hysteresis ``clear`` level), ``roc`` (rate of
  change per second over the window). The catalogue is sorted,
  snake_case, and every detector is documented in
  docs/OBSERVABILITY.md — all linted by
  tests/test_zgate4_metrics_lint.py.
* **Latched incidents, not spam.** A breach must persist ``sustain``
  consecutive evaluations to open an incident; a sustained breach is
  ONE incident with a growing duration; clearing enters a cooldown
  during which a re-breach REOPENS the same incident (a flap, not a
  new row). The ledger is bounded (``max_incidents``; old rows
  evicted, never reallocated).
* **Correlated capture.** Opening an incident writes one
  atomically-written JSON bundle (schema :data:`SCHEMA` =
  ``lighthouse_tpu.incident/1``): the flight-recorder tail, the
  relevant timeseries windows (± ``margin_s``), the newest slot
  report cards, pipeline-profiler attribution, the capacity block,
  any registered health provider's document, and the detector's own
  trigger trace (value, baseline, gate). Resolution atomically
  rewrites the same bundle so the post-margin window and the final
  duration land in the artifact. ``tools/incident_report.py`` renders
  a bundle into a human timeline; ``tools/forensics_report.py`` and
  ``tools/slot_report.py`` accept the same artifact.

Surfaces: ``GET /lighthouse/incidents``, the ``watchtower`` block of
``/lighthouse/health`` (per-detector state
``armed``/``firing``/``latched``/``cooldown``), ``watchtower_*``
metric families, ``incident_opened``/``incident_resolved`` journal
kinds, and ``tools/traffic_replay.py --watchtower`` which measures
**detection lead time** (incident-open vs the first deadline-miss
burst) as a first-class replay output.

Design constraints (the house observability discipline):

* jax-free at import (tools read bundles offline; subprocess-pinned).
* DISABLED :func:`evaluate` costs well under 1 µs — one global check,
  no allocation (pinned like disabled spans).
* Thread-safe: detector/incident state mutates under one lock; any
  number of threads may call :func:`evaluate` while writers hammer
  the store. Journal writes and bundle I/O happen OUTSIDE the lock.

Env knobs (read at import; :func:`configure` overrides at runtime):

    LIGHTHOUSE_TPU_WATCHTOWER        1|0   evaluation enabled (default 1)
    LIGHTHOUSE_TPU_WT_INTERVAL_S     float evaluator period (default 2)
    LIGHTHOUSE_TPU_WT_COOLDOWN_S     float post-resolve reopen window (30)
    LIGHTHOUSE_TPU_WT_MAX_INCIDENTS  int   incident ledger bound (64)
    LIGHTHOUSE_TPU_WT_BUNDLE         1|0   write incident bundles (1)
    LIGHTHOUSE_TPU_WT_BUNDLE_DIR     path  bundle directory (tempdir)
    LIGHTHOUSE_TPU_WT_BUNDLE_RETAIN  int   newest bundles kept (8)
    LIGHTHOUSE_TPU_WT_MARGIN_S       float timeseries pre/post margin (10)
    LIGHTHOUSE_TPU_WT_FLIGHT_TAIL    int   journal events per bundle (256)
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import flight_recorder, metrics, slot_ledger, timeseries

SCHEMA = "lighthouse_tpu.incident/1"
BUNDLE_PREFIX = "lighthouse_tpu_incident_"

SEVERITIES = ("info", "warn", "page")
ALGOS = ("ceil", "floor", "roc", "zscore")
# the per-detector lifecycle /lighthouse/health shows; the gauge code
# for watchtower_detector_state uses the same order (armed=0 firing=1
# latched=2 cooldown=3)
STATES = ("armed", "firing", "latched", "cooldown")
_STATE_CODE = {s: i for i, s in enumerate(STATES)}
# "worst across labels" ordering for the health roll-up (an actively
# breaching label outranks a latched one outranks a cooling one)
_STATE_RANK = {"armed": 0, "cooldown": 1, "latched": 2, "firing": 3}

_env_int = flight_recorder._env_int
_env_float = flight_recorder._env_float


# ---------------------------------------------------------------------------
# The detector catalogue: sorted, snake_case, every entry documented in
# docs/OBSERVABILITY.md (linted by tests/test_zgate4_metrics_lint.py —
# an undeclared detector cannot silently appear)
# ---------------------------------------------------------------------------


class DetectorSpec:
    __slots__ = ("name", "algo", "source", "window_s", "threshold",
                 "clear", "direction", "min_points", "min_delta",
                 "sustain", "severity", "doc")

    def __init__(self, name: str, algo: str, source: str, window_s: float,
                 threshold: float, severity: str, doc: str,
                 clear: Optional[float] = None, direction: str = "above",
                 min_points: int = 4, min_delta: float = 0.0,
                 sustain: int = 1):
        self.name = name
        self.algo = algo
        self.source = source
        self.window_s = window_s
        self.threshold = threshold
        self.clear = clear
        self.direction = direction
        self.min_points = min_points
        self.min_delta = min_delta
        self.sustain = sustain
        self.severity = severity
        self.doc = doc


DETECTORS: Tuple[DetectorSpec, ...] = (
    DetectorSpec(
        "bubble_share_jump", "zscore",
        "series:capacity_shard_bubble_ratio",
        window_s=300.0, threshold=4.0, min_points=8, min_delta=0.15,
        sustain=2, severity="warn",
        doc="a shard's pipeline bubble share jumping out of its own "
            "recent baseline — overlap lost to serialized flushes",
    ),
    DetectorSpec(
        "first_sighting_hit_regression", "floor",
        "series:slot_first_sighting_hit_ratio",
        window_s=900.0, threshold=0.9, clear=0.97, min_points=1,
        sustain=2, severity="warn",
        doc="the per-epoch committee first-sighting hit ratio crossing "
            "below its floor — duty-lookahead (ISSUE 19) holds the "
            "steady state at ~1.0, so a drop means epoch warms are "
            "failing (or the aggregate cache is collapsing) and first "
            "sightings are paying host EC sums again; the incident "
            "bundle's health snapshot carries the duty_lookahead block "
            "for direct attribution",
    ),
    DetectorSpec(
        "headroom_floor", "floor", "series:capacity_headroom_ratio",
        window_s=120.0, threshold=0.2, clear=0.35, min_points=1,
        sustain=2, severity="page",
        doc="capacity headroom crossing below its floor (the PR 14 "
            "predictive dial: crossing PRECEDES the first deadline-"
            "miss burst on a saturation ramp); hysteresis resolves "
            "only above the clear level",
    ),
    DetectorSpec(
        "pack_share_drift", "zscore", "probe:pack_share",
        window_s=600.0, threshold=4.0, min_points=8, min_delta=0.1,
        sustain=2, severity="info",
        doc="host-side pack share of device verify wall drifting up — "
            "the host is becoming the bottleneck",
    ),
    DetectorSpec(
        "recompile_burst", "ceil", "series:capacity_recompiles_per_sec",
        window_s=120.0, threshold=0.5, clear=0.1, min_points=1,
        sustain=2, severity="warn",
        doc="device recompiles per second above the burst ceiling — "
            "traffic is escaping the padded rung ladder",
    ),
    DetectorSpec(
        "reupload_ratio_regression", "zscore",
        "series:capacity_pubkey_reupload_ratio",
        window_s=900.0, threshold=4.0, min_points=8, min_delta=0.1,
        sustain=2, severity="info",
        doc="the repeat-pubkey reupload ratio rising out of baseline — "
            "the device key-table dedup losing its hit rate",
    ),
    DetectorSpec(
        "slo_burn_spike", "roc", "series:capacity_slo_burn_rate",
        window_s=60.0, threshold=0.2, min_points=3, sustain=1,
        severity="page",
        doc="SLO miss-budget burn rate rising faster than the "
            "rate-of-change ceiling (budget/s) — sustained misses "
            "are seconds away",
    ),
    DetectorSpec(
        "verdict_p99_drift", "zscore", "probe:verdict_p99_ms",
        window_s=600.0, threshold=4.0, min_points=8, min_delta=10.0,
        sustain=2, severity="warn",
        doc="the in-slot verdict-latency p99 (slot-ledger report "
            "cards) drifting above its own recent baseline",
    ),
)


# ---------------------------------------------------------------------------
# Metric families (prefix `watchtower_`, declared in the zgate4 lint)
# ---------------------------------------------------------------------------

_EVALS_TOTAL = metrics.counter(
    "watchtower_evaluations_total",
    "detector-catalogue evaluation passes (background evaluator ticks "
    "+ explicit evaluate() calls)",
)
_EVAL_ERRORS = metrics.counter(
    "watchtower_evaluator_errors_total",
    "evaluation passes that raised (the pass is dropped, the thread "
    "survives) — a climbing rate with stalled "
    "watchtower_evaluations_total means the watchtower is blind",
)
_INCIDENTS_TOTAL = metrics.counter_vec(
    "watchtower_incidents_total",
    "incidents OPENED, by detector and severity (a reopen within the "
    "cooldown window is a flap on the existing incident, not a new "
    "one — dedup is the point)",
    ("detector", "severity"),
)
_INCIDENTS_OPEN = metrics.gauge(
    "watchtower_incidents_open",
    "incidents currently open (firing or latched) across every "
    "detector/label",
)
_DETECTOR_STATE = metrics.gauge_vec(
    "watchtower_detector_state",
    "per-detector lifecycle state, worst across labels: 0=armed "
    "1=firing 2=latched 3=cooldown (see docs/OBSERVABILITY.md)",
    ("detector",),
)
_BUNDLES_TOTAL = metrics.counter(
    "watchtower_bundles_written_total",
    "correlated incident bundles atomically written (open captures + "
    "resolve rewrites), schema lighthouse_tpu.incident/1",
)

# ---------------------------------------------------------------------------
# Enable / configure
# ---------------------------------------------------------------------------

_enabled = os.environ.get(
    "LIGHTHOUSE_TPU_WATCHTOWER", "1"
) not in ("", "0")
_interval_s = max(0.05, _env_float("LIGHTHOUSE_TPU_WT_INTERVAL_S", 2.0))
_cooldown_s = max(0.0, _env_float("LIGHTHOUSE_TPU_WT_COOLDOWN_S", 30.0))
_max_incidents = max(4, _env_int("LIGHTHOUSE_TPU_WT_MAX_INCIDENTS", 64))
_bundle = os.environ.get(
    "LIGHTHOUSE_TPU_WT_BUNDLE", "1"
) not in ("", "0")
_bundle_dir = os.environ.get("LIGHTHOUSE_TPU_WT_BUNDLE_DIR") or os.path.join(
    tempfile.gettempdir(), "lighthouse_tpu_incidents"
)
_bundle_retain = max(1, _env_int("LIGHTHOUSE_TPU_WT_BUNDLE_RETAIN", 8))
_margin_s = max(1.0, _env_float("LIGHTHOUSE_TPU_WT_MARGIN_S", 10.0))
_flight_tail = max(16, _env_int("LIGHTHOUSE_TPU_WT_FLIGHT_TAIL", 256))

# bounded per-(detector,label) probe history (probe sources have no
# ring in the store; series sources read the store's own rings)
_PROBE_POINTS = 512


def enabled() -> bool:
    return _enabled


def configure(
    enabled: Optional[bool] = None,
    interval_s: Optional[float] = None,
    cooldown_s: Optional[float] = None,
    max_incidents: Optional[int] = None,
    bundle: Optional[bool] = None,
    bundle_dir: Optional[str] = None,
    bundle_retain: Optional[int] = None,
    margin_s: Optional[float] = None,
) -> dict:
    """Override knobs at runtime; returns the PREVIOUS values so tests
    can restore with ``configure(**prev)`` (flight_recorder's
    contract)."""
    global _enabled, _interval_s, _cooldown_s, _max_incidents, _bundle
    global _bundle_dir, _bundle_retain, _margin_s
    with _lock:
        prev = {
            "enabled": _enabled,
            "interval_s": _interval_s,
            "cooldown_s": _cooldown_s,
            "max_incidents": _max_incidents,
            "bundle": _bundle,
            "bundle_dir": _bundle_dir,
            "bundle_retain": _bundle_retain,
            "margin_s": _margin_s,
        }
        if enabled is not None:
            _enabled = bool(enabled)
        if interval_s is not None:
            _interval_s = max(0.05, float(interval_s))
        if cooldown_s is not None:
            _cooldown_s = max(0.0, float(cooldown_s))
        if max_incidents is not None:
            _max_incidents = max(4, int(max_incidents))
            _resize_ledger()
        if bundle is not None:
            _bundle = bool(bundle)
        if bundle_dir is not None:
            _bundle_dir = str(bundle_dir)
        if bundle_retain is not None:
            _bundle_retain = max(1, int(bundle_retain))
        if margin_s is not None:
            _margin_s = max(1.0, float(margin_s))
    return prev


def bundle_dir() -> str:
    return _bundle_dir


# ---------------------------------------------------------------------------
# Runtime state
# ---------------------------------------------------------------------------


class _DetState:
    __slots__ = ("state", "streak", "since", "cooldown_until", "incident",
                 "value", "trace")

    def __init__(self):
        self.state = "armed"
        self.streak = 0
        self.since: Optional[float] = None
        self.cooldown_until = 0.0
        self.incident: Optional[dict] = None
        self.value: Optional[float] = None
        self.trace: Optional[dict] = None


_lock = threading.Lock()
_det: Dict[Tuple[str, str], _DetState] = {}
_probe_hist: Dict[Tuple[str, str], deque] = {}
_incidents: deque = deque(maxlen=_max_incidents)
_seq = 0
_evals = 0
_verdict_seen: Dict[str, Optional[int]] = {"slot": None}
_health_provider: Optional[Callable[[], dict]] = None


def _resize_ledger() -> None:
    """Called under _lock: rebuild the bounded ledger at the new cap,
    keeping the newest rows."""
    global _incidents
    _incidents = deque(_incidents, maxlen=_max_incidents)


def set_health_provider(fn: Optional[Callable[[], dict]]) -> None:
    """Register the callable whose document lands in the ``health``
    field of every bundle (the client wires the /lighthouse/health
    builder here; chain-less tools and replays leave it unset and the
    bundle still carries the utils-level blocks)."""
    global _health_provider
    _health_provider = fn


def reset() -> None:
    """Fresh detector state + incident ledger + probe history (knobs
    keep their values) — tests and replay runs start clean."""
    global _seq, _evals
    with _lock:
        _det.clear()
        _probe_hist.clear()
        _incidents.clear()
        _verdict_seen["slot"] = None
        _seq = 0
        _evals = 0
    for spec in DETECTORS:
        _DETECTOR_STATE.with_labels(spec.name).set(0)
    _INCIDENTS_OPEN.set(0)


# ---------------------------------------------------------------------------
# Probes: named value sources a detector can watch when the signal is
# not (only) a stored series — computed registry reads and slot-ledger
# walks, never jax
# ---------------------------------------------------------------------------


def _probe_pack_share() -> Dict[str, float]:
    """Host pack wall as a share of device verify wall, straight off
    the two registry histograms. Deliberately NOT
    ``transfer_ledger.summary()`` — that walks ``jax.live_arrays()``
    for the memory block, which a per-tick evaluator must never do."""
    pack = metrics.get("bls_device_pack_seconds")
    verify = metrics.get("bls_device_verify_seconds")
    if pack is None or verify is None or not hasattr(pack, "children"):
        return {}
    pack_total = 0.0
    for labels, child in pack.children().items():
        if labels and labels[0] == "total":
            _t, s, _c = child.snapshot()
            pack_total += s
    verify_wall = 0.0
    if hasattr(verify, "children"):
        for _labels, child in verify.children().items():
            _t, s, _c = child.snapshot()
            verify_wall += s
    if verify_wall <= 0:
        return {}
    return {"": pack_total / verify_wall}


def _probe_verdict_p99() -> Dict[str, float]:
    """The newest slot report card's in-slot p99 — one point per slot
    (re-reading the same card contributes nothing; the baseline is
    slots, not evaluator ticks)."""
    for card in reversed(slot_ledger.slot_cards(last=3)):
        p99 = card.get("p99_ms")
        if p99 is None:
            continue
        if _verdict_seen["slot"] == card["slot"]:
            return {}
        _verdict_seen["slot"] = card["slot"]
        return {"": float(p99)}
    return {}


PROBES: Dict[str, Callable[[], Dict[str, float]]] = {
    "pack_share": _probe_pack_share,
    "verdict_p99_ms": _probe_verdict_p99,
}


# ---------------------------------------------------------------------------
# Algorithms: one reading -> (breached, cleared, value, trace). The
# middle ground (neither) is the hysteresis band that keeps an open
# incident latched.
# ---------------------------------------------------------------------------


def _eval_algo(spec: DetectorSpec, pts: List[Tuple[float, float]],
               now: float) -> Tuple[bool, bool, float, dict]:
    value = pts[-1][1]
    if spec.algo == "floor":
        clear = spec.clear if spec.clear is not None else spec.threshold
        breached = value < spec.threshold
        cleared = value >= clear
        trace = {"algo": "floor", "value": value,
                 "threshold": spec.threshold, "clear": clear,
                 "n_points": len(pts)}
    elif spec.algo == "ceil":
        clear = spec.clear if spec.clear is not None else spec.threshold
        breached = value > spec.threshold
        cleared = value <= clear
        trace = {"algo": "ceil", "value": value,
                 "threshold": spec.threshold, "clear": clear,
                 "n_points": len(pts)}
    elif spec.algo == "roc":
        slope = None
        breached, cleared = False, True
        if len(pts) >= max(2, spec.min_points):
            dt = pts[-1][0] - pts[0][0]
            slope = (pts[-1][1] - pts[0][1]) / dt if dt > 0 else 0.0
            breached = slope >= spec.threshold
            cleared = slope < spec.threshold * 0.5
        trace = {"algo": "roc", "value": value, "slope_per_s": slope,
                 "threshold": spec.threshold, "window_s": spec.window_s,
                 "n_points": len(pts)}
    else:  # zscore
        base = pts[:-1]
        breached, cleared = False, True
        mean = std = dev = gate = None
        if len(base) >= spec.min_points:
            mean = sum(v for _, v in base) / len(base)
            var = sum((v - mean) ** 2 for _, v in base) / len(base)
            std = var ** 0.5
            dev = (value - mean) if spec.direction == "above" \
                else (mean - value)
            # BOTH gates: `threshold` standard deviations AND the
            # absolute min_delta — a near-zero-variance baseline must
            # not page on an invisible wiggle
            gate = max(spec.threshold * std, spec.min_delta)
            breached = gate > 0 and dev >= gate
            cleared = gate <= 0 or dev < gate * 0.5
        trace = {"algo": "zscore", "value": value, "mean": mean,
                 "std": std, "deviation": dev, "gate": gate,
                 "direction": spec.direction, "n_points": len(pts)}
    return breached, cleared, value, trace


def _readings(spec: DetectorSpec, store: timeseries.TimeseriesStore,
              now: float) -> Dict[str, Tuple[bool, bool, float, dict]]:
    """Per-label algorithm outcomes for one detector. Called under
    _lock (probe history is module state); the store takes its own
    lock — store methods never call back into this module, so the
    ordering is acyclic."""
    kind, _, name = spec.source.partition(":")
    out: Dict[str, Tuple[bool, bool, float, dict]] = {}
    if kind == "series":
        d = store.doc(families=[name], tier="raw")
        for label, pts in d["families"].get(name, {}).items():
            win = [(p[0], p[1]) for p in pts
                   if p[0] >= now - spec.window_s]
            if win:
                out[label] = _eval_algo(spec, win, now)
    else:  # probe
        probe = PROBES.get(name)
        vals = probe() if probe is not None else {}
        for label, v in vals.items():
            hist = _probe_hist.get((spec.name, label))
            if hist is None:
                hist = _probe_hist[(spec.name, label)] = deque(
                    maxlen=_PROBE_POINTS
                )
            hist.append((now, float(v)))
            win = [p for p in hist if p[0] >= now - spec.window_s]
            if win:
                out[label] = _eval_algo(spec, win, now)
    return out


# ---------------------------------------------------------------------------
# The incident ledger + state machine
# ---------------------------------------------------------------------------


def _open_incident(spec: DetectorSpec, label: str, value: float,
                   trace: dict, now: float) -> dict:
    """Called under _lock."""
    global _seq
    _seq += 1
    inc = {
        "id": f"inc-{_seq:06d}",
        "detector": spec.name,
        "severity": spec.severity,
        "label": label,
        "opened_t": now,
        "opened_at": _iso(now),
        "resolved_t": None,
        "duration_s": 0.0,
        "last_breach_t": now,
        "flaps": 0,
        "value": value,
        "last_value": value,
        "threshold": spec.threshold,
        "trigger": trace,
        "bundle_path": None,
    }
    _incidents.append(inc)
    return inc


def _iso(t: float) -> str:
    return (time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t))
            + f".{int(t * 1000) % 1000:03d}Z")


def _step(spec: DetectorSpec, label: str, breached: bool, cleared: bool,
          value: float, trace: dict, now: float) -> Optional[tuple]:
    """One state-machine step for one (detector, label). Called under
    _lock; returns ("open"|"reopen"|"resolve", incident) when a
    transition needs journal/bundle work outside the lock."""
    key = (spec.name, label)
    st = _det.get(key)
    if st is None:
        st = _det[key] = _DetState()
    st.value = value
    st.trace = trace
    action = None
    if st.state == "armed":
        if breached:
            st.streak += 1
            if st.streak >= spec.sustain:
                inc = _open_incident(spec, label, value, trace, now)
                st.incident = inc
                st.state = "firing"
                st.since = now
                action = ("open", inc)
        else:
            st.streak = 0
    elif st.state in ("firing", "latched"):
        inc = st.incident
        if breached:
            if st.state == "latched":
                st.state = "firing"
                st.since = now
            if inc is not None:
                inc["last_breach_t"] = now
                inc["last_value"] = value
                inc["duration_s"] = round(now - inc["opened_t"], 6)
        elif cleared:
            st.state = "cooldown"
            st.since = now
            st.cooldown_until = now + _cooldown_s
            st.streak = 0
            if inc is not None and inc["resolved_t"] is None:
                inc["resolved_t"] = now
                inc["resolved_at"] = _iso(now)
                inc["duration_s"] = round(now - inc["opened_t"], 6)
                action = ("resolve", inc)
        elif st.state == "firing":
            # the hysteresis band: no longer breaching, not yet
            # cleared — the incident stays open, latched
            st.state = "latched"
            st.since = now
            if inc is not None:
                inc["duration_s"] = round(now - inc["opened_t"], 6)
    elif st.state == "cooldown":
        if breached:
            # dedup: a re-breach inside the cooldown REOPENS the same
            # incident as a flap instead of spamming a new row
            inc = st.incident
            st.state = "firing"
            st.since = now
            if inc is not None:
                inc["resolved_t"] = None
                inc.pop("resolved_at", None)
                inc["flaps"] += 1
                inc["last_breach_t"] = now
                inc["last_value"] = value
                action = ("reopen", inc)
        elif now >= st.cooldown_until:
            st.state = "armed"
            st.since = now
            st.streak = 0
    return action


# ---------------------------------------------------------------------------
# One evaluation pass (the hot-path seam; < 1 µs disabled)
# ---------------------------------------------------------------------------


def evaluate(now: Optional[float] = None) -> Optional[dict]:
    """Walk the detector catalogue once: read every detector's input,
    step its state machine, open/reopen/resolve incidents, write
    bundles. Returns ``{"t", "transitions"}`` (None when disabled — a
    single global check, pinned < 1 µs like disabled spans)."""
    if not _enabled:
        return None
    global _evals
    if now is None:
        now = time.time()
    store = timeseries.get_store()
    actions: List[tuple] = []
    with _lock:
        for spec in DETECTORS:
            for label, (breached, cleared, value, trace) in \
                    _readings(spec, store, now).items():
                act = _step(spec, label, breached, cleared, value,
                            trace, now)
                if act is not None:
                    actions.append((act[0], act[1], spec))
        open_n = sum(
            1 for st in _det.values() if st.state in ("firing", "latched")
        )
        worst: Dict[str, str] = {}
        for (name, _label), st in _det.items():
            cur = worst.get(name, "armed")
            if _STATE_RANK[st.state] >= _STATE_RANK[cur]:
                worst[name] = st.state
        _evals += 1
    # journal + metrics + bundle I/O outside the lock
    _EVALS_TOTAL.inc()
    _INCIDENTS_OPEN.set(open_n)
    for name, state in worst.items():
        _DETECTOR_STATE.with_labels(name).set(_STATE_CODE[state])
    transitions = []
    for action, inc, spec in actions:
        if action == "open":
            _INCIDENTS_TOTAL.with_labels(spec.name, spec.severity).inc()
            flight_recorder.record(
                "incident_opened",
                id=inc["id"], detector=spec.name, severity=spec.severity,
                label=inc["label"], value=inc["value"],
                threshold=spec.threshold,
            )
        elif action == "reopen":
            flight_recorder.record(
                "incident_opened",
                id=inc["id"], detector=spec.name, severity=spec.severity,
                label=inc["label"], value=inc["last_value"],
                threshold=spec.threshold, reopened=inc["flaps"],
            )
        else:  # resolve
            flight_recorder.record(
                "incident_resolved",
                id=inc["id"], detector=spec.name, severity=spec.severity,
                label=inc["label"], duration_s=inc["duration_s"],
            )
        if _bundle:
            try:
                path = _write_bundle(inc, spec, now)
                inc["bundle_path"] = path
            except OSError:
                _EVAL_ERRORS.inc()
        transitions.append({
            "action": action, "incident": inc["id"],
            "detector": spec.name, "label": inc["label"],
        })
    return {"t": now, "transitions": transitions}


# ---------------------------------------------------------------------------
# Correlated capture: the atomically-written incident bundle
# ---------------------------------------------------------------------------


def _bundle_doc(inc: dict, spec: DetectorSpec, now: float) -> dict:
    from . import pipeline_profiler

    store = timeseries.get_store()
    # the detector's own series plus the dials any triage starts from,
    # windowed margin_s before the open through margin_s after `now`
    fams = sorted({
        spec.source.partition(":")[2] if spec.source.startswith("series:")
        else None,
        "capacity_arrival_sets_per_sec",
        "capacity_deadline_miss_per_sec",
        "capacity_estimated_sets_per_sec",
        "capacity_headroom_ratio",
        "capacity_utilization",
    } - {None})
    window_s = (now - inc["opened_t"]) + 2 * _margin_s
    health = None
    provider = _health_provider
    if provider is not None:
        try:
            health = provider()
        except Exception:
            health = {"error": "health provider raised"}
    return {
        "schema": SCHEMA,
        "captured_at": _iso(now),
        "t": now,
        "pid": os.getpid(),
        "margin_s": _margin_s,
        "incident": dict(inc),
        "detector": _spec_doc(spec),
        "flight_recorder": flight_recorder.snapshot(
            trigger=f"incident:{spec.name}",
            context={"incident": inc["id"]},
        ),
        "timeseries": store.doc(families=fams, tier="raw",
                                window_s=window_s),
        "slot_cards": slot_ledger.slot_cards(last=8),
        "chain_time": slot_ledger.summary(),
        "profiler": pipeline_profiler.summary(),
        "capacity": timeseries.capacity_summary(),
        "health": health,
    }


def _write_bundle(inc: dict, spec: DetectorSpec, now: float) -> str:
    """Write (or, at resolve time, atomically REWRITE) the incident's
    bundle: tmp file in the target directory + os.replace, so a reader
    never sees a torn document."""
    doc = _bundle_doc(inc, spec, now)
    # trim the flight tail to the configured bound
    evs = doc["flight_recorder"].get("events", [])
    if len(evs) > _flight_tail:
        doc["flight_recorder"]["events"] = evs[-_flight_tail:]
    os.makedirs(_bundle_dir, exist_ok=True)
    path = inc.get("bundle_path") or os.path.join(
        _bundle_dir,
        f"{BUNDLE_PREFIX}{int(inc['opened_t'] * 1000)}_{inc['id']}.json",
    )
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    os.replace(tmp, path)
    _BUNDLES_TOTAL.inc()
    _apply_retention()
    return path


def _apply_retention() -> None:
    try:
        names = sorted(
            n for n in os.listdir(_bundle_dir)
            if n.startswith(BUNDLE_PREFIX) and n.endswith(".json")
        )
        for n in names[:-_bundle_retain]:
            os.unlink(os.path.join(_bundle_dir, n))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Introspection: the incident ledger, the health block, the catalogue
# ---------------------------------------------------------------------------


def incidents(limit: Optional[int] = None,
              open_only: bool = False) -> List[dict]:
    """Retained incidents, oldest first; ``limit`` keeps the newest N
    (after the open filter)."""
    with _lock:
        out = [dict(i) for i in _incidents]
    if open_only:
        out = [i for i in out if i["resolved_t"] is None]
    if limit is not None:
        out = out[-limit:] if limit > 0 else []
    return out


def _spec_doc(spec: DetectorSpec) -> dict:
    return {
        "name": spec.name, "algo": spec.algo, "source": spec.source,
        "window_s": spec.window_s, "threshold": spec.threshold,
        "clear": spec.clear, "direction": spec.direction,
        "min_points": spec.min_points, "min_delta": spec.min_delta,
        "sustain": spec.sustain, "severity": spec.severity,
        "doc": spec.doc,
    }


def catalogue() -> List[dict]:
    """The declared detector catalogue as documents (the endpoint, the
    docs table, and tools/incident_report.py --list-detectors)."""
    return [_spec_doc(s) for s in DETECTORS]


def summary() -> dict:
    """The ``watchtower`` block of ``/lighthouse/health``: per-detector
    state (worst across labels, plus each label's reading), incident
    accounting, evaluator state, bundle config."""
    with _lock:
        detectors = {}
        for spec in DETECTORS:
            labels = {}
            worst = "armed"
            for (name, label), st in _det.items():
                if name != spec.name:
                    continue
                labels[label] = {
                    "state": st.state,
                    "value": st.value,
                    "since": st.since,
                    "incident": (
                        st.incident["id"] if st.incident else None
                    ),
                }
                if _STATE_RANK[st.state] > _STATE_RANK[worst]:
                    worst = st.state
            detectors[spec.name] = {
                "state": worst,
                "severity": spec.severity,
                "algo": spec.algo,
                "source": spec.source,
                "labels": labels,
            }
        open_n = sum(
            1 for st in _det.values() if st.state in ("firing", "latched")
        )
        retained = len(_incidents)
        opened = _seq
        evals = _evals
    return {
        "enabled": _enabled,
        "evaluator": {
            "running": evaluator_running(),
            "interval_s": (
                _evaluator.interval_s if _evaluator is not None
                else _interval_s
            ),
            "evaluations_total": evals,
        },
        "detectors": detectors,
        "incidents": {
            "open": open_n,
            "opened_total": opened,
            "retained": retained,
            "max_retained": _max_incidents,
        },
        "cooldown_s": _cooldown_s,
        "bundle": {
            "enabled": _bundle,
            "dir": _bundle_dir,
            "retain": _bundle_retain,
            "margin_s": _margin_s,
        },
    }


# ---------------------------------------------------------------------------
# Background evaluator
# ---------------------------------------------------------------------------


class Evaluator:
    """Background thread calling :func:`evaluate` every ``interval_s``
    (the timeseries Sampler's shape — started by the client lifecycle,
    tools, tests)."""

    def __init__(self, interval_s: Optional[float] = None):
        self.interval_s = float(
            interval_s if interval_s is not None else _interval_s
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Evaluator":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="watchtower-evaluator", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                evaluate()
            except Exception:
                # an evaluation crash must never kill the thread — and
                # never pass silently (the sampler's error-counter
                # convention)
                _EVAL_ERRORS.inc()
            self._stop.wait(self.interval_s)


_evaluator: Optional[Evaluator] = None
_evaluator_lock = threading.Lock()


def start_evaluator(interval_s: Optional[float] = None) -> Evaluator:
    global _evaluator
    with _evaluator_lock:
        if _evaluator is None or not _evaluator.running():
            _evaluator = Evaluator(interval_s=interval_s)
        e = _evaluator
        e.start()
    return e


def stop_evaluator() -> None:
    global _evaluator
    with _evaluator_lock:
        e = _evaluator
        _evaluator = None
    # join OUTSIDE the lock: the evaluator thread may be mid-evaluate()
    if e is not None:
        e.stop()


def evaluator_running() -> bool:
    e = _evaluator
    return e is not None and e.running()
