"""Deterministic fault injection for the device stack (ISSUE 13).

The self-healing machinery this PR adds — shard probation/recovery,
the dispatch watchdog, compile retry, key-table re-sync — is only
trustworthy if its failure paths can be driven ON DEMAND and
REPRODUCIBLY. This module is that seam: named fault points compiled
into the hot path (``staged_dispatch`` in ``crypto/device/bls._run_
stage``, ``device_put`` in the raw/indexed packers, ``compile`` in
``compile_service/service._compile_rung``, ``key_table_sync`` in
``crypto/device/key_table.sync``) that cost one global check when
disarmed and fire a DETERMINISTIC schedule of injected failures when
armed — the same discipline production chaos tooling applies to
consensus clients (the reference's peer manager is tested by scripted
misbehavior, not by waiting for real peers to misbehave).

Triggers, per point (call indices are 1-based, counted from arming,
after an optional ``after`` warm-in):

* ``nth=N`` — fire exactly on the Nth call (one-shot unless sticky);
* ``every=K`` — fire on every Kth call;
* ``p=0.3,seed=S`` — seeded Bernoulli per call index: the schedule is
  a pure function of (seed, index), so the SAME seed reproduces the
  SAME injected-failure schedule in any process (pinned by
  ``tests/test_fault_injection.py`` in a jax-free subprocess);
* ``mode=sticky`` — once fired, every later call fires too (a chip
  that died and stays dead), vs the default one-shot/scheduled modes
  (a transient);
* ``count=C`` — cap total injections;
* ``hang=S`` — the action: instead of raising :class:`InjectedFault`,
  sleep S seconds then return (a stalled dispatch — the shape the
  scheduler's watchdog exists to reap).

Config: env ``LIGHTHOUSE_TPU_FAULTS="point:k=v,k=v;point:k=v"`` read
at import, or :func:`configure`/:func:`arm` at runtime (the replay
driver's ``--fault`` flag scripts it per run). Every injection ticks
``fault_injections_total{point,action}`` and journals a
``fault_injected`` flight-recorder event; ``/lighthouse/health``
serves :func:`status` as the ``fault_injection`` block while armed.

Design constraints (same discipline as spans/ledger/profiler hooks):

* DISABLED ``fire()`` must cost well under 1 microsecond — one global
  check, no allocation (pinned by test).
* jax-free at import: the mesh recovery worker, the compile service
  and the metrics lint all import this module on boxes that must not
  initialize a backend.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional

from . import flight_recorder, metrics

# The fault-point catalogue: one entry per instrumented seam, sorted
# (the zgate4 lint reads it like EVENT_KINDS). arm()/fire() reject
# unknown points so a typo cannot silently no-op a chaos run.
FAULT_POINTS = (
    "compile",          # compile_service/service.py, per AOT rung compile
    "device_put",       # crypto/device/bls.py, raw/indexed pack upload
    "duty_lookahead",   # duty_lookahead/, per epoch warm attempt
    "key_table_sync",   # crypto/device/key_table.py, mirror sync
    "staged_dispatch",  # crypto/device/bls.py, per staged program dispatch
)

_ENV_FAULTS = "LIGHTHOUSE_TPU_FAULTS"


class InjectedFault(RuntimeError):
    """The failure an armed fault point raises — deliberately a plain
    RuntimeError subtype so every recovery layer handles it exactly
    like a real backend failure (nothing may special-case chaos)."""


_INJECTIONS = metrics.counter_vec(
    "fault_injections_total",
    "injected faults fired, by fault point and action (raise = "
    "InjectedFault thrown at the seam, hang = the call slept its "
    "configured stall instead)",
    ("point", "action"),
)
_ARMED_GAUGE = metrics.gauge(
    "fault_points_armed",
    "fault points currently armed (0 = the fault-injection layer is "
    "disarmed and fire() costs one global check)",
)


class _FaultPoint:
    __slots__ = (
        "point", "nth", "every", "p", "seed", "after", "hang_s",
        "sticky", "count", "calls", "injected", "tripped",
    )

    def __init__(
        self,
        point: str,
        nth: Optional[int] = None,
        every: Optional[int] = None,
        p: Optional[float] = None,
        seed: int = 0,
        after: int = 0,
        hang_s: Optional[float] = None,
        sticky: bool = False,
        count: Optional[int] = None,
    ):
        self.point = point
        self.nth = None if nth is None else int(nth)
        self.every = None if every is None else max(1, int(every))
        self.p = None if p is None else float(p)
        self.seed = int(seed)
        self.after = max(0, int(after))
        self.hang_s = None if hang_s is None else float(hang_s)
        self.sticky = bool(sticky)
        # nth without sticky is one-shot by construction; an explicit
        # count caps every other trigger shape
        self.count = None if count is None else max(0, int(count))
        self.calls = 0
        self.injected = 0
        self.tripped = False

    def scheduled(self, i: int) -> bool:
        """Pure trigger schedule for 1-based call index ``i`` — no
        state, so the same spec yields the same schedule anywhere
        (the determinism the chaos tests pin)."""
        i -= self.after
        if i <= 0:
            return False
        if self.nth is not None and i == self.nth:
            return True
        if self.every is not None and i % self.every == 0:
            return True
        if self.p is not None:
            # seeded per-index Bernoulli: a pure function of
            # (seed, index), never of call interleaving
            return random.Random((self.seed << 20) ^ i).random() < self.p
        return False

    def decide(self, i: int) -> bool:
        if self.sticky and self.tripped:
            return True
        if self.count is not None and self.injected >= self.count:
            return False
        return self.scheduled(i)

    def config(self) -> dict:
        return {
            "nth": self.nth,
            "every": self.every,
            "p": self.p,
            "seed": self.seed,
            "after": self.after,
            "hang_s": self.hang_s,
            "sticky": self.sticky,
            "count": self.count,
        }


_lock = threading.Lock()
_points: Dict[str, _FaultPoint] = {}
_armed = False  # the single global the disarmed fire() checks


def fire(point: str) -> None:
    """The hot-path hook compiled into every fault seam. Disarmed this
    is one global check (< 1 µs, pinned by test); armed it advances the
    point's call counter and either returns, raises
    :class:`InjectedFault`, or sleeps the configured hang."""
    if not _armed:
        return
    with _lock:
        fpt = _points.get(point)
        if fpt is None:
            if point not in FAULT_POINTS:
                raise ValueError(
                    f"unknown fault point {point!r}; declare it in "
                    f"fault_injection.FAULT_POINTS"
                )
            return
        fpt.calls += 1
        i = fpt.calls
        trig = fpt.decide(i)
        if trig:
            fpt.injected += 1
            fpt.tripped = True
        hang_s = fpt.hang_s
    if not trig:
        return
    action = "hang" if hang_s else "raise"
    _INJECTIONS.with_labels(point, action).inc()
    flight_recorder.record(
        "fault_injected",
        point=point,
        call=i,
        action=action,
        hang_s=hang_s,
    )
    if hang_s:
        time.sleep(hang_s)
        return
    raise InjectedFault(f"injected fault at {point!r} (call {i})")


def arm(point: str, **kwargs) -> None:
    """Arm one fault point (see module docstring for the trigger
    grammar). Re-arming a point replaces its spec and resets its
    counters."""
    if point not in FAULT_POINTS:
        raise ValueError(
            f"unknown fault point {point!r}; have {FAULT_POINTS}"
        )
    global _armed
    with _lock:
        _points[point] = _FaultPoint(point, **kwargs)
        _armed = True
        _ARMED_GAUGE.set(len(_points))


def clear(point: Optional[str] = None) -> None:
    """Disarm one point (or all of them); the global flag drops as
    soon as nothing is armed, restoring the < 1 µs disabled path."""
    global _armed
    with _lock:
        if point is None:
            _points.clear()
        else:
            _points.pop(point, None)
        _armed = bool(_points)
        _ARMED_GAUGE.set(len(_points))


def armed() -> bool:
    return _armed


def schedule(n_calls: int, **kwargs) -> list:
    """The deterministic trigger schedule a spec would produce for
    calls 1..n — the pure-function view the determinism gate pins and
    replay scripts can precompute (sticky expansion included)."""
    fpt = _FaultPoint("schedule", **kwargs)
    out = []
    tripped = False
    fired = 0
    for i in range(1, n_calls + 1):
        hit = (fpt.sticky and tripped) or (
            (fpt.count is None or fired < fpt.count) and fpt.scheduled(i)
        )
        if hit:
            tripped = True
            fired += 1
        out.append(hit)
    return out


def status() -> dict:
    """The ``/lighthouse/health`` ``fault_injection`` block (served
    only while armed — a production node without chaos config never
    shows the surface)."""
    with _lock:
        return {
            "armed": _armed,
            "points": {
                name: {
                    "calls": fpt.calls,
                    "injected": fpt.injected,
                    "tripped": fpt.tripped,
                    "config": fpt.config(),
                }
                for name, fpt in sorted(_points.items())
            },
        }


# ---------------------------------------------------------------------------
# Spec parsing (env + CLI): "point:k=v,k=v;point:k=v"
# ---------------------------------------------------------------------------

_KEYS = {
    "nth": int,
    "every": int,
    "p": float,
    "seed": int,
    "after": int,
    "hang": float,   # spelled hang= in specs, hang_s in arm()
    "count": int,
    "mode": str,     # oneshot | sticky
}


def parse_spec(spec: str) -> Dict[str, dict]:
    """``{point: arm_kwargs}`` from a spec string; raises ValueError on
    malformed input (a chaos run with a typo'd spec must fail loudly,
    not silently run fault-free)."""
    out: Dict[str, dict] = {}
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        if ":" not in chunk:
            raise ValueError(f"fault spec chunk {chunk!r} has no point:")
        point, _, body = chunk.partition(":")
        point = point.strip()
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; have {FAULT_POINTS}"
            )
        kwargs: dict = {}
        for kv in body.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, _, val = kv.partition("=")
            key = key.strip()
            caster = _KEYS.get(key)
            if caster is None:
                raise ValueError(
                    f"unknown fault spec key {key!r} in {chunk!r}; "
                    f"have {sorted(_KEYS)}"
                )
            if key == "mode":
                if val not in ("oneshot", "sticky"):
                    raise ValueError(f"mode must be oneshot|sticky: {kv!r}")
                kwargs["sticky"] = val == "sticky"
            elif key == "hang":
                kwargs["hang_s"] = caster(val)
            else:
                kwargs[key] = caster(val)
        out[point] = kwargs
    return out


def configure(spec: str) -> None:
    """Parse and arm a whole spec string (the env / ``--fault`` entry
    point)."""
    for point, kwargs in parse_spec(spec).items():
        arm(point, **kwargs)


_env_spec = os.environ.get(_ENV_FAULTS, "").strip()
if _env_spec:
    configure(_env_spec)
