"""Host health observations (reference ``common/system_health``: load,
memory, disk, network counters surfaced on the lighthouse-specific API).
Reads /proc (Linux) with graceful zeros elsewhere — no external deps."""

from __future__ import annotations

import os
import shutil


def observe(datadir: str | None = None) -> dict:
    load1 = load5 = load15 = 0.0
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        pass

    mem_total = mem_free = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    mem_total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    mem_free = int(line.split()[1]) * 1024
    except OSError:
        pass

    disk_total = disk_free = 0
    try:
        usage = shutil.disk_usage(datadir or "/")
        disk_total, disk_free = usage.total, usage.free
    except OSError:
        pass

    uptime = 0.0
    try:
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
    except OSError:
        pass

    return {
        "sys_loadavg_1": load1,
        "sys_loadavg_5": load5,
        "sys_loadavg_15": load15,
        "sys_ram_total": mem_total,
        "sys_ram_free": mem_free,
        "disk_node_bytes_total": disk_total,
        "disk_node_bytes_free": disk_free,
        "host_uptime_s": uptime,
        "system_cpu_count": os.cpu_count() or 0,
    }
