"""Process-global metrics registry (reference:
``common/lighthouse_metrics/src/lib.rs:1-56`` — a lazy_static Prometheus
registry with counters/gauges/histograms used by every subsystem, scraped
by ``http_metrics``).

Same shape here: module-level registry, get-or-create metric handles,
Prometheus text exposition for the metrics endpoint. No external deps.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Sequence


class _Metric:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_)
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def expose(self) -> str:
        return f"{self.name} {self.value}"


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_)
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def expose(self) -> str:
        return f"{self.name} {self.value}"


class Histogram(_Metric):
    kind = "histogram"

    DEFAULT_BUCKETS = (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
        0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    )

    def __init__(self, name: str, help_: str, buckets: Sequence[float] | None = None):
        super().__init__(name, help_)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.total += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def time(self):
        """Context manager: observe elapsed seconds."""
        return _Timer(self)

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        with self._lock:
            if self.total == 0:
                return 0.0
            target = q * self.total
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += self.counts[i]
                if acc >= target:
                    return b
            return float("inf")

    def expose(self) -> str:
        lines = []
        acc = 0
        for i, b in enumerate(self.buckets):
            acc += self.counts[i]
            lines.append(f'{self.name}_bucket{{le="{b}"}} {acc}')
        acc += self.counts[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {acc}')
        lines.append(f"{self.name}_sum {self.sum}")
        lines.append(f"{self.name}_count {self.total}")
        return "\n".join(lines)


class _Timer:
    def __init__(self, h: Histogram):
        self.h = h

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.h.observe(time.perf_counter() - self.t0)
        return False


_REGISTRY: Dict[str, _Metric] = {}
_reg_lock = threading.Lock()


def _get_or_create(cls, name: str, help_: str, **kw):
    with _reg_lock:
        m = _REGISTRY.get(name)
        if m is None:
            m = cls(name, help_, **kw)
            _REGISTRY[name] = m
        return m


def counter(name: str, help_: str = "") -> Counter:
    return _get_or_create(Counter, name, help_)


def gauge(name: str, help_: str = "") -> Gauge:
    return _get_or_create(Gauge, name, help_)


def histogram(name: str, help_: str = "", buckets=None) -> Histogram:
    return _get_or_create(Histogram, name, help_, buckets=buckets)


def gather() -> str:
    """Prometheus text exposition of every registered metric."""
    out = []
    with _reg_lock:
        metrics = list(_REGISTRY.values())
    for m in sorted(metrics, key=lambda m: m.name):
        out.append(f"# HELP {m.name} {m.help}")
        out.append(f"# TYPE {m.name} {m.kind}")
        out.append(m.expose())
    return "\n".join(out) + "\n"
