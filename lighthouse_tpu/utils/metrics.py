"""Process-global metrics registry (reference:
``common/lighthouse_metrics/src/lib.rs`` — a lazy_static Prometheus
registry with counters/gauges/histograms AND label-vector families
(``IntCounterVec``/``HistogramVec`` behind ``try_create_*_vec`` +
``metrics::get_metric(&VEC, &[label])`` handles) used by every subsystem,
scraped by ``http_metrics``).

Same shape here: module-level registry, get-or-create metric handles,
``*_vec`` families whose :meth:`~_MetricVec.with_labels` returns a child
handle per label combination, and Prometheus text exposition (HELP/TYPE
headers, escaped help text and label values) for the metrics endpoint.
No external deps.

Concurrency contract: every mutator and every exposition/quantile read
holds the metric's lock, so a scrape observes a consistent snapshot even
while hot paths observe into the same family from worker threads.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Sequence, Tuple


def _escape_help(s: str) -> str:
    """Prometheus text format: HELP text escapes backslash and newline."""
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(s: str) -> str:
    """Label values escape backslash, double-quote and newline — an
    adversarial peer id or engine name must not corrupt the scrape."""
    return (
        s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    return ",".join(
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    )


class _Metric:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_)
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def expose(self, labels: str = "") -> str:
        with self._lock:
            v = self.value
        return f"{self.name}{{{labels}}} {v}" if labels else f"{self.name} {v}"


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_)
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount

    def expose(self, labels: str = "") -> str:
        with self._lock:
            v = self.value
        return f"{self.name}{{{labels}}} {v}" if labels else f"{self.name} {v}"


class Histogram(_Metric):
    kind = "histogram"

    DEFAULT_BUCKETS = (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
        0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    )

    def __init__(self, name: str, help_: str, buckets: Sequence[float] | None = None):
        super().__init__(name, help_)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.total += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def time(self):
        """Context manager: observe elapsed seconds."""
        return _Timer(self)

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        with self._lock:
            if self.total == 0:
                return 0.0
            target = q * self.total
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += self.counts[i]
                if acc >= target:
                    return b
            return float("inf")

    def snapshot(self) -> tuple[int, float, tuple[int, ...]]:
        """(total, sum, cumulative bucket counts incl. +Inf) — one
        consistent read for reporting (bench stage attribution)."""
        with self._lock:
            acc, cum = 0, []
            for c in self.counts:
                acc += c
                cum.append(acc)
            return self.total, self.sum, tuple(cum)

    def expose(self, labels: str = "") -> str:
        with self._lock:
            counts = list(self.counts)
            total, sum_ = self.total, self.sum
        sep = labels + "," if labels else ""
        tail = f"{{{labels}}}" if labels else ""
        lines = []
        acc = 0
        for i, b in enumerate(self.buckets):
            acc += counts[i]
            lines.append(f'{self.name}_bucket{{{sep}le="{b}"}} {acc}')
        acc += counts[-1]
        lines.append(f'{self.name}_bucket{{{sep}le="+Inf"}} {acc}')
        lines.append(f"{self.name}_sum{tail} {sum_}")
        lines.append(f"{self.name}_count{tail} {total}")
        return "\n".join(lines)


class _Timer:
    def __init__(self, h: Histogram):
        self.h = h

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.h.observe(time.perf_counter() - self.t0)
        return False


# ---------------------------------------------------------------------------
# Label-vector families (reference ``try_create_int_counter_vec`` + the
# ``get_metric(&VEC, &[..])`` handle pattern): one registered family, one
# child metric per label-value combination, created on first touch.
# ---------------------------------------------------------------------------


class _MetricVec(_Metric):
    _child_cls: type = _Metric  # overridden

    def __init__(self, name: str, help_: str, labelnames: Sequence[str], **kw):
        super().__init__(name, help_)
        labelnames = tuple(labelnames)
        if not labelnames:
            raise ValueError(f"{name}: a metric vec needs >= 1 label name")
        if len(set(labelnames)) != len(labelnames):
            raise ValueError(f"{name}: duplicate label names {labelnames}")
        self.labelnames = labelnames
        self._kw = kw
        self._children: Dict[Tuple[str, ...], _Metric] = {}
        self._lock = threading.Lock()

    @property
    def kind(self) -> str:
        return self._child_cls.kind

    def with_labels(self, *values, **kwvalues):
        """Child handle for one label combination (Lighthouse's
        ``get_metric(&VEC, &[v, ...])``). Accepts positional values in
        ``labelnames`` order, or keyword values by label name."""
        if kwvalues:
            if values:
                raise TypeError("label values: positional OR keyword, not both")
            if set(kwvalues) != set(self.labelnames):
                raise ValueError(
                    f"{self.name}: keyword labels {sorted(kwvalues)} != "
                    f"declared {sorted(self.labelnames)}"
                )
            values = tuple(kwvalues[n] for n in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label values "
                f"{self.labelnames}, got {len(values)}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._child_cls(self.name, self.help, **self._kw)
                self._children[values] = child
            return child

    # prometheus-client spelling, same handle
    labels = with_labels

    def children(self) -> Dict[Tuple[str, ...], _Metric]:
        """Snapshot of label-values -> child (reporting/bench reads)."""
        with self._lock:
            return dict(self._children)

    def expose(self) -> str:
        with self._lock:
            items = sorted(self._children.items())
        return "\n".join(
            child.expose(_label_str(self.labelnames, values))
            for values, child in items
        )


class CounterVec(_MetricVec):
    _child_cls = Counter


class GaugeVec(_MetricVec):
    _child_cls = Gauge


class HistogramVec(_MetricVec):
    _child_cls = Histogram


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, _Metric] = {}
_reg_lock = threading.Lock()


def _get_or_create(cls, name: str, help_: str, **kw):
    with _reg_lock:
        m = _REGISTRY.get(name)
        if m is None:
            m = cls(name, help_, **kw)
            _REGISTRY[name] = m
            return m
        if type(m) is not cls:
            # one name, one metric type — a family silently re-registered
            # as another kind would corrupt the scrape
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}"
            )
        # omitted labelnames/buckets = fetch-by-name; provided ones must
        # match what the family was registered with (a silently ignored
        # mismatch would skew every reader)
        if isinstance(m, _MetricVec) and kw.get("labelnames") and tuple(
            kw["labelnames"]
        ) != m.labelnames:
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{m.labelnames}, not {tuple(kw['labelnames'])}"
            )
        if kw.get("buckets") is not None:
            have = (
                m.buckets
                if isinstance(m, Histogram)
                else tuple(m._kw.get("buckets") or Histogram.DEFAULT_BUCKETS)
            )
            if tuple(kw["buckets"]) != have:
                raise ValueError(
                    f"metric {name!r} already registered with buckets "
                    f"{have}, not {tuple(kw['buckets'])}"
                )
        return m


def get(name: str):
    """Registered metric by name (None if absent): the read-side fetch
    that does not need to repeat a vec's label names."""
    with _reg_lock:
        return _REGISTRY.get(name)


def counter(name: str, help_: str = "") -> Counter:
    return _get_or_create(Counter, name, help_)


def gauge(name: str, help_: str = "") -> Gauge:
    return _get_or_create(Gauge, name, help_)


def histogram(name: str, help_: str = "", buckets=None) -> Histogram:
    return _get_or_create(Histogram, name, help_, buckets=buckets)


def counter_vec(name: str, help_: str = "", labelnames: Sequence[str] = ()) -> CounterVec:
    return _get_or_create(CounterVec, name, help_, labelnames=labelnames)


def gauge_vec(name: str, help_: str = "", labelnames: Sequence[str] = ()) -> GaugeVec:
    return _get_or_create(GaugeVec, name, help_, labelnames=labelnames)


def histogram_vec(
    name: str, help_: str = "", labelnames: Sequence[str] = (), buckets=None
) -> HistogramVec:
    return _get_or_create(
        HistogramVec, name, help_, labelnames=labelnames, buckets=buckets
    )


def registry_snapshot() -> Dict[str, _Metric]:
    """Name -> metric, one consistent read (the hygiene gate's surface)."""
    with _reg_lock:
        return dict(_REGISTRY)


_SAMPLE_RE = re.compile(
    r'^([a-z_][a-zA-Z0-9_]*)(\{(?:[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\\n]|\\["\\n])*",?)*\})? (.+)$'
)


def parse_exposition(text: str) -> List[Tuple[str, str, float]]:
    """Parse text in the format :func:`gather` produces; returns
    ``(name, raw label block, value)`` per sample line and raises
    ``ValueError`` on any malformed one. Lives next to the producer so
    the format's one grammar has one home (the metrics gates share it)."""
    samples = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        samples.append((m.group(1), m.group(2) or "", float(m.group(3))))
    return samples


def gather() -> str:
    """Prometheus text exposition of every registered metric."""
    out = []
    with _reg_lock:
        metrics = list(_REGISTRY.values())
    for m in sorted(metrics, key=lambda m: m.name):
        out.append(f"# HELP {m.name} {_escape_help(m.help)}")
        out.append(f"# TYPE {m.name} {m.kind}")
        body = m.expose()
        if body:  # a vec with no children yet has headers only
            out.append(body)
    return "\n".join(out) + "\n"
