"""Data-movement ledger: host↔device byte attribution for the staged
BLS verifier (ISSUE 8).

ROADMAP item 2 claims host→device pubkey re-upload is "the dominant
host→device bytes and most host pack time" — this module makes that
claim MEASURABLE. The FPGA verification-engine paper (PAPERS.md, arxiv
2112.02229) wins by keeping precomputed keys device-resident, and the
committee cost model (arxiv 2302.00418) prices verification in
data-movement terms; before the device-resident pubkey table is built,
every byte it would save must be visible, per-kind, under real traffic.

Three surfaces, one module:

* **Per-verify cost attribution** — the raw packer
  (``crypto/device/bls.pack_signature_sets_raw``) measures its phases
  (``decode`` byte parsing, ``limb_split`` int→limb conversion, ``pad``
  allocation + padding-lane fill, ``hash`` hash_to_field, ``device_put``
  host→device transfer) and reports per-operand byte splits here:
  ``bls_device_pack_seconds{phase}``,
  ``bls_device_h2d_bytes_total{operand,kind}`` (operands ``pubkeys`` /
  ``signatures`` / ``messages`` / ``aux`` count LIVE bytes; ``padding``
  counts every byte shipped for lanes no caller asked for — the label
  sums to the exact ``ndarray.nbytes`` the device_put moved, pinned by
  test), ``bls_device_d2h_bytes_total`` (verdict reads). Each staged
  verify journals ONE ``transfer_ledger`` flight-recorder event carrying
  the whole row.
* **Repeat-pubkey evidence** — :class:`ReuploadTracker`, a bounded
  sliding-window sketch keyed by pubkey digest: what fraction of the G1
  bytes uploaded within the last N verifies were re-uploads of
  already-seen keys (``bls_device_pubkey_reupload_ratio{kind}``). THE
  number that sizes the device-resident key table's win: ratio × pubkey
  bytes/s = the H2D bandwidth a device-side gather would reclaim.
* **Device-memory telemetry** — ``device_memory_bytes{kind}`` from JAX
  live-buffer stats (``live_buffers`` everywhere; allocator
  ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` where the
  backend supports ``memory_stats()``, null-safe elsewhere), probed on
  a throttle from the health surface — never from the verify hot path,
  whose latency feeds the SLO layer.

Attribution context (caller kind + resolution path) is THREAD-LOCAL:
the scheduler (``verification_service/batcher.py``) wraps each backend
call in :func:`context`, so a planned sub-batch attributes its bytes to
its own kind and a split-and-retry re-pack is labeled
``path=bisection`` — the retry's bytes are real (the host DID re-ship
them) but they can never be mistaken for the original flush's
(exactly-once per pack, pinned by test). CPU resolutions
(compile-service fallback) record zero-device-byte rows via
:func:`record_cpu`.

Import-time this module is jax-free (tools read it offline); the
device-memory probe imports jax lazily and degrades to nothing. With
the ledger disabled (``LIGHTHOUSE_TPU_TRANSFER_LEDGER=0``) every
recording entry point returns in well under 1 µs (pinned like disabled
spans).

Byte model: :func:`operand_bytes_model` is the ONE analytic formula for
what a padded (B, K, M) raw-pack ships per operand — shared by the
flush planner's plan accounting, ``tools/transfer_report.py``'s replay
mode and ``tools/cost_model.py``; equality with the packer's actual
``ndarray.nbytes`` is pinned by test.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import flight_recorder, metrics, slot_ledger

# ---------------------------------------------------------------------------
# Byte model (int32 limb layout, crypto/device/fp.py: NL=32 12-bit limbs)
# ---------------------------------------------------------------------------

NL = 32                         # limbs per field element (pinned == fp.NL)
_FP_BYTES = NL * 4              # one Fp element, int32 limbs
G1_POINT_BYTES = 2 * _FP_BYTES  # affine (x, y) — one packed pubkey row
_FP2_BYTES = 2 * _FP_BYTES

PACK_PHASES = ("decode", "limb_split", "pad", "hash", "device_put")
OPERANDS = ("pubkeys", "signatures", "messages", "aux", "padding")


# one pubkey slot on the wire: raw = a limb-packed G1 affine row + its
# mask bool; indexed = an int32 table index + its mask bool (the device-
# resident key table, ISSUE 10 — crypto/device/key_table.py)
INDEXED_SLOT_BYTES = 4 + 1


def operand_bytes_model(
    b: int, k: int, m: int, indexed: bool = False
) -> Dict[str, int]:
    """Exact bytes a padded (B, K, M) raw pack ships host→device, per
    operand family (the ``ndarray.nbytes`` of the device_put arguments;
    equality pinned by test):

    * ``pubkeys``: ``pk_xy`` int32[B,K,2,NL] + ``pk_mask`` bool[B,K] —
      or, with ``indexed=True`` (``pack_signature_sets_indexed``, the
      static half of the packer split), ``pk_idx`` int32[B,K] +
      ``pk_mask`` bool[B,K]
    * ``signatures``: ``sig_x`` int32[B,2,NL] + ``sig_larger`` bool[B]
    * ``messages``: ``msg_u`` int32[M,2,2,NL] + ``msg_idx`` int32[B]
    * ``aux``: ``rand`` int32[B,2] + ``set_mask`` bool[B]
    """
    slot = INDEXED_SLOT_BYTES if indexed else G1_POINT_BYTES + 1
    out = {
        "pubkeys": b * k * slot,
        "signatures": b * (_FP2_BYTES + 1),
        "messages": m * 2 * _FP2_BYTES + b * 4,
        "aux": b * (2 * 4 + 1),
    }
    out["total"] = sum(out.values())
    return out


def live_operand_bytes(
    n_sets: int, pk_slots: int, m_req: int, indexed: bool = False
) -> Dict[str, int]:
    """The share of :func:`operand_bytes_model` the callers actually
    asked for: ``pk_slots`` real pubkey slots, ``n_sets`` live lanes,
    ``m_req`` distinct messages. ``padded − live`` is the padding
    share."""
    slot = INDEXED_SLOT_BYTES if indexed else G1_POINT_BYTES + 1
    out = {
        "pubkeys": pk_slots * slot,
        "signatures": n_sets * (_FP2_BYTES + 1),
        "messages": m_req * 2 * _FP2_BYTES + n_sets * 4,
        "aux": n_sets * (2 * 4 + 1),
    }
    out["total"] = sum(out.values())
    return out


# ---------------------------------------------------------------------------
# Metric families
# ---------------------------------------------------------------------------

_H2D_BYTES = metrics.counter_vec(
    "bls_device_h2d_bytes_total",
    "host→device bytes shipped by the raw staged packer, by operand "
    "(pubkeys/signatures/messages/aux count LIVE bytes; padding counts "
    "every byte shipped for lanes no caller asked for — the labels sum "
    "to the exact device_put ndarray.nbytes) and caller kind (the "
    "scheduler's attribution context; `direct` outside a scheduler)",
    ("operand", "kind"),
)
_D2H_BYTES = metrics.counter(
    "bls_device_d2h_bytes_total",
    "device→host bytes read back per staged verify (the verdict scalar "
    "— intermediates stay on device by design)",
)
_PACK_SECONDS = metrics.histogram_vec(
    "bls_device_pack_seconds",
    "host-side raw-pack wall time by phase: decode (signature byte "
    "parsing + randomness), limb_split (int→limb conversion + array "
    "fill), pad (allocation + padding-lane fill), hash (message "
    "hash_to_field), device_put (host→device transfer, measured "
    "dispatch-to-ready when the ledger is enabled; with it disabled "
    "async backends record enqueue time only — the hot path keeps its "
    "transfer/dispatch overlap), total (the whole pack — phase sum ≈ "
    "total, pinned by test). Replaces the unlabeled family of the "
    "same name (ISSUE 8)",
    ("phase",),
)
# public handle: the device backend's non-instrumented packers observe
# phase="total" directly (crypto/device/bls.py)
PACK_SECONDS = _PACK_SECONDS
_REUPLOAD_RATIO = metrics.gauge_vec(
    "bls_device_pubkey_reupload_ratio",
    "fraction of G1 pubkey bytes uploaded within the sliding window "
    "(last N staged verifies) that were re-uploads of already-seen "
    "keys, per caller kind — the number that sizes ROADMAP item 2's "
    "device-resident pubkey table win (ratio × pubkey bytes/s = "
    "reclaimable H2D bandwidth)",
    ("kind",),
)
_DEVICE_MEMORY = metrics.gauge_vec(
    "device_memory_bytes",
    "device memory telemetry from JAX: live_buffers (sum of live array "
    "nbytes, every backend) plus allocator stats (bytes_in_use / "
    "peak_bytes_in_use / bytes_limit) where the backend supports "
    "memory_stats(); kinds absent where the backend reports nothing "
    "(null-safe), and a kind the latest probe no longer reports decays "
    "to 0 rather than serving its last value as current",
    ("kind",),
)
_LEDGER_VERIFIES = metrics.counter_vec(
    "bls_device_ledger_rows_total",
    "transfer-ledger rows committed, by resolution path (device = a "
    "staged verify with measured bytes; cpu paths record zero device "
    "bytes)",
    ("path",),
)


# ---------------------------------------------------------------------------
# Enable / configure
# ---------------------------------------------------------------------------


# one env-parsing convention across the observability knobs
_env_int = flight_recorder._env_int
_env_float = flight_recorder._env_float

_enabled = os.environ.get("LIGHTHOUSE_TPU_TRANSFER_LEDGER", "1") not in ("", "0")
_mem_interval_s = _env_float("LIGHTHOUSE_TPU_LEDGER_MEM_INTERVAL_S", 5.0)
_window = _env_int("LIGHTHOUSE_TPU_LEDGER_WINDOW", 1024)


def enabled() -> bool:
    return _enabled


def configure(
    enabled: Optional[bool] = None,
    window: Optional[int] = None,
    mem_interval_s: Optional[float] = None,
) -> dict:
    """Override knobs at runtime; returns the PREVIOUS values so tests
    can restore them (flight_recorder.configure's contract)."""
    global _enabled, _window, _mem_interval_s, _tracker
    prev = {
        "enabled": _enabled,
        "window": _window,
        "mem_interval_s": _mem_interval_s,
    }
    if enabled is not None:
        _enabled = bool(enabled)
    if window is not None and int(window) != _window:
        _window = max(1, int(window))
        _tracker = ReuploadTracker(_window)
    if mem_interval_s is not None:
        _mem_interval_s = float(mem_interval_s)
    return prev


# ---------------------------------------------------------------------------
# Attribution context (thread-local kind + resolution path)
# ---------------------------------------------------------------------------

_tls = threading.local()

_DEFAULT_CONTEXT = ("direct", "direct")


class _Ctx:
    """Context manager scoping one (kind, path) attribution frame."""

    __slots__ = ("kind", "path", "_prev")

    def __init__(self, kind: str, path: str):
        self.kind = kind
        self.path = path

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = (self.kind, self.path)
        return self

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def context(kind: str, path: str) -> _Ctx:
    """Attribute every pack/commit on THIS thread inside the ``with`` to
    ``(kind, path)`` — the scheduler wraps each backend call so bytes
    land on the caller kind, and bisection retries are labeled
    ``path=bisection`` instead of inflating the original flush."""
    return _Ctx(str(kind), str(path))


def current_context() -> Tuple[str, str]:
    ctx = getattr(_tls, "ctx", None)
    return ctx if ctx is not None else _DEFAULT_CONTEXT


# ---------------------------------------------------------------------------
# Repeat-pubkey sliding-window sketch
# ---------------------------------------------------------------------------


def pubkey_digest(blob: bytes) -> bytes:
    """16-byte blake2b digest of one packed pubkey row (the canonical
    int32 limb encoding) — the window key. Exposed so the replay
    modeling in ``tools/transfer_report.py`` keys the same space."""
    return hashlib.blake2b(blob, digest_size=16).digest()


class ReuploadTracker:
    """Bounded sliding window over the last ``window`` observations
    (one observation = one staged verify's pubkey uploads): per kind,
    what fraction of uploaded G1 bytes were re-uploads of a digest
    already present in the window. Thread-safe; eviction is exact for
    totals (a record leaving the window removes its bytes) and
    first-upload-sticky for membership (an entry marked re-upload at
    insert time stays one for its lifetime — the sketch answers "how
    much of the recent upload stream was redundant", not "which copy
    was first").
    """

    def __init__(self, window: int = 1024):
        self.window = max(1, int(window))
        self._ring: deque = deque()
        self._counts: Dict[bytes, int] = {}
        self._uploaded: Dict[str, int] = {}
        self._reuploaded: Dict[str, int] = {}
        self._lock = threading.Lock()

    def observe(
        self, kind: str, entries: Iterable[Tuple[bytes, int]]
    ) -> Tuple[int, int]:
        """Record one verify's pubkey uploads: ``entries`` are
        ``(digest, nbytes)`` pairs. Returns ``(reuploaded_bytes,
        uploaded_bytes)`` for THIS observation."""
        kind = str(kind)
        with self._lock:
            rec: List[Tuple[bytes, int, bool]] = []
            up = re = 0
            for digest, nb in entries:
                nb = int(nb)
                seen = self._counts.get(digest, 0) > 0
                self._counts[digest] = self._counts.get(digest, 0) + 1
                rec.append((digest, nb, seen))
                up += nb
                if seen:
                    re += nb
            self._ring.append((kind, rec))
            self._uploaded[kind] = self._uploaded.get(kind, 0) + up
            self._reuploaded[kind] = self._reuploaded.get(kind, 0) + re
            while len(self._ring) > self.window:
                old_kind, old_rec = self._ring.popleft()
                o_up = o_re = 0
                for digest, nb, was_re in old_rec:
                    c = self._counts.get(digest, 0) - 1
                    if c <= 0:
                        self._counts.pop(digest, None)
                    else:
                        self._counts[digest] = c
                    o_up += nb
                    if was_re:
                        o_re += nb
                # .get defaults: a zero-upload record can outlive its
                # kind's popped totals (the kind re-appears at 0 and is
                # re-popped below) — eviction must never raise
                self._uploaded[old_kind] = (
                    self._uploaded.get(old_kind, 0) - o_up
                )
                self._reuploaded[old_kind] = (
                    self._reuploaded.get(old_kind, 0) - o_re
                )
                if self._uploaded[old_kind] <= 0:
                    self._uploaded.pop(old_kind, None)
                    self._reuploaded.pop(old_kind, None)
            return re, up

    def ratio(self, kind: Optional[str] = None) -> float:
        """Re-upload fraction of the current window, per kind or (with
        ``kind=None``) over every kind together. 0.0 when nothing was
        uploaded."""
        with self._lock:
            if kind is None:
                up = sum(self._uploaded.values())
                re = sum(self._reuploaded.values())
            else:
                up = self._uploaded.get(kind, 0)
                re = self._reuploaded.get(kind, 0)
        return re / up if up else 0.0

    def summary(self) -> dict:
        with self._lock:
            kinds = {}
            for k in sorted(self._uploaded):
                k_up = self._uploaded.get(k, 0)
                k_re = self._reuploaded.get(k, 0)
                kinds[k] = {
                    "uploaded_bytes": k_up,
                    "reuploaded_bytes": k_re,
                    "ratio": round(k_re / k_up, 4) if k_up else 0.0,
                }
            up = sum(self._uploaded.values())
            re = sum(self._reuploaded.values())
            return {
                "window": self.window,
                "records": len(self._ring),
                "distinct_keys": len(self._counts),
                "uploaded_bytes": up,
                "reuploaded_bytes": re,
                "ratio": round(re / up, 4) if up else 0.0,
                "kinds": kinds,
            }


_tracker = ReuploadTracker(_window)


def tracker() -> ReuploadTracker:
    """The process-global sketch (the gauges' backing store)."""
    return _tracker


# ---------------------------------------------------------------------------
# Recording entry points (the hot path; <1 µs disabled)
# ---------------------------------------------------------------------------


def observe_pack_phases(phases: Dict[str, float], total_s: float) -> None:
    """Land pack-phase seconds in ``bls_device_pack_seconds{phase}``.
    NOT gated by the ledger knob: the pack histogram predates the ledger
    (it was the unlabeled family) and metric families stay always-on —
    ``LIGHTHOUSE_TPU_TRANSFER_LEDGER=0`` turns off byte accounting, the
    sketch and the journal rows, never pack-time telemetry."""
    for phase, s in phases.items():
        _PACK_SECONDS.with_labels(phase).observe(s)
    _PACK_SECONDS.with_labels("total").observe(total_s)


def note_pack(
    n_sets: int,
    b: int,
    k: int,
    m: int,
    pk_slots: int,
    m_req: int,
    phases: Dict[str, float],
    total_s: float,
    operand_nbytes: Dict[str, int],
    pubkey_blobs: Sequence[bytes],
    indexed: bool = False,
) -> None:
    """One raw pack completed: attribute operand bytes to the current
    (kind, path) context, feed the repeat-pubkey sketch, and stage the
    row for :func:`commit_verify` (same thread). The packer calls this
    unconditionally; disabled = immediate return (phase telemetry goes
    through :func:`observe_pack_phases`, which is not gated).

    ``operand_nbytes`` are the ACTUAL per-operand array nbytes (ground
    truth, not the model); ``pubkey_blobs`` the packed per-pubkey limb
    rows as bytes. ``indexed=True`` marks the static packer (device
    key-table gather): the pubkey operand is the index plane, and no G1
    blobs feed the re-upload sketch — nothing G1-shaped crossed the
    boundary."""
    if not _enabled:
        return
    kind, path = current_context()
    live = live_operand_bytes(n_sets, pk_slots, m_req, indexed=indexed)
    total_bytes = 0
    by_operand = {}
    for op in ("pubkeys", "signatures", "messages", "aux"):
        nb = int(operand_nbytes.get(op, 0))
        total_bytes += nb
        by_operand[op] = min(live[op], nb)
    padding = total_bytes - sum(by_operand.values())
    by_operand["padding"] = padding
    for op, nb in by_operand.items():
        if nb:
            _H2D_BYTES.with_labels(op, kind).inc(nb)
    if total_bytes:
        # chain-time attribution: the slot's report card carries the
        # byte total (operand split stays in the counter family)
        slot_ledger.note_h2d_bytes(total_bytes)

    entries = [
        (pubkey_digest(blob), len(blob)) for blob in pubkey_blobs
    ]
    re_b, up_b = _tracker.observe(kind, entries)
    # refresh EVERY exported kind, not just the one that packed: a kind
    # whose window entries evicted must decay to 0.0 on the scrape, or
    # /metrics would disagree with the health block about the same
    # window (gauge children cannot be unregistered)
    _REUPLOAD_RATIO.with_labels(kind).set(_tracker.ratio(kind))
    for (k_label,), child in _REUPLOAD_RATIO.children().items():
        if k_label != kind:
            child.set(_tracker.ratio(k_label))

    _tls.pending = {
        "kind": kind,
        "path": path,
        "indexed": bool(indexed),
        "n_sets": int(n_sets),
        "b": int(b), "k": int(k), "m": int(m),
        "pk_slots": int(pk_slots), "m_req": int(m_req),
        "phases": {p: round(s, 6) for p, s in phases.items()},
        "pack_s": round(total_s, 6),
        "h2d_bytes": by_operand,
        "h2d_bytes_total": total_bytes,
        "pubkeys_uploaded_bytes": up_b,
        "pubkeys_reuploaded_bytes": re_b,
    }


def pending_pack() -> Optional[dict]:
    """Peek at this thread's staged (not yet committed) pack row."""
    return getattr(_tls, "pending", None)


def commit_verify(verdict: Optional[bool], d2h_bytes: int = 1) -> None:
    """One staged verify completed on THIS thread: pop the staged pack
    row, count the verdict read-back, and journal the full ledger row
    as ONE ``transfer_ledger`` flight-recorder event. No staged row
    (ledger was off at pack time, or a non-instrumented packer ran) =
    no event — the journal never carries fabricated bytes. The pop
    happens even when disabled: a row staged before a disable/enable
    cycle must never be journaled against a later, unrelated verify."""
    row = getattr(_tls, "pending", None)
    _tls.pending = None
    if not _enabled or row is None:
        return
    _D2H_BYTES.inc(int(d2h_bytes))
    _LEDGER_VERIFIES.with_labels("device").inc()
    ops = row["h2d_bytes"]
    phase_fields = {
        f"{p}_s": s for p, s in row["phases"].items()
    }
    flight_recorder.record(
        "transfer_ledger",
        kind=row["kind"], path=row["path"],
        indexed=row.get("indexed", False),
        n_sets=row["n_sets"],
        b=row["b"], k=row["k"], m=row["m"],
        pack_s=row["pack_s"],
        **phase_fields,
        h2d_bytes_total=row["h2d_bytes_total"],
        pubkeys_bytes=ops.get("pubkeys", 0),
        signatures_bytes=ops.get("signatures", 0),
        messages_bytes=ops.get("messages", 0),
        aux_bytes=ops.get("aux", 0),
        padding_bytes=ops.get("padding", 0),
        pubkeys_uploaded_bytes=row["pubkeys_uploaded_bytes"],
        pubkeys_reuploaded_bytes=row["pubkeys_reuploaded_bytes"],
        d2h_bytes=int(d2h_bytes),
        # None = the verify raised before producing a verdict (the row
        # still lands: the pack's bytes were real)
        verdict=None if verdict is None else bool(verdict),
    )


def note_op_bytes(operand_nbytes: Dict[str, int], kind: Optional[str] = None) -> None:
    """Standalone device-op H2D attribution for dispatches that are NOT
    a signature-set pack — the MSM-stage host helpers (``device_msm_g1``
    ships G1 points + scalars, ``device_sum_g2`` ships G2 points; ISSUE
    17 satellite: "msm can't run dark"). Ticks the same
    ``bls_device_h2d_bytes_total{operand,kind}`` family against the
    current attribution context (or an explicit ``kind``) and lands the
    byte total in the slot ledger. No journal row and no re-upload
    sketch: those are per-verify surfaces, and an MSM dispatch is not a
    verify."""
    if not _enabled:
        return
    k = kind if kind is not None else current_context()[0]
    total = 0
    for op, nb in operand_nbytes.items():
        nb = int(nb)
        if nb:
            _H2D_BYTES.with_labels(op, k).inc(nb)
            total += nb
    if total:
        slot_ledger.note_h2d_bytes(total)


def record_cpu(n_sets: int, kind: Optional[str] = None,
               path: Optional[str] = None) -> None:
    """A CPU-resolved verification (compile-service fallback): journal a
    zero-device-byte ledger row so data-movement accounting stays
    exactly-once across resolution paths — the device shipped nothing
    for these sets, and the row says so explicitly."""
    if not _enabled:
        return
    ckind, cpath = current_context()
    _LEDGER_VERIFIES.with_labels("cpu").inc()
    flight_recorder.record(
        "transfer_ledger",
        kind=kind if kind is not None else ckind,
        path=path if path is not None else cpath,
        n_sets=int(n_sets),
        b=0, k=0, m=0,
        pack_s=0.0,
        h2d_bytes_total=0,
        pubkeys_bytes=0, signatures_bytes=0, messages_bytes=0,
        aux_bytes=0, padding_bytes=0,
        pubkeys_uploaded_bytes=0, pubkeys_reuploaded_bytes=0,
        d2h_bytes=0,
        verdict=None,
    )


# ---------------------------------------------------------------------------
# Device-memory telemetry (lazy jax import; null-safe everywhere)
# ---------------------------------------------------------------------------

_mem_lock = threading.Lock()
_last_mem_update = 0.0
_MEM_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def update_device_memory(force: bool = False) -> Optional[dict]:
    """Refresh ``device_memory_bytes{kind}`` from JAX. Throttled to one
    probe per ``mem_interval_s`` unless ``force``; returns the gauge
    values, or None when jax is absent / not yet imported / reports
    nothing (the null-safe contract — a CPU-only host simply has no
    allocator stats, and live_buffers alone still reports)."""
    global _last_mem_update
    if not _enabled and not force:
        return None
    now = time.monotonic()
    with _mem_lock:
        if not force and now - _last_mem_update < _mem_interval_s:
            return None
        _last_mem_update = now
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        # never IMPORT jax from the telemetry path: a jax-free process
        # (tools, lockstep replay) stays jax-free
        return None
    out = {}
    try:
        live = getattr(jax, "live_arrays", None)
        if live is None:
            return None
        bufs = live()
        out["live_buffers"] = int(sum(a.nbytes for a in bufs))
        # allocator stats need jax.local_devices(), which INITIALIZES
        # the backend as a side effect — only safe once a live array
        # proves the backend is already up (a health scrape on a node
        # that has not verified yet must never trigger platform init
        # from the HTTP thread: on a dead device tunnel that is a hang)
        if bufs:
            for dev in jax.local_devices():
                stats = None
                try:
                    stats = dev.memory_stats()
                except Exception:
                    stats = None
                if stats:
                    for key in _MEM_STAT_KEYS:
                        if key in stats:
                            out[key] = int(stats[key])
                break  # device 0 describes the node this ledger serves
    except Exception:
        return out or None
    # refresh EVERY exported kind: one the current probe no longer
    # reports decays to 0 (a vanished allocator stat must not serve
    # its last value as current — same decay rule as the reupload
    # gauge; children cannot be unregistered)
    stale = {
        labels[0] for labels in _DEVICE_MEMORY.children()
    } - set(out)
    for kind, v in out.items():
        _DEVICE_MEMORY.with_labels(kind).set(v)
    for kind in stale:
        _DEVICE_MEMORY.with_labels(kind).set(0)
    return out or None


# ---------------------------------------------------------------------------
# Summary (the /lighthouse/health `data_movement` block; jax-free)
# ---------------------------------------------------------------------------


def summary() -> dict:
    """One document for ``/lighthouse/health`` and the bench
    ``data_movement`` block: cumulative per-operand/per-kind H2D bytes,
    pack-phase seconds, pack share of the device verify wall, effective
    H2D bandwidth over the device_put phase, the repeat-pubkey window,
    and device memory."""
    by_operand: Dict[str, float] = {}
    by_kind: Dict[str, float] = {}
    for (operand, kind), child in _H2D_BYTES.children().items():
        by_operand[operand] = by_operand.get(operand, 0) + child.value
        by_kind[kind] = by_kind.get(kind, 0) + child.value
    h2d_total = sum(by_operand.values())

    phases = {}
    for (phase,), child in _PACK_SECONDS.children().items():
        total, sum_, _ = child.snapshot()
        if total:
            phases[phase] = {"count": total, "sum_s": round(sum_, 6)}
    pack_sum = phases.get("total", {}).get("sum_s", 0.0)
    dput_sum = phases.get("device_put", {}).get("sum_s", 0.0)

    # pack share of the end-to-end verify wall (device histogram family
    # registered by crypto/device/bls.py; absent in a jax-free process)
    verify_wall = 0.0
    fam = metrics.get("bls_device_verify_seconds")
    if fam is not None and hasattr(fam, "children"):
        for _labels, child in fam.children().items():
            _t, s, _c = child.snapshot()
            verify_wall += s

    # throttle-respecting probe (a dashboard polling /lighthouse/health
    # must not walk jax.live_arrays() every few seconds); between probes
    # the gauges' last values serve — same data at probe-interval
    # freshness
    mem = update_device_memory()
    if mem is None:
        mem = {
            labels[0]: child.value
            for labels, child in _DEVICE_MEMORY.children().items()
        } or None

    return {
        "enabled": _enabled,
        "h2d_bytes_total": int(h2d_total),
        "h2d_bytes_by_operand": {
            op: int(v) for op, v in sorted(by_operand.items())
        },
        "h2d_bytes_by_kind": {
            k: int(v) for k, v in sorted(by_kind.items())
        },
        "d2h_bytes_total": int(_D2H_BYTES.value),
        "pack_seconds": phases,
        "pack_share_of_verify_wall": (
            round(pack_sum / verify_wall, 4) if verify_wall else None
        ),
        # needs BOTH: the phase histogram is always-on, so with the
        # ledger disabled dput_sum > 0 while bytes stay 0 — that is
        # "unmeasured", never a confident 0.0 B/s
        "h2d_bandwidth_bytes_per_s": (
            round(h2d_total / dput_sum, 1)
            if dput_sum and h2d_total else None
        ),
        "pubkey_reupload": _tracker.summary(),
        "device_memory": mem,
    }
