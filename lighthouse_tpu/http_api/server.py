"""Standard Beacon API over the stdlib threading HTTP server (reference:
``beacon_node/http_api/src/lib.rs`` — one router over the chain; routes
from ``:483``; plus the ``/metrics`` scrape endpoint of
``beacon_node/http_metrics``).

Routes implemented (the set the validator client + checkpoint sync
consume):

    GET  /eth/v1/node/health | /eth/v1/node/version | /eth/v1/node/syncing
    GET  /eth/v1/beacon/genesis
    GET  /eth/v1/beacon/states/{state_id}/root
    GET  /eth/v1/beacon/states/{state_id}/fork
    GET  /eth/v1/beacon/states/{state_id}/finality_checkpoints
    GET  /eth/v1/beacon/states/{state_id}/validators
    GET  /eth/v1/beacon/headers/{block_id}
    GET  /eth/v2/beacon/blocks/{block_id}            (+ .ssz via Accept)
    POST /eth/v1/beacon/blocks
    GET/POST /eth/v1/beacon/pool/attestations
    POST /eth/v1/beacon/pool/voluntary_exits
    POST /eth/v1/beacon/pool/attester_slashings
    POST /eth/v1/beacon/pool/proposer_slashings
    GET  /eth/v1/beacon/states/{state_id}/committees
    GET  /eth/v1/node/identity | /eth/v1/node/peers
    GET  /eth/v1/beacon/light_client/{bootstrap/{root},finality_update,optimistic_update}
    POST /eth/v1/beacon/pool/sync_committees
    GET  /eth/v2/debug/beacon/states/{state_id}  (SSZ, checkpoint sync)
    GET  /eth/v1/config/spec
    GET  /eth/v1/validator/duties/proposer/{epoch}
    POST /eth/v1/validator/duties/attester/{epoch}
    POST /eth/v1/validator/duties/sync/{epoch}
    GET  /eth/v2/validator/blocks/{slot}
    GET  /eth/v1/validator/attestation_data
    GET  /eth/v1/validator/aggregate_attestation
    POST /eth/v1/validator/aggregate_and_proofs
    GET  /eth/v1/validator/blinded_blocks/{slot}
    POST /eth/v1/beacon/blinded_blocks
    GET  /eth/v1/beacon/rewards/blocks/{block_id}
    POST /eth/v1/beacon/rewards/attestations/{epoch}
    POST /eth/v1/validator/liveness/{epoch}
    GET  /eth/v1/node/peer_count | /eth/v1/node/peers/{peer_id}
    GET  /eth/v1/beacon/headers (+ ?slot= / ?parent_root= filters)
    GET  /eth/v1/beacon/blocks/{block_id}/root
    GET  /eth/v1/beacon/blocks/{block_id}/attestations
    GET  /eth/v1/beacon/states/{state_id}/validators/{validator_id}
    GET  /eth/v1/beacon/deposit_snapshot
    GET  /eth/v1/debug/beacon/heads
    GET  /lighthouse/health (short-TTL cached snapshot, see below)
    GET  /lighthouse/timeseries (?family=&window=&tier= filters)
    GET  /lighthouse/slots (?view=slots|epochs, ?last=N)
    GET  /lighthouse/incidents (?limit=N, ?open=1 — the watchtower ledger)
    GET  /metrics
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..ssz import hash_tree_root
from ..ssz.json import from_json, to_json
from ..state_transition import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
    partial_state_advance,
)
from ..state_transition.epoch import fork_of
from ..beacon_chain.pubkey_cache import PubkeyCacheError
from ..types.containers import FORK_IDS as _FORK_IDS
from ..utils import metrics, tracing

_HTTP_REQS = metrics.counter_vec(
    "http_api_requests_total",
    "beacon API requests by method and response code",
    ("method", "code"),
)
_HTTP_SECONDS = metrics.histogram(
    "http_api_request_seconds", "beacon API request handling wall time"
)

# /lighthouse/health snapshot TTL (ISSUE 18 satellite): assembling the
# document walks EVERY collector — scheduler, ledgers, profiler, mesh,
# capacity — so concurrent scrapers (dashboards + the watchtower
# evaluator's health provider) must not multiply that cost on the HTTP
# threads. 0 disables caching (every scrape re-collects).
try:
    _HEALTH_TTL_S = max(
        0.0, float(os.environ.get("LIGHTHOUSE_TPU_HEALTH_TTL_S", "") or 1.0)
    )
except ValueError:
    _HEALTH_TTL_S = 1.0


def build_health_doc(chain) -> dict:
    """Assemble the ONE consolidated node-health document (reference:
    the lighthouse-specific API namespace pulls common/system_health +
    monitoring_api process/beacon data): host stats, process + beacon-
    node state, beacon-processor queue depths, peer counts, and every
    instrument's own block — the page an operator reads first when the
    node misbehaves. Module-level so the watchtower's incident bundles
    can snapshot the same document the endpoint serves; callers wanting
    the short-TTL cache go through ``BeaconApiServer._health_doc``."""
    from ..utils import (
        fault_injection,
        flight_recorder,
        monitoring,
        pipeline_profiler,
        slot_ledger,
        system_health,
        timeseries,
        transfer_ledger,
        watchtower,
    )

    doc = {"system": system_health.observe()}
    try:
        doc.update(monitoring.collect(chain))
    except Exception as e:  # a degraded chain still reports hosts
        doc["collect_error"] = repr(e)
    proc = getattr(chain, "beacon_processor", None)
    doc["beacon_processor"] = (
        None
        if proc is None
        else {
            "queues": proc.queue_lengths(),
            "dropped_total": metrics.get(
                "beacon_processor_dropped_total"
            ).value,
        }
    )
    # derived from the collected doc: one transport read, one fact —
    # and UNKNOWN (null) when collect failed, never a fabricated
    # "0 peers" on the page operators read first
    bn = doc.get("beacon_node")
    doc["network"] = (
        None if bn is None else {"peer_count": bn.get("peers", 0)}
    )
    doc["flight_recorder"] = flight_recorder.status()
    # continuous-batching scheduler: queue depth + batch occupancy
    # (null when the chain runs without one)
    sched = getattr(chain, "verification_scheduler", None)
    doc["verification_scheduler"] = (
        None if sched is None else sched.status()
    )
    # verdict-latency SLO: rolling p50/p99 + deadline-miss ratio per
    # caller kind over the scheduler's sample window (null when the
    # chain runs without a scheduler) — the page that answers "what
    # are submitters experiencing right now", certified offline by
    # tools/traffic_replay.py (docs/TRAFFIC_REPLAY.md)
    doc["slo"] = None if sched is None else sched.slo_summary()
    # AOT compile service: warm-shape surface, compile queue and
    # persistent-cache state (null when the node runs without one)
    csvc = getattr(chain, "compile_service", None)
    doc["compile_service"] = None if csvc is None else csvc.status()
    # data-movement ledger (ISSUE 8): per-operand/per-kind H2D bytes,
    # pack-phase seconds + pack share of verify wall, repeat-pubkey
    # re-upload window, device memory — the evidence base for the
    # device-resident pubkey table (ROADMAP item 2); rendered by
    # tools/transfer_report.py
    doc["data_movement"] = transfer_ledger.summary()
    # device-resident pubkey table (ISSUE 10): residency, index-shipped
    # vs raw-shipped sets (hit ratio), the aggregate-sum cache and
    # upload accounting (null when the node runs without one)
    ktable = getattr(chain, "device_key_table", None)
    doc["key_table"] = None if ktable is None else ktable.status()
    # duty-lookahead precompute (ISSUE 19): worker state, warmed epoch,
    # per-path committee counts, pre-insert outcomes and the
    # failure/backoff posture (null when the node runs without the
    # worker — no key table, or disabled by config/env)
    lookahead = getattr(chain, "duty_lookahead", None)
    doc["duty_lookahead"] = None if lookahead is None else lookahead.status()
    # served dp mesh (ISSUE 11): per-chip sets/s, shard health,
    # per-chip device memory and the aggregate throughput the dp axis
    # delivers (null when the node runs single-device)
    dmesh = getattr(chain, "device_mesh", None)
    doc["mesh"] = None if dmesh is None else dmesh.status()
    # pipeline-occupancy profiler (ISSUE 12): per-shard device bubble
    # ratios with cause attribution, flush critical-path phase totals,
    # flush-thread saturation and the overlap-potential projection —
    # the evidence base for ROADMAP item 5; rendered by
    # tools/pipeline_report.py
    doc["pipeline"] = pipeline_profiler.summary()
    # fault injection (ISSUE 13): armed fault points + their
    # call/injection counters — served ONLY while a chaos run is
    # armed; a production node without chaos config shows null here
    # (and pays one global check per fault seam)
    doc["fault_injection"] = (
        fault_injection.status() if fault_injection.armed() else None
    )
    # capacity & saturation (ISSUE 14): the timeseries sampler's state
    # + memory accounting, the sampled family catalogue and the latest
    # capacity/headroom estimate — the dial ROADMAP item 2's admission
    # control reads; history at /lighthouse/timeseries, rendered by
    # tools/capacity_report.py
    doc["capacity"] = timeseries.capacity_summary()
    # chain-time attribution (ISSUE 17): the slot ledger's rollup
    # state — current slot/epoch, retained report cards, lifetime
    # totals and the latest epoch's first-sighting ratio (ROADMAP
    # item 3's go/no-go dial); per-slot cards at /lighthouse/slots,
    # rendered by tools/slot_report.py
    doc["chain_time"] = slot_ledger.summary()
    # the watchtower (ISSUE 18): per-detector state (armed/firing/
    # latched/cooldown), incident accounting, evaluator + bundle
    # config; the incident ledger itself at /lighthouse/incidents,
    # bundles rendered by tools/incident_report.py
    doc["watchtower"] = watchtower.summary()
    return doc


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class BeaconApiServer:
    """``chain`` is the BeaconChain; ``op_pool`` its pool. Runs on a
    daemon thread; ``port=0`` picks a free port (tests)."""

    def __init__(self, chain, host: str = "127.0.0.1", port: int = 5052):
        self.chain = chain
        # blinded-block flow: payload-header root -> full payload, filled
        # at blinded production, consumed (popped) at blinded submission
        # (the in-process stand-in for the builder's payload reveal);
        # bounded FIFO so polling production cannot leak payloads
        from collections import OrderedDict as _OD

        self._payload_cache: dict = _OD()
        self._payload_cache_cap = 8
        # handlers run on ThreadingHTTPServer threads: insert/evict/pop race
        self._payload_cache_lock = threading.Lock()
        # short-TTL /lighthouse/health snapshot (ISSUE 18 satellite):
        # N concurrent scrapes inside the TTL do ONE underlying collect
        # (pinned by the stampede test); (monotonic_t, doc)
        self._health_lock = threading.Lock()
        self._health_cache: tuple = (0.0, None)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                outer._dispatch(self, "GET")

            def do_POST(self):
                outer._dispatch(self, "POST")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def _health_doc(self) -> dict:
        """The ``/lighthouse/health`` document through the short-TTL
        snapshot cache: scrapes landing inside ``_HEALTH_TTL_S`` of the
        last collect are served the cached document; the collect runs
        UNDER the lock so a thundering herd does exactly one walk. TTL
        0 disables caching."""
        if _HEALTH_TTL_S <= 0:
            return build_health_doc(self.chain)
        with self._health_lock:
            t, doc = self._health_cache
            if doc is not None and time.monotonic() - t < _HEALTH_TTL_S:
                return doc
            doc = build_health_doc(self.chain)
            self._health_cache = (time.monotonic(), doc)
            return doc

    # -- plumbing --------------------------------------------------------

    def _dispatch(self, req, method: str) -> None:
        url = urlparse(req.path)
        # repeated params join to a comma list (the spec's ?id=1&id=2 and
        # ?id=1,2 forms become equivalent)
        query = {k: ",".join(v) for k, v in parse_qs(url.query).items()}
        counted = False  # one request = one http_api_requests_total sample
        try:
            body = None
            if method == "POST":
                n = int(req.headers.get("Content-Length") or 0)
                raw = req.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw) if raw else None
                except json.JSONDecodeError as e:
                    # a counted 400, not an uncounted dropped connection
                    raise ApiError(400, f"malformed JSON body: {e}")
            if url.path == "/eth/v1/events":
                if method != "GET":
                    raise ApiError(405, "GET only")
                # SSE streams until disconnect: counted once it ends
                # cleanly, never timed; a failure mid-setup falls through
                # to the 500 accounting below
                self._stream_events(req, query)
                _HTTP_REQS.with_labels(method, "200").inc()
                counted = True
                return
            with tracing.span(
                "http_api.request", method=method, path=url.path
            ), _HTTP_SECONDS.time():
                out = self._route(method, url.path, query, body)
            if out is None:
                payload, ctype = b"", "application/json"
            elif isinstance(out, bytes):
                payload, ctype = out, "application/octet-stream"
            elif isinstance(out, str):
                payload, ctype = out.encode(), "text/plain; charset=utf-8"
            else:
                payload, ctype = json.dumps(out).encode(), "application/json"
            # counted only once the response is fully serialized: a
            # serialization bug is a 500, a failed write after this point
            # is the client going away (not re-counted)
            _HTTP_REQS.with_labels(method, "200").inc()
            counted = True
            req.send_response(200)
            req.send_header("Content-Type", ctype)
            req.send_header("Content-Length", str(len(payload)))
            req.end_headers()
            req.wfile.write(payload)
        except ApiError as e:
            if not counted:
                _HTTP_REQS.with_labels(method, str(e.status)).inc()
            payload = json.dumps(
                {"code": e.status, "message": e.message}
            ).encode()
            req.send_response(e.status)
            req.send_header("Content-Type", "application/json")
            req.send_header("Content-Length", str(len(payload)))
            req.end_headers()
            req.wfile.write(payload)
        except Exception as e:  # internal error -> 500 with message
            # a write failure after the 200 was counted (client went
            # away) must not re-count the request as a 500
            if not counted:
                _HTTP_REQS.with_labels(method, "500").inc()
            payload = json.dumps({"code": 500, "message": repr(e)}).encode()
            try:
                req.send_response(500)
                req.send_header("Content-Type", "application/json")
                req.send_header("Content-Length", str(len(payload)))
                req.end_headers()
                req.wfile.write(payload)
            except Exception:
                pass

    def _stream_events(self, req, query) -> None:
        """Server-sent events (reference http_api ``events`` route):
        ``head`` and ``finalized_checkpoint`` topics, polled off the
        chain's canonical head; streams until the client disconnects
        (periodic keepalive comments bound disconnect detection and stop
        dead-connection threads accumulating)."""
        import time as _time

        topics = set((query.get("topics") or "head").split(","))
        chain = self.chain
        req.send_response(200)
        req.send_header("Content-Type", "text/event-stream")
        req.send_header("Cache-Control", "no-cache")
        req.end_headers()
        last_head = None
        last_epoch = None
        # a new subscriber must NOT get a synthetic event for a
        # finalization that happened long ago
        last_fin = chain.fork_choice.store.finalized_checkpoint
        last_write = _time.monotonic()
        try:
            while True:
                head = chain.head_block_root
                if "head" in topics and head != last_head:
                    last_head = head
                    # derive slot + state root from the STORED block:
                    # immune to the non-atomic head_block_root/head_state
                    # update in recompute_head
                    block = chain.store.get_block(head)
                    if block is not None:
                        slot = block.message.slot
                        state_root = bytes(block.message.state_root)
                    else:  # anchor edge: fall back to the state
                        state = chain.head_state
                        slot = state.slot
                        state_root = hash_tree_root(state)
                    epoch = slot // chain.preset.SLOTS_PER_EPOCH
                    data = {
                        "slot": str(slot),
                        "block": "0x" + head.hex(),
                        "state": "0x" + state_root.hex(),
                        "epoch_transition": (
                            last_epoch is not None and epoch != last_epoch
                        ),
                    }
                    last_epoch = epoch
                    req.wfile.write(
                        b"event: head\ndata: " + json.dumps(data).encode() + b"\n\n"
                    )
                    req.wfile.flush()
                    last_write = _time.monotonic()
                fin = chain.fork_choice.store.finalized_checkpoint
                if "finalized_checkpoint" in topics and fin != last_fin:
                    last_fin = fin
                    data = {
                        "epoch": str(fin[0]),
                        "block": "0x" + fin[1].hex(),
                    }
                    req.wfile.write(
                        b"event: finalized_checkpoint\ndata: "
                        + json.dumps(data).encode() + b"\n\n"
                    )
                    req.wfile.flush()
                    last_write = _time.monotonic()
                if _time.monotonic() - last_write > 5.0:
                    req.wfile.write(b":keepalive\n\n")
                    req.wfile.flush()
                    last_write = _time.monotonic()
                _time.sleep(0.2)
        except (BrokenPipeError, ConnectionResetError, OSError):
            return

    # -- state/block resolution ------------------------------------------

    def _state_for(self, state_id: str):
        chain = self.chain
        if state_id == "head":
            return chain.head_state
        if state_id == "genesis":
            return chain.store.get_state(chain.store.get_genesis_state_root())
        if state_id == "finalized":
            _, root = chain.fork_choice.store.finalized_checkpoint
            block = chain.store.get_block(root)
            if block is None:
                return chain.head_state
            return chain.store.get_state(bytes(block.message.state_root))
        if state_id.startswith("0x"):
            st = chain.store.get_state(bytes.fromhex(state_id[2:]))
            if st is None:
                raise ApiError(404, f"state {state_id} not found")
            return st
        raise ApiError(400, f"unsupported state id {state_id!r}")

    def _block_for(self, block_id: str):
        chain = self.chain
        if block_id == "head":
            root = chain.head_block_root
        elif block_id == "genesis":
            root = chain.genesis_block_root
        elif block_id == "finalized":
            _, root = chain.fork_choice.store.finalized_checkpoint
        elif block_id.startswith("0x"):
            root = bytes.fromhex(block_id[2:])
        else:
            raise ApiError(400, f"unsupported block id {block_id!r}")
        block = chain.store.get_block(root)
        if block is None:
            raise ApiError(404, f"block {block_id} not found")
        return root, block

    # -- router ----------------------------------------------------------

    def _route(self, method, path, query, body):
        chain = self.chain
        t = chain.types

        if path == "/eth/v1/node/health":
            return None
        if path == "/eth/v1/node/version":
            return {"data": {"version": "lighthouse_tpu/0.2.0"}}
        if path == "/eth/v1/node/syncing":
            head_slot = chain.head_state.slot
            current = chain.slot()
            return {
                "data": {
                    "head_slot": str(head_slot),
                    "sync_distance": str(max(0, current - head_slot)),
                    "is_syncing": current > head_slot + 1,
                    "is_optimistic": False,
                    "el_offline": False,
                }
            }
        if path == "/eth/v1/beacon/genesis":
            st = chain.store.get_state(chain.store.get_genesis_state_root())
            return {
                "data": {
                    "genesis_time": str(st.genesis_time),
                    "genesis_validators_root": "0x"
                    + bytes(st.genesis_validators_root).hex(),
                    "genesis_fork_version": "0x"
                    + bytes(chain.spec.genesis_fork_version).hex(),
                }
            }
        if path == "/eth/v1/config/spec":
            return {"data": chain.spec.to_api_dict(chain.preset)}
        if path == "/eth/v1/config/deposit_contract":
            return {
                "data": {
                    "chain_id": str(chain.spec.deposit_chain_id),
                    "address": "0x"
                    + bytes(chain.spec.deposit_contract_address).hex(),
                }
            }
        if path == "/eth/v1/config/fork_schedule":
            spec = chain.spec
            entries = []
            prev_version = spec.genesis_fork_version
            for fork in ("phase0", "altair", "bellatrix"):
                epoch = spec.fork_epoch_for(fork)
                if epoch is None:
                    continue
                version = spec.fork_version_for(fork)
                entries.append(
                    {
                        "previous_version": "0x" + bytes(prev_version).hex(),
                        "current_version": "0x" + bytes(version).hex(),
                        "epoch": str(epoch),
                    }
                )
                prev_version = version
            return {"data": entries}
        if path == "/metrics":
            return metrics.gather()
        if path == "/lighthouse/health":
            # the consolidated node-health document (assembled by
            # build_health_doc) through the short-TTL snapshot cache —
            # concurrent scrapes do ONE collector walk per TTL
            return {"data": self._health_doc()}
        if path == "/lighthouse/flight_recorder":
            # live journal tail: ?kind=a,b filters, ?limit=N bounds the
            # reply (newest events win); recorder status rides along
            from ..utils import flight_recorder

            kinds = None
            if "kind" in query:
                kinds = [k for k in query["kind"].split(",") if k]
            try:
                limit = int(query.get("limit", "256"))
            except ValueError:
                raise ApiError(400, "malformed limit parameter")
            return {
                "data": {
                    **flight_recorder.status(),
                    "events": flight_recorder.events(kinds=kinds, limit=limit),
                }
            }
        if path == "/lighthouse/timeseries":
            # retained on-node metrics history (ISSUE 14): ?family=a,b
            # filters to those series families, ?tier=raw|1m|10m picks
            # the downsampling tier, ?window=SECONDS keeps only points
            # newer than now − window. The latest capacity estimate
            # rides along so one fetch answers "how much headroom, and
            # which way is it trending".
            from ..utils import timeseries

            families = None
            if "family" in query:
                families = [f for f in query["family"].split(",") if f]
            tier = query.get("tier", "raw")
            window_s = None
            if "window" in query:
                try:
                    window_s = float(query["window"])
                except ValueError:
                    raise ApiError(400, "malformed window parameter")
                # nan compares False against every timestamp (silently
                # empty series), negative/inf windows are nonsense —
                # all are 400s per the documented grammar
                if window_s != window_s or window_s < 0 \
                        or window_s == float("inf"):
                    raise ApiError(400, "malformed window parameter")
            try:
                doc = timeseries.get_store().doc(
                    families=families, tier=tier, window_s=window_s
                )
            except ValueError as e:
                raise ApiError(400, str(e))
            doc["estimate"] = timeseries.last_estimate()
            return {"data": doc}
        if path == "/lighthouse/slots":
            # per-slot report cards (ISSUE 17): ?view=slots (default)
            # serves the retained slot cards, ?view=epochs the epoch
            # first-sighting rollup; ?last=N keeps only the N newest
            # rows. Lifetime + evicted totals ride along so a reader
            # can verify conservation (retained + evicted == lifetime)
            # from one fetch.
            from ..utils import slot_ledger

            view = query.get("view", "slots")
            if view not in ("slots", "epochs"):
                raise ApiError(400, "malformed view parameter")
            last = None
            if "last" in query:
                try:
                    last = int(query["last"])
                except ValueError:
                    raise ApiError(400, "malformed last parameter")
                if last < 0:
                    raise ApiError(400, "malformed last parameter")
            rows = (
                slot_ledger.slot_cards(last=last)
                if view == "slots"
                else slot_ledger.epoch_cards(last=last)
            )
            return {
                "data": {
                    "schema": slot_ledger.SCHEMA,
                    "view": view,
                    "chain_time": slot_ledger.summary(),
                    "rows": rows,
                    "lifetime": slot_ledger.lifetime_totals(),
                    "evicted": slot_ledger.evicted_totals(),
                }
            }
        if path == "/lighthouse/incidents":
            # the watchtower's incident ledger (ISSUE 18): ?limit=N
            # keeps the newest rows, ?open=1 filters to still-open
            # incidents; the per-detector state block and the declared
            # catalogue ride along so one fetch answers "what is
            # armed, what fired, and what does it watch". Bundles on
            # disk (schema lighthouse_tpu.incident/1) are rendered by
            # tools/incident_report.py.
            from ..utils import watchtower

            limit = None
            if "limit" in query:
                try:
                    limit = int(query["limit"])
                except ValueError:
                    raise ApiError(400, "malformed limit parameter")
                if limit < 0:
                    raise ApiError(400, "malformed limit parameter")
            open_q = query.get("open", "0")
            if open_q not in ("0", "1"):
                raise ApiError(400, "malformed open parameter")
            return {
                "data": {
                    "bundle_schema": watchtower.SCHEMA,
                    "watchtower": watchtower.summary(),
                    "catalogue": watchtower.catalogue(),
                    "incidents": watchtower.incidents(
                        limit=limit, open_only=open_q == "1"
                    ),
                }
            }


        m = re.fullmatch(r"/eth/v1/beacon/states/([^/]+)/root", path)
        if m:
            st = self._state_for(m.group(1))
            return {"data": {"root": "0x" + hash_tree_root(st).hex()}}
        m = re.fullmatch(r"/eth/v1/beacon/states/([^/]+)/fork", path)
        if m:
            st = self._state_for(m.group(1))
            return {"data": to_json(type(st.fork), st.fork)}
        m = re.fullmatch(
            r"/eth/v1/beacon/states/([^/]+)/finality_checkpoints", path
        )
        if m:
            st = self._state_for(m.group(1))
            cp = lambda c: {"epoch": str(c.epoch), "root": "0x" + bytes(c.root).hex()}
            return {
                "data": {
                    "previous_justified": cp(st.previous_justified_checkpoint),
                    "current_justified": cp(st.current_justified_checkpoint),
                    "finalized": cp(st.finalized_checkpoint),
                }
            }
        m = re.fullmatch(r"/eth/v1/beacon/states/([^/]+)/validator_balances", path)
        if m:
            st = self._state_for(m.group(1))
            ids = _parse_validator_ids(query)
            out = []
            for i, bal in enumerate(st.balances):
                if ids is not None:
                    pk_hex = "0x" + bytes(st.validators[i].pubkey).hex()
                    if str(i) not in ids and pk_hex not in ids:
                        continue
                out.append({"index": str(i), "balance": str(bal)})
            return {"data": out}

        m = re.fullmatch(r"/eth/v1/beacon/states/([^/]+)/sync_committees", path)
        if m:
            st = self._state_for(m.group(1))
            if fork_of(st) == "phase0":
                raise ApiError(400, "state has no sync committees (phase0)")
            P = chain.preset
            state_epoch = int(st.slot) // P.SLOTS_PER_EPOCH
            period = state_epoch // P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
            committee = st.current_sync_committee
            if "epoch" in query:
                want_period = int(query["epoch"]) // P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
                if want_period == period + 1:
                    committee = st.next_sync_committee
                elif want_period != period:
                    raise ApiError(
                        400, f"epoch outside the state's sync-committee periods"
                    )
            indices = []
            for pk in committee.pubkeys:
                idx = chain.pubkey_cache.get_index(bytes(pk))
                if idx is not None:
                    indices.append(idx)
            sub = P.sync_subcommittee_size or 1
            aggregates = [
                [str(i) for i in indices[k : k + sub]]
                for k in range(0, len(indices), sub)
            ]
            return {
                "data": {
                    "validators": [str(i) for i in indices],
                    "validator_aggregates": aggregates,
                }
            }

        m = re.fullmatch(r"/eth/v1/beacon/pool/(voluntary_exits|attester_slashings|proposer_slashings)", path)
        if m and method == "GET":
            pool = chain.op_pool
            if pool is None:
                return {"data": []}
            kind = m.group(1)
            tpe = {
                "voluntary_exits": t.SignedVoluntaryExit,
                "attester_slashings": t.AttesterSlashing,
                "proposer_slashings": t.ProposerSlashing,
            }[kind]
            return {"data": [to_json(tpe, o) for o in pool.contents()[kind]]}

        m = re.fullmatch(r"/eth/v1/beacon/states/([^/]+)/validators", path)
        if m:
            st = self._state_for(m.group(1))
            ids = _parse_validator_ids(query)
            out = []
            for i, (v, bal) in enumerate(zip(st.validators, st.balances)):
                pk_hex = "0x" + bytes(v.pubkey).hex()
                if ids is not None and str(i) not in ids and pk_hex not in ids:
                    continue
                out.append(
                    {
                        "index": str(i),
                        "balance": str(bal),
                        "status": _validator_status(chain.preset, st, v),
                        "validator": to_json(type(v), v),
                    }
                )
            return {"data": out}

        m = re.fullmatch(r"/eth/v1/beacon/states/([^/]+)/committees", path)
        if m:
            st = self._state_for(m.group(1))
            P = chain.preset
            try:
                epoch = (
                    int(query["epoch"])
                    if "epoch" in query
                    else st.slot // P.SLOTS_PER_EPOCH
                )
                want_slot = int(query["slot"]) if "slot" in query else None
                want_index = int(query["index"]) if "index" in query else None
            except ValueError:
                raise ApiError(400, "malformed epoch/slot/index parameter")
            head_epoch = chain.head_state.slot // P.SLOTS_PER_EPOCH
            # lookahead is only defined one epoch out; unbounded epochs
            # would make the shuffling cache advance a state arbitrarily
            # far (CPU DoS)
            if epoch > head_epoch + 1:
                raise ApiError(400, f"epoch {epoch} beyond lookahead")
            cache = chain.shuffling_cache.get(chain, epoch, chain.head_block_root)
            out = []
            for slot in range(
                epoch * P.SLOTS_PER_EPOCH, (epoch + 1) * P.SLOTS_PER_EPOCH
            ):
                if want_slot is not None and slot != want_slot:
                    continue
                for index in range(cache.committees_per_slot):
                    if want_index is not None and index != want_index:
                        continue
                    out.append(
                        {
                            "index": str(index),
                            "slot": str(slot),
                            "validators": [
                                str(int(v)) for v in cache.committee(slot, index)
                            ],
                        }
                    )
            return {"data": out}

        if path == "/eth/v1/node/identity":
            net = getattr(chain, "network", None)
            return {
                "data": {
                    "peer_id": f"lighthouse_tpu-{chain.genesis_block_root.hex()[:8]}",
                    "enr": "",
                    "p2p_addresses": (
                        [f"/ip4/127.0.0.1/tcp/{net.port}"] if net else []
                    ),
                    "discovery_addresses": [],
                    "metadata": {"seq_number": "0", "attnets": "0x" + "ff" * 8},
                }
            }
        if path == "/eth/v1/node/peers":
            net = getattr(chain, "network", None)
            peers = []
            if net is not None:
                peers = [_peer_json(p) for p in net.transport.peers_snapshot()]
            return {"data": peers, "meta": {"count": len(peers)}}

        m = re.fullmatch(r"/eth/v1/beacon/light_client/bootstrap/([^/]+)", path)
        if m:
            from ..beacon_chain.light_client import produce_bootstrap

            # the id is a BLOCK root per the beacon-API spec
            _root, block = self._block_for(m.group(1))
            st = chain.store.get_state(bytes(block.message.state_root))
            if st is None:
                raise ApiError(404, "state for bootstrap block unavailable")
            if not hasattr(st, "current_sync_committee"):
                raise ApiError(400, "pre-altair state has no light-client data")
            boot = produce_bootstrap(chain, st)
            return {"version": fork_of(st), "data": to_json(type(boot), boot)}
        if path == "/eth/v1/beacon/light_client/finality_update":
            from ..beacon_chain.light_client import produce_finality_update

            if not hasattr(chain.head_state, "current_sync_committee"):
                raise ApiError(400, "pre-altair state has no light-client data")
            upd = produce_finality_update(chain)
            if upd is None:
                raise ApiError(404, "no finality yet")
            return {
                "version": fork_of(chain.head_state),
                "data": to_json(type(upd), upd),
            }
        if path == "/eth/v1/beacon/light_client/optimistic_update":
            from ..beacon_chain.light_client import produce_optimistic_update

            if not hasattr(chain.head_state, "current_sync_committee"):
                raise ApiError(400, "pre-altair state has no light-client data")
            upd = produce_optimistic_update(chain)
            return {
                "version": fork_of(chain.head_state),
                "data": to_json(type(upd), upd),
            }

        m = re.fullmatch(r"/eth/v2/debug/beacon/states/([^/]+)", path)
        if m:
            # SSZ bytes (checkpoint-sync serving, reference http_api
            # debug routes + SURVEY §5 checkpoint sync)
            st = self._state_for(m.group(1))
            return bytes([_FORK_IDS[fork_of(st)]]) + type(st).encode(st)

        m = re.fullmatch(r"/eth/v1/beacon/headers/([^/]+)", path)
        if m:
            root, block = self._block_for(m.group(1))
            msg = block.message
            header = {
                "slot": str(msg.slot),
                "proposer_index": str(msg.proposer_index),
                "parent_root": "0x" + bytes(msg.parent_root).hex(),
                "state_root": "0x" + bytes(msg.state_root).hex(),
                "body_root": "0x" + hash_tree_root(msg.body).hex(),
            }
            return {
                "data": {
                    "root": "0x" + root.hex(),
                    "canonical": True,
                    "header": {
                        "message": header,
                        "signature": "0x" + bytes(block.signature).hex(),
                    },
                }
            }
        if path == "/eth/v1/beacon/headers":
            # canonical head when unfiltered; ?slot= / ?parent_root= list
            # matching blocks across the fork-choice DAG (reference
            # http_api/src/lib.rs:975 block_headers)
            proto = chain.fork_choice.proto
            head_root = chain.head_block_root
            matches = []
            if "slot" in query or "parent_root" in query:
                want_slot = int(query["slot"]) if "slot" in query else None
                want_parent = (
                    bytes.fromhex(query["parent_root"][2:])
                    if "parent_root" in query
                    else None
                )
                for node in proto.nodes:
                    if want_slot is not None and node.slot != want_slot:
                        continue
                    if want_parent is not None:
                        p = proto.nodes[node.parent] if node.parent is not None else None
                        if p is None or p.root != want_parent:
                            continue
                    matches.append(node.root)
            else:
                matches = [head_root]
            out = []
            for root in matches:
                block = chain.store.get_block(root)
                if block is None:
                    continue
                canonical = (
                    proto.ancestor_at_slot(head_root, block.message.slot) == root
                )
                out.append(_header_json(root, block, canonical))
            return {"data": out}
        m = re.fullmatch(r"/eth/v1/beacon/blocks/([^/]+)/root", path)
        if m:
            root, _ = self._block_for(m.group(1))
            return {"data": {"root": "0x" + root.hex()}}
        m = re.fullmatch(r"/eth/v1/beacon/blocks/([^/]+)/attestations", path)
        if m:
            root, block = self._block_for(m.group(1))
            return {
                "version": _fork_of_block(t, block),
                "data": [
                    to_json(type(a), a)
                    for a in block.message.body.attestations
                ],
            }
        m = re.fullmatch(
            r"/eth/v1/beacon/states/([^/]+)/validators/([^/]+)", path
        )
        if m:
            st = self._state_for(m.group(1))
            vid = m.group(2)
            idx = None
            if vid.startswith("0x"):
                try:
                    pk = bytes.fromhex(vid[2:])
                except ValueError:
                    raise ApiError(400, f"malformed pubkey {vid!r}")
                for i, v in enumerate(st.validators):
                    if bytes(v.pubkey) == pk:
                        idx = i
                        break
            else:
                try:
                    idx = int(vid)
                except ValueError:
                    raise ApiError(400, f"malformed validator id {vid!r}")
            if idx is None or not 0 <= idx < len(st.validators):
                raise ApiError(404, f"validator {vid} not found")
            v = st.validators[idx]
            return {
                "data": {
                    "index": str(idx),
                    "balance": str(st.balances[idx]),
                    "status": _validator_status(chain.preset, st, v),
                    "validator": to_json(type(v), v),
                }
            }
        if path == "/eth/v1/beacon/deposit_snapshot":
            # EIP-4881 deposit-tree snapshot (reference :1657); served from
            # the eth1 service's incremental tree when wired
            eth1 = getattr(chain, "eth1", None)
            if eth1 is None:
                raise ApiError(404, "no eth1 service attached")
            with eth1._lock:
                count = len(eth1.deposits)
                tree = eth1.deposit_tree
                root = tree.root(count)
                # EIP-4881: roots of the complete left subtrees covering
                # `count` leaves (one per set bit, high to low)
                finalized = []
                acc = 0
                for d in range(len(tree.levels) - 1, -1, -1):
                    if count & (1 << d):
                        finalized.append(
                            "0x" + tree._node(d, acc >> d, count).hex()
                        )
                        acc += 1 << d
                # read under the SAME lock: a concurrent eth1 update must
                # not advance the block pointer past the snapshotted count
                blocks = eth1.blocks
                last = blocks[-1] if blocks else None
            return {
                "data": {
                    "finalized": finalized,
                    "deposit_root": "0x" + root.hex(),
                    "deposit_count": str(count),
                    "execution_block_hash": (
                        "0x" + last.hash.hex() if last else "0x" + "00" * 32
                    ),
                    "execution_block_height": str(last.number if last else 0),
                }
            }
        if path == "/eth/v1/debug/beacon/heads":
            # viable fork-choice leaves (reference :1821): nodes that are
            # no other node's parent
            proto = chain.fork_choice.proto
            parents = {n.parent for n in proto.nodes if n.parent is not None}
            out = [
                {
                    "slot": str(n.slot),
                    "root": "0x" + n.root.hex(),
                    "execution_optimistic": False,
                }
                for i, n in enumerate(proto.nodes)
                if i not in parents
            ]
            return {"data": out}
        m = re.fullmatch(r"/eth/v1/node/peers/([^/]+)", path)
        if m:
            net = getattr(chain, "network", None)
            if net is not None:
                for peer in net.transport.peers_snapshot():
                    if peer.node_id == m.group(1):
                        return {"data": _peer_json(peer)}
            raise ApiError(404, f"peer {m.group(1)} not known")
        m = re.fullmatch(r"/eth/v2/beacon/blocks/([^/]+)", path)
        if m:
            root, block = self._block_for(m.group(1))
            return {
                "version": _fork_of_block(t, block),
                "data": to_json(type(block), block),
            }
        if path == "/eth/v1/beacon/blocks" and method == "POST":
            fork = body.get("version") if isinstance(body, dict) and "version" in body else None
            payload = body["data"] if isinstance(body, dict) and "data" in body else body
            fork = fork or fork_of(chain.head_state)
            sb = from_json(t.signed_block[fork], payload)
            try:
                chain.process_block(sb)
            except Exception as e:
                raise ApiError(400, f"block rejected: {e}")
            _publish(chain, "publish_block", sb)
            return None

        if path == "/eth/v1/beacon/pool/attestations":
            if method == "GET":
                return {"data": []}  # pending pool dump (not tracked per-data)
            results = []
            for obj in body:
                att = from_json(t.Attestation, obj)
                try:
                    v = chain.verify_unaggregated_attestation_for_gossip(att)
                    chain.apply_attestation_to_fork_choice(v)
                    if chain.op_pool is not None:
                        chain.op_pool.insert_attestation(att)
                    _publish(chain, "publish_attestation", att, int(att.data.index))
                except Exception as e:
                    results.append(str(e))
            if results:
                raise ApiError(400, "; ".join(results))
            return None
        if path == "/eth/v1/beacon/pool/voluntary_exits" and method == "POST":
            ex = from_json(t.SignedVoluntaryExit, body)
            if chain.op_pool is not None:
                chain.op_pool.insert_voluntary_exit(ex)
            _publish(chain, "publish_voluntary_exit", ex)
            return None
        if path == "/eth/v1/beacon/pool/attester_slashings" and method == "POST":
            s = from_json(t.AttesterSlashing, body)
            if chain.op_pool is not None:
                chain.op_pool.insert_attester_slashing(s)
            chain.on_attester_slashing(s)
            _publish(chain, "publish_attester_slashing", s)
            return None
        if path == "/eth/v1/beacon/pool/proposer_slashings" and method == "POST":
            s = from_json(t.ProposerSlashing, body)
            if chain.op_pool is not None:
                chain.op_pool.insert_proposer_slashing(s)
            _publish(chain, "publish_proposer_slashing", s)
            return None

        if path == "/eth/v1/beacon/pool/sync_committees" and method == "POST":
            st = chain.head_state
            if not hasattr(st, "current_sync_committee"):
                raise ApiError(400, "pre-altair state has no sync committee")
            from ..crypto import bls as _bls
            from ..types.chain_spec import DOMAIN_SYNC_COMMITTEE
            from ..types.domains import compute_signing_root, get_domain

            rejected = 0
            for obj in body:
                vi = int(obj["validator_index"])
                slot = int(obj["slot"])
                if not 0 <= vi < len(st.validators):
                    rejected += 1
                    continue
                committee = _sync_committee_for_slot(chain, st, slot)
                if committee is None:
                    rejected += 1
                    continue
                pk_raw = bytes(st.validators[vi].pubkey)
                positions = [i for i, c in enumerate(committee) if c == pk_raw]
                if not positions:
                    rejected += 1
                    continue
                root = bytes.fromhex(obj["beacon_block_root"][2:])
                sig_raw = bytes.fromhex(obj["signature"][2:])
                # verify BEFORE pooling: a junk signature must never be
                # able to poison block production
                domain = get_domain(
                    chain.spec, st, DOMAIN_SYNC_COMMITTEE,
                    slot // chain.preset.SLOTS_PER_EPOCH,
                )
                signing_root = compute_signing_root(None, root, domain)
                try:
                    sig = _bls.Signature.deserialize(sig_raw)
                    pk = chain.pubkey_cache.get(vi)
                    ok = sig.verify(pk, signing_root)
                except (_bls.BlsError, PubkeyCacheError):
                    ok = False
                if not ok:
                    rejected += 1
                    continue
                for pos in positions:
                    chain.op_pool.insert_sync_committee_message(
                        slot, root, pos, sig_raw
                    )
                # propagate node->node on the per-subnet gossip topics
                # (reference topics.rs:19-20, sync_committee_{subnet})
                net = getattr(chain, "network", None)
                if net is not None:
                    msg = t.SyncCommitteeMessage(
                        slot=slot,
                        beacon_block_root=root,
                        validator_index=vi,
                        signature=sig_raw,
                    )
                    sub_size = chain.preset.sync_subcommittee_size
                    for subnet in sorted({p // sub_size for p in positions}):
                        net.publish_sync_committee_message(msg, subnet)
            if rejected:
                raise ApiError(400, f"{rejected} sync message(s) rejected")
            return None

        m = re.fullmatch(r"/eth/v1/validator/duties/sync/(\d+)", path)
        if m and method == "POST":
            st = chain.head_state
            if not hasattr(st, "current_sync_committee"):
                return {"data": []}
            epoch = int(m.group(1))
            committee_b = _sync_committee_for_epoch(chain, st, epoch)
            if committee_b is None:
                raise ApiError(
                    400, "epoch outside current/next sync-committee period"
                )
            wanted = {int(i) for i in (body or [])}
            committee = committee_b
            by_pk = {}
            for i, v in enumerate(st.validators):
                by_pk[bytes(v.pubkey)] = i
            duties = []
            seen = {}
            for pos, pk in enumerate(committee):
                vi = by_pk.get(pk)
                if vi is None or (wanted and vi not in wanted):
                    continue
                seen.setdefault(vi, []).append(pos)
            for vi, positions in seen.items():
                duties.append(
                    {
                        "pubkey": "0x" + bytes(st.validators[vi].pubkey).hex(),
                        "validator_index": str(vi),
                        "validator_sync_committee_indices": [
                            str(p) for p in positions
                        ],
                    }
                )
            return {"data": duties}

        m = re.fullmatch(r"/eth/v1/validator/duties/proposer/(\d+)", path)
        if m:
            epoch = int(m.group(1))
            P = chain.preset
            st = chain.head_state
            start = epoch * P.SLOTS_PER_EPOCH
            proposers = chain.proposers_for_epoch(epoch)
            duties = []
            for slot, proposer in zip(
                range(start, start + P.SLOTS_PER_EPOCH), proposers
            ):
                duties.append(
                    {
                        "pubkey": "0x"
                        + bytes(st.validators[proposer].pubkey).hex(),
                        "validator_index": str(proposer),
                        "slot": str(slot),
                    }
                )
            return {
                "dependent_root": "0x" + chain.head_block_root.hex(),
                "execution_optimistic": False,
                "data": duties,
            }
        m = re.fullmatch(r"/eth/v1/validator/duties/attester/(\d+)", path)
        if m:
            epoch = int(m.group(1))
            P = chain.preset
            wanted = {int(i) for i in (body or [])}
            st = chain.head_state
            cache = chain.shuffling_cache.get(chain, epoch, chain.head_block_root)
            duties = []
            for slot in range(
                epoch * P.SLOTS_PER_EPOCH, (epoch + 1) * P.SLOTS_PER_EPOCH
            ):
                for index in range(cache.committees_per_slot):
                    committee = cache.committee(slot, index)
                    for pos, vi in enumerate(committee):
                        vi = int(vi)
                        if wanted and vi not in wanted:
                            continue
                        duties.append(
                            {
                                "pubkey": "0x"
                                + bytes(st.validators[vi].pubkey).hex(),
                                "validator_index": str(vi),
                                "committee_index": str(index),
                                "committee_length": str(len(committee)),
                                "committees_at_slot": str(
                                    cache.committees_per_slot
                                ),
                                "validator_committee_index": str(pos),
                                "slot": str(slot),
                            }
                        )
            return {
                "dependent_root": "0x" + chain.head_block_root.hex(),
                "execution_optimistic": False,
                "data": duties,
            }
        m = re.fullmatch(r"/eth/v2/validator/blocks/(\d+)", path)
        if m:
            slot = int(m.group(1))
            randao = bytes.fromhex(query["randao_reveal"][2:])
            graffiti = (
                bytes.fromhex(query["graffiti"][2:])
                if "graffiti" in query
                else bytes(32)
            )
            block, _proposer = chain.produce_block_on_state(slot, randao, graffiti)
            return {
                "version": fork_of(chain.head_state),
                "data": to_json(type(block), block),
            }

        m = re.fullmatch(r"/eth/v1/validator/blinded_blocks/(\d+)", path)
        if m:
            # blinded production (reference http_api blinded-block routes +
            # builder flow): bellatrix payloads are replaced by their
            # header; the full payload is cached for the submit leg
            slot = int(m.group(1))
            randao = bytes.fromhex(query["randao_reveal"][2:])
            graffiti = (
                bytes.fromhex(query["graffiti"][2:])
                if "graffiti" in query
                else bytes(32)
            )
            block, _proposer = chain.produce_block_on_state(slot, randao, graffiti)
            fork = fork_of(chain.head_state)
            if fork != "bellatrix":
                return {"version": fork, "data": to_json(type(block), block)}
            blinded, payload = _blind_block(t, block)
            header = blinded.body.execution_payload_header
            with self._payload_cache_lock:
                self._payload_cache[
                    hash_tree_root(t.ExecutionPayloadHeader, header)
                ] = payload
                while len(self._payload_cache) > self._payload_cache_cap:
                    self._payload_cache.popitem(last=False)
            return {
                "version": fork,
                "data": to_json(t.BlindedBeaconBlockBellatrix, blinded),
            }

        if path == "/eth/v1/beacon/blinded_blocks" and method == "POST":
            payload_json = (
                body["data"] if isinstance(body, dict) and "data" in body else body
            )
            fork = fork_of(chain.head_state)
            if fork != "bellatrix":
                sb = from_json(t.signed_block[fork], payload_json)
            else:
                sbb = from_json(t.SignedBlindedBeaconBlockBellatrix, payload_json)
                header = sbb.message.body.execution_payload_header
                with self._payload_cache_lock:
                    payload = self._payload_cache.pop(
                        hash_tree_root(t.ExecutionPayloadHeader, header), None
                    )
                if payload is None:
                    raise ApiError(400, "unknown payload header (not produced here)")
                bb = sbb.message
                full_body = t.block_body["bellatrix"](
                    **{
                        name: getattr(bb.body, name)
                        for name, _ in t.BlindedBeaconBlockBodyBellatrix.fields
                        if name != "execution_payload_header"
                    },
                    execution_payload=payload,
                )
                full = t.block["bellatrix"](
                    slot=bb.slot,
                    proposer_index=bb.proposer_index,
                    parent_root=bb.parent_root,
                    state_root=bb.state_root,
                    body=full_body,
                )
                sb = t.signed_block["bellatrix"](
                    message=full, signature=sbb.signature
                )
            try:
                chain.process_block(sb)
            except Exception as e:
                raise ApiError(400, f"block rejected: {e}")
            _publish(chain, "publish_block", sb)
            return None

        m = re.fullmatch(r"/eth/v1/beacon/rewards/blocks/([^/]+)", path)
        if m:
            return _block_rewards(chain, t, *self._block_for(m.group(1)))

        m = re.fullmatch(r"/eth/v1/beacon/rewards/attestations/(\d+)", path)
        if m and method == "POST":
            return _attestation_rewards(
                chain, t, int(m.group(1)), body or []
            )

        m = re.fullmatch(r"/eth/v1/validator/liveness/(\d+)", path)
        if m and method == "POST":
            epoch = int(m.group(1))
            out = []
            for idx in body or []:
                v = int(idx)
                live = (
                    chain.observed_attesters.is_known(v, epoch)
                    or chain.observed_aggregators.is_known(v, epoch)
                )
                out.append({"index": str(v), "is_live": bool(live)})
            return {"data": out}

        if path == "/eth/v1/node/peer_count":
            net = getattr(chain, "network", None)
            n = net.transport.peer_count() if net is not None else 0
            return {
                "data": {
                    "disconnected": "0",
                    "connecting": "0",
                    "connected": str(n),
                    "disconnecting": "0",
                }
            }
        if path == "/eth/v1/validator/attestation_data":
            slot = int(query["slot"])
            index = int(query["committee_index"])
            data = chain.produce_unaggregated_attestation(slot, index)
            return {"data": to_json(type(data), data)}
        if path == "/eth/v1/validator/aggregate_attestation":
            slot = int(query["slot"])
            data_root = bytes.fromhex(query["attestation_data_root"][2:])
            agg = _best_aggregate(chain, slot, data_root)
            if agg is None:
                raise ApiError(404, "no matching aggregate")
            return {"data": to_json(type(agg), agg)}
        if path == "/eth/v1/validator/aggregate_and_proofs" and method == "POST":
            for obj in body:
                sa = from_json(t.SignedAggregateAndProof, obj)
                v = chain.verify_aggregated_attestation_for_gossip(sa)
                chain.apply_attestation_to_fork_choice(v)
                if chain.op_pool is not None:
                    chain.op_pool.insert_attestation(sa.message.aggregate)
            return None

        # -- sync-committee aggregation surface (reference
        #    http_api/src/lib.rs:2375-2518) -------------------------------
        if path == "/eth/v1/validator/sync_committee_contribution":
            slot = int(query["slot"])
            subc = int(query["subcommittee_index"])
            root = bytes.fromhex(query["beacon_block_root"][2:])
            contribution = (
                chain.op_pool.sync_contribution_for(slot, root, subc)
                if chain.op_pool is not None
                else None
            )
            if contribution is None:
                raise ApiError(404, "no matching sync contribution")
            return {"data": to_json(type(contribution), contribution)}
        if path == "/eth/v1/validator/contribution_and_proofs" and method == "POST":
            from ..beacon_chain import (
                SyncCommitteeError,
                verify_sync_contribution,
            )

            failures = []
            for obj in body:
                sc = from_json(t.SignedContributionAndProof, obj)
                try:
                    verify_sync_contribution(chain, sc)
                except SyncCommitteeError as e:
                    # duplicates are normal between competing aggregators
                    # of the same subcommittee — not a client error
                    if e.kind not in (
                        "ContributionAlreadyKnown",
                        "AggregatorAlreadyKnown",
                    ):
                        failures.append(str(e))
                    continue
                if chain.op_pool is not None:
                    chain.op_pool.insert_sync_contribution(sc.message.contribution)
                net = getattr(chain, "network", None)
                if net is not None:
                    net.publish_sync_contribution(sc)
            if failures:
                raise ApiError(400, "; ".join(failures))
            return None
        if (
            path == "/eth/v1/validator/beacon_committee_subscriptions"
            and method == "POST"
        ):
            subs = getattr(chain, "committee_subscriptions", None)
            if subs is None:
                subs = chain.committee_subscriptions = []
            subs.extend(body)
            return None
        if (
            path == "/eth/v1/validator/sync_committee_subscriptions"
            and method == "POST"
        ):
            subs = getattr(chain, "sync_committee_subscriptions", None)
            if subs is None:
                subs = chain.sync_committee_subscriptions = []
            subs.extend(body)
            return None
        if path == "/eth/v1/validator/prepare_beacon_proposer" and method == "POST":
            prep = getattr(chain, "proposer_preparations", None)
            if prep is None:
                prep = chain.proposer_preparations = {}
            for obj in body:
                prep[int(obj["validator_index"])] = obj["fee_recipient"]
            return None
        if path == "/eth/v1/validator/register_validator" and method == "POST":
            regs = getattr(chain, "validator_registrations", None)
            if regs is None:
                regs = chain.validator_registrations = {}
            for obj in body:
                msg = obj.get("message", obj)
                regs[msg["pubkey"]] = msg
            return None

        raise ApiError(404, f"no route for {method} {path}")


def _sync_committee_for_epoch(chain, state, epoch: int):
    """Pubkey list for the sync-committee period containing ``epoch``:
    current period -> current committee, next period -> next committee,
    anything else -> None (the state cannot know it)."""
    P = chain.preset
    period = epoch // P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    head_period = (
        state.slot // P.SLOTS_PER_EPOCH
    ) // P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    if period == head_period:
        return [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    if period == head_period + 1:
        return [bytes(pk) for pk in state.next_sync_committee.pubkeys]
    return None


def _sync_committee_for_slot(chain, state, slot: int):
    return _sync_committee_for_epoch(
        chain, state, slot // chain.preset.SLOTS_PER_EPOCH
    )


def _validator_status(P, state, v) -> str:
    from ..types.chain_spec import FAR_FUTURE_EPOCH

    epoch = state.slot // P.SLOTS_PER_EPOCH
    if v.activation_epoch > epoch:
        return (
            "pending_queued"
            if v.activation_eligibility_epoch != FAR_FUTURE_EPOCH
            else "pending_initialized"
        )
    if epoch < v.exit_epoch:
        return "active_slashed" if v.slashed else "active_ongoing"
    if epoch < v.withdrawable_epoch:
        return "exited_slashed" if v.slashed else "exited_unslashed"
    return "withdrawal_possible"


def _header_json(root: bytes, block, canonical: bool) -> dict:
    msg = block.message
    return {
        "root": "0x" + root.hex(),
        "canonical": canonical,
        "header": {
            "message": {
                "slot": str(msg.slot),
                "proposer_index": str(msg.proposer_index),
                "parent_root": "0x" + bytes(msg.parent_root).hex(),
                "state_root": "0x" + bytes(msg.state_root).hex(),
                "body_root": "0x" + hash_tree_root(msg.body).hex(),
            },
            "signature": "0x" + bytes(block.signature).hex(),
        },
    }


def _peer_json(peer) -> dict:
    return {
        "peer_id": peer.node_id,
        "last_seen_p2p_address": f"/ip4/{peer.addr[0]}/tcp/{peer.addr[1]}",
        "state": "connected",
        "direction": "outbound",
        "enr": "",
    }


def _fork_of_block(t, signed_block) -> str:
    for fork, cls in t.signed_block.items():
        if isinstance(signed_block, cls):
            return fork
    return "phase0"


def _best_aggregate(chain, slot: int, data_root: bytes):
    """Best-coverage aggregate for (slot, data_root) from the op pool
    (the naive-aggregation-pool read path)."""
    pool = chain.op_pool
    if pool is None:
        return None
    t = chain.types
    with pool._lock:
        entry = pool._attestations.get(bytes(data_root))
        if entry is None:
            return None
        data, groups = entry
        if data.slot != slot or not groups:
            return None
        best = max(groups, key=lambda g: sum(g.aggregation_bits))
        return t.Attestation(
            aggregation_bits=list(best.aggregation_bits),
            data=data,
            signature=best.signature,
        )


def _parse_validator_ids(query) -> set | None:
    """Spec ValidatorId filter: ?id=1,2 / repeated ?id= / 0x-pubkeys."""
    ids = {
        x
        for chunk in query.get("id", "").split(",")
        for x in [chunk.strip()]
        if x
    }
    return ids or None


def _publish(chain, method: str, *args) -> None:
    """Gossip an API-submitted object when a network is attached
    (reference: the publish routes gossip after import)."""
    net = getattr(chain, "network", None)
    if net is None:
        return
    try:
        getattr(net, method)(*args)
    except Exception:
        pass  # gossip is best-effort; the object is already imported


def _blind_block(t, block):
    """Full bellatrix block -> (blinded block, extracted payload).
    The header's transactions_root commits to the withheld payload."""
    payload = block.body.execution_payload
    header = t.ExecutionPayloadHeader(
        **{
            name: getattr(payload, name)
            for name, _ in t.ExecutionPayloadHeader.fields
            if name != "transactions_root"
        },
        transactions_root=hash_tree_root(
            dict(t.ExecutionPayload.fields)["transactions"], payload.transactions
        ),
    )
    body = t.BlindedBeaconBlockBodyBellatrix(
        **{
            name: getattr(block.body, name)
            for name, _ in t.BlindedBeaconBlockBodyBellatrix.fields
            if name != "execution_payload_header"
        },
        execution_payload_header=header,
    )
    blinded = t.BlindedBeaconBlockBellatrix(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=block.state_root,
        body=body,
    )
    return blinded, payload


def _block_rewards(chain, t, root, signed_block):
    """Proposer reward decomposition for one block (reference http_api
    block-rewards route): each component measured as the proposer-balance
    delta of applying exactly that op class with the REAL op processors —
    no formula duplication to drift."""
    import copy as _copy

    from ..state_transition import block as st_block
    from ..state_transition import partial_state_advance
    from ..state_transition.block import (
        state_pubkey_bytes_resolver,
        state_pubkey_resolver,
    )

    block = signed_block.message
    preset, spec = chain.preset, chain.spec
    parent = chain.state_at_block_root(bytes(block.parent_root))
    state = partial_state_advance(
        preset, spec, _copy.deepcopy(parent), int(block.slot)
    )
    fork = fork_of(state)
    proposer = int(block.proposer_index)
    resolver = state_pubkey_resolver(state)

    def bal() -> int:
        return int(state.balances[proposer])

    components = {}
    b0 = bal()
    for ps in block.body.proposer_slashings:
        st_block.process_proposer_slashing(preset, spec, state, ps, fork, False, resolver)
    components["proposer_slashings"] = bal() - b0
    b0 = bal()
    for asl in block.body.attester_slashings:
        st_block.process_attester_slashing(preset, spec, state, asl, fork, False, resolver)
    components["attester_slashings"] = bal() - b0
    b0 = bal()
    for att in block.body.attestations:
        st_block.process_attestation(preset, spec, state, att, fork, False, resolver)
    components["attestations"] = bal() - b0
    components["sync_aggregate"] = 0
    if fork != "phase0":
        # spec definition: proposer_reward per included bit — NOT the raw
        # proposer-balance delta, which on small committees also contains
        # the proposer's own participant reward
        _, proposer_per_bit = st_block.sync_aggregate_rewards(preset, state)
        n_bits = sum(
            1 for b in block.body.sync_aggregate.sync_committee_bits if b
        )
        components["sync_aggregate"] = proposer_per_bit * n_bits
        st_block.process_sync_aggregate(
            preset, spec, state, int(block.slot), block.body.sync_aggregate,
            False, state_pubkey_bytes_resolver(state),
        )
    return {
        "execution_optimistic": False,
        "finalized": False,
        "data": {
            "proposer_index": str(proposer),
            "total": str(sum(components.values())),
            "attestations": str(components["attestations"]),
            "sync_aggregate": str(components["sync_aggregate"]),
            "proposer_slashings": str(components["proposer_slashings"]),
            "attester_slashings": str(components["attester_slashings"]),
        },
    }


def _phase0_attestation_rewards(chain, state, indices) -> dict:
    """Phase0 attestation rewards from PendingAttestations (un-501s the
    route; reference computes the same from get_attestation_deltas —
    ``consensus/state_processing/src/per_epoch_processing/base/rewards_and_penalties.rs``).
    Per spec semantics: attested components earn the proportional reward,
    missed components cost the full base reward (negative)."""
    from ..state_transition.epoch import (
        _base_reward_phase0,
        _eligible_indices,
        _is_in_inactivity_leak,
        _matching_attestations,
        _matching_head_attestations,
        _matching_target_attestations,
        _unslashed_attesting_indices,
    )
    from ..state_transition.helpers import (
        get_previous_epoch,
        get_total_active_balance,
        get_total_balance,
    )

    P = chain.preset
    previous = get_previous_epoch(P, state)
    total = get_total_active_balance(P, state)
    increment = P.EFFECTIVE_BALANCE_INCREMENT
    in_leak = _is_in_inactivity_leak(P, state)
    eligible = _eligible_indices(P, state)

    if indices:
        want = [int(i) for i in indices]
        n = len(state.validators)
        for i in want:
            if not 0 <= i < n:
                raise ApiError(400, f"validator index {i} out of range")
    else:
        want = eligible

    comps = {}
    ideal_by_eff: dict[int, dict[str, int]] = {}
    for name, atts in (
        ("source", _matching_attestations(P, state, previous)),
        ("target", _matching_target_attestations(P, state, previous)),
        ("head", _matching_head_attestations(P, state, previous)),
    ):
        unslashed = set(_unslashed_attesting_indices(P, state, atts))
        attesting_balance = get_total_balance(P, state, unslashed)
        vals = {}
        eligible_set = set(eligible)
        for i in want:
            if i not in eligible_set:
                vals[i] = 0
                continue
            base = _base_reward_phase0(P, state, total, i)
            if i in unslashed:
                vals[i] = (
                    base if in_leak
                    else base * (attesting_balance // increment) // (total // increment)
                )
            else:
                vals[i] = -base
        comps[name] = vals
        for i in eligible:
            eff = int(state.validators[i].effective_balance)
            base = _base_reward_phase0(P, state, total, i)
            ideal_by_eff.setdefault(eff, {})[name] = (
                base if in_leak
                else base * (attesting_balance // increment) // (total // increment)
            )

    total_rewards = [
        {
            "validator_index": str(i),
            "head": str(comps["head"][i]),
            "target": str(comps["target"][i]),
            "source": str(comps["source"][i]),
            "inactivity": "0",
        }
        for i in want
    ]
    ideal = [
        {
            "effective_balance": str(eff),
            "head": str(v.get("head", 0)),
            "target": str(v.get("target", 0)),
            "source": str(v.get("source", 0)),
            "inactivity": "0",
        }
        for eff, v in sorted(ideal_by_eff.items())
    ]
    return {"data": {"ideal_rewards": ideal, "total_rewards": total_rewards}}


def _attestation_rewards(chain, t, epoch: int, indices) -> dict:
    """Attestation rewards for ``epoch`` (reference http_api
    attestation-rewards route): per-validator source/target/head +
    inactivity from the columnar reward kernels, computed on a state
    whose PREVIOUS epoch is the requested one."""
    from ..state_transition.helpers import compute_epoch_at_slot
    from ..state_transition.state.epoch import altair_reward_components

    state = chain.head_state
    cur = compute_epoch_at_slot(chain.preset, state.slot)
    if fork_of(state) == "phase0":
        if cur < epoch + 1:
            raise ApiError(400, f"epoch {epoch} is not yet complete (current {cur})")
        if cur > epoch + 1:
            raise ApiError(501, "historical attestation rewards not supported")
        return _phase0_attestation_rewards(chain, state, indices)
    # rewards for epoch E are defined once E is the PREVIOUS epoch of a
    # completed head (advancing a copy cannot conjure the attestations,
    # and an unbounded requested epoch would be a remote CPU sink)
    if cur < epoch + 1:
        raise ApiError(400, f"epoch {epoch} is not yet complete (current {cur})")
    if cur > epoch + 1:
        raise ApiError(501, "historical attestation rewards not supported")
    comp = altair_reward_components(chain.preset, chain.spec, state)
    if indices:
        want = [int(i) for i in indices]
        n = len(state.validators)
        for i in want:
            if not 0 <= i < n:
                raise ApiError(400, f"validator index {i} out of range")
    else:
        want = [
            i for i in range(len(state.validators)) if comp["eligible"][i]
        ]
    total = [
        {
            "validator_index": str(i),
            "head": str(int(comp["head"][i])),
            "target": str(int(comp["target"][i])),
            "source": str(int(comp["source"][i])),
            "inactivity": str(int(comp["inactivity"][i])),
        }
        for i in want
    ]
    ideal = [
        {
            "effective_balance": str(eff),
            "head": str(int(v["head"])),
            "target": str(int(v["target"])),
            "source": str(int(v["source"])),
            "inactivity": "0",
        }
        for eff, v in sorted(comp["ideal"].items())
    ]
    return {"data": {"ideal_rewards": ideal, "total_rewards": total}}
