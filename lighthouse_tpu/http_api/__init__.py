"""L6: the standard Beacon API HTTP server + metrics endpoint.

Reference: ``beacon_node/http_api`` (warp router, ``src/lib.rs:483+``)
and ``beacon_node/http_metrics``.
"""

from .server import BeaconApiServer

__all__ = ["BeaconApiServer"]
