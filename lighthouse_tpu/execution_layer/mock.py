"""Mock execution layer: an in-process engine-API HTTP server that
accepts everything (reference: ``execution_layer/src/test_utils`` —
MockExecutionLayer + mock server used by BeaconChainHarness and the
simulator).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MockExecutionLayer:
    """Configurable verdicts: set ``payload_status`` to INVALID/SYNCING to
    exercise the optimistic/invalid paths."""

    def __init__(self, port: int = 0):
        self.payload_status = "VALID"
        self.requests: list[dict] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                outer.requests.append(body)
                method = body.get("method", "")
                result: object = None
                if method == "engine_newPayloadV1":
                    result = {
                        "status": outer.payload_status,
                        "latestValidHash": body["params"][0].get("blockHash"),
                        "validationError": None,
                    }
                elif method == "engine_forkchoiceUpdatedV1":
                    has_attrs = body["params"][1] is not None
                    result = {
                        "payloadStatus": {"status": outer.payload_status},
                        "payloadId": "0x0000000000000001" if has_attrs else None,
                    }
                elif method == "engine_getPayloadV1":
                    result = outer._empty_payload()
                payload = json.dumps(
                    {"jsonrpc": "2.0", "id": body.get("id"), "result": result}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    @staticmethod
    def _empty_payload() -> dict:
        z32 = "0x" + "00" * 32
        return {
            "parentHash": z32,
            "feeRecipient": "0x" + "00" * 20,
            "stateRoot": z32,
            "receiptsRoot": z32,
            "logsBloom": "0x" + "00" * 256,
            "prevRandao": z32,
            "blockNumber": "0x0",
            "gasLimit": "0x1c9c380",
            "gasUsed": "0x0",
            "timestamp": "0x0",
            "extraData": "0x",
            "baseFeePerGas": "0x7",
            "blockHash": z32,
            "transactions": [],
        }

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
