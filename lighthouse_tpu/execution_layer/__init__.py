"""L4b: execution-layer bridge — engine API client, state machine,
payload cache, and the mock EL used by tests.

Reference: ``beacon_node/execution_layer`` (``src/engine_api/http.rs:31-41``
new_payload/forkchoice_updated/get_payload, ``src/engines.rs`` upcheck
state machine, ``src/test_utils`` MockExecutionLayer).
"""

from .engine_api import EngineApiClient, EngineApiError, PayloadStatus
from .execution_layer import ExecutionLayer
from .mock import MockExecutionLayer

__all__ = [
    "EngineApiClient",
    "EngineApiError",
    "ExecutionLayer",
    "MockExecutionLayer",
    "PayloadStatus",
]
