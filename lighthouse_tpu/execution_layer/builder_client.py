"""External block-builder (MEV-boost) HTTP client + in-process mock
(reference: ``beacon_node/builder_client/src/lib.rs`` — status /
register_validators / get_header / submit_blinded_block over the
builder-specs REST API).

The BN uses this when ``--builder <url>`` is configured: registrations
forwarded from the VC's ``register_validator`` route, a header fetched at
proposal time, and the signed blinded block submitted back for unblinding.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class BuilderError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"builder HTTP {status}: {message}")
        self.status = status


class BuilderHttpClient:
    """Thin typed client over the builder-specs routes."""

    def __init__(self, base_url: str, timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _req(self, method: str, path: str, body=None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            raise BuilderError(e.code, e.read().decode(errors="replace")) from None
        except OSError as e:
            raise BuilderError(0, str(e)) from None

    # -- builder-specs surface -------------------------------------------

    def status(self) -> bool:
        self._req("GET", "/eth/v1/builder/status")
        return True

    def register_validators(self, registrations: list) -> None:
        self._req("POST", "/eth/v1/builder/validators", registrations)

    def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes):
        return self._req(
            "GET",
            f"/eth/v1/builder/header/{slot}/0x{bytes(parent_hash).hex()}"
            f"/0x{bytes(pubkey).hex()}",
        )["data"]

    def submit_blinded_block(self, signed_blinded_block_json):
        return self._req(
            "POST", "/eth/v1/builder/blinded_blocks", signed_blinded_block_json
        )["data"]


class MockBuilder:
    """In-process builder server for tests (reference
    ``execution_layer/src/test_utils`` mock builder): records
    registrations, serves a canned header bid, and unblinds submissions."""

    def __init__(self, port: int = 0, bid_value_wei: int = 10**18):
        self.registrations: dict[str, dict] = {}
        self.headers_served: list[tuple] = []
        self.submitted: list = []
        self.bid_value_wei = bid_value_wei
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, obj=None) -> None:
                payload = json.dumps(obj).encode() if obj is not None else b""
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if self.path == "/eth/v1/builder/status":
                    return self._reply(200, {})
                if self.path.startswith("/eth/v1/builder/header/"):
                    parts = self.path.split("/")
                    slot, parent_hash, pubkey = parts[5], parts[6], parts[7]
                    outer.headers_served.append((int(slot), parent_hash, pubkey))
                    return self._reply(
                        200,
                        {
                            "version": "bellatrix",
                            "data": {
                                "message": {
                                    "header": {"parent_hash": parent_hash},
                                    "value": str(outer.bid_value_wei),
                                    "pubkey": pubkey,
                                },
                                "signature": "0x" + "00" * 96,
                            },
                        },
                    )
                return self._reply(404, {"message": "no route"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"null")
                if self.path == "/eth/v1/builder/validators":
                    for reg in body or []:
                        msg = reg.get("message", {})
                        outer.registrations[msg.get("pubkey", "")] = reg
                    return self._reply(200, {})
                if self.path == "/eth/v1/builder/blinded_blocks":
                    outer.submitted.append(body)
                    return self._reply(
                        200, {"version": "bellatrix", "data": {"unblinded": True}}
                    )
                return self._reply(404, {"message": "no route"})

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_port
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )

    def start(self) -> "MockBuilder":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
