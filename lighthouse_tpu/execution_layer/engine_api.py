"""Engine API JSON-RPC client (reference:
``execution_layer/src/engine_api/http.rs:31-41,667-722`` —
``engine_newPayloadV1``, ``engine_forkchoiceUpdatedV1``,
``engine_getPayloadV1`` with JWT auth).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.request


class EngineApiError(Exception):
    pass


class PayloadStatus:
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"


def _jwt(secret: bytes) -> str:
    """HS256 JWT with an iat claim (the engine-API auth scheme)."""
    header = base64.urlsafe_b64encode(
        json.dumps({"alg": "HS256", "typ": "JWT"}).encode()
    ).rstrip(b"=")
    claims = base64.urlsafe_b64encode(
        json.dumps({"iat": int(time.time())}).encode()
    ).rstrip(b"=")
    signing_input = header + b"." + claims
    sig = base64.urlsafe_b64encode(
        hmac.new(secret, signing_input, hashlib.sha256).digest()
    ).rstrip(b"=")
    return (signing_input + b"." + sig).decode()


class EngineApiClient:
    def __init__(self, url: str, jwt_secret: bytes | None = None, timeout: float = 8.0):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self._id = 0

    def _call(self, method: str, params: list):
        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "method": method, "params": params, "id": self._id}
        ).encode()
        headers = {"Content-Type": "application/json"}
        if self.jwt_secret:
            headers["Authorization"] = "Bearer " + _jwt(self.jwt_secret)
        req = urllib.request.Request(self.url, data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                out = json.loads(r.read())
        except OSError as e:
            raise EngineApiError(f"engine unreachable: {e}") from None
        except ValueError as e:  # non-JSON body (HTML error page, truncation)
            raise EngineApiError(f"engine returned non-JSON: {e}") from None
        if not isinstance(out, dict):
            raise EngineApiError("engine returned non-object response")
        err = out.get("error")
        if err:
            msg = err.get("message", "engine error") if isinstance(err, dict) else str(err)
            raise EngineApiError(msg)
        return out.get("result")

    # -- the three verbs -------------------------------------------------

    def new_payload(self, payload_json: dict) -> dict:
        return self._call("engine_newPayloadV1", [payload_json])

    def forkchoice_updated(self, state: dict, attributes: dict | None = None) -> dict:
        return self._call("engine_forkchoiceUpdatedV1", [state, attributes])

    def get_payload(self, payload_id: str) -> dict:
        return self._call("engine_getPayloadV1", [payload_id])
