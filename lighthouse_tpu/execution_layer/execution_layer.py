"""ExecutionLayer service: engine state machine + payload plumbing
(reference: ``execution_layer/src/lib.rs`` + ``engines.rs`` — upcheck /
retry, falling back to SYNCING-optimistic verdicts when the EL is out).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..fork_choice.proto_array import ExecutionStatus
from .engine_api import EngineApiClient, EngineApiError, PayloadStatus


class ExecutionLayer:
    def __init__(self, engine: EngineApiClient):
        self.engine = engine
        self._lock = threading.Lock()
        self._online = True
        self._payload_cache: dict[bytes, dict] = {}

    # -- engine state ----------------------------------------------------

    def upcheck(self) -> bool:
        try:
            self.engine.forkchoice_updated(
                {
                    "headBlockHash": "0x" + "00" * 32,
                    "safeBlockHash": "0x" + "00" * 32,
                    "finalizedBlockHash": "0x" + "00" * 32,
                },
                None,
            )
            online = True
        except EngineApiError:
            online = False
        with self._lock:
            self._online = online
        return online

    @property
    def online(self) -> bool:
        with self._lock:
            return self._online

    # -- consensus-side entry points -------------------------------------

    def notify_new_payload(self, payload_json: dict) -> ExecutionStatus:
        """-> fork-choice execution status (optimistic on EL outage, the
        reference's optimistic-sync behaviour)."""
        try:
            out = self.engine.new_payload(payload_json)
        except EngineApiError:
            with self._lock:
                self._online = False
            return ExecutionStatus.OPTIMISTIC
        status = (out or {}).get("status", PayloadStatus.SYNCING)
        if status == PayloadStatus.VALID:
            return ExecutionStatus.VALID
        if status == PayloadStatus.INVALID:
            return ExecutionStatus.INVALID
        return ExecutionStatus.OPTIMISTIC

    def notify_forkchoice_updated(
        self,
        head_block_hash: bytes,
        finalized_block_hash: bytes,
        payload_attributes: dict | None = None,
    ) -> Optional[str]:
        """-> payload_id when attributes were supplied (block production)."""
        try:
            out = self.engine.forkchoice_updated(
                {
                    "headBlockHash": "0x" + head_block_hash.hex(),
                    "safeBlockHash": "0x" + head_block_hash.hex(),
                    "finalizedBlockHash": "0x" + finalized_block_hash.hex(),
                },
                payload_attributes,
            )
        except EngineApiError:
            with self._lock:
                self._online = False
            return None
        return (out or {}).get("payloadId")

    def get_payload(self, payload_id: str) -> Optional[dict]:
        try:
            return self.engine.get_payload(payload_id)
        except EngineApiError:
            return None
