"""Canonical lowering/warmup helpers for the staged device BLS programs.

ONE definition of the staged programs' argument shapes at a bucket rung
(B, K, M), shared by every consumer that needs "the programs the node
actually dispatches":

* the compile-budget gate (``tools/hlo_stats.py`` ->
  ``tests/test_zgate2_compile_budget.py``) lowers them to count HLO
  instructions;
* the compile profilers (``tools/profile_compile.py`` /
  ``profile_compile2.py``) time lower+compile on them;
* the :class:`~lighthouse_tpu.compile_service.service.CompileService`
  warms them ahead of traffic (:func:`warm_staged`).

Before this module each consumer rebuilt the shapes by hand, so the
budgets could silently drift from what the service compiled and the
node served. Now drift is a merge conflict.

No jax at import time: every helper imports lazily so the package can be
imported (for plans, metrics lint, ``tools/warmup.py --dry-run``)
without initializing a backend.
"""

from __future__ import annotations

import time

STAGES = ("stage1", "stage2", "stage3")


class StageWarmupError(RuntimeError):
    """One stage of a rung warmup failed. Carries WHICH stage raised and
    the per-stage records of the stages that had already succeeded, so
    the compile service can count `ok` for real work done and `error`
    only for the stage that actually failed."""

    def __init__(self, stage: str, partial: dict, cause: BaseException):
        super().__init__(f"{stage}: {cause!r}")
        self.stage = stage
        self.partial = partial
        self.__cause__ = cause


def hlo_instruction_count(lowered_or_text) -> int:
    """SSA assignments in a lowered program's StableHLO text. Accepts the
    lowered object or its pre-rendered ``as_text()`` string (rendering a
    100k-line program is itself expensive — callers that also need line
    counts should render once and pass the text)."""
    try:
        text = (
            lowered_or_text
            if isinstance(lowered_or_text, str)
            else lowered_or_text.as_text()
        )
        return sum(1 for ln in text.splitlines() if " = " in ln)
    except Exception:
        return -1


def staged_dummy_args(B: int, K: int, M: int) -> dict:
    """Zero-filled device arrays matching EXACTLY the (shape, dtype)
    signatures ``verify_batch_raw_staged`` dispatches at bucket rung
    (B, K, M) — the signatures ``bls._run_stage`` keys its recompile
    accounting on."""
    import jax.numpy as jnp

    from ..crypto.device import fp

    return {
        "stage1": (
            jnp.zeros((B, 2, fp.NL), jnp.int32),      # sig_x
            jnp.zeros((B,), bool),                     # sig_larger
            jnp.zeros((M, 2, 2, fp.NL), jnp.int32),    # msg_u
        ),
        "stage2": (
            jnp.zeros((B, K, 2, fp.NL), jnp.int32),    # pk_xy
            jnp.zeros((B, K), bool),                   # pk_mask
            jnp.zeros((B, 2, 2, fp.NL), jnp.int32),    # sig_xy
            jnp.zeros((B, 2), jnp.int32),              # rand
            jnp.zeros((B,), bool),                     # set_mask
        ),
        "stage3": (
            jnp.zeros((B, fp.NL), jnp.int32),          # pk_x
            jnp.zeros((B, fp.NL), jnp.int32),          # pk_y
            jnp.zeros((B,), bool),                     # pk_inf
            jnp.zeros((B, 2, fp.NL), jnp.int32),       # msg_aff_x
            jnp.zeros((B, 2, fp.NL), jnp.int32),       # msg_aff_y
            jnp.zeros((B,), bool),                     # msg_aff_inf
            jnp.zeros((2, fp.NL), jnp.int32),          # acc_x
            jnp.zeros((2, fp.NL), jnp.int32),          # acc_y
            jnp.zeros((), bool),                       # acc_inf
        ),
    }


def staged_programs(B: int, K: int, M: int) -> dict:
    """``{stage: (unjitted_fn, dummy_args)}`` for fresh lowering (the
    budget gate and profilers jit these themselves to measure)."""
    from ..crypto.device import bls as dbls

    args = staged_dummy_args(B, K, M)
    fns = {
        "stage1": dbls._stage1_fn,
        "stage2": dbls._stage2_fn,
        "stage3": dbls._stage3_fn,
    }
    return {s: (fns[s], args[s]) for s in STAGES}


def staged_jitted() -> dict:
    """The module-level jitted stage callables the node dispatches —
    warming THESE (not fresh ``jax.jit`` wrappers) is what populates the
    dispatch cache real traffic hits."""
    from ..crypto.device import bls as dbls

    return {
        "stage1": dbls._stage1,
        "stage2": dbls._stage2,
        "stage3": dbls._stage3,
    }


def timed_lower_compile(fn, args, compile: bool = True) -> dict:
    """Shared profiler clock body: jit-lower ``fn`` on ``args`` and
    (optionally) compile, timing both phases and sizing the emitted
    StableHLO. Returns ``{lower_s, compile_s, hlo_lines, hlo_instr}``
    (``compile_s`` None when ``compile=False``; sizes -1 when the text
    render fails)."""
    import jax

    t0 = time.perf_counter()
    lowered = jax.jit(fn).lower(*args)
    lower_s = time.perf_counter() - t0
    try:
        text = lowered.as_text()  # rendered ONCE; both sizes come from it
        hlo_lines = len(text.splitlines())
        hlo_instr = hlo_instruction_count(text)
    except Exception:
        hlo_lines = hlo_instr = -1
    compile_s = None
    if compile:
        t1 = time.perf_counter()
        lowered.compile()
        compile_s = time.perf_counter() - t1
    return {
        "lower_s": lower_s,
        "compile_s": compile_s,
        "hlo_lines": hlo_lines,
        "hlo_instr": hlo_instr,
    }


def staged_instruction_counts(B: int, K: int, M: int) -> dict:
    """Lower (no compile) the three staged programs at bucket rung
    (B, K, M) and return ``{stage: {instructions, lower_s}}`` — the
    compile-budget gate's measurement."""
    out = {}
    for name, (fn, args) in staged_programs(B, K, M).items():
        rec = timed_lower_compile(fn, args, compile=False)
        out[name] = {
            "instructions": rec["hlo_instr"],
            "lower_s": round(rec["lower_s"], 2),
        }
    return out


def _shard_scope(shard):
    """The dispatch scope a warmup runs under: ``mesh.dispatch_to`` for
    a mesh shard (sets the thread-local shard AND jax's default device,
    so the dummy args and the staged dispatch land on THAT chip — the
    compile the mesh ladder is paying for), a no-op otherwise."""
    from ..crypto.device import mesh as mesh_mod

    if shard is None or mesh_mod.get_active_mesh() is None:
        import contextlib

        return contextlib.nullcontext()
    return mesh_mod.dispatch_to(int(shard))


def warm_gather(B: int, K: int, table, shard=None) -> dict:
    """Warm the device key-table gather program (ISSUE 10) for rung
    (B, K) against ``table``'s CURRENT device array — the gathered
    variant of the rung, keyed on the table's capacity rung
    (key_table.CAPACITY_LADDER). With a mesh shard (ISSUE 11) the
    gather warms against THAT device's replica. Dispatched through
    ``bls._run_stage`` (stage label "gather") like the staged programs,
    so the recompile counter and the stage histogram see exactly what
    gathered traffic sees. Sub-second on every backend (one take +
    reshape); not manifested — a restart re-warms it in-process."""
    import jax.numpy as jnp

    from ..crypto.device import bls as dbls

    with _shard_scope(shard):
        dev, agg = table.device_arrays()
        if dev is None:
            raise StageWarmupError(
                "gather", {}, RuntimeError("key table has no device array")
            )
        idx = jnp.zeros((B, K), jnp.int32)
        try:
            _, elapsed, fresh = dbls._run_stage(
                "gather", dbls._gather, dev, agg, idx
            )
        except Exception as e:
            raise StageWarmupError("gather", {}, e)
    return {"seconds": elapsed, "fresh": fresh}


def warm_msm(n: int, shard=None) -> dict:
    """Warm the device MSM pair (ISSUE 16) at point-count rung ``n``:
    the G1 windowed-MSM program AND the G2 masked point-sum program the
    operation_pool's device aggregation dispatches. Both go through
    ``bls._run_stage`` under the shared stage label "msm" (their arg
    shapes differ, so they key distinct recompile entries), exactly like
    gathered traffic — the recompile counter and stage histogram see
    what real aggregation sees. Keyed on the point axis only: warming
    the MSM ladder can never perturb the staged (B, K, M) shapes."""
    import jax.numpy as jnp

    from ..crypto.device import bls as dbls
    from ..crypto.device import fp

    seconds = 0.0
    fresh = False
    with _shard_scope(shard):
        g1_args = (
            jnp.zeros((n, 2, fp.NL), jnp.int32),       # pt_xy
            jnp.ones((n,), bool),                      # pt_inf
            jnp.zeros((n, 2), jnp.int32),              # scalars (u64 words)
        )
        g2_args = (
            jnp.zeros((n, 2, 2, fp.NL), jnp.int32),    # pt_xy
            jnp.ones((n,), bool),                      # pt_inf
        )
        for prog, args in ((dbls._msm, g1_args), (dbls._g2sum, g2_args)):
            try:
                _, elapsed, was_fresh = dbls._run_stage("msm", prog, *args)
            except Exception as e:
                raise StageWarmupError("msm", {}, e)
            seconds += elapsed
            fresh = fresh or was_fresh
    return {"seconds": seconds, "fresh": fresh}


def warm_staged(B: int, K: int, M: int, shard=None) -> dict:
    """Warm the staged pipeline at rung (B, K, M) under the ACTIVE fp
    impl: dispatch each module-level jitted stage on zero-filled dummy
    args THROUGH ``bls._run_stage``, so the jit dispatch cache, the
    persistent compile cache (when configured), the per-stage latency
    histogram and the recompile counter all see exactly what real
    traffic at this rung will see — a warmed signature is then NOT fresh
    for the first real batch. ``shard`` (ISSUE 11) scopes the whole
    warmup to a mesh device: the dummy args commit there and the
    compile is that chip's, exactly like a sharded sub-batch's
    dispatch. Returns ``{stage: {seconds, fresh}}``."""
    from ..crypto.device import bls as dbls

    out = {}
    with _shard_scope(shard):
        args = staged_dummy_args(B, K, M)
        jitted = staged_jitted()
        for stage in STAGES:
            try:
                _, elapsed, fresh = dbls._run_stage(
                    stage, jitted[stage], *args[stage]
                )
            except Exception as e:
                raise StageWarmupError(stage, out, e)
            out[stage] = {"seconds": elapsed, "fresh": fresh}
    return out
