"""CompileService: ahead-of-time warmup, warm-shape routing, and
persistent executable caching for the staged device BLS pipeline.

The headline bench pays a ~120 s XLA warmup compile before the FIRST
staged verify at a fresh bucket shape (BENCH_r05), and before this
module the node had no defense: the verification scheduler fuses
cross-caller traffic onto the bucket ladder, but the first flush onto a
*cold* rung blocked a gossip-hot thread on a multi-minute compile while
queues backed up. Serving stacks solve this with ahead-of-time
compilation and shape-aware routing — the same pattern that makes
fixed-function pipelines viable on AI ASICs ("Enabling AI ASICs for
Zero Knowledge Proof", PAPERS.md) and that amortizes batch-verification
setup cost in committee-based consensus (arxiv 2302.00418). This module
is that layer:

* **AOT warmup** — a bounded background worker walks the bucket ladder
  under the active ``fp_impl`` in priority order at client startup and
  warms the staged programs off the hot path
  (:func:`~lighthouse_tpu.compile_service.lowering.warm_staged`: the
  REAL module-level jitted stage callables, dispatched through
  ``bls._run_stage`` so every cache and counter sees exactly what
  traffic will see), maintaining a thread-safe warm-shape registry.
* **Warm-shape routing** — :meth:`CompileService.route` answers "can
  rung (B, K, M) dispatch without compiling?": ``warm`` (exact bucket
  compiled), ``padded`` (a larger warm rung covers it — pad up), or
  ``shed`` (nothing warm — the scheduler serves the flush via the
  counted synchronous CPU-native fallback while the cold rung compiles
  in the background). A cold rung never stalls a flush.
* **Persistent executable caching** — when a cache directory is
  configured (``LIGHTHOUSE_TPU_COMPILE_CACHE_DIR`` /
  ``ClientConfig.compile_cache_dir``) the JAX persistent compilation
  cache plus a manifest (see :mod:`.cache`) make a restarted node's
  warmup walk hit disk instead of XLA: zero fresh staged compiles on
  warm start, prebaked by ``tools/warmup.py``.

The module imports no jax at import time (the metrics lint imports it
on a box that must not initialize a backend); everything device-shaped
is imported lazily.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional, Sequence, Tuple

from ..utils import (
    fault_injection,
    flight_recorder,
    metrics,
    pipeline_profiler,
    tracing,
    transfer_ledger,
)
from ..verification_service import planner as _planner
from ..verification_service import round_up_bucket
from . import cache as _cache

Rung = Tuple[int, int, int]  # (B, K, M) padded bucket shape

# Ladder walk order (priority): the gossip-aggregate headline bucket
# first (the 120 s problem in BENCH_r05), then the flush planner's
# kind-homogeneous sub-batch shapes (ISSUE 6: a planned split of the
# 48-set headline flush lands unaggregated/sync-message sets on K=1
# rungs and committee sets on small-B K=16 rungs), the intermediate
# B rungs (48/96/192) the bin-packer targets for the observed traffic
# shapes, then the scheduler's large fused bucket and descending rungs
# for trickle/single-set traffic. K=16/M=8 are the bench headline pads
# (committee sets pad K up; the message-dedup plane rarely exceeds 8
# uniques per flush). The BULK rungs (ISSUE 15) close the ladder at
# LOWEST priority: B=512/256 is where the bulk QoS class drains
# (bulk_flush_sets chunks — DP_SCALING.json measures the best sets/s
# at B=256/512, exactly where the committee cost model's batching
# gains peak) — gossip's headline rungs must all be warm before the
# AOT walk spends minutes on backfill's. Their geometry is the REAL
# wired bulk callers' (chain-segment import + checkpoint backfill =
# proposal signatures: K=1, one DISTINCT message per set, so M pads
# to B — an M=8 rung could never cover a drain whose unique-message
# count scales with its set count); committee-carrying bulk ingest
# (slasher-style, K>1) re-bins onto whatever warm coverage exists or
# sheds to the fallback until an operator adds its rung via
# LIGHTHOUSE_TPU_COMPILE_RUNGS.
DEFAULT_RUNGS: Tuple[Rung, ...] = (
    (64, 16, 8),
    (48, 16, 8),
    (32, 1, 8),
    (16, 16, 8),
    (64, 1, 8),
    (256, 16, 8),
    (96, 16, 8),
    (192, 16, 8),
    (4, 16, 8),
    (1, 16, 8),
    (512, 1, 512),
    (256, 1, 256),
)

# device MSM ladder (ISSUE 16): padded point counts N the G1 windowed
# MSM / G2 point-sum staged programs are warmed at. These programs are
# keyed on their OWN rung (the point axis), NOT on (B, K, M) — an MSM
# dispatch can never perturb the staged-verify ladder's warm shapes.
# 512 covers a full mainnet committee; the smaller rungs are the
# operation_pool's greedy-merge and sync-contribution batch sizes.
# Warming is OFF unless a caller opts in (ClientConfig.device_msm ->
# set_msm_warm_enabled): nodes not running the device aggregation path
# must not spend AOT minutes on programs they never dispatch.
MSM_RUNGS: Tuple[int, ...] = (64, 128, 256, 512)

_msm_warm_enabled = False


def set_msm_warm_enabled(on: bool) -> None:
    """Opt the AOT walk into warming the MSM ladder alongside the first
    staged rung (per fp-impl x device). Process-global because the
    service is constructed before the client config is applied."""
    global _msm_warm_enabled
    _msm_warm_enabled = bool(on)


def msm_warm_enabled() -> bool:
    return _msm_warm_enabled


_ENV_ENABLED = "LIGHTHOUSE_TPU_COMPILE_SERVICE"
_ENV_RUNGS = "LIGHTHOUSE_TPU_COMPILE_RUNGS"
# compile retry (ISSUE 13): a compile_failed rung re-queues with
# bounded exponential backoff + jitter instead of dying — a transient
# XLA/tunnel error must not leave a rung permanently cold — capped at
# a per-rung attempt budget so a deterministic failure cannot spin
_ENV_RETRY_MAX = "LIGHTHOUSE_TPU_COMPILE_RETRY_MAX"
_ENV_RETRY_BASE = "LIGHTHOUSE_TPU_COMPILE_RETRY_BASE_S"
_ENV_RETRY_CAP = "LIGHTHOUSE_TPU_COMPILE_RETRY_MAX_S"

DEFAULT_RETRY_MAX_ATTEMPTS = 3
DEFAULT_RETRY_BASE_S = 1.0
DEFAULT_RETRY_MAX_S = 60.0


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default

_COMPILE_BUCKETS = (
    0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1200.0,
)

_IN_FLIGHT = metrics.gauge(
    "compile_service_compiles_in_flight",
    "staged-program compiles the background worker is running right now",
)
_WARM_RUNGS = metrics.gauge(
    "compile_service_warm_rungs",
    "bucket rungs (B, K, M) x fp_impl x mesh device whose three staged "
    "programs are compiled and routable (single-device nodes only ever "
    "count device 0)",
)
_QUEUE_DEPTH = metrics.gauge(
    "compile_service_queue_depth",
    "bucket rungs queued for background compilation",
)
_COMPILES = metrics.counter_vec(
    "compile_service_compiles_total",
    "per-stage AOT warmup compiles by outcome (ok includes "
    "persistent-cache hits — those are compiles XLA served from disk)",
    ("stage", "outcome"),
)
_COMPILE_SECONDS = metrics.histogram_vec(
    "compile_service_compile_seconds",
    "per-stage AOT warmup wall time per rung (a persistent-cache hit is "
    "the sub-second mode; a fresh XLA compile the minutes mode)",
    ("stage",),
    buckets=_COMPILE_BUCKETS,
)
_COLD_ROUTES = metrics.counter_vec(
    "compile_service_cold_routes_total",
    "scheduler flushes that arrived at a cold bucket: padded = served "
    "on a larger warm rung, shed = served via the synchronous CPU-native "
    "fallback while the rung compiles in the background",
    ("action",),
)
_COMPILE_RETRIES = metrics.counter(
    "compile_service_compile_retries_total",
    "failed rung compiles re-queued with backoff by the retry layer "
    "(ISSUE 13; see the compile_retry journal kind) — retries beyond "
    "the per-rung attempt cap are NOT scheduled and the rung stays "
    "cold until invalidate()/demand re-queues it",
)
_FALLBACK_SECONDS = metrics.histogram(
    "compile_service_fallback_verify_seconds",
    "wall time of one synchronous CPU fallback verify of a shed flush — "
    "the latency a submission pays on the SLO layer's `fallback` "
    "resolution path (verification_scheduler_verdict_latency_seconds"
    "{path=fallback}) while the cold rung compiles behind it",
)
_MEASURED_COST = metrics.gauge(
    "compile_service_measured_cost_seconds_per_set",
    "organically measured WARM serving cost per signature set: "
    "cumulative staged-verify wall / cumulative sets across every rung "
    "note_rung_verified reported, EXCLUDING each rung's first dispatch "
    "(whose wall includes the XLA compile — one cold compile must not "
    "read the capacity dial as saturated for thousands of sets). The "
    "rung-cost feed the capacity/headroom estimator reads when no "
    "per-shard mesh walls exist (ISSUE 14); per-rung splits incl. "
    "first dispatches in status()['rung_costs'] / measured_rung_costs()",
)


def _env_rungs() -> Optional[Tuple[Rung, ...]]:
    """Parse LIGHTHOUSE_TPU_COMPILE_RUNGS=\"B:K:M,B:K:M\"; None when unset
    or malformed (malformed falls back to the default plan, loudly)."""
    raw = os.environ.get(_ENV_RUNGS)
    if not raw:
        return None
    try:
        rungs = tuple(
            tuple(int(p) for p in chunk.split(":"))
            for chunk in raw.split(",")
            if chunk.strip()
        )
        if rungs and all(len(r) == 3 and all(v > 0 for v in r) for r in rungs):
            return rungs  # type: ignore[return-value]
    except ValueError:
        pass
    from ..utils import logging as tlog

    tlog.log("warn", "malformed LIGHTHOUSE_TPU_COMPILE_RUNGS ignored", raw=raw[:80])
    return None


def _geometry(sets) -> Tuple[int, int, int]:
    """(n_sets, max pubkeys/set, unique messages) of a flush — the three
    padded dims the packers derive, computed WITHOUT importing the
    device stack. ONE definition, shared with the flush planner
    (verification_service/planner.py): items are SignatureSet objects or
    (sig, pks, msg) triples; anything else conservatively counts as a
    1-pubkey set with its own message (over-reserving only risks extra
    padding)."""
    return _planner.flush_geometry(sets)


class WarmShapeRegistry:
    """Thread-safe set of (B, K, M, fp_impl, device) rungs whose staged
    programs are compiled — ``device`` is the dp-mesh shard index
    (ISSUE 11; always 0 on a single-device node, and a jitted program
    compiled for one chip is NOT routable on another: each device key
    is its own compile). ``invalidate()`` bumps an epoch so an
    in-flight compile that started before e.g. an ``fp.set_impl``
    switch + ``device.reset_compiled_state()`` cannot resurrect a stale
    rung."""

    def __init__(self):
        self._lock = threading.Lock()
        self._warm: set = set()
        self._epoch = 0

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def mark_ready(
        self, rung: Rung, impl: str, epoch: int | None = None,
        device: int = 0,
    ) -> bool:
        """Record ``rung`` warm under ``impl`` on mesh ``device``; False
        when the mark is stale (epoch advanced since the compile
        started) or already present."""
        with self._lock:
            if epoch is not None and epoch != self._epoch:
                return False
            key = (*rung, impl, int(device))
            if key in self._warm:
                return False
            self._warm.add(key)
            _WARM_RUNGS.set(len(self._warm))
            return True

    def is_warm(self, rung: Rung, impl: str, device: int = 0) -> bool:
        with self._lock:
            return (*rung, impl, int(device)) in self._warm

    def best_covering(
        self, n_sets: int, k_req: int, m_req: int, impl: str,
        device: int = 0,
    ) -> Optional[Rung]:
        """Cheapest warm rung ON ``device`` that can hold the request
        padded up (B >= n_sets, K >= k_req, M >= m_req). ONE covering
        policy: delegates to ``planner.best_covering_rung`` (min padded
        lanes B*K*M), so the rung the flush planner scores a sub-batch
        at is the rung this routing actually lands it on. None when
        nothing warm covers it."""
        with self._lock:
            warm = [
                (b, k, m)
                for (b, k, m, i, d) in self._warm
                if i == impl and d == int(device)
            ]
        return _planner.best_covering_rung(warm, n_sets, k_req, m_req)

    def warm_rungs(self) -> list:
        """Device-0 view as (B, K, M, fp_impl) tuples — the
        single-device surface every pre-mesh caller and test reads;
        :meth:`warm_rungs_all` carries the device axis."""
        with self._lock:
            return sorted(
                (b, k, m, i) for (b, k, m, i, d) in self._warm if d == 0
            )

    def warm_rungs_all(self) -> list:
        """Every warm (B, K, M, fp_impl, device) key."""
        with self._lock:
            return sorted(self._warm)

    def invalidate(self) -> None:
        with self._lock:
            self._warm.clear()
            self._epoch += 1
            _WARM_RUNGS.set(0)


class CompileService:
    """Background AOT compiler + warm-shape router for the staged device
    BLS pipeline (see module docstring). ``compile_rung_fn`` and
    ``fallback_verify_fn`` are injectable for tests; the defaults are
    :func:`lowering.warm_staged` and a CPU-native (falling back to
    CPU-oracle) ``verify_signature_sets``."""

    def __init__(
        self,
        rungs: Optional[Iterable[Rung]] = None,
        cache_dir: str | None = None,
        compile_rung_fn: Optional[Callable[[int, int, int], dict]] = None,
        fallback_verify_fn: Optional[Callable[[list], bool]] = None,
    ):
        self.plan: Tuple[Rung, ...] = tuple(
            tuple(r) for r in (rungs or _env_rungs() or DEFAULT_RUNGS)
        )
        self.cache_dir = _cache.resolve_cache_dir(cache_dir)
        self.cache_status: dict = {"enabled": False, "dir": None, "reason": "unconfigured"}
        self.manifest: Optional[_cache.Manifest] = None
        self._compile_rung_fn = compile_rung_fn
        self._fallback_fn = fallback_verify_fn
        self._fallback_backend = None
        self.registry = WarmShapeRegistry()
        self._cv = threading.Condition()
        # work items are (rung, device): the mesh ladder (ISSUE 11) —
        # a single-device node only ever queues device 0
        self._queue: deque = deque()
        self._queued: set = set()
        self._in_flight = None  # (rung, device) | None
        self._devices: Tuple[int, ...] = (0,)
        self._stopped = True
        self._thread: Optional[threading.Thread] = None
        self._compiled_total = 0
        self._failed_total = 0
        self._cold_routes = {"padded": 0, "shed": 0}
        # compile retry (ISSUE 13): per-(rung, device) failed-attempt
        # counts and the delayed re-queue the worker promotes when due
        self.retry_max_attempts = max(
            1, _env_int(_ENV_RETRY_MAX, DEFAULT_RETRY_MAX_ATTEMPTS)
        )
        self.retry_base_s = _env_float(_ENV_RETRY_BASE, DEFAULT_RETRY_BASE_S)
        self.retry_max_s = _env_float(_ENV_RETRY_CAP, DEFAULT_RETRY_MAX_S)
        self._attempts: dict = {}   # (rung, device) -> failures so far
        self._retry_at: dict = {}   # (rung, device) -> due monotonic time
        # MSM ladder (ISSUE 16): (fp_impl, device) pairs whose MSM rungs
        # are already warm — the ladder rides the FIRST staged rung
        # compile per pair, not every rung
        self._msm_warmed: set = set()
        self._retries_total = 0
        # rung-cost feed (ISSUE 14): measured verify cost from
        # note_rung_verified — bounded by ladder size x mesh width (the
        # registry only ever sees padded ladder rungs)
        # (rung, device) -> [dispatches, sum_s, sum_sets]
        self._rung_costs: dict = {}
        self._cost_sum_s = 0.0
        self._cost_sum_sets = 0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "CompileService":
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            if self.cache_dir:
                # min_compile_time 0: jax's default 1 s floor would skip
                # persisting the small rungs' sub-second compiles while
                # _record_ready still wrote their manifest entries — a
                # warm-start claim with no executables behind it
                self.cache_status = _cache.enable_persistent_cache(
                    self.cache_dir, min_compile_time_s=0.0
                )
                # manifest only over a LIVE cache: entries written while
                # the jax knob is missing/broken would claim a warm start
                # that holds no executables (warm_warmup_s == cold)
                if self.cache_status["enabled"]:
                    self.manifest = _cache.Manifest(self.cache_dir)
            # mesh ladder (ISSUE 11): with a served dp mesh attached the
            # walk is rung x device, HEADLINE RUNGS FIRST — every chip
            # gets the big warm rung before any chip gets the next one,
            # so the dp axis is servable at the headline shape as early
            # as possible. Without a mesh this is the pre-mesh walk.
            self._devices = self._mesh_devices()
            for rung in self.plan:
                for dev in self._devices:
                    self._enqueue_locked((rung, dev), front=False)
            self._stopped = False
            self._thread = threading.Thread(
                target=self._loop, name="compile-service", daemon=True
            )
            self._thread.start()
            # wake any SUPERSEDED worker blocked in _cv.wait() so it can
            # observe it is no longer self._thread and exit
            self._cv.notify_all()
        return self

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        self._thread = None

    def active(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stopped

    def invalidate(self) -> None:
        """Drop every warm rung (the ``device.reset_compiled_state()``
        hook: jit caches are gone, so the registry must not keep routing
        to shapes that would now recompile) and re-queue the configured
        plan so the background worker re-warms under the new state."""
        self.registry.invalidate()
        with self._cv:
            self._queue.clear()
            self._queued.clear()
            # retry state is per-epoch: the re-queued plan below starts
            # every rung with a fresh failure budget
            self._retry_at.clear()
            self._attempts.clear()
            # the new epoch's jit caches are empty: the MSM ladder must
            # re-warm alongside the re-queued plan
            self._msm_warmed.clear()
            for rung in self.plan:
                for dev in self._devices:
                    # even_in_flight: a rung compiling RIGHT NOW finishes
                    # against the old epoch (its mark_ready is stale), so
                    # it must be queued again or it would stay cold until
                    # traffic demand-pages it
                    self._enqueue_locked(
                        (rung, dev), front=False, even_in_flight=True
                    )
            self._cv.notify_all()

    @staticmethod
    def _mesh_devices() -> Tuple[int, ...]:
        """Shard indices the ladder walks: the attached mesh's full
        shard axis, (0,) without one. Lazy seam read — the mesh module
        is jax-free at import."""
        try:
            from ..crypto.device import mesh as _mesh

            m = _mesh.get_active_mesh()
            if m is not None:
                return tuple(m.all_shards())
        except Exception:
            pass
        return (0,)

    def _device_healthy(self, dev: int) -> bool:
        if dev == 0 and len(self._devices) == 1:
            return True  # single-device node: no mesh to consult
        try:
            from ..crypto.device import mesh as _mesh

            m = _mesh.get_active_mesh()
            # a PROBING shard's rungs are live work: the recovery
            # worker's re-warm (ISSUE 13) queues them before the shard
            # is re-admitted, so they must not be skipped as dead
            return m is None or m.is_healthy(dev) or m.is_probing(dev)
        except Exception:
            return True

    # -- queueing ---------------------------------------------------------

    def _enqueue_locked(
        self, item, front: bool, even_in_flight: bool = False
    ) -> None:
        if item in self._queued:
            # already queued: a demand-paged request (front=True) still
            # PROMOTES it — live traffic's shape must compile next, not
            # wait behind the remaining plan walk
            if front and self._queue and self._queue[0] != item:
                self._queue.remove(item)
                self._queue.appendleft(item)
            return
        if item == self._in_flight and not even_in_flight:
            return
        self._queued.add(item)
        if front:
            self._queue.appendleft(item)
        else:
            self._queue.append(item)
        _QUEUE_DEPTH.set(len(self._queue))
        self._cv.notify()

    def request(self, b: int, k: int, m: int, device: int = 0) -> None:
        """Ask the background worker to compile rung (b, k, m) on mesh
        ``device`` next — demand-paged warming for traffic the
        configured plan missed."""
        with self._cv:
            self._enqueue_locked(
                ((int(b), int(k), int(m)), int(device)), front=True
            )

    # -- routing ----------------------------------------------------------

    @staticmethod
    def _impl() -> str:
        from ..crypto.device import fp

        return fp.get_impl()

    def route(
        self, n_sets: int, k_req: int = 1, m_req: int = 1,
        device: int = 0,
    ) -> dict:
        """Routing decision for a flush of ``n_sets`` sets with up to
        ``k_req`` pubkeys/set and ``m_req`` distinct messages on mesh
        ``device``: ``{"action": warm|padded|shed, "rung": (B,K,M)|None,
        "exact": (B,K,M), "fp_impl": impl, "device": device}``. Pure
        registry read — counting/journaling belongs to
        :meth:`decide_flush`. Warmth is PER DEVICE: a rung compiled for
        one chip does not make another chip's dispatch warm."""
        impl = self._impl()
        exact = (
            round_up_bucket(n_sets),
            round_up_bucket(k_req),
            round_up_bucket(m_req),
        )
        if self.registry.is_warm(exact, impl, device=device):
            return {
                "action": "warm", "rung": exact, "exact": exact,
                "fp_impl": impl, "device": device,
            }
        covering = self.registry.best_covering(
            n_sets, k_req, m_req, impl, device=device
        )
        if covering is not None:
            return {
                "action": "padded", "rung": covering, "exact": exact,
                "fp_impl": impl, "device": device,
            }
        return {
            "action": "shed", "rung": None, "exact": exact,
            "fp_impl": impl, "device": device,
        }

    def decide_flush(
        self, sets, caller: str = "flush",
        geometry: Optional[Tuple[int, int, int]] = None,
        device_index: int = 0,
    ) -> dict:
        """The scheduler-facing entry: route the flush, account cold
        buckets (``compile_service_cold_routes_total``, ``cold_route``
        journal event) and queue the exact rung for background
        compilation so the NEXT flush of this shape runs on device.
        ``geometry`` is the caller's precomputed (n_sets, k_req, m_req)
        — the flush planner already derived it per plan element, so it
        is not re-extracted from the sets here. ``device_index`` is the
        dp shard the sub-batch will dispatch on (ISSUE 11): a rung that
        is warm on another chip but COLD on this one sheds to the
        fallback instead of stalling the shard's flush on a compile."""
        n, k, m = geometry if geometry is not None else _geometry(sets)
        decision = self.route(n, k, m, device=int(device_index))
        if decision["action"] == "padded" and get_active_service() is not self:
            # the pad-up itself happens inside the device backend, which
            # consults the process-global seam (set_service) — a service
            # injected into the scheduler but never registered there
            # cannot deliver it, and claiming "padded" would send the
            # flush into the exact cold-compile stall the route promises
            # to avoid. Downgrade to shed: the fallback never stalls.
            decision = {
                "action": "shed",
                "rung": None,
                "exact": decision["exact"],
                "fp_impl": decision["fp_impl"],
                "device": decision["device"],
            }
        if decision["action"] != "warm":
            action = decision["action"]
            with self._cv:  # flush thread AND verify_now caller threads
                self._cold_routes[action] += 1
            _COLD_ROUTES.with_labels(action).inc()
            eb, ek, em = decision["exact"]
            rung = decision["rung"]
            flight_recorder.record(
                "cold_route",
                action=action,
                caller=caller,
                n_sets=n,
                k_req=k,
                m_req=m,
                exact_b=eb, exact_k=ek, exact_m=em,
                warm_b=None if rung is None else rung[0],
                warm_k=None if rung is None else rung[1],
                warm_m=None if rung is None else rung[2],
                fp_impl=decision["fp_impl"],
                device=decision["device"],
            )
            self.request(eb, ek, em, device=int(device_index))
        return decision

    def warm_rungs_active(self, device: int = 0) -> list:
        """Warm (B, K, M) rungs under the ACTIVE fp engine on mesh
        ``device`` — the rung set the flush planner bin-packs onto (a
        planned sub-batch must land warm or the plan falls back to the
        single rung)."""
        impl = self._impl()
        return [
            (b, k, m)
            for (b, k, m, i, d) in self.registry.warm_rungs_all()
            if i == impl and d == int(device)
        ]

    def warm_rungs_by_shard(self, shards) -> dict:
        """``{shard: [(B, K, M), ...]}`` under the active engine — the
        planner's mesh-aware warm view (ISSUE 11): a shard whose rung
        set is empty plans COLD there and the sub-batch sheds to the
        fallback instead of stalling the flush."""
        impl = self._impl()
        out = {int(s): [] for s in shards}
        for (b, k, m, i, d) in self.registry.warm_rungs_all():
            if i == impl and d in out:
                out[d].append((b, k, m))
        return out

    def pads_for(
        self, n_sets: int, k_req: int, m_req: int, device: int = 0
    ) -> Optional[Rung]:
        """Pad target for the device packers: the warm rung a
        warm/padded route lands on for this mesh device, or None when
        nothing warm covers the request (the packers then use their
        default round-up — the pre-service behavior)."""
        decision = self.route(n_sets, k_req, m_req, device=int(device))
        return decision["rung"]

    # -- fallback ---------------------------------------------------------

    def fallback_verify(self, sets) -> bool:
        """Synchronous CPU verification for shed flushes: CPU-native (the
        C backend) when buildable, the pure-Python oracle otherwise.
        Verdict-identical to the device call by the backend-differential
        invariant the whole test suite pins — including the device
        backend's infinity pre-screens, and exceptions PROPAGATE like the
        direct call's would (the scheduler's bisection delivers them to
        exactly the leaf submission that caused them)."""
        t0 = time.perf_counter()
        try:
            with tracing.span(
                "compile_service.fallback_verify", n_sets=len(sets)
            ), _FALLBACK_SECONDS.time():
                if self._fallback_fn is not None:
                    return bool(self._fallback_fn(list(sets)))
                from ..crypto import bls as _bls

                prepared = []
                for item in sets:
                    if isinstance(item, _bls.SignatureSet):
                        if not item.signing_keys or item.signature.is_infinity():
                            return False
                        if any(pk.point.is_infinity() for pk in item.signing_keys):
                            return False
                        prepared.append(
                            (
                                item.signature,
                                [pk.point for pk in item.signing_keys],
                                item.message,
                            )
                        )
                    else:
                        prepared.append(item)
                return bool(
                    self._fallback_backend_inst().verify_signature_sets(
                        prepared
                    )
                )
        finally:
            # data-movement ledger: a CPU resolution ships ZERO
            # host→device bytes — the zero row keeps byte attribution
            # exactly-once across resolution paths (kind/path from the
            # scheduler's attribution context on this thread). In a
            # finally so a raising verify still journals exactly one
            # row, mirroring the device path's raise behavior
            transfer_ledger.record_cpu(len(sets))
            # pipeline profiler (ISSUE 12): a shed flush resolving on
            # the CPU is exactly the window the device idles for a
            # compile-caused reason — the wall lands as `compile`
            # activity (bubble attribution) and as the current flush
            # record's `fallback` phase
            pipeline_profiler.note_fallback_wall(t0, time.perf_counter())

    def _fallback_backend_inst(self):
        if self._fallback_backend is None:
            from ..crypto import backend as _backend

            try:
                self._fallback_backend = _backend._REGISTRY["cpu-native"]()
            except Exception:  # no C toolchain: the oracle is always there
                self._fallback_backend = _backend.CpuBackend()
        return self._fallback_backend

    # -- warmth notification ---------------------------------------------

    def note_rung_verified(
        self, b: int, k: int, m: int, epoch: int | None = None,
        device: int = 0, seconds: float | None = None,
        n_sets: int | None = None,
    ) -> None:
        """Organic warmth: a staged verify at (b, k, m) just succeeded on
        the dispatch path — on mesh ``device`` — so its three programs
        are compiled there: routable without the background worker ever
        touching the rung. ``epoch`` is the registry epoch the caller
        captured BEFORE dispatching: a verify racing
        ``device.reset_compiled_state()`` must not resurrect a rung
        whose jit caches were just dropped.

        ``seconds``/``n_sets`` (ISSUE 14) is the rung-cost feed: the
        dispatcher reports the verify's full serving wall (pack +
        staged dispatch) and live set count, accumulated per rung and
        mirrored into ``compile_service_measured_cost_seconds_per_set``
        — the cost input the capacity/headroom estimator
        (``utils/timeseries.py``) falls back to when no per-shard mesh
        walls exist. First-sighting walls include the XLA compile, so
        the per-rung record keeps the dispatch count: a cost dominated
        by one compiled dispatch washes out as the rung serves."""
        rung = (int(b), int(k), int(m))
        if seconds is not None and n_sets:
            with self._cv:
                # keyed per (rung, DEVICE): compiles are per chip, so a
                # failover re-verify on a shard where the rung is still
                # cold pays the compile again — its wall must be
                # excluded exactly like device 0's first sighting was
                rec = self._rung_costs.setdefault(
                    (rung, int(device)), [0, 0.0, 0]
                )
                warm = rec[0] > 0
                rec[0] += 1
                rec[1] += float(seconds)
                rec[2] += int(n_sets)
                # the GAUGE excludes each (rung, device)'s FIRST
                # dispatch: its wall includes the XLA compile (~minutes
                # over a few sets), and a cumulative average would read
                # the capacity dial as saturated for thousands of sets
                # after one cold compile. The per-rung record keeps
                # every dispatch (the compile cost is real and
                # reportable); only the serving-cost feed is warm-only.
                if warm:
                    self._cost_sum_s += float(seconds)
                    self._cost_sum_sets += int(n_sets)
                    if self._cost_sum_sets:
                        _MEASURED_COST.set(
                            self._cost_sum_s / self._cost_sum_sets
                        )
        impl = self._impl()
        if self.registry.mark_ready(rung, impl, epoch=epoch, device=device):
            # persisted=False: the compile happened inside the verify,
            # with no before/after cache probe around it — organic warmth
            # is in-process routing knowledge only and never writes
            # manifest entries (the AOT walk and warmup CLI, which DO
            # probe, own the warm-start claims)
            self._record_ready(
                rung, impl, seconds=None, source="organic",
                persisted=False, device=device,
            )

    def measured_rung_costs(self) -> dict:
        """Per-(rung, device) measured serving cost (the ISSUE 14
        rung-cost feed): ``"BxKxM@devD" -> {dispatches, sum_s,
        sum_sets, s_per_set}`` (ALL dispatches, first-sighting compile
        walls included) plus the aggregate warm-only ``s_per_set`` the
        estimator reads via the
        ``compile_service_measured_cost_seconds_per_set`` gauge (each
        (rung, device)'s first dispatch excluded — see the gauge
        help)."""
        with self._cv:
            rungs = {
                "x".join(str(v) for v in rung) + f"@dev{dev}": {
                    "dispatches": n,
                    "sum_s": round(s, 6),
                    "sum_sets": sets,
                    "s_per_set": round(s / sets, 9) if sets else None,
                }
                for (rung, dev), (n, s, sets)
                in sorted(self._rung_costs.items())
            }
            total_s, total_sets = self._cost_sum_s, self._cost_sum_sets
        return {
            "rungs": rungs,
            "s_per_set": (
                round(total_s / total_sets, 9) if total_sets else None
            ),
            "sum_sets": total_sets,
        }

    def _cache_files(self) -> Optional[set]:
        """Executable entries currently in the cache dir (None when no
        live manifest/cache): the before half of the probe that keeps
        the manifest at least as conservative as the cache."""
        if self.manifest is None or not self.cache_dir:
            return None
        return _cache.executable_entries(self.cache_dir)

    def _record_ready(
        self,
        rung: Rung,
        impl: str,
        seconds: float | None,
        source: str,
        persisted: bool = True,
        device: int = 0,
    ) -> None:
        with self._cv:  # worker thread AND organic-warmth verify threads
            self._compiled_total += 1
        if self.manifest is not None and persisted:
            try:
                env_key = _cache.environment_key(impl)
                self.manifest.add_many(
                    [
                        _cache.manifest_key(
                            env_key, stage, *rung, device=device
                        )
                        for stage in ("stage1", "stage2", "stage3")
                    ],
                    source=source,
                )
            except Exception:
                pass  # manifest is an optimization, never a failure source
        flight_recorder.record(
            "compile_ready",
            b=rung[0], k=rung[1], m=rung[2],
            fp_impl=impl,
            seconds=None if seconds is None else round(seconds, 3),
            source=source,
            persisted=persisted,
            device=device,
        )

    # -- background worker ------------------------------------------------

    def _loop(self) -> None:
        # identity check: stop() gives up joining after 10 s (a compile
        # cannot be cancelled) and a subsequent start() spawns a fresh
        # worker — when THIS thread is no longer self._thread it has been
        # superseded and must exit instead of double-draining the queue
        me = threading.current_thread()
        while True:
            with self._cv:
                while True:
                    if self._stopped or self._thread is not me:
                        return
                    self._promote_due_retries_locked()
                    if self._queue:
                        break
                    # sleep until the earliest pending retry is due (or
                    # indefinitely when none is scheduled)
                    wait = None
                    if self._retry_at:
                        wait = max(
                            0.01,
                            min(self._retry_at.values()) - time.monotonic(),
                        )
                    self._cv.wait(wait)
                rung = self._queue.popleft()
                self._queued.discard(rung)
                self._in_flight = rung
                _QUEUE_DEPTH.set(len(self._queue))
            try:
                self._compile_rung(rung)
            finally:
                with self._cv:
                    # a superseding worker may already be mid-compile on
                    # its own rung: only clear OUR marker (and the gauge —
                    # a superseded worker's cleanup must not zero it under
                    # the replacement's active compile)
                    if self._in_flight == rung:
                        self._in_flight = None
                        _IN_FLIGHT.set(0)

    def _promote_due_retries_locked(self) -> None:
        """Move due retry items back onto the work queue (called under
        the cv by the worker loop)."""
        if not self._retry_at:
            return
        now = time.monotonic()
        due = [it for it, t in self._retry_at.items() if t <= now]
        for it in due:
            del self._retry_at[it]
            if it not in self._queued and it != self._in_flight:
                self._queued.add(it)
                self._queue.append(it)
        if due:
            _QUEUE_DEPTH.set(len(self._queue))

    def _schedule_retry(self, rung: Rung, dev: int, impl: str,
                        error: BaseException) -> None:
        """One rung compile failed: re-queue it with bounded backoff +
        jitter unless its per-rung attempt budget is spent (the
        monitoring.py retry shape — a deterministic failure must not
        spin, a transient one must not leave the rung cold forever)."""
        key = (rung, int(dev))
        with self._cv:
            attempts = self._attempts.get(key, 0) + 1
            self._attempts[key] = attempts
            if attempts >= self.retry_max_attempts:
                return  # budget spent: the rung stays cold (journaled)
            if key in self._queued or key in self._retry_at:
                return
            delay = min(
                self.retry_max_s,
                self.retry_base_s * (2.0 ** (attempts - 1)),
            ) * random.uniform(0.5, 1.0)
            self._retry_at[key] = time.monotonic() + delay
            self._retries_total += 1
            self._cv.notify_all()
        _COMPILE_RETRIES.inc()
        b, k, m = rung
        flight_recorder.record(
            "compile_retry",
            b=b, k=k, m=m, fp_impl=impl, device=dev,
            attempt=attempts,
            max_attempts=self.retry_max_attempts,
            delay_s=round(delay, 3),
            error=repr(error)[:200],
        )

    def _compile_rung(self, item) -> None:
        # item is ((B, K, M), device); a bare (B, K, M) means device 0
        # (direct callers/tests that predate the mesh ladder)
        if len(item) == 2 and isinstance(item[0], tuple):
            rung, dev = item
        else:
            rung, dev = tuple(item), 0
        impl = self._impl()
        if self.registry.is_warm(rung, impl, device=dev):
            return  # warmed organically while queued
        if not self._device_healthy(dev):
            return  # a lost shard's rungs are dead weight, not work
        epoch = self.registry.epoch
        b, k, m = rung
        flight_recorder.record(
            "compile_started", b=b, k=k, m=m, fp_impl=impl, source="aot",
            device=dev,
        )
        _IN_FLIGHT.set(1)
        files_before = self._cache_files()
        t0 = time.perf_counter()
        try:
            with tracing.span(
                "compile_service.compile", b=b, k=k, m=m, fp_impl=impl,
                device=dev,
            ):
                # chaos seam (ISSUE 13): an armed `compile` fault point
                # raises here and exercises the retry layer exactly
                # like a real XLA failure would
                fault_injection.fire("compile")
                if self._compile_rung_fn is not None:
                    stages = self._compile_rung_fn(b, k, m)
                else:
                    from . import lowering

                    stages = lowering.warm_staged(b, k, m, shard=dev)
        except Exception as e:  # a failed compile must not kill the worker
            with self._cv:
                self._failed_total += 1
            # stage-attributed accounting: stages that DID compile before
            # the failure count ok (with their durations); only the stage
            # that raised counts error. A non-staged exception (injected
            # compile fns, import failures) attributes all three.
            partial = getattr(e, "partial", None) or {}
            failed_stage = getattr(e, "stage", None)
            for stage, rec in partial.items():
                _COMPILES.with_labels(stage, "ok").inc()
                _COMPILE_SECONDS.with_labels(stage).observe(
                    float(rec.get("seconds", 0.0))
                )
            failed = (
                (failed_stage,)
                if failed_stage is not None
                else tuple(
                    s for s in ("stage1", "stage2", "stage3")
                    if s not in partial
                )
            )
            for stage in failed:
                _COMPILES.with_labels(stage, "error").inc()
            flight_recorder.record(
                "compile_failed", b=b, k=k, m=m, fp_impl=impl,
                error=repr(e)[:200], device=dev,
                attempt=self._attempts.get((rung, dev), 0) + 1,
            )
            from ..utils import logging as tlog

            tlog.log(
                "warn", "compile service rung failed",
                b=b, k=k, m=m, fp_impl=impl, device=dev,
                error=repr(e)[:120],
            )
            # retry with backoff (ISSUE 13): the rung re-queues instead
            # of dying, up to the per-rung attempt cap
            self._schedule_retry(rung, dev, impl, e)
            return
        seconds = time.perf_counter() - t0
        # a success retires the rung's failure budget: the next
        # transient failure (after an invalidate) starts fresh
        with self._cv:
            self._attempts.pop((rung, dev), None)
        for stage, rec in (stages or {}).items():
            _COMPILES.with_labels(stage, "ok").inc()
            _COMPILE_SECONDS.with_labels(stage).observe(
                float(rec.get("seconds", 0.0))
            )
        # gathered variant (ISSUE 10): with a device key table attached,
        # this rung's traffic dispatches the "gather" program ahead of
        # stage 2 — warm it alongside so the first static batch at the
        # rung pays zero fresh compiles. Sub-second; a failure degrades
        # the gathered variant only (the raw rung above is already warm)
        # and must not fail the rung.
        if self._compile_rung_fn is None:
            try:
                from ..crypto.device import key_table as _kt

                tbl = _kt.get_active_table()
                if tbl is not None:
                    from . import lowering

                    # the replicated key table's gather is warmed per
                    # device against THAT device's replica (ISSUE 11)
                    grec = lowering.warm_gather(b, k, tbl, shard=dev)
                    _COMPILES.with_labels("gather", "ok").inc()
                    _COMPILE_SECONDS.with_labels("gather").observe(
                        float(grec.get("seconds", 0.0))
                    )
            except Exception:
                _COMPILES.with_labels("gather", "error").inc()
        # MSM ladder (ISSUE 16): when the node opted into device
        # aggregation, warm the windowed-MSM / G2-sum programs alongside
        # staged rung compiles — ONE cold MSM rung per staged compile,
        # smallest first, so the background chunk stays bounded (a full
        # 4-rung interpret-mode warm monopolizes the worker — and the
        # GIL — for minutes, starving health serving and shutdown). They
        # are keyed on their own point-count rung, so this never disturbs
        # the staged shapes above; a failure degrades the device-MSM path
        # only (the operation_pool falls back to host sums, and a cold
        # MSM rung compiles on first use) and must not fail the rung.
        if self._compile_rung_fn is None and msm_warm_enabled():
            for n in MSM_RUNGS:
                mkey = (impl, dev, n)
                if mkey in self._msm_warmed or self._stopped:
                    continue
                from . import lowering

                try:
                    mrec = lowering.warm_msm(n, shard=dev)
                    _COMPILES.with_labels("msm", "ok").inc()
                    _COMPILE_SECONDS.with_labels("msm").observe(
                        float(mrec.get("seconds", 0.0))
                    )
                    self._msm_warmed.add(mkey)
                except Exception:
                    _COMPILES.with_labels("msm", "error").inc()
                break
        # manifest honesty: a FRESH compile that left no new executable
        # in the cache dir must not add manifest entries — the manifest
        # stays at least as conservative as the cache
        persisted = _cache.persisted_after(
            self.cache_dir,
            files_before,
            any(rec.get("fresh") for rec in (stages or {}).values()),
        )
        if self.registry.mark_ready(rung, impl, epoch=epoch, device=dev):
            self._record_ready(
                rung, impl, seconds=seconds, source="aot",
                persisted=persisted, device=dev,
            )

    # -- introspection ----------------------------------------------------

    def status(self) -> dict:
        """One document for /lighthouse/health: warm surface, queue,
        cold-route pressure and the persistent-cache state."""
        with self._cv:
            queue = list(self._queue)
            in_flight = self._in_flight
            compiled_total = self._compiled_total
            failed_total = self._failed_total
            cold_routes = dict(self._cold_routes)
            devices = self._devices
            now = time.monotonic()
            retry_pending = [
                [*rung, dev, round(max(0.0, due - now), 2)]
                for (rung, dev), due in sorted(self._retry_at.items())
            ]
            retries_total = self._retries_total
        prebaked = []
        if self.manifest is not None:
            try:
                prebaked = self.manifest.prebaked_rungs(
                    _cache.environment_key(self._impl())
                )
            except Exception:
                prebaked = []
        multi = len(devices) > 1

        def _item(it):
            # single-device nodes keep the pre-mesh [B, K, M] rendering;
            # a mesh walk appends the device so operators can see WHICH
            # chip a queued compile is for
            (b, k, m), dev = it
            return [b, k, m, dev] if multi else [b, k, m]

        doc = {
            "running": self.active(),
            "plan": [list(r) for r in self.plan],
            "warm_rungs": [list(r) for r in self.registry.warm_rungs()],
            "queue": [_item(it) for it in queue],
            "in_flight": None if in_flight is None else _item(in_flight),
            "compiled_total": compiled_total,
            "failed_total": failed_total,
            "cold_routes": cold_routes,
            "retry": {
                "max_attempts": self.retry_max_attempts,
                "base_s": self.retry_base_s,
                "retries_total": retries_total,
                "pending": retry_pending,
            },
            "cache": {**self.cache_status, "prebaked_rungs": [list(r) for r in prebaked]},
            # the ISSUE 14 rung-cost feed the capacity estimator reads
            "rung_costs": self.measured_rung_costs(),
        }
        if multi:
            doc["mesh_devices"] = list(devices)
            doc["warm_rungs_by_device"] = [
                list(r) for r in self.registry.warm_rungs_all()
            ]
        return doc


# ---------------------------------------------------------------------------
# Process-global service (the seam bls.TpuBackend and
# device.reset_compiled_state reach without plumbing a handle through
# every caller; the client builder owns the lifecycle).
# ---------------------------------------------------------------------------

_service_lock = threading.Lock()
_service: Optional[CompileService] = None


def set_service(svc: Optional[CompileService]) -> None:
    global _service
    with _service_lock:
        _service = svc


def clear_service(svc: Optional[CompileService] = None) -> None:
    """Detach the global service (only if it still IS ``svc`` when one
    is given — a racing rebuild must not lose its fresh service)."""
    global _service
    with _service_lock:
        if svc is None or _service is svc:
            _service = None


def get_service() -> Optional[CompileService]:
    return _service


def get_active_service() -> Optional[CompileService]:
    svc = _service
    if svc is not None and svc.active():
        return svc
    return None


def invalidate_registry() -> None:
    """``device.reset_compiled_state()`` hook: invalidate the global
    service's warm-shape registry (no-op without one)."""
    svc = _service
    if svc is not None:
        svc.invalidate()


def env_enabled() -> bool:
    return os.environ.get(_ENV_ENABLED, "1") not in ("", "0")
