"""Persistent executable caching for the compile service.

Two cooperating layers:

* the **JAX persistent compilation cache** (``jax_compilation_cache_dir``)
  holds the compiled executables themselves — a restarted node's AOT
  warmup walk finds every staged program on disk and "compiles" in
  milliseconds instead of minutes (``bench.py`` already proved this for
  the bench harness; this module wires the same machinery into the node
  proper). Feature-detected: older/stripped jax builds without the
  config knob degrade to no persistence, loudly reported in
  :func:`enable_persistent_cache`'s return value rather than raised.
* a **manifest** (``manifest.json`` next to the cache entries) records
  WHICH rungs were baked under WHICH environment, keyed on
  backend platform | jax version | device-code hash | fp_impl |
  (B, K, M) | stage. The executables alone cannot answer "is this cache
  warm for ME?" — the manifest can, and a key mismatch (engine switch,
  device-code edit, jax upgrade) is a MISS by construction, so a stale
  bake can never masquerade as a warm start
  (``tests/test_compile_service.py`` pins the invalidation).

Known failure mode (documented in ``tests/conftest.py`` and
``docs/COMPILE_SERVICE.md``): on some CPU host families XLA:CPU AOT
cache entries round-trip with mismatched machine features and SIGSEGV
on load. The node therefore only enables the cache when a directory is
explicitly configured (``LIGHTHOUSE_TPU_COMPILE_CACHE_DIR`` or
``ClientConfig.compile_cache_dir``) — never by default.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

ENV_CACHE_DIR = "LIGHTHOUSE_TPU_COMPILE_CACHE_DIR"
MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = "lighthouse_tpu.compile_manifest/1"

# The device modules whose source defines the staged programs: an edit
# to any of them changes the emitted HLO, so it must change the cache
# key. Order is part of the hash input (kept sorted).
_CODE_MODULES = (
    "bls", "curve", "fp", "fp2", "htc", "pairing", "pallas_fp", "tower",
)


def resolve_cache_dir(explicit: str | None = None) -> str | None:
    """The configured cache directory: explicit arg wins, then the env
    knob; None means persistent caching stays OFF (the safe default —
    see the SIGSEGV note in the module docstring)."""
    return explicit or os.environ.get(ENV_CACHE_DIR) or None


def enable_persistent_cache(cache_dir: str, min_compile_time_s: float = 1.0) -> dict:
    """Point the in-process JAX persistent compilation cache at
    ``cache_dir``. Feature-detected, never raises: returns
    ``{enabled, dir, reason}`` where ``reason`` explains a False."""
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_time_s
        )
    except Exception as e:  # missing knob / read-only dir / old jax
        return {"enabled": False, "dir": cache_dir, "reason": repr(e)[:200]}
    return {"enabled": True, "dir": cache_dir, "reason": None}


_code_hash: str | None = None


def code_version_hash() -> str:
    """Hash of the device crypto sources that define the staged
    programs (12 hex chars). Any edit to them invalidates every
    manifest key — the executables in the jax cache key on the real HLO
    fingerprint; the manifest must be at least as conservative. The
    sources cannot change under a running process, so the hash is
    computed once and memoized — ``environment_key`` sits on the
    /lighthouse/health scrape path."""
    global _code_hash
    if _code_hash is None:
        import lighthouse_tpu.crypto.device as _device

        h = hashlib.sha256()
        base = os.path.dirname(os.path.abspath(_device.__file__))
        for mod in _CODE_MODULES:
            path = os.path.join(base, mod + ".py")
            try:
                with open(path, "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(b"missing:" + mod.encode())
        _code_hash = h.hexdigest()[:12]
    return _code_hash


def environment_key(
    fp_impl: str,
    platform: str | None = None,
    jax_version: str | None = None,
    code_hash: str | None = None,
) -> str:
    """The environment half of a manifest key. The defaults describe
    THIS process (lazily querying jax); tests inject explicit parts to
    pin the invalidation semantics without a backend."""
    if platform is None or jax_version is None:
        import jax

        platform = platform or jax.default_backend()
        jax_version = jax_version or jax.__version__
    code_hash = code_hash or code_version_hash()
    return f"{platform}|jax-{jax_version}|code-{code_hash}|{fp_impl}"


def manifest_key(
    env_key: str, stage: str, b: int, k: int, m: int, device: int = 0
) -> str:
    """Device 0 keeps the pre-mesh key (existing manifests stay valid);
    a mesh walk's other chips key with a ``dev{n}`` component — their
    executables are distinct cache entries (a compile is per device
    assignment), so their warm-start claims must be too."""
    base = f"{env_key}|B{b}K{k}M{m}"
    if device:
        base += f"|dev{int(device)}"
    return f"{base}|{stage}"


def executable_entries(cache_dir: str) -> set | None:
    """``(name, mtime_ns)`` of the executable entries currently in
    ``cache_dir`` (the manifest and atomic-write temp files excluded);
    None when the dir is unreadable. The before/after probe both the
    service's AOT walk and the warmup CLI use to keep the manifest at
    least as conservative as the cache. Snapshotting mtimes (not just
    names) lets a re-warm over an already-baked cache count as
    persisted when the load path touches its entries — a manifest lost
    after a successful bake can then be rebuilt without wiping the
    cache."""
    try:
        with os.scandir(cache_dir) as it:
            return {
                (e.name, e.stat().st_mtime_ns)
                for e in it
                if e.name != MANIFEST_NAME and not e.name.endswith(".tmp")
            }
    except OSError:
        return None


def persisted_after(cache_dir: str, before: set | None, any_fresh: bool) -> bool:
    """Did a compile walk actually involve the executable cache? True
    unless a FRESH compile left the cache dir byte-for-byte untouched —
    no new entries AND no existing entry touched — which is what a
    silent write failure looks like. Conservative residual: a cache-
    served re-warm whose load path touches nothing reads as
    not-persisted, so a lost manifest may stay unreported until a fresh
    bake (warm-start claims err cold, never warm)."""
    if before is None or not any_fresh:
        return True
    after = executable_entries(cache_dir)
    return after is None or bool(after - before)


class Manifest:
    """Thread-safe record of baked rungs, persisted as one JSON file in
    the cache directory. ``has(key)`` answers warm-start questions;
    ``add(key)`` is called by the compile worker after each successful
    stage compile. A missing/corrupt file reads as empty (a lost
    manifest only costs re-warming, never correctness)."""

    def __init__(self, cache_dir: str):
        self.path = os.path.join(cache_dir, MANIFEST_NAME)
        self._lock = threading.Lock()
        self._entries: dict = {}
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if doc.get("schema") == MANIFEST_SCHEMA:
                self._entries = dict(doc.get("entries", {}))
        except (OSError, ValueError):
            self._entries = {}

    def _save_locked(self) -> None:
        doc = {"schema": MANIFEST_SCHEMA, "entries": self._entries}
        tmp = self.path + ".tmp"
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)  # atomic: readers never see a torn file
        except OSError:
            pass  # best-effort: the jax cache still holds the executables

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def add(self, key: str, **meta) -> None:
        self.add_many((key,), **meta)

    def add_many(self, keys, **meta) -> None:
        """Record several keys in ONE file rewrite — a rung's readiness
        adds its three stage keys together, so per-key ``add`` would
        fsync-replace the whole manifest three times back to back."""
        with self._lock:
            for key in keys:
                self._entries[key] = dict(meta)
            self._save_locked()

    def entries(self) -> dict:
        with self._lock:
            return dict(self._entries)

    def prebaked_rungs(self, env_key: str, stages=("stage1", "stage2", "stage3")) -> list:
        """Rungs (B, K, M) whose EVERY stage is recorded under
        ``env_key`` — the rungs a restarted node re-warms from disk with
        zero fresh XLA work."""
        prefix = env_key + "|"
        with self._lock:
            shapes: dict = {}
            for key in self._entries:
                if not key.startswith(prefix):
                    continue
                try:
                    shape_part, stage = key[len(prefix):].split("|")
                    b, rest = shape_part[1:].split("K")
                    k, m = rest.split("M")
                    rung = (int(b), int(k), int(m))
                except ValueError:
                    continue
                shapes.setdefault(rung, set()).add(stage)
        return sorted(r for r, st in shapes.items() if st >= set(stages))
