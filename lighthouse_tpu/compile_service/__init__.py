"""Compile service: ahead-of-time warmup, warm-shape routing, and
persistent executable caching for the staged device BLS pipeline (see
``service.py`` for the design, ``docs/COMPILE_SERVICE.md`` for the
operator view). The verification scheduler routes cold-bucket flushes
through :meth:`CompileService.decide_flush`; the device backend pads
batches up to warm rungs via :meth:`CompileService.pads_for`;
``tools/warmup.py`` prebakes the persistent cache."""

from .service import (
    DEFAULT_RUNGS,
    CompileService,
    WarmShapeRegistry,
    clear_service,
    get_active_service,
    get_service,
    invalidate_registry,
    set_service,
)

__all__ = [
    "DEFAULT_RUNGS",
    "CompileService",
    "WarmShapeRegistry",
    "clear_service",
    "get_active_service",
    "get_service",
    "invalidate_registry",
    "set_service",
]
