"""Web3Signer remote signing (reference: ``signing_method.rs:78-169`` —
the VC posts signing roots to an external signer service holding the
keys; plus ``testing/web3signer_tests``' real-signer rig, here an
in-process mock).
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Web3SignerError(Exception):
    pass


class Web3SignerClient:
    """Minimal client for the Web3Signer eth2 signing API."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    def sign(self, pubkey: bytes, signing_root: bytes,
             artifact_type: str = "AGGREGATION_SLOT") -> bytes:
        """POST the signing root. NOTE: a production Web3Signer validates
        per-type request metadata (fork_info + the full object) beyond the
        signing root; this client implements the signingRoot-carrying
        subset that the in-repo mock (and permissive signer configs)
        accept. Extending to full artifact payloads is additive — the
        ValidatorStore seam passes through here for every signature."""
        body = json.dumps(
            {"type": artifact_type, "signingRoot": "0x" + signing_root.hex()}
        ).encode()
        req = urllib.request.Request(
            f"{self.base}/api/v1/eth2/sign/0x{pubkey.hex()}",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                out = json.loads(r.read())
        except OSError as e:
            raise Web3SignerError(f"signer unreachable: {e}") from None
        sig = out.get("signature", "")
        if not sig.startswith("0x"):
            raise Web3SignerError("signer returned no signature")
        return bytes.fromhex(sig[2:])

    def public_keys(self) -> list[bytes]:
        req = urllib.request.Request(self.base + "/api/v1/eth2/publicKeys")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return [bytes.fromhex(p[2:]) for p in json.loads(r.read())]


class MockWeb3Signer:
    """In-process signer holding real secret keys (the role the Java
    Web3Signer binary plays in the reference's web3signer_tests)."""

    def __init__(self, secret_keys, port: int = 0):
        self._keys = {
            sk.public_key().serialize(): sk for sk in secret_keys
        }
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/api/v1/eth2/publicKeys":
                    payload = json.dumps(
                        ["0x" + pk.hex() for pk in outer._keys]
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                self.send_response(404)
                self.end_headers()

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path.startswith("/api/v1/eth2/sign/0x"):
                    pk = bytes.fromhex(self.path.rsplit("/0x", 1)[1])
                    sk = outer._keys.get(pk)
                    if sk is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    root = bytes.fromhex(body["signingRoot"][2:])
                    sig = sk.sign(root).serialize()
                    payload = json.dumps({"signature": "0x" + sig.hex()}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                self.send_response(404)
                self.end_headers()

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
