"""Validator client services (reference: ``validator_client/src/``
``duties_service.rs:107-110``, ``attestation_service.rs:23-126``,
``block_service.rs``, ``beacon_node_fallback.rs``,
``doppelganger_service.rs:1-30``).

Event loop shape mirrors the reference: a slot tick drives — duties are
polled per epoch; attestations are produced at slot + 1/3 and aggregates
at slot + 2/3; proposals fire at the slot start when a proposer duty
matches. Here the services expose explicit ``on_slot``-style methods so
tests (and the simulator) can drive them deterministically with a
ManualSlotClock; ``run_forever`` wires them to wall-clock time.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field

from ..keys import SlashingProtectionError
from ..eth2_client import BeaconNodeError
from ..ssz import hash_tree_root
from ..utils import metrics

_PUBLISHED_ATTS = metrics.counter("vc_published_attestations_total")
_PUBLISHED_BLOCKS = metrics.counter("vc_published_blocks_total")
_FAILED_DUTIES = metrics.counter("vc_failed_duties_total")

TARGET_AGGREGATORS_PER_COMMITTEE = 16


class BeaconNodeFallback:
    """Health-ranked multi-node redundancy (reference
    ``beacon_node_fallback.rs``): try nodes in order, demote failures."""

    def __init__(self, clients: list):
        if not clients:
            raise ValueError("at least one beacon node required")
        self.clients = list(clients)
        self._lock = threading.Lock()

    def first_healthy(self):
        with self._lock:
            order = list(self.clients)
        for c in order:
            if c.health():
                return c
        return order[0]

    def call(self, fn_name: str, *args, **kwargs):
        last_err = None
        with self._lock:
            order = list(self.clients)
        for i, c in enumerate(order):
            try:
                return getattr(c, fn_name)(*args, **kwargs)
            except BeaconNodeError as e:
                last_err = e
                if i == 0 and len(order) > 1:
                    # demote the failing primary
                    with self._lock:
                        if self.clients and self.clients[0] is c:
                            self.clients.append(self.clients.pop(0))
        raise last_err


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    slot: int
    committee_index: int
    committee_length: int
    committees_at_slot: int
    validator_committee_index: int


@dataclass
class ProposerDuty:
    pubkey: bytes
    validator_index: int
    slot: int


class DutiesService:
    """Polls duties per epoch and resolves validator indices (reference
    ``duties_service.rs``)."""

    def __init__(self, store, nodes: BeaconNodeFallback, preset):
        self.store = store
        self.nodes = nodes
        self.preset = preset
        self.attesters: dict[int, list[AttesterDuty]] = {}
        self.proposers: dict[int, list[ProposerDuty]] = {}

    def resolve_indices(self) -> None:
        for pk in self.store.pubkeys():
            if self.store.index_of(pk) is None:
                found = self.nodes.call(
                    "validators", "head", id="0x" + pk.hex()
                )
                if found:
                    self.store.set_index(pk, int(found[0]["index"]))

    def poll_epoch(self, epoch: int) -> None:
        self.resolve_indices()
        own = {
            self.store.index_of(pk): pk
            for pk in self.store.pubkeys()
            if self.store.index_of(pk) is not None
        }
        if not own:
            return
        att = self.nodes.call("attester_duties", epoch, sorted(own))
        self.attesters[epoch] = [
            AttesterDuty(
                pubkey=bytes.fromhex(d["pubkey"][2:]),
                validator_index=int(d["validator_index"]),
                slot=int(d["slot"]),
                committee_index=int(d["committee_index"]),
                committee_length=int(d["committee_length"]),
                committees_at_slot=int(d["committees_at_slot"]),
                validator_committee_index=int(d["validator_committee_index"]),
            )
            for d in att["data"]
            if int(d["validator_index"]) in own
        ]
        prop = self.nodes.call("proposer_duties", epoch)
        self.proposers[epoch] = [
            ProposerDuty(
                pubkey=bytes.fromhex(d["pubkey"][2:]),
                validator_index=int(d["validator_index"]),
                slot=int(d["slot"]),
            )
            for d in prop["data"]
            if int(d["validator_index"]) in own
        ]
        # prune old epochs
        for e in [e for e in self.attesters if e + 2 < epoch]:
            del self.attesters[e]
            self.proposers.pop(e, None)


class AttestationService:
    """Produce + sign + publish per duty; aggregate when selected
    (reference ``attestation_service.rs``)."""

    def __init__(self, store, nodes: BeaconNodeFallback, duties: DutiesService, types):
        self.store = store
        self.nodes = nodes
        self.duties = duties
        self.t = types

    def attest(self, slot: int) -> int:
        """slot+1/3 work: one attestation per duty at this slot."""
        epoch = slot // self.duties.preset.SLOTS_PER_EPOCH
        published = 0
        for duty in self.duties.attesters.get(epoch, []):
            if duty.slot != slot:
                continue
            try:
                data = self.nodes.call(
                    "attestation_data", slot, duty.committee_index
                )
                sig = self.store.sign_attestation(duty.pubkey, data)
                bits = [
                    i == duty.validator_committee_index
                    for i in range(duty.committee_length)
                ]
                att = self.t.Attestation(
                    aggregation_bits=bits, data=data, signature=sig
                )
                self.nodes.call("publish_attestations", [att])
                published += 1
                _PUBLISHED_ATTS.inc()
            except (BeaconNodeError, SlashingProtectionError, KeyError):
                # KeyError: key disabled/removed (doppelganger) — skip the
                # duty, never kill the loop
                _FAILED_DUTIES.inc()
        return published

    def aggregate(self, slot: int) -> int:
        """slot+2/3 work: publish SignedAggregateAndProof where this
        validator is the committee's aggregator (spec is_aggregator)."""
        epoch = slot // self.duties.preset.SLOTS_PER_EPOCH
        published = 0
        for duty in self.duties.attesters.get(epoch, []):
            if duty.slot != slot:
                continue
            try:
                proof = self.store.selection_proof(duty.pubkey, slot)
                modulo = max(
                    1, duty.committee_length // TARGET_AGGREGATORS_PER_COMMITTEE
                )
                h = hashlib.sha256(proof).digest()
                if int.from_bytes(h[:8], "little") % modulo != 0:
                    continue
                data = self.nodes.call(
                    "attestation_data", slot, duty.committee_index
                )
                agg = self.nodes.call(
                    "aggregate_attestation", slot, hash_tree_root(data)
                )
                msg = self.t.AggregateAndProof(
                    aggregator_index=duty.validator_index,
                    aggregate=agg,
                    selection_proof=proof,
                )
                signed = self.store.sign_aggregate_and_proof(duty.pubkey, msg)
                self.nodes.call("publish_aggregate_and_proofs", [signed])
                published += 1
            except (BeaconNodeError, SlashingProtectionError, KeyError):
                _FAILED_DUTIES.inc()
        return published


class SyncCommitteeService:
    """Sync-committee message production per slot (reference
    ``sync_committee_service.rs``): every duty signs the head block root
    and publishes; contribution aggregation happens node-side via the
    sync-message pool."""

    def __init__(self, store, nodes: BeaconNodeFallback, preset):
        self.store = store
        self.nodes = nodes
        self.preset = preset
        self.duties: dict[int, list[dict]] = {}  # epoch -> duty dicts

    def poll_epoch(self, epoch: int) -> None:
        own = [
            self.store.index_of(pk)
            for pk in self.store.pubkeys()
            if self.store.index_of(pk) is not None
        ]
        if not own:
            self.duties[epoch] = []
            return
        try:
            out = self.nodes.call("sync_duties", epoch, sorted(own))
            self.duties[epoch] = out.get("data", [])
        except BeaconNodeError:
            _FAILED_DUTIES.inc()
            return  # transient: retry next slot instead of caching empty
        for e in [e for e in self.duties if e + 2 < epoch]:
            del self.duties[e]

    def sign_and_publish(self, slot: int) -> int:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        if epoch not in self.duties:
            self.poll_epoch(epoch)
        duties = self.duties.get(epoch, [])
        if not duties:
            return 0
        published = 0
        try:
            head = self.nodes.call("header", "head")
            root = bytes.fromhex(head["root"][2:])
            msgs = []
            for d in duties:
                pk = bytes.fromhex(d["pubkey"][2:])
                try:
                    sig = self.store.sign_sync_committee_message(pk, slot, root)
                except KeyError:
                    continue
                msgs.append(
                    {
                        "slot": str(slot),
                        "beacon_block_root": "0x" + root.hex(),
                        "validator_index": d["validator_index"],
                        "signature": "0x" + sig.hex(),
                    }
                )
            if msgs:
                self.nodes.call("publish_sync_committee_messages", msgs)
                published = len(msgs)
        except BeaconNodeError:
            _FAILED_DUTIES.inc()
        return published

    def aggregate_and_publish(self, slot: int) -> int:
        """Sync-committee CONTRIBUTION aggregation (reference
        ``sync_committee_service.rs`` at slot+2/3): for every duty whose
        selection proof makes it a subcommittee aggregator, fetch the
        node's aggregated contribution, wrap + sign a
        ContributionAndProof, and publish."""
        from ..beacon_chain.sync_committee_verification import (
            is_sync_committee_aggregator,
        )

        epoch = slot // self.preset.SLOTS_PER_EPOCH
        duties = self.duties.get(epoch, [])
        if not duties:
            return 0
        published = 0
        try:
            head = self.nodes.call("header", "head")
            root = bytes.fromhex(head["root"][2:])
            sub_size = self.preset.sync_subcommittee_size
            signed_out = []
            for d in duties:
                pk = bytes.fromhex(d["pubkey"][2:])
                positions = [
                    int(p) for p in d["validator_sync_committee_indices"]
                ]
                for subc in sorted({p // sub_size for p in positions}):
                    try:
                        proof = self.store.sign_sync_selection_proof(
                            pk, slot, subc
                        )
                    except KeyError:
                        continue
                    if not is_sync_committee_aggregator(self.preset, proof):
                        continue
                    try:
                        contribution = self.nodes.call(
                            "sync_committee_contribution", slot, subc, root
                        )
                    except BeaconNodeError:
                        continue  # nothing collected for this subcommittee
                    msg = self.store.t.ContributionAndProof(
                        aggregator_index=int(d["validator_index"]),
                        contribution=contribution,
                        selection_proof=proof,
                    )
                    signed_out.append(
                        self.store.sign_contribution_and_proof(pk, msg)
                    )
            if signed_out:
                self.nodes.call("publish_contribution_and_proofs", signed_out)
                published = len(signed_out)
        except (BeaconNodeError, SlashingProtectionError, KeyError):
            _FAILED_DUTIES.inc()
        return published


class BlockService:
    """Proposal flow: randao -> produce -> sign -> publish (reference
    ``block_service.rs``)."""

    def __init__(self, store, nodes: BeaconNodeFallback, duties: DutiesService, preset,
                 graffiti_file=None):
        self.store = store
        self.nodes = nodes
        self.duties = duties
        self.preset = preset
        # reference common/graffiti_file: reread per proposal
        self.graffiti_file = graffiti_file

    def propose(self, slot: int) -> int:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        published = 0
        for duty in self.duties.proposers.get(epoch, []):
            if duty.slot != slot:
                continue
            try:
                randao = self.store.randao_reveal(duty.pubkey, epoch)
                graffiti = bytes(32)
                if self.graffiti_file is not None:
                    graffiti = (
                        self.graffiti_file.graffiti_for(duty.pubkey) or graffiti
                    )
                block = self.nodes.call("produce_block", slot, randao, graffiti)
                signed = self.store.sign_block(duty.pubkey, block)
                self.nodes.call("publish_block", signed)
                published += 1
                _PUBLISHED_BLOCKS.inc()
            except (BeaconNodeError, SlashingProtectionError, KeyError):
                _FAILED_DUTIES.inc()
        return published


class DoppelgangerService:
    """Liveness-based protection (reference
    ``doppelganger_service.rs:1-30``): keys stay disabled for N epochs
    while the BN is polled for evidence they are attesting elsewhere."""

    def __init__(self, store, nodes: BeaconNodeFallback, epochs_to_check: int = 2):
        self.store = store
        self.nodes = nodes
        self.epochs_to_check = epochs_to_check
        self._start_epoch: int | None = None
        self.detection = False

    def begin(self, epoch: int) -> None:
        self._start_epoch = epoch
        with self.store._lock:
            for v in self.store._validators.values():
                v.enabled = False

    def on_epoch(self, epoch: int, seen_validator_indices: set[int]) -> None:
        """``seen_validator_indices``: indices observed attesting on the
        network this epoch (from the BN's liveness endpoint / gossip)."""
        if self._start_epoch is None:
            return
        own = {
            self.store.index_of(pk)
            for pk in list(self.store._validators)
            if self.store.index_of(pk) is not None
        }
        if own & seen_validator_indices:
            # another instance is signing with our keys: shut down
            self.detection = True
            return
        if epoch >= self._start_epoch + self.epochs_to_check:
            with self.store._lock:
                for v in self.store._validators.values():
                    v.enabled = True
            self._start_epoch = None


class ValidatorClient:
    """Wires the services to a slot clock (reference
    ``validator_client/src/lib.rs``)."""

    def __init__(self, store, nodes: BeaconNodeFallback, types, preset, slot_clock,
                 graffiti_file=None):
        self.store = store
        self.nodes = nodes
        self.preset = preset
        self.slot_clock = slot_clock
        self.duties = DutiesService(store, nodes, preset)
        self.attestations = AttestationService(store, nodes, self.duties, types)
        self.blocks = BlockService(
            store, nodes, self.duties, preset, graffiti_file=graffiti_file
        )
        self.sync_committee = SyncCommitteeService(store, nodes, preset)
        from .preparation_service import PreparationService

        self.preparation = PreparationService(store, nodes, preset)
        self._stop = threading.Event()

    def on_slot(self, slot: int) -> None:
        """One deterministic slot of work (tests/simulator drive this)."""
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        try:
            if epoch not in self.duties.attesters:
                self.duties.poll_epoch(epoch)
            if epoch + 1 not in self.duties.attesters and (
                slot % self.preset.SLOTS_PER_EPOCH
            ) >= self.preset.SLOTS_PER_EPOCH // 2:
                self.duties.poll_epoch(epoch + 1)
        except BeaconNodeError:
            _FAILED_DUTIES.inc()
            return
        try:
            self.preparation.prepare_proposers(epoch)
        except BeaconNodeError:
            _FAILED_DUTIES.inc()
        self.blocks.propose(slot)
        self.attestations.attest(slot)
        self.attestations.aggregate(slot)
        self.sync_committee.sign_and_publish(slot)
        self.sync_committee.aggregate_and_publish(slot)

    def run_forever(self) -> None:
        while not self._stop.is_set():
            slot = self.slot_clock.now()
            self.on_slot(slot)
            wait = self.slot_clock.duration_to_next_slot()
            self._stop.wait(max(0.05, wait))

    def stop(self) -> None:
        self._stop.set()
