"""Fee-recipient preparation + builder registration (reference:
``validator_client/src/preparation_service.rs``).

Two duties, both idempotent and epoch-periodic:

* ``prepare_proposers`` — POST ``prepare_beacon_proposer`` with every
  known validator's fee recipient so the BN can pass it to the EL in
  ``forkchoice_updated`` payload attributes.
* ``register_validators`` — sign ``ValidatorRegistration`` messages with
  the application-builder domain and POST ``register_validator`` (the
  MEV-boost relay path; the BN forwards to its builder client).
"""

from __future__ import annotations

import time

from ..types.domains import compute_domain, compute_signing_root
from ..utils import metrics

_PREPARED = metrics.counter(
    "vc_preparation_sent_total", "prepare_beacon_proposer payloads sent"
)
_REGISTERED = metrics.counter(
    "vc_registrations_sent_total", "validator registrations sent"
)

# Spec DomainType 0x00000001 for the application builder (not a consensus
# domain — computed over the GENESIS fork with an empty
# genesis_validators_root). The repo encodes domain types as little-endian
# ints, so the byte string 00 00 00 01 is the int 0x01000000.
DOMAIN_APPLICATION_BUILDER = 0x01000000

DEFAULT_GAS_LIMIT = 30_000_000


class PreparationService:
    def __init__(
        self,
        store,
        nodes,
        preset,
        fee_recipient: bytes = b"\x00" * 20,
        per_validator: dict | None = None,
        gas_limit: int = DEFAULT_GAS_LIMIT,
    ):
        self.store = store
        self.nodes = nodes
        self.preset = preset
        self.fee_recipient = bytes(fee_recipient)
        self.per_validator = dict(per_validator or {})  # pubkey -> recipient
        self.gas_limit = gas_limit
        self._last_prepared_epoch = -1
        self._registered = False

    def fee_recipient_for(self, pubkey: bytes) -> bytes:
        return self.per_validator.get(bytes(pubkey), self.fee_recipient)

    def prepare_proposers(self, epoch: int) -> int:
        """Send (validator_index, fee_recipient) pairs; once per epoch."""
        if epoch == self._last_prepared_epoch:
            return 0
        prep = []
        for pk in self.store.pubkeys():
            vi = self.store.index_of(pk)
            if vi is None:
                continue
            prep.append(
                {
                    "validator_index": str(vi),
                    "fee_recipient": "0x" + self.fee_recipient_for(pk).hex(),
                }
            )
        if not prep:
            return 0
        self.nodes.call("prepare_beacon_proposer", prep)
        self._last_prepared_epoch = epoch
        _PREPARED.inc(len(prep))
        return len(prep)

    def register_validators(self) -> int:
        """Builder-path registrations, signed with the application-builder
        domain (reference ``signing_method.rs`` SignableMessage::
        ValidatorRegistration)."""
        domain = compute_domain(
            self.store.spec,
            DOMAIN_APPLICATION_BUILDER,
            self.store.spec.genesis_fork_version,
            b"\x00" * 32,
        )
        regs = []
        ts = int(time.time())
        for pk in self.store.pubkeys():
            message = {
                "fee_recipient": "0x" + self.fee_recipient_for(pk).hex(),
                "gas_limit": str(self.gas_limit),
                "timestamp": str(ts),
                "pubkey": "0x" + bytes(pk).hex(),
            }
            root = _registration_root(message, domain)
            try:
                sig = self.store._sign(bytes(pk), root)
            except KeyError:
                continue
            regs.append({"message": message, "signature": "0x" + sig.hex()})
        if not regs:
            return 0
        self.nodes.call("register_validator", regs)
        self._registered = True
        _REGISTERED.inc(len(regs))
        return len(regs)


def _registration_root(message: dict, domain: bytes) -> bytes:
    """hash_tree_root of the ValidatorRegistrationV1 container under the
    builder domain (fields: fee_recipient:Bytes20, gas_limit:u64,
    timestamp:u64, pubkey:Bytes48)."""
    from ..ssz import core as ssz
    from ..ssz.hash import hash_tree_root

    class _Registration(ssz.Container):
        fields = [
            ("fee_recipient", ssz.ByteVector(20)),
            ("gas_limit", ssz.Uint64),
            ("timestamp", ssz.Uint64),
            ("pubkey", ssz.Bytes48),
        ]

    reg = _Registration(
        fee_recipient=bytes.fromhex(message["fee_recipient"][2:]),
        gas_limit=int(message["gas_limit"]),
        timestamp=int(message["timestamp"]),
        pubkey=bytes.fromhex(message["pubkey"][2:]),
    )
    root = hash_tree_root(_Registration, reg)
    return compute_signing_root(None, root, domain)
