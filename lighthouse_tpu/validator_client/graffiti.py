"""Graffiti file support (reference ``common/graffiti_file``): a
per-validator graffiti mapping reread at every proposal so operators can
edit it without restarting the VC.

Format (one entry per line)::

    default: lighthouse_tpu
    0x<pubkey-hex>: my validator 7

Values are encoded UTF-8, truncated/zero-padded to 32 bytes.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional


def _to_bytes32(text: str) -> bytes:
    raw = text.strip().encode()[:32]
    return raw.ljust(32, b"\x00")


class GraffitiFile:
    def __init__(self, path):
        self.path = Path(path)

    def graffiti_for(self, pubkey: bytes) -> Optional[bytes]:
        """Mapping lookup for ``pubkey`` (falls back to ``default``);
        None when the file is missing/unreadable or has no match —
        callers then use their own default. Reread per call by design."""
        try:
            text = self.path.read_text()
        except OSError:
            return None
        default = None
        want = "0x" + bytes(pubkey).hex()
        for line in text.splitlines():
            if ":" not in line or line.lstrip().startswith("#"):
                continue
            key, _, value = line.partition(":")
            key = key.strip().lower()
            if key == "default":
                default = _to_bytes32(value)
            elif key == want:
                return _to_bytes32(value)
        return default
