"""ValidatorStore: decrypted keys + slashing-protected signing (reference:
``validator_client/src/validator_store.rs`` + ``signing_method.rs`` —
every signature passes through the slashing DB first).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from ..crypto import bls
from ..keys import SlashingDatabase, SlashingProtectionError, decrypt
from ..ssz import Uint64, hash_tree_root
from ..types.chain_spec import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
)
from ..types.domains import compute_domain, compute_signing_root


@dataclass
class InitializedValidator:
    """A loaded, enabled validator (reference initialized_validators.rs +
    ``signing_method.rs:78-89``: LocalKeystore vs Web3Signer)."""

    pubkey: bytes
    secret_key: Optional[bls.SecretKey] = None  # LocalKeystore
    remote_signer: Optional[object] = None      # Web3Signer client
    index: Optional[int] = None  # validator index once known on-chain
    enabled: bool = True


class ValidatorStore:
    def __init__(
        self,
        spec,
        preset,
        types,
        genesis_validators_root: bytes,
        slashing_db: SlashingDatabase | None = None,
    ):
        self.spec = spec
        self.preset = preset
        self.t = types
        self.genesis_validators_root = genesis_validators_root
        self.slashing_db = slashing_db or SlashingDatabase(
            genesis_validators_root=genesis_validators_root
        )
        self._validators: dict[bytes, InitializedValidator] = {}
        self._lock = threading.Lock()

    # -- key management --------------------------------------------------

    def add_secret_key(self, sk: bls.SecretKey) -> bytes:
        pk = sk.public_key().serialize()
        with self._lock:
            self._validators[pk] = InitializedValidator(pk, secret_key=sk)
        self.slashing_db.register_validator(pk)
        return pk

    def add_remote_key(self, pubkey: bytes, signer) -> bytes:
        """Web3Signer-style remote signing (reference
        ``signing_method.rs`` Web3Signer variant): the private key never
        enters this process. Refuses to replace an existing validator
        (a silent signing-method swap would drop a local secret key)."""
        pubkey = bytes(pubkey)
        if len(pubkey) != 48:
            raise ValueError(f"pubkey must be 48 bytes, got {len(pubkey)}")
        with self._lock:
            if pubkey in self._validators:
                raise ValueError("duplicate: validator already loaded")
            self._validators[pubkey] = InitializedValidator(
                pubkey, remote_signer=signer
            )
        self.slashing_db.register_validator(pubkey)
        return pubkey

    def add_keystore(self, keystore: dict, password: str) -> bytes:
        sk_bytes = decrypt(keystore, password)
        return self.add_secret_key(
            bls.SecretKey(int.from_bytes(sk_bytes, "big"))
        )

    def remove(self, pubkey: bytes) -> bool:
        with self._lock:
            return self._validators.pop(pubkey, None) is not None

    def has(self, pubkey: bytes) -> bool:
        with self._lock:
            return bytes(pubkey) in self._validators

    def is_local(self, pubkey: bytes) -> bool:
        with self._lock:
            v = self._validators.get(bytes(pubkey))
        return v is not None and v.secret_key is not None

    def remote_url(self, pubkey: bytes) -> str:
        with self._lock:
            v = self._validators.get(bytes(pubkey))
        signer = getattr(v, "remote_signer", None) if v else None
        return getattr(signer, "base", "") if signer else ""

    def pubkeys(self) -> list[bytes]:
        with self._lock:
            return [p for p, v in self._validators.items() if v.enabled]

    def set_index(self, pubkey: bytes, index: int) -> None:
        with self._lock:
            if pubkey in self._validators:
                self._validators[pubkey].index = index

    def index_of(self, pubkey: bytes) -> Optional[int]:
        with self._lock:
            v = self._validators.get(pubkey)
            return v.index if v else None

    def _sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        """Signature bytes via the validator's signing method."""
        with self._lock:
            v = self._validators.get(pubkey)
        if v is None or not v.enabled:
            raise KeyError(f"unknown/disabled validator {pubkey.hex()[:12]}")
        if v.secret_key is not None:
            return v.secret_key.sign(signing_root).serialize()
        return v.remote_signer.sign(pubkey, signing_root)

    # -- domains ---------------------------------------------------------

    def _domain(self, domain_type: int, epoch: int) -> bytes:
        version = self.spec.fork_version_at_epoch(epoch)
        return compute_domain(
            self.spec, domain_type, version, self.genesis_validators_root
        )

    # -- signing (every path slashing-protected where applicable) --------

    def sign_block(self, pubkey: bytes, block):
        epoch = block.slot // self.preset.SLOTS_PER_EPOCH
        domain = self._domain(DOMAIN_BEACON_PROPOSER, epoch)
        root = compute_signing_root(type(block), block, domain)
        self.slashing_db.check_and_insert_block_proposal(
            pubkey, block.slot, root
        )
        sig = self._sign(pubkey, root)
        fork = self.spec.fork_name_at_epoch(epoch)
        return self.t.signed_block[fork](message=block, signature=sig)

    def sign_attestation(self, pubkey: bytes, data):
        domain = self._domain(DOMAIN_BEACON_ATTESTER, data.target.epoch)
        root = compute_signing_root(type(data), data, domain)
        self.slashing_db.check_and_insert_attestation(
            pubkey, data.source.epoch, data.target.epoch, root
        )
        return self._sign(pubkey, root)

    def randao_reveal(self, pubkey: bytes, epoch: int) -> bytes:
        domain = self._domain(DOMAIN_RANDAO, epoch)
        root = compute_signing_root(Uint64, epoch, domain)
        return self._sign(pubkey, root)

    def selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        domain = self._domain(DOMAIN_SELECTION_PROOF, epoch)
        root = compute_signing_root(Uint64, slot, domain)
        return self._sign(pubkey, root)

    def sign_aggregate_and_proof(self, pubkey: bytes, aggregate_and_proof):
        epoch = aggregate_and_proof.aggregate.data.target.epoch
        domain = self._domain(DOMAIN_AGGREGATE_AND_PROOF, epoch)
        root = compute_signing_root(
            type(aggregate_and_proof), aggregate_and_proof, domain
        )
        return self.t.SignedAggregateAndProof(
            message=aggregate_and_proof,
            signature=self._sign(pubkey, root),
        )

    def sign_sync_committee_message(
        self, pubkey: bytes, slot: int, block_root: bytes
    ) -> bytes:
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        domain = self._domain(DOMAIN_SYNC_COMMITTEE, epoch)
        root = compute_signing_root(None, bytes(block_root), domain)
        return self._sign(pubkey, root)

    def sign_sync_selection_proof(
        self, pubkey: bytes, slot: int, subcommittee_index: int
    ) -> bytes:
        """Selection proof over SyncAggregatorSelectionData (reference
        ``sync_committee_service.rs`` aggregation duty)."""
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        domain = self._domain(DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch)
        data = self.t.SyncAggregatorSelectionData(
            slot=slot, subcommittee_index=subcommittee_index
        )
        root = compute_signing_root(
            self.t.SyncAggregatorSelectionData, data, domain
        )
        return self._sign(pubkey, root)

    def sign_contribution_and_proof(self, pubkey: bytes, message):
        """Sign a ContributionAndProof -> SignedContributionAndProof."""
        epoch = int(message.contribution.slot) // self.preset.SLOTS_PER_EPOCH
        domain = self._domain(DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
        root = compute_signing_root(self.t.ContributionAndProof, message, domain)
        return self.t.SignedContributionAndProof(
            message=message, signature=self._sign(pubkey, root)
        )

    def sign_voluntary_exit(self, pubkey: bytes, exit_msg):
        domain = self._domain(DOMAIN_VOLUNTARY_EXIT, exit_msg.epoch)
        root = compute_signing_root(type(exit_msg), exit_msg, domain)
        return self.t.SignedVoluntaryExit(
            message=exit_msg, signature=self._sign(pubkey, root)
        )
