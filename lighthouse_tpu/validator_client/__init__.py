"""L7: validator client — duties/attestation/block services,
slashing-protected ValidatorStore, doppelganger protection, multi-BN
fallback.

Reference: ``validator_client/`` (SURVEY.md §2.5).
"""

from .services import (
    AttestationService,
    AttesterDuty,
    BeaconNodeFallback,
    BlockService,
    DoppelgangerService,
    DutiesService,
    ProposerDuty,
    SyncCommitteeService,
    ValidatorClient,
)
from .validator_store import InitializedValidator, ValidatorStore

__all__ = [
    "AttestationService",
    "AttesterDuty",
    "BeaconNodeFallback",
    "BlockService",
    "DoppelgangerService",
    "DutiesService",
    "InitializedValidator",
    "ProposerDuty",
    "SyncCommitteeService",
    "ValidatorClient",
    "ValidatorStore",
]
