"""Keymanager API (reference: ``validator_client/src/http_api`` — the
standardized key-manager routes with bearer-token auth):

    GET    /eth/v1/keystores          list local keys
    POST   /eth/v1/keystores          import keystores (+passwords)
    DELETE /eth/v1/keystores          delete keys (+ slashing data export)
    GET    /eth/v1/remotekeys         list Web3Signer-backed keys
    POST   /eth/v1/remotekeys         register remote keys (pubkey + url)
    DELETE /eth/v1/remotekeys         deregister remote keys
"""

from __future__ import annotations

import hmac
import json
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _parse_pubkey(s: str) -> bytes:
    """0x-prefixed 48-byte hex pubkey, strictly validated."""
    if not isinstance(s, str) or not s.startswith("0x"):
        raise ValueError("pubkey must be 0x-prefixed hex")
    pk = bytes.fromhex(s[2:])
    if len(pk) != 48:
        raise ValueError(f"pubkey must be 48 bytes, got {len(pk)}")
    return pk


class KeymanagerApi:
    def __init__(self, store, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None):
        self.store = store
        self.token = token or secrets.token_hex(16)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _auth(self) -> bool:
                # bytes operands: compare_digest raises TypeError on
                # non-ASCII str, which would crash the handler
                header = self.headers.get("Authorization", "")
                return hmac.compare_digest(
                    header.encode("utf-8", "surrogateescape"),
                    f"Bearer {outer.token}".encode(),
                )

            def _reply(self, code: int, obj) -> None:
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if not self._auth():
                    return self._reply(403, {"message": "invalid token"})
                if self.path == "/eth/v1/keystores":
                    data = [
                        {
                            "validating_pubkey": "0x" + pk.hex(),
                            "derivation_path": "",
                            "readonly": False,
                        }
                        for pk in outer.store.pubkeys()
                        if outer.store.is_local(pk)
                    ]
                    return self._reply(200, {"data": data})
                if self.path == "/eth/v1/remotekeys":
                    data = [
                        {
                            "pubkey": "0x" + pk.hex(),
                            "url": outer.store.remote_url(pk),
                            "readonly": False,
                        }
                        for pk in outer.store.pubkeys()
                        if not outer.store.is_local(pk)
                    ]
                    return self._reply(200, {"data": data})
                self._reply(404, {"message": "not found"})

            def do_POST(self):
                if not self._auth():
                    return self._reply(403, {"message": "invalid token"})
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/eth/v1/keystores":
                    out = []
                    for ks_raw, pw in zip(
                        body.get("keystores", []), body.get("passwords", [])
                    ):
                        try:
                            ks = (
                                json.loads(ks_raw)
                                if isinstance(ks_raw, str)
                                else ks_raw
                            )
                            outer.store.add_keystore(ks, pw)
                            out.append({"status": "imported"})
                        except Exception as e:
                            out.append({"status": "error", "message": str(e)})
                    return self._reply(200, {"data": out})
                if self.path == "/eth/v1/remotekeys":
                    from .web3signer import Web3SignerClient

                    out = []
                    for rk in body.get("remote_keys", []):
                        try:
                            pk = _parse_pubkey(rk["pubkey"])
                            if outer.store.has(pk):
                                out.append({"status": "duplicate"})
                                continue
                            outer.store.add_remote_key(
                                pk, Web3SignerClient(rk["url"])
                            )
                            out.append({"status": "imported"})
                        except Exception as e:
                            out.append({"status": "error", "message": str(e)})
                    return self._reply(200, {"data": out})
                self._reply(404, {"message": "not found"})

            def do_DELETE(self):
                if not self._auth():
                    return self._reply(403, {"message": "invalid token"})
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/eth/v1/keystores":
                    out = []
                    for pk_hex in body.get("pubkeys", []):
                        pk = bytes.fromhex(pk_hex[2:])
                        ok = outer.store.remove(pk)
                        out.append({"status": "deleted" if ok else "not_found"})
                    # EIP-3076 slashing data rides along, per the keymanager spec
                    return self._reply(
                        200,
                        {
                            "data": out,
                            "slashing_protection": outer.store.slashing_db.export_json(),
                        },
                    )
                if self.path == "/eth/v1/remotekeys":
                    out = []
                    for pk_hex in body.get("pubkeys", []):
                        try:
                            pk = _parse_pubkey(pk_hex)
                            if not outer.store.has(pk):
                                out.append({"status": "not_found"})
                            elif outer.store.is_local(pk):
                                # a LOCAL key must go through the keystores
                                # route (which exports slashing data)
                                out.append({
                                    "status": "error",
                                    "message": "local key: use /eth/v1/keystores",
                                })
                            else:
                                outer.store.remove(pk)
                                out.append({"status": "deleted"})
                        except Exception as e:
                            out.append({"status": "error", "message": str(e)})
                    return self._reply(200, {"data": out})
                self._reply(404, {"message": "not found"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
