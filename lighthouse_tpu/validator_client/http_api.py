"""Keymanager API (reference: ``validator_client/src/http_api`` — the
standardized key-manager routes with bearer-token auth):

    GET    /eth/v1/keystores          list local keys
    POST   /eth/v1/keystores          import keystores (+passwords)
    DELETE /eth/v1/keystores          delete keys (+ slashing data export)
"""

from __future__ import annotations

import json
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class KeymanagerApi:
    def __init__(self, store, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None):
        self.store = store
        self.token = token or secrets.token_hex(16)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _auth(self) -> bool:
                return (
                    self.headers.get("Authorization", "")
                    == f"Bearer {outer.token}"
                )

            def _reply(self, code: int, obj) -> None:
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if not self._auth():
                    return self._reply(403, {"message": "invalid token"})
                if self.path == "/eth/v1/keystores":
                    data = [
                        {
                            "validating_pubkey": "0x" + pk.hex(),
                            "derivation_path": "",
                            "readonly": False,
                        }
                        for pk in outer.store.pubkeys()
                    ]
                    return self._reply(200, {"data": data})
                self._reply(404, {"message": "not found"})

            def do_POST(self):
                if not self._auth():
                    return self._reply(403, {"message": "invalid token"})
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/eth/v1/keystores":
                    out = []
                    for ks_raw, pw in zip(
                        body.get("keystores", []), body.get("passwords", [])
                    ):
                        try:
                            ks = (
                                json.loads(ks_raw)
                                if isinstance(ks_raw, str)
                                else ks_raw
                            )
                            outer.store.add_keystore(ks, pw)
                            out.append({"status": "imported"})
                        except Exception as e:
                            out.append({"status": "error", "message": str(e)})
                    return self._reply(200, {"data": out})
                self._reply(404, {"message": "not found"})

            def do_DELETE(self):
                if not self._auth():
                    return self._reply(403, {"message": "invalid token"})
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/eth/v1/keystores":
                    out = []
                    for pk_hex in body.get("pubkeys", []):
                        pk = bytes.fromhex(pk_hex[2:])
                        ok = outer.store.remove(pk)
                        out.append({"status": "deleted" if ok else "not_found"})
                    # EIP-3076 slashing data rides along, per the keymanager spec
                    return self._reply(
                        200,
                        {
                            "data": out,
                            "slashing_protection": outer.store.slashing_db.export_json(),
                        },
                    )
                self._reply(404, {"message": "not found"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
