"""BeaconProcessor: bounded work queues + worker pool + batch coalescing.

Reference: ``beacon_node/network/src/beacon_processor/mod.rs`` — a manager
task feeding <= CPU-count blocking workers from bounded per-kind queues;
when a worker frees up, up to MAX_GOSSIP_ATTESTATION_BATCH_SIZE=64 pending
gossip attestations (or aggregates) are popped and executed as ONE batch
(``mod.rs:176-177,1008-1099``), with queue-overflow shedding and a
re-processing queue for too-early/unknown-parent work
(``work_reprocessing_queue.rs``).

TPU-first deltas from the reference's design:

* the coalesced batch is the DEVICE batch: default ceilings match the
  device bucket sizes (256 unaggregated / 64 aggregates vs the
  reference's 64/64) — the whole point of the TPU backend is that the
  batch ceiling rises without per-item latency cost;
* batch assembly is paced by worker availability exactly like the
  reference: an idle pool drains items one-by-one (lowest latency), a
  busy pool accumulates device-sized batches (highest throughput).
"""

from .processor import BeaconProcessor, Work, WorkKind

__all__ = ["BeaconProcessor", "Work", "WorkKind"]
