"""Work-queue scheduler (see package docstring; reference
``beacon_processor/mod.rs``)."""

from __future__ import annotations

import enum
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import flight_recorder, logging, metrics, tracing

_QUEUE_LEN = metrics.gauge("beacon_processor_queue_total", "queued work items")
_WORK_TOTAL = metrics.counter_vec(
    "beacon_processor_work_total", "work items executed per kind", ("kind",)
)
_HANDLE_SECONDS = metrics.histogram_vec(
    "beacon_processor_handle_seconds",
    "handler execution wall time per drained batch",
    ("kind",),
)
_BATCH_SIZE = metrics.histogram(
    "beacon_processor_batch_size",
    "coalesced attestation batch sizes",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
_WAIT_TIME = metrics.histogram(
    "beacon_processor_queue_wait_seconds", "submit-to-execution latency"
)
_DROPPED = metrics.counter(
    "beacon_processor_dropped_total", "work items shed on full queues"
)
_SHED_LATCH = logging.TimeLatch(10.0)


class WorkKind(enum.Enum):
    # priority order: lower value = drained first (reference's match order
    # in InboundEvents / the Work enum priorities)
    CHAIN_SEGMENT = 0
    GOSSIP_BLOCK = 1
    GOSSIP_AGGREGATE = 2
    GOSSIP_SYNC_CONTRIBUTION = 3
    GOSSIP_ATTESTATION = 4
    GOSSIP_SYNC_MESSAGE = 5
    API_REQUEST = 6


# Bounded queue sizes (reference mod.rs:84-105: 16_384 unagg, 4_096 agg,
# 1_024 blocks; sync queues sized like their attestation analogues).
DEFAULT_QUEUE_BOUNDS = {
    WorkKind.CHAIN_SEGMENT: 64,
    WorkKind.GOSSIP_BLOCK: 1_024,
    WorkKind.GOSSIP_AGGREGATE: 4_096,
    WorkKind.GOSSIP_SYNC_CONTRIBUTION: 4_096,
    WorkKind.GOSSIP_ATTESTATION: 16_384,
    WorkKind.GOSSIP_SYNC_MESSAGE: 16_384,
    WorkKind.API_REQUEST: 1_024,
}

# Device-bucket batch ceilings (the reference caps both at 64,
# mod.rs:176-177; the TPU backend's batch lanes are cheaper).
DEFAULT_BATCH_CEILINGS = {
    WorkKind.GOSSIP_ATTESTATION: 256,
    WorkKind.GOSSIP_AGGREGATE: 64,
    WorkKind.GOSSIP_SYNC_MESSAGE: 128,
}

# LIFO kinds (the reference drains attestations newest-first so stale
# items shed under load).
_LIFO = {WorkKind.GOSSIP_ATTESTATION, WorkKind.GOSSIP_SYNC_MESSAGE}


@dataclass
class Work:
    kind: WorkKind
    item: object
    submitted_at: float = field(default_factory=time.monotonic)
    done: Optional[Callable] = None  # called with the handler's result


class BeaconProcessor:
    """``handlers`` maps WorkKind -> callable. Batchable kinds receive a
    LIST of items; others receive one item. Results are delivered through
    each Work's ``done`` callback (None = fire-and-forget)."""

    def __init__(
        self,
        handlers: dict,
        n_workers: int = 2,
        queue_bounds: dict | None = None,
        batch_ceilings: dict | None = None,
    ):
        self.handlers = handlers
        self.queue_bounds = dict(queue_bounds or DEFAULT_QUEUE_BOUNDS)
        self.batch_ceilings = dict(batch_ceilings or DEFAULT_BATCH_CEILINGS)
        self._queues: dict[WorkKind, deque] = {k: deque() for k in WorkKind}
        self._lock = threading.Lock()
        self._work_ready = threading.Condition(self._lock)
        self._shutdown = False
        self._delayed: list[tuple[float, Work]] = []
        self._workers = [
            threading.Thread(target=self._worker_loop, name=f"bp-worker-{i}", daemon=True)
            for i in range(n_workers)
        ]
        self._timer = threading.Thread(target=self._delay_loop, daemon=True)
        for w in self._workers:
            w.start()
        self._timer.start()

    # -- submission ------------------------------------------------------

    def submit(self, work: Work) -> bool:
        """False if the bounded queue is full and the item was shed
        (reference queue-overflow shedding, mod.rs:1179-1204)."""
        with self._lock:
            if self._shutdown:
                return False
            q = self._queues[work.kind]
            if len(q) >= self.queue_bounds[work.kind]:
                _DROPPED.inc()
                flight_recorder.record(
                    "queue_shed", kind=work.kind.name, queue_len=len(q),
                    bound=self.queue_bounds[work.kind],
                    total_queued=sum(len(x) for x in self._queues.values()),
                )
                logging.rate_limited(
                    _SHED_LATCH, "warn", "work queue full, shedding",
                    kind=work.kind.name,
                )
                return False
            q.append(work)
            _QUEUE_LEN.set(sum(len(q) for q in self._queues.values()))
            self._work_ready.notify()
            return True

    def submit_later(self, work: Work, delay_s: float) -> None:
        """Re-processing queue: schedule for re-submission after a delay
        (reference work_reprocessing_queue — early blocks / attestations
        for unknown blocks)."""
        with self._lock:
            self._delayed.append((time.monotonic() + delay_s, work))

    # -- worker loop -----------------------------------------------------

    def _next_batch(self) -> Optional[tuple[WorkKind, list[Work]]]:
        """Called under the lock: drain by priority, coalescing batchable
        kinds up to their ceiling."""
        for kind in sorted(WorkKind, key=lambda k: k.value):
            q = self._queues[kind]
            if not q:
                continue
            ceiling = self.batch_ceilings.get(kind, 1)
            batch = []
            while q and len(batch) < ceiling:
                batch.append(q.pop() if kind in _LIFO else q.popleft())
            _QUEUE_LEN.set(sum(len(q) for q in self._queues.values()))
            return kind, batch
        return None

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                got = self._next_batch()
                while not self._shutdown and got is None:
                    self._work_ready.wait(timeout=0.1)
                    got = self._next_batch()
                if got is None:  # shutdown with empty queues
                    return
                kind, batch = got
            now = time.monotonic()
            for w in batch:
                _WAIT_TIME.observe(now - w.submitted_at)
            if kind in self.batch_ceilings:
                _BATCH_SIZE.observe(len(batch))
            self._execute(kind, batch)

    def _execute(self, kind: WorkKind, batch: list[Work]) -> None:
        handler = self.handlers.get(kind)
        if handler is None:
            return
        _WORK_TOTAL.with_labels(kind.name).inc(len(batch))
        with tracing.span(
            "beacon_processor.execute", kind=kind.name, batch=len(batch)
        ), _HANDLE_SECONDS.with_labels(kind.name).time():
            self._execute_inner(kind, batch, handler)

    def _execute_inner(self, kind: WorkKind, batch: list[Work], handler) -> None:
        if kind in self.batch_ceilings:
            try:
                results = handler([w.item for w in batch])
                if results is None:
                    results = [None] * len(batch)
                else:
                    results = list(results)
            except Exception as e:  # handler bugs must not kill the worker
                results = [e] * len(batch)
            if len(results) < len(batch):
                # a short handler return must never strand a done callback
                short = RuntimeError("batch handler returned too few results")
                results += [short] * (len(batch) - len(results))
            for w, r in zip(batch, results):
                self._complete(w, r)
        else:
            for w in batch:
                try:
                    r = handler(w.item)
                except Exception as e:
                    r = e
                self._complete(w, r)

    @staticmethod
    def _complete(w: Work, result) -> None:
        """Invoke the callback exactly once; its own exceptions are the
        callback owner's bug, not a reason to re-complete anything."""
        if w.done:
            try:
                w.done(result)
            except Exception:
                pass

    def _delay_loop(self) -> None:
        while True:
            time.sleep(0.02)
            with self._lock:
                if self._shutdown:
                    return
                now = time.monotonic()
                ready = [w for t, w in self._delayed if t <= now]
                self._delayed = [(t, w) for t, w in self._delayed if t > now]
            for w in ready:
                self.submit(w)

    # -- lifecycle -------------------------------------------------------

    def queue_lengths(self) -> dict:
        with self._lock:
            return {k.name: len(q) for k, q in self._queues.items()}

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._work_ready.notify_all()
        for w in self._workers:
            w.join(timeout=5)
