"""lighthouse_tpu — a TPU-native Ethereum consensus-layer framework.

A ground-up rebuild of the capabilities of Lighthouse (the reference Rust
client, see SURVEY.md) designed for TPUs: the data-parallel cryptographic
hot path — BLS12-381 batch signature verification (multi-pairing, MSM) and
hashing — runs as JAX/Pallas kernels behind a runtime-selectable backend
seam (mirroring the reference's ``crypto/bls`` generic-backend trait,
``crypto/bls/src/lib.rs:99-140``), while the consensus runtime (state
transition, fork choice, storage, networking, validator client) is host
code engineered around device-sized batches.

Layout (§2 of SURVEY.md maps each subpackage to reference crates):
  crypto/            L0  — BLS12-381 + hashing; cpu oracle + jax device stack
  ssz/               L1  — SSZ encode/decode + merkleization
  types/             L2  — spec datatypes, presets, ChainSpec
  state_transition/  L2  — per-slot/block/epoch + BlockSignatureVerifier
  fork_choice/       L2  — proto-array LMD-GHOST
  store/             L3  — hot/cold persistence
  chain/             L4  — BeaconChain runtime, verification pipelines, caches
  net/               L5  — gossip/rpc host layer + beacon processor
  api/               L6  — Beacon API (HTTP)
  vc/                L7  — validator client + slashing protection
  cli/               L8  — process entry points
  parallel/          —   — device mesh / sharding helpers
  ops/               —   — pallas kernels
  utils/             LX  — metrics, logging, slot clock, task executor
"""

__version__ = "0.1.0"
