"""Test rigs (reference: ``beacon_node/beacon_chain/src/test_utils.rs``
``BeaconChainHarness``, ``testing/node_test_rig``): deterministic interop
validators driving real state transitions with real BLS signatures."""

from .harness import StateHarness

__all__ = ["StateHarness"]
