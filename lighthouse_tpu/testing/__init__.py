"""Test rigs (reference: ``beacon_node/beacon_chain/src/test_utils.rs``
``BeaconChainHarness``, ``testing/node_test_rig``): deterministic interop
validators driving real state transitions with real BLS signatures."""

from .fork_choice_runner import ForkChoiceRunner
from .harness import StateHarness


def spec_for_fork(fork: str):
    """Minimal-preset ChainSpec with fork-activation epochs set for
    ``fork`` — the one mapping shared by the ef vector generator and the
    ef handlers (a fork added in only one place breaks the selfcheck)."""
    from ..types.chain_spec import minimal_spec

    return minimal_spec(
        altair_fork_epoch=0 if fork != "phase0" else None,
        bellatrix_fork_epoch=0 if fork == "bellatrix" else None,
    )


__all__ = ["ForkChoiceRunner", "StateHarness", "spec_for_fork"]
