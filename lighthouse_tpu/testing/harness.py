"""StateHarness: interop-keyed block/attestation production over the pure
state-transition function — the minimal analogue of the reference's
``BeaconChainHarness`` (``test_utils.rs:68-69``) before the full chain
runtime exists. Signs everything for real, so it exercises the BLS
backends end-to-end (any backend: cpu / tpu / fake).
"""

from __future__ import annotations

import copy

from ..crypto import bls
from ..ssz import hash_tree_root
from ..state_transition import signature_sets as sigsets
from ..state_transition.block import process_block
from ..state_transition.genesis import interop_genesis_state, interop_secret_key
from ..state_transition.helpers import (
    CommitteeCache,
    compute_epoch_at_slot,
    get_beacon_proposer_index,
    get_block_root_at_slot,
    get_committee_count_per_slot,
)
from ..state_transition.slot import partial_state_advance, per_slot_processing
from ..types import (
    ChainSpec,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    compute_signing_root,
    get_domain,
    types_for,
)
from ..types.preset import Preset
from .. import ssz


class StateHarness:
    def __init__(
        self,
        preset: Preset,
        spec: ChainSpec,
        validator_count: int = 64,
        fork_name: str = "phase0",
        fake_sign: bool = False,
    ):
        """``fake_sign=True`` stamps a constant valid G2 point instead of
        signing (pair with signature_strategy="none" — the reference's
        ``fake_crypto`` testing seam, ``crypto/bls/src/lib.rs:13-14``)."""
        self.preset = preset
        self.spec = spec
        self.fork_name = fork_name
        self.t = types_for(preset)
        self.keys = [interop_secret_key(i) for i in range(validator_count)]
        self.state = interop_genesis_state(
            preset, spec, validator_count, fork_name=fork_name
        )
        self.fake_sign = fake_sign
        if fake_sign:
            from ..crypto.cpu.curve import g2_generator

            self._fake_sig = bls.Signature(g2_generator()).serialize()
        else:
            self._fake_sig = None

    # -- signing ---------------------------------------------------------

    def sign_block(self, block, proposer_index: int):
        if self.fake_sign:
            return self.t.signed_block[self.fork_name](
                message=block, signature=self._fake_sig
            )
        domain = get_domain(
            self.spec,
            self.state,
            DOMAIN_BEACON_PROPOSER,
            block.slot // self.preset.SLOTS_PER_EPOCH,
        )
        root = compute_signing_root(type(block), block, domain)
        sig = self.keys[proposer_index].sign(root)
        signed = self.t.signed_block[self.fork_name](
            message=block, signature=sig.serialize()
        )
        return signed

    def randao_reveal(self, state, slot: int, proposer_index: int) -> bytes:
        if self.fake_sign:
            return self._fake_sig
        epoch = slot // self.preset.SLOTS_PER_EPOCH
        domain = get_domain(self.spec, state, DOMAIN_RANDAO, epoch)
        root = compute_signing_root(ssz.Uint64, epoch, domain)
        return self.keys[proposer_index].sign(root).serialize()

    # -- attestations ----------------------------------------------------

    def _head_block_root(self, state) -> bytes:
        from ..state_transition.helpers import latest_block_header_root

        return latest_block_header_root(state)

    def attestations_for_slot(self, state, slot: int):
        """Fully-participating attestations for every committee at ``slot``
        (state must be at a slot where block_roots[slot] is known)."""
        t = self.t
        epoch = compute_epoch_at_slot(self.preset, slot)
        cache = CommitteeCache(self.preset, state, epoch)
        head_root = (
            get_block_root_at_slot(self.preset, state, slot)
            if slot < state.slot
            else self._head_block_root(state)
        )
        target_root = (
            get_block_root_at_slot(
                self.preset, state, epoch * self.preset.SLOTS_PER_EPOCH
            )
            if epoch * self.preset.SLOTS_PER_EPOCH < state.slot
            else head_root
        )
        source = (
            state.current_justified_checkpoint
            if epoch == compute_epoch_at_slot(self.preset, state.slot)
            else state.previous_justified_checkpoint
        )
        out = []
        for index in range(cache.committees_per_slot):
            committee = cache.committee(slot, index)
            data = t.AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head_root,
                source=source,
                target=t.Checkpoint(epoch=epoch, root=target_root),
            )
            if self.fake_sign:
                sig_bytes = self._fake_sig
            else:
                domain = get_domain(self.spec, state, DOMAIN_BEACON_ATTESTER, epoch)
                root = compute_signing_root(t.AttestationData, data, domain)
                agg = bls.AggregateSignature.infinity()
                for v in committee:
                    agg.add_assign(self.keys[int(v)].sign(root))
                sig_bytes = agg.serialize()
            out.append(
                t.Attestation(
                    aggregation_bits=[True] * len(committee),
                    data=data,
                    signature=sig_bytes,
                )
            )
        return out


    def sync_aggregate_for(self, state, block_slot: int):
        """Fully-participating sync aggregate signing the previous block
        root (altair+)."""
        t = self.t
        prev_slot = max(block_slot, 1) - 1
        root = (
            get_block_root_at_slot(self.preset, state, prev_slot)
            if prev_slot < state.slot
            else self._head_block_root(state)
        )
        domain = get_domain(
            self.spec, state, DOMAIN_SYNC_COMMITTEE,
            prev_slot // self.preset.SLOTS_PER_EPOCH,
        )
        if self.fake_sign:
            return t.SyncAggregate(
                sync_committee_bits=[True] * self.preset.SYNC_COMMITTEE_SIZE,
                sync_committee_signature=self._fake_sig,
            )
        signing_root = compute_signing_root(None, root, domain)
        pk_to_key = {
            self.keys[i].public_key().serialize(): self.keys[i]
            for i in range(len(self.keys))
        }
        agg = bls.AggregateSignature.infinity()
        for pk_bytes in state.current_sync_committee.pubkeys:
            agg.add_assign(pk_to_key[pk_bytes].sign(signing_root))
        return t.SyncAggregate(
            sync_committee_bits=[True] * self.preset.SYNC_COMMITTEE_SIZE,
            sync_committee_signature=agg.serialize(),
        )

    # -- block production / import --------------------------------------

    def produce_block(self, slot: int, attestations=(), full_sync: bool = False):
        """Advance a copy of the head state to ``slot`` and build a signed
        block on it (reference: ``produce_block_on_state``,
        ``beacon_chain.rs:3364``)."""
        state = copy.deepcopy(self.state)
        state = partial_state_advance(self.preset, self.spec, state, slot)
        proposer = get_beacon_proposer_index(self.preset, state)
        t = self.t
        body_kwargs = dict(
            randao_reveal=self.randao_reveal(state, slot, proposer),
            eth1_data=state.eth1_data,
            attestations=list(attestations),
        )
        if self.fork_name in ("altair", "bellatrix"):
            if full_sync:
                body_kwargs["sync_aggregate"] = self.sync_aggregate_for(state, slot)
            else:
                body_kwargs["sync_aggregate"] = t.SyncAggregate(
                    sync_committee_signature=bls.INFINITY_SIGNATURE
                )
        body = t.block_body[self.fork_name](**body_kwargs)
        block = t.block[self.fork_name](
            slot=slot,
            proposer_index=proposer,
            parent_root=hash_tree_root(state.latest_block_header),
            state_root=bytes(32),
            body=body,
        )
        # compute the post-state root with signatures skipped
        trial = copy.deepcopy(state)
        signed_unsigned = t.signed_block[self.fork_name](message=block)
        process_block(
            self.preset, self.spec, trial, signed_unsigned, self.fork_name,
            signature_strategy="none",
        )
        block.state_root = hash_tree_root(trial)
        return self.sign_block(block, proposer)

    def process_block(self, signed_block, strategy: str = "individual"):
        """per-slot advance + per-block processing onto the head state."""
        self.state = partial_state_advance(
            self.preset, self.spec, self.state, signed_block.message.slot
        )
        process_block(
            self.preset,
            self.spec,
            self.state,
            signed_block,
            self.fork_name,
            signature_strategy=strategy,
        )
        return self.state

    def advance_slots(self, n: int) -> None:
        for _ in range(n):
            self.state = per_slot_processing(self.preset, self.spec, self.state)

    def extend_chain(self, n_blocks: int, strategy: str = "bulk", attest: bool = True):
        """Produce and import ``n_blocks`` consecutive blocks, attesting to
        the previous slot when possible."""
        blocks = []
        for _ in range(n_blocks):
            slot = self.state.slot + 1
            atts = []
            if attest and slot >= 2:
                atts = self.attestations_for_slot(self.state, slot - 1)[
                    : self.preset.MAX_ATTESTATIONS
                ]
            sb = self.produce_block(slot, attestations=atts)
            self.process_block(sb, strategy=strategy)
            blocks.append(sb)
        return blocks
