"""Multi-node simulator: N full beacon nodes in ONE process, connected
over real localhost TCP networking (reference: ``testing/simulator`` —
``src/main.rs:1-15``, ``local_network.rs``, invariant ``checks.rs`` —
and ``testing/node_test_rig``).

Each node: its own store, BeaconChain, BeaconProcessor, NetworkService.
Validators are partitioned across nodes; block proposals and
attestations are produced by the owning node and propagate over gossip.
A shared ManualSlotClock keeps the run deterministic.
"""

from __future__ import annotations

import copy
import time

from ..beacon_chain import BeaconChain, VerifiedUnaggregatedAttestation
from ..client import _build_processor
from ..network import NetworkService
from ..operation_pool import OperationPool
from ..ssz import hash_tree_root
from ..state_transition import store_replayer
from ..store import HotColdDB, MemoryStore
from ..testing.harness import StateHarness
from ..types.chain_spec import minimal_spec
from ..types.preset import MINIMAL
from ..utils.slot_clock import ManualSlotClock


class LocalNode:
    def __init__(self, harness_template, genesis, clock):
        h = harness_template
        db = HotColdDB(
            MemoryStore(), h.t, h.spec, store_replayer(h.preset, h.spec),
            slots_per_snapshot=8,
        )
        self.chain = BeaconChain(
            h.preset, h.spec, h.t, db, copy.deepcopy(genesis), slot_clock=clock
        )
        self.chain.op_pool = OperationPool(h.preset, h.spec, h.t)
        self.processor = _build_processor(self.chain, n_workers=1)
        self.net = NetworkService(self.chain, self.processor)

    def close(self):
        self.net.close()
        self.processor.shutdown()


class LocalNetwork:
    """``validator_split``: list of validator-index sets, one per node."""

    def __init__(self, n_nodes: int, validator_count: int = 8, fork: str = "phase0"):
        self.h = StateHarness(
            MINIMAL, minimal_spec(), validator_count=validator_count,
            fork_name=fork, fake_sign=True,
        )
        self.genesis = copy.deepcopy(self.h.state)
        self.clock = ManualSlotClock(
            self.genesis.genesis_time, self.h.spec.seconds_per_slot
        )
        self.nodes = [
            LocalNode(self.h, self.genesis, self.clock) for _ in range(n_nodes)
        ]
        # everyone dials the bootnode; peer exchange fills the mesh
        boot = self.nodes[0]
        for node in self.nodes[1:]:
            node.net.connect("127.0.0.1", boot.net.port)
        # dial() registers the peer on the DIALING side synchronously, but
        # the bootnode's accept-loop thread registers inbound peers after
        # its half of the handshake — callers touching
        # ``nodes[0].net.transport.peers`` right after construction raced
        # that thread (the one red test in the default gate). Block until
        # every inbound peer is registered.
        self._wait_inbound(boot, n_nodes - 1)
        self.validator_owner = {
            v: v % n_nodes for v in range(validator_count)
        }

    @staticmethod
    def _wait_inbound(node: LocalNode, n: int, timeout: float = 5.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if node.net.transport.peer_count() >= n:
                return
            time.sleep(0.01)
        raise AssertionError(
            f"bootnode registered {node.net.transport.peer_count()} of "
            f"{n} inbound peers within {timeout}s"
        )

    def add_node(self) -> LocalNode:
        node = LocalNode(self.h, self.genesis, self.clock)
        have = self.nodes[0].net.transport.peer_count()
        node.net.connect("127.0.0.1", self.nodes[0].net.port)
        self._wait_inbound(self.nodes[0], have + 1)
        self.nodes.append(node)
        return node

    # -- driving ---------------------------------------------------------

    def tick_slot(self, attest: bool = True) -> None:
        """Advance one slot: proposer's node builds + publishes the block;
        every validator's node attests to it over gossip."""
        h = self.h
        slot = self.h.state.slot + 1
        self.clock.set_slot(slot)
        for node in self.nodes:
            node.chain.on_tick(slot)

        # canonical copy of the chain lives in the harness (proposer keys)
        atts = []
        if attest and slot >= 2:
            atts = h.attestations_for_slot(h.state, slot - 1)[
                : h.preset.MAX_ATTESTATIONS
            ]
        sb = h.produce_block(slot, attestations=atts)
        h.process_block(sb, strategy="none")
        proposer_node = self.nodes[sb.message.proposer_index % len(self.nodes)]
        proposer_node.chain.process_block(
            proposer_node.chain.verify_block_for_gossip(sb)
        )
        proposer_node.net.publish_block(sb)
        self._settle()

        if attest:
            # single-bit gossip attestations from each owner node
            for att in h.attestations_for_slot(h.state, slot):
                bits = list(att.aggregation_bits)
                from ..state_transition import get_beacon_committee

                committee = get_beacon_committee(
                    h.preset, h.state, att.data.slot, att.data.index
                )
                for pos, v in enumerate(committee):
                    single = copy.deepcopy(att)
                    single.aggregation_bits = [
                        i == pos for i in range(len(bits))
                    ]
                    node = self.nodes[int(v) % len(self.nodes)]
                    res = node.chain.batch_verify_unaggregated_attestations_for_gossip(
                        [single]
                    )
                    if isinstance(res[0], VerifiedUnaggregatedAttestation):
                        node.chain.apply_attestation_to_fork_choice(res[0])
                        node.chain.op_pool.insert_attestation(single)
                        node.net.publish_attestation(single, att.data.index)
            self._settle()

    def _settle(self, timeout: float = 5.0) -> None:
        """Wait until every node's queues drain."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(
                sum(n.processor.queue_lengths().values()) == 0
                for n in self.nodes
            ):
                time.sleep(0.05)
                if all(
                    sum(n.processor.queue_lengths().values()) == 0
                    for n in self.nodes
                ):
                    return
            time.sleep(0.01)

    def recompute_heads(self) -> None:
        for n in self.nodes:
            n.chain.recompute_head()

    # -- invariant checks (reference checks.rs) --------------------------

    def check_all_heads_equal(self) -> bytes:
        self.recompute_heads()
        heads = {n.chain.head_block_root for n in self.nodes}
        assert len(heads) == 1, f"forked heads: {[h.hex()[:8] for h in heads]}"
        return heads.pop()

    def check_finalization(self, min_epoch: int) -> None:
        for i, n in enumerate(self.nodes):
            fin = n.chain.fork_choice.store.finalized_checkpoint[0]
            assert fin >= min_epoch, f"node {i} finalized {fin} < {min_epoch}"

    def close(self) -> None:
        for n in self.nodes:
            n.close()
