"""Step-driven fork-choice harness: the consumer of the ef
``fork_choice`` vector format (anchor + tick/block/attestation/
attester_slashing/checks steps).

This is the analogue of the reference's ef fork_choice case runner
(``testing/ef_tests/src/cases/fork_choice.rs:1-688``), which drives a
full ``BeaconChainHarness``; here the runner owns a :class:`ForkChoice`
plus a root→state map maintained by replaying blocks through the real
state transition. Shared by the vector GENERATOR
(``tools/gen_ef_vectors.py``) and the ef handler test
(``tests/ef/test_ef_fork_choice.py``) — the generator records this
runner's own observable outputs as the expected checks (self-generated;
see tests/ef/README.md for what that does and does not certify).
"""

from __future__ import annotations

import copy

from ..fork_choice import ForkChoice
from ..ssz import hash_tree_root
from ..state_transition import partial_state_advance
from ..state_transition.block import process_block
from ..state_transition.helpers import get_indexed_attestation
from ..types.chain_spec import ChainSpec
from ..types.preset import Preset


class ForkChoiceRunner:
    def __init__(
        self, preset: Preset, spec: ChainSpec, fork_name: str,
        anchor_state, anchor_block,
    ):
        self.preset = preset
        self.spec = spec
        self.fork_name = fork_name
        anchor_root = hash_tree_root(type(anchor_block), anchor_block)
        self.anchor_root = anchor_root
        self.genesis_time = anchor_state.genesis_time
        # anchor checkpoints root to the anchor block itself (chain.py:146)
        self.fc = ForkChoice(
            preset,
            spec,
            anchor_state.slot,
            anchor_root,
            (anchor_state.current_justified_checkpoint.epoch, anchor_root),
            (anchor_state.finalized_checkpoint.epoch, anchor_root),
            [v.effective_balance for v in anchor_state.validators],
        )
        self.states = {anchor_root: copy.deepcopy(anchor_state)}

    # -- steps -----------------------------------------------------------

    def on_tick(self, time: int) -> None:
        slot = (time - self.genesis_time) // self.spec.seconds_per_slot
        self.fc.on_tick(slot)

    def on_block(self, signed_block) -> bytes:
        """Replay through the state transition, then register with fork
        choice. Raises on any invalid block (unknown parent, bad
        transition, fork-choice rejection)."""
        block = signed_block.message
        parent = self.states.get(bytes(block.parent_root))
        if parent is None:
            raise KeyError("unknown parent block")
        state = copy.deepcopy(parent)
        state = partial_state_advance(self.preset, self.spec, state, block.slot)
        process_block(
            self.preset, self.spec, state, signed_block, self.fork_name,
            signature_strategy="none",
        )
        root = hash_tree_root(type(block), block)
        self.fc.on_block(self.fc.store.current_slot, block, root, state)
        self.states[root] = state
        return root

    def on_attestation(self, attestation) -> None:
        target_state = self.states.get(bytes(attestation.data.target.root))
        if target_state is None:
            raise KeyError("unknown attestation target")
        indexed = get_indexed_attestation(self.preset, target_state, attestation)
        self.fc.on_attestation(self.fc.store.current_slot, indexed)

    def on_attester_slashing(self, slashing) -> None:
        self.fc.on_attester_slashing(
            slashing.attestation_1, slashing.attestation_2
        )

    # -- observables -----------------------------------------------------

    def checks(self) -> dict:
        head = self.fc.get_head()
        jc = self.fc.store.justified_checkpoint
        fin = self.fc.store.finalized_checkpoint
        return {
            "head": {
                "slot": int(self.fc.proto.get_block_slot(head)),
                "root": "0x" + head.hex(),
            },
            "justified_checkpoint": {"epoch": int(jc[0]), "root": "0x" + jc[1].hex()},
            "finalized_checkpoint": {"epoch": int(fin[0]), "root": "0x" + fin[1].hex()},
            "proposer_boost_root": "0x" + self.fc.store.proposer_boost_root.hex(),
        }
