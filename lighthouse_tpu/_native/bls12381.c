/* Native BLS12-381 batch signature-set verification (backend "cpu-native").
 *
 * This is the blst-class CPU baseline the TPU backend is measured against
 * (BASELINE.md; the reference's default backend is
 * /root/reference/crypto/bls/src/impls/blst.rs:36-119 — random-linear-
 * combination batching over an aggregated Miller loop). Everything here is
 * an independent implementation: Montgomery 6x64 field arithmetic (CIOS),
 * the 2-3-2 tower, Jacobian curve ops, an aggregated optimal-ate Miller
 * loop with sparse line multiplication, the machine-checked x-chain final
 * exponentiation (same chain as crypto/device/pairing.py), RFC 9380
 * hash-to-curve for G2, and the batch verification equation
 *
 *   prod_i e([r_i] agg_pk_i, H(m_i)) * e(-g1, sum_i [r_i] sig_i) == 1.
 *
 * Curve constants are generated from the repo's own params by
 * tools/gen_bls_c_tables.py into bls12381_tables.h.
 *
 * Build: cc -O3 -fPIC -shared bls12381.c (needs __uint128_t; x86-64/ARM64).
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

#include "bls12381_tables.h"

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned __int128 u128;

/* ===================================================================== */
/* fp: 6x64 Montgomery                                                    */
/* ===================================================================== */

typedef struct { uint64_t l[6]; } fp;

static fp FP_ZERO;          /* 0 */
static fp FP_ONE;           /* R mod p (Montgomery 1) */
static fp FP_R2;            /* 2^768 mod p */

static inline int fp_is_zero(const fp *a) {
    uint64_t v = 0;
    for (int i = 0; i < 6; i++) v |= a->l[i];
    return v == 0;
}

static inline int fp_eq(const fp *a, const fp *b) {
    uint64_t v = 0;
    for (int i = 0; i < 6; i++) v |= a->l[i] ^ b->l[i];
    return v == 0;
}

/* a >= p ? */
static inline int fp_ge_p(const fp *a) {
    for (int i = 5; i >= 0; i--) {
        if (a->l[i] > BLS_P[i]) return 1;
        if (a->l[i] < BLS_P[i]) return 0;
    }
    return 1; /* equal */
}

static inline void fp_sub_p(fp *a) {
    u128 bw = 0;
    for (int i = 0; i < 6; i++) {
        u128 t = (u128)a->l[i] - BLS_P[i] - bw;
        a->l[i] = (uint64_t)t;
        bw = (t >> 64) & 1; /* borrow */
    }
}

static void fp_add(fp *o, const fp *a, const fp *b) {
    u128 c = 0;
    for (int i = 0; i < 6; i++) {
        c += (u128)a->l[i] + b->l[i];
        o->l[i] = (uint64_t)c;
        c >>= 64;
    }
    if (c || fp_ge_p(o)) fp_sub_p(o);
}

static void fp_sub(fp *o, const fp *a, const fp *b) {
    u128 bw = 0;
    for (int i = 0; i < 6; i++) {
        u128 t = (u128)a->l[i] - b->l[i] - bw;
        o->l[i] = (uint64_t)t;
        bw = (t >> 64) & 1;
    }
    if (bw) { /* += p */
        u128 c = 0;
        for (int i = 0; i < 6; i++) {
            c += (u128)o->l[i] + BLS_P[i];
            o->l[i] = (uint64_t)c;
            c >>= 64;
        }
    }
}

static void fp_neg(fp *o, const fp *a) {
    if (fp_is_zero(a)) { *o = *a; return; }
    fp z = FP_ZERO;
    fp_sub(o, &z, a);
}

static void fp_dbl(fp *o, const fp *a) { fp_add(o, a, a); }

/* Montgomery CIOS multiplication: o = a*b*R^-1 mod p */
static void fp_mul(fp *o, const fp *a, const fp *b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 6; i++) {
        u128 c = 0;
        uint64_t ai = a->l[i];
        for (int j = 0; j < 6; j++) {
            c = (u128)ai * b->l[j] + t[j] + (uint64_t)c;
            t[j] = (uint64_t)c;
            c >>= 64;
        }
        c = (u128)t[6] + (uint64_t)c;
        t[6] = (uint64_t)c;
        t[7] = (uint64_t)(c >> 64);

        uint64_t m = t[0] * BLS_PINV;
        c = (u128)m * BLS_P[0] + t[0];
        c >>= 64;
        for (int j = 1; j < 6; j++) {
            c = (u128)m * BLS_P[j] + t[j] + (uint64_t)c;
            t[j - 1] = (uint64_t)c;
            c >>= 64;
        }
        c = (u128)t[6] + (uint64_t)c;
        t[5] = (uint64_t)c;
        t[6] = t[7] + (uint64_t)(c >> 64);
        t[7] = 0;
    }
    for (int i = 0; i < 6; i++) o->l[i] = t[i];
    if (t[6] || fp_ge_p(o)) fp_sub_p(o);
}

static void fp_sqr(fp *o, const fp *a) { fp_mul(o, a, a); }

static void fp_from_raw(fp *o, const uint64_t raw[6]) {
    fp t;
    for (int i = 0; i < 6; i++) t.l[i] = raw[i];
    fp_mul(o, &t, &FP_R2); /* to Montgomery */
}

static void fp_to_raw(uint64_t raw[6], const fp *a) {
    fp one = {{1, 0, 0, 0, 0, 0}};
    fp t;
    fp_mul(&t, a, &one); /* from Montgomery */
    for (int i = 0; i < 6; i++) raw[i] = t.l[i];
}

/* generic fixed-window-free pow: e is n_limbs little-endian (raw int) */
static void fp_pow(fp *o, const fp *a, const uint64_t *e, int n_limbs) {
    fp acc = FP_ONE;
    int started = 0;
    for (int i = n_limbs - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) fp_sqr(&acc, &acc);
            if ((e[i] >> b) & 1) {
                if (!started) { acc = *a; started = 1; }
                else fp_mul(&acc, &acc, a);
            }
        }
    }
    *o = started ? acc : FP_ONE;
}

static void fp_inv(fp *o, const fp *a) { fp_pow(o, a, BLS_P_MINUS_2, 6); }

/* sqrt for p = 3 mod 4: a^((p+1)/4); returns 0 if a is not a square */
static int fp_sqrt(fp *o, const fp *a) {
    fp r, chk;
    fp_pow(&r, a, BLS_P_PLUS_1_DIV_4, 6);
    fp_sqr(&chk, &r);
    if (!fp_eq(&chk, a)) return 0;
    *o = r;
    return 1;
}

/* canonical big-endian 48-byte IO */
static void fp_to_bytes(uint8_t out[48], const fp *a) {
    uint64_t raw[6];
    fp_to_raw(raw, a);
    for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
            out[48 - 8 * (i + 1) + (7 - j)] = (uint8_t)(raw[i] >> (8 * j));
}

static int fp_from_bytes(fp *o, const uint8_t in[48]) {
    uint64_t raw[6] = {0};
    for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
            raw[i] |= (uint64_t)in[48 - 8 * (i + 1) + (7 - j)] << (8 * j);
    /* must be < p */
    for (int i = 5; i >= 0; i--) {
        if (raw[i] < BLS_P[i]) break;
        if (raw[i] > BLS_P[i]) return 0;
        if (i == 0) return 0; /* == p */
    }
    fp_from_raw(o, raw);
    return 1;
}

/* lexicographic compare of canonical values: sign of a - b */
static int fp_cmp(const fp *a, const fp *b) {
    uint64_t ra[6], rb[6];
    fp_to_raw(ra, a);
    fp_to_raw(rb, b);
    for (int i = 5; i >= 0; i--) {
        if (ra[i] > rb[i]) return 1;
        if (ra[i] < rb[i]) return -1;
    }
    return 0;
}

static int fp_sgn0(const fp *a) {
    uint64_t raw[6];
    fp_to_raw(raw, a);
    return (int)(raw[0] & 1);
}

/* ===================================================================== */
/* fp2 = fp[u]/(u^2+1)                                                    */
/* ===================================================================== */

typedef struct { fp c0, c1; } fp2;

static fp2 FP2_ZERO, FP2_ONE;

static inline int fp2_is_zero(const fp2 *a) { return fp_is_zero(&a->c0) && fp_is_zero(&a->c1); }
static inline int fp2_eq(const fp2 *a, const fp2 *b) { return fp_eq(&a->c0, &b->c0) && fp_eq(&a->c1, &b->c1); }

static void fp2_add(fp2 *o, const fp2 *a, const fp2 *b) { fp_add(&o->c0, &a->c0, &b->c0); fp_add(&o->c1, &a->c1, &b->c1); }
static void fp2_sub(fp2 *o, const fp2 *a, const fp2 *b) { fp_sub(&o->c0, &a->c0, &b->c0); fp_sub(&o->c1, &a->c1, &b->c1); }
static void fp2_neg(fp2 *o, const fp2 *a) { fp_neg(&o->c0, &a->c0); fp_neg(&o->c1, &a->c1); }
static void fp2_dbl(fp2 *o, const fp2 *a) { fp2_add(o, a, a); }
static void fp2_conj(fp2 *o, const fp2 *a) { o->c0 = a->c0; fp_neg(&o->c1, &a->c1); }

/* Karatsuba: 3 fp muls */
static void fp2_mul(fp2 *o, const fp2 *a, const fp2 *b) {
    fp aa, bb, t0, t1, t2;
    fp_mul(&aa, &a->c0, &b->c0);
    fp_mul(&bb, &a->c1, &b->c1);
    fp_add(&t0, &a->c0, &a->c1);
    fp_add(&t1, &b->c0, &b->c1);
    fp_mul(&t2, &t0, &t1);
    fp_sub(&t2, &t2, &aa);
    fp_sub(&t2, &t2, &bb);
    fp_sub(&o->c0, &aa, &bb);
    o->c1 = t2;
}

static void fp2_sqr(fp2 *o, const fp2 *a) {
    /* (c0+c1 u)^2 = (c0+c1)(c0-c1) + 2 c0 c1 u */
    fp s, d, m;
    fp_add(&s, &a->c0, &a->c1);
    fp_sub(&d, &a->c0, &a->c1);
    fp_mul(&m, &a->c0, &a->c1);
    fp_mul(&o->c0, &s, &d);
    fp_dbl(&o->c1, &m);
}

static void fp2_mul_fp(fp2 *o, const fp2 *a, const fp *s) {
    fp_mul(&o->c0, &a->c0, s);
    fp_mul(&o->c1, &a->c1, s);
}

/* multiply by the non-residue xi = u + 1: (c0+c1u)(1+u) = c0-c1 + (c0+c1)u */
static void fp2_mul_xi(fp2 *o, const fp2 *a) {
    fp t0, t1;
    fp_sub(&t0, &a->c0, &a->c1);
    fp_add(&t1, &a->c0, &a->c1);
    o->c0 = t0;
    o->c1 = t1;
}

static void fp2_inv(fp2 *o, const fp2 *a) {
    fp t0, t1;
    fp_sqr(&t0, &a->c0);
    fp_sqr(&t1, &a->c1);
    fp_add(&t0, &t0, &t1);
    fp_inv(&t0, &t0);
    fp_mul(&o->c0, &a->c0, &t0);
    fp_mul(&t1, &a->c1, &t0);
    fp_neg(&o->c1, &t1);
}

static void fp2_pow(fp2 *o, const fp2 *a, const uint64_t *e, int n_limbs) {
    fp2 acc = FP2_ONE;
    int started = 0;
    for (int i = n_limbs - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) fp2_sqr(&acc, &acc);
            if ((e[i] >> b) & 1) {
                if (!started) { acc = *a; started = 1; }
                else fp2_mul(&acc, &acc, a);
            }
        }
    }
    *o = started ? acc : FP2_ONE;
}

/* sqrt in Fp2 for p = 3 mod 4 (Adj-Rodriguez alg. 9); 0 if not square */
static int fp2_sqrt(fp2 *o, const fp2 *a) {
    if (fp2_is_zero(a)) { *o = FP2_ZERO; return 1; }
    fp2 a1, alpha, x0, t, neg1;
    fp2_pow(&a1, a, BLS_P_MINUS_3_DIV_4, 6);
    fp2_sqr(&alpha, &a1);
    fp2_mul(&alpha, &alpha, a);
    fp2_mul(&x0, &a1, a);
    neg1 = FP2_ONE;
    fp2_neg(&neg1, &neg1);
    if (fp2_eq(&alpha, &neg1)) {
        /* x = u * x0 */
        fp_neg(&o->c0, &x0.c1);
        o->c1 = x0.c0;
    } else {
        fp2_add(&t, &alpha, &FP2_ONE);
        fp2_pow(&t, &t, BLS_P_MINUS_1_DIV_2, 6);
        fp2_mul(o, &t, &x0);
    }
    fp2_sqr(&t, o);
    return fp2_eq(&t, a);
}

static int fp2_sgn0(const fp2 *a) {
    /* RFC 9380 sgn0 for m=2 */
    int s0 = fp_sgn0(&a->c0);
    int z0 = fp_is_zero(&a->c0);
    int s1 = fp_sgn0(&a->c1);
    return s0 | (z0 & s1);
}

/* lexicographically larger rule for compressed-point sign: compare (c1, c0) */
static int fp2_lex_gt(const fp2 *a, const fp2 *b) {
    int c = fp_cmp(&a->c1, &b->c1);
    if (c != 0) return c > 0;
    return fp_cmp(&a->c0, &b->c0) > 0;
}

static void fp2_from_raw(fp2 *o, const fp2_raw *r) {
    fp_from_raw(&o->c0, r->c0.l);
    fp_from_raw(&o->c1, r->c1.l);
}

/* ===================================================================== */
/* fp6 = fp2[v]/(v^3 - xi),  fp12 = fp6[w]/(w^2 - v)                      */
/* ===================================================================== */

typedef struct { fp2 c0, c1, c2; } fp6;
typedef struct { fp6 c0, c1; } fp12;

static void fp6_add(fp6 *o, const fp6 *a, const fp6 *b) { fp2_add(&o->c0, &a->c0, &b->c0); fp2_add(&o->c1, &a->c1, &b->c1); fp2_add(&o->c2, &a->c2, &b->c2); }
static void fp6_sub(fp6 *o, const fp6 *a, const fp6 *b) { fp2_sub(&o->c0, &a->c0, &b->c0); fp2_sub(&o->c1, &a->c1, &b->c1); fp2_sub(&o->c2, &a->c2, &b->c2); }
static void fp6_neg(fp6 *o, const fp6 *a) { fp2_neg(&o->c0, &a->c0); fp2_neg(&o->c1, &a->c1); fp2_neg(&o->c2, &a->c2); }
static int fp6_is_zero(const fp6 *a) { return fp2_is_zero(&a->c0) && fp2_is_zero(&a->c1) && fp2_is_zero(&a->c2); }

/* multiply by v: (c0, c1, c2) -> (xi*c2, c0, c1) */
static void fp6_mul_v(fp6 *o, const fp6 *a) {
    fp2 t;
    fp2_mul_xi(&t, &a->c2);
    o->c2 = a->c1;
    o->c1 = a->c0;
    o->c0 = t;
}

static void fp6_mul(fp6 *o, const fp6 *a, const fp6 *b) {
    /* schoolbook with xi folds (6 fp2 muls via Toom-ish grouping kept
     * simple: 9 muls schoolbook — clarity over the last 15%) */
    fp2 t00, t11, t22, t, u;
    fp6 r;
    fp2_mul(&t00, &a->c0, &b->c0);
    fp2_mul(&t11, &a->c1, &b->c1);
    fp2_mul(&t22, &a->c2, &b->c2);

    /* r0 = t00 + xi*(a1 b2 + a2 b1) */
    fp2_mul(&t, &a->c1, &b->c2);
    fp2_mul(&u, &a->c2, &b->c1);
    fp2_add(&t, &t, &u);
    fp2_mul_xi(&t, &t);
    fp2_add(&r.c0, &t00, &t);

    /* r1 = a0 b1 + a1 b0 + xi * t22 */
    fp2_mul(&t, &a->c0, &b->c1);
    fp2_mul(&u, &a->c1, &b->c0);
    fp2_add(&t, &t, &u);
    fp2_mul_xi(&u, &t22);
    fp2_add(&r.c1, &t, &u);

    /* r2 = a0 b2 + a2 b0 + t11 */
    fp2_mul(&t, &a->c0, &b->c2);
    fp2_mul(&u, &a->c2, &b->c0);
    fp2_add(&t, &t, &u);
    fp2_add(&r.c2, &t, &t11);
    *o = r;
}

static void fp6_sqr(fp6 *o, const fp6 *a) { fp6_mul(o, a, a); }

static void fp6_mul_fp2(fp6 *o, const fp6 *a, const fp2 *s) {
    fp2_mul(&o->c0, &a->c0, s);
    fp2_mul(&o->c1, &a->c1, s);
    fp2_mul(&o->c2, &a->c2, s);
}

static void fp6_inv(fp6 *o, const fp6 *a) {
    /* standard: c = a0^2 - xi a1 a2, etc. */
    fp2 c0, c1, c2, t, u, d;
    fp2_sqr(&c0, &a->c0);
    fp2_mul(&t, &a->c1, &a->c2);
    fp2_mul_xi(&t, &t);
    fp2_sub(&c0, &c0, &t);

    fp2_sqr(&c1, &a->c2);
    fp2_mul_xi(&c1, &c1);
    fp2_mul(&t, &a->c0, &a->c1);
    fp2_sub(&c1, &c1, &t);

    fp2_sqr(&c2, &a->c1);
    fp2_mul(&t, &a->c0, &a->c2);
    fp2_sub(&c2, &c2, &t);

    /* d = a0 c0 + xi (a2 c1 + a1 c2) */
    fp2_mul(&t, &a->c2, &c1);
    fp2_mul(&u, &a->c1, &c2);
    fp2_add(&t, &t, &u);
    fp2_mul_xi(&t, &t);
    fp2_mul(&d, &a->c0, &c0);
    fp2_add(&d, &d, &t);
    fp2_inv(&d, &d);

    fp2_mul(&o->c0, &c0, &d);
    fp2_mul(&o->c1, &c1, &d);
    fp2_mul(&o->c2, &c2, &d);
}

static fp12 FP12_ONE;

static void fp12_mul(fp12 *o, const fp12 *a, const fp12 *b) {
    fp6 aa, bb, t0, t1;
    fp12 r;
    fp6_mul(&aa, &a->c0, &b->c0);
    fp6_mul(&bb, &a->c1, &b->c1);
    fp6_add(&t0, &a->c0, &a->c1);
    fp6_add(&t1, &b->c0, &b->c1);
    fp6_mul(&t0, &t0, &t1);
    fp6_sub(&t0, &t0, &aa);
    fp6_sub(&t0, &t0, &bb);   /* a0 b1 + a1 b0 */
    fp6_mul_v(&t1, &bb);
    fp6_add(&r.c0, &aa, &t1);
    r.c1 = t0;
    *o = r;
}

static void fp12_sqr(fp12 *o, const fp12 *a) {
    /* (a0 + a1 w)^2 = a0^2 + v a1^2 + 2 a0 a1 w */
    fp6 t0, t1, t2;
    fp6_mul(&t2, &a->c0, &a->c1);
    fp6_add(&t0, &a->c0, &a->c1);
    fp6_mul_v(&t1, &a->c1);
    fp6_add(&t1, &t1, &a->c0);
    fp6_mul(&t0, &t0, &t1);       /* (a0+a1)(a0+v a1) = a0^2 + v a1^2 + (1+v) a0a1 */
    fp6_sub(&t0, &t0, &t2);
    fp6_mul_v(&t1, &t2);
    fp6_sub(&o->c0, &t0, &t1);
    fp6_add(&o->c1, &t2, &t2);
}

static void fp12_conj(fp12 *o, const fp12 *a) { o->c0 = a->c0; fp6_neg(&o->c1, &a->c1); }

static void fp12_inv(fp12 *o, const fp12 *a) {
    fp6 t0, t1;
    fp6_sqr(&t0, &a->c0);
    fp6_sqr(&t1, &a->c1);
    fp6_mul_v(&t1, &t1);
    fp6_sub(&t0, &t0, &t1);
    fp6_inv(&t0, &t0);
    fp6_mul(&o->c0, &a->c0, &t0);
    fp6_mul(&t1, &a->c1, &t0);
    fp6_neg(&o->c1, &t1);
}

static int fp12_is_one(const fp12 *a) {
    fp6 d;
    if (!fp6_is_zero(&a->c1)) return 0;
    fp6 one = {{{0}}};
    one.c0 = FP2_ONE;
    fp6_sub(&d, &a->c0, &one);
    return fp6_is_zero(&d);
}

/* Frobenius: gamma1[k] = xi^(k (p-1)/6), k = 1..5, set up at init */
static fp2 G1F[6];

static void fp12_frobenius(fp12 *o, const fp12 *a) {
    /* w-basis coefficient at w^k is (k even: c0.a_{k/2}) / (k odd:
     * c1.a_{(k-1)/2}); frob conjugates it and scales by gamma1[k]. */
    fp2 x[6], y[6];
    x[0] = a->c0.c0; x[2] = a->c0.c1; x[4] = a->c0.c2;
    x[1] = a->c1.c0; x[3] = a->c1.c1; x[5] = a->c1.c2;
    for (int k = 0; k < 6; k++) {
        fp2_conj(&y[k], &x[k]);
        if (k) fp2_mul(&y[k], &y[k], &G1F[k]);
    }
    o->c0.c0 = y[0]; o->c0.c1 = y[2]; o->c0.c2 = y[4];
    o->c1.c0 = y[1]; o->c1.c1 = y[3]; o->c1.c2 = y[5];
}

static void fp12_frobenius2(fp12 *o, const fp12 *a) {
    fp12 t;
    fp12_frobenius(&t, a);
    fp12_frobenius(o, &t);
}

/* f^e for 64-bit e (square-and-multiply, MSB first), e >= 1 */
static void fp12_pow_u64(fp12 *o, const fp12 *a, uint64_t e) {
    fp12 acc = *a;
    int top = 63;
    while (top > 0 && !((e >> top) & 1)) top--;
    for (int b = top - 1; b >= 0; b--) {
        fp12_sqr(&acc, &acc);
        if ((e >> b) & 1) fp12_mul(&acc, &acc, a);
    }
    *o = acc;
}

/* conj(a^e) — a^(−e) for unitary a */
static void fp12_conj_pow_u64(fp12 *o, const fp12 *a, uint64_t e) {
    fp12 t;
    fp12_pow_u64(&t, a, e);
    fp12_conj(o, &t);
}

/* ===================================================================== */
/* G1 (E: y^2 = x^3 + 4) — Jacobian                                       */
/* ===================================================================== */

typedef struct { fp x, y, z; int inf; } g1p;
typedef struct { fp x, y; int inf; } g1a;

static g1a G1_GEN;
static fp G1_B_M;

static void g1_set_inf(g1p *p) { p->inf = 1; p->x = FP_ONE; p->y = FP_ONE; p->z = FP_ZERO; }

static void g1_from_affine(g1p *o, const g1a *a) {
    if (a->inf) { g1_set_inf(o); return; }
    o->x = a->x; o->y = a->y; o->z = FP_ONE; o->inf = 0;
}

static void g1_dbl(g1p *o, const g1p *p) {
    if (p->inf || fp_is_zero(&p->y)) { g1_set_inf(o); return; }
    fp a, b, c, d, e, f, t;
    fp_sqr(&a, &p->x);
    fp_sqr(&b, &p->y);
    fp_sqr(&c, &b);
    fp_add(&t, &p->x, &b);
    fp_sqr(&d, &t);
    fp_sub(&d, &d, &a);
    fp_sub(&d, &d, &c);
    fp_dbl(&d, &d);          /* 4 X Y^2 */
    fp_dbl(&e, &a);
    fp_add(&e, &e, &a);      /* 3 X^2 */
    fp_sqr(&f, &e);
    fp_sub(&o->x, &f, &d);
    fp_sub(&o->x, &o->x, &d);
    fp_sub(&t, &d, &o->x);
    fp_mul(&t, &e, &t);
    fp dc8; fp_dbl(&dc8, &c); fp_dbl(&dc8, &dc8); fp_dbl(&dc8, &dc8);
    fp zz;
    fp_mul(&zz, &p->y, &p->z);
    fp_sub(&o->y, &t, &dc8);
    fp_dbl(&o->z, &zz);
    o->inf = 0;
}

static void g1_add(g1p *o, const g1p *p, const g1p *q) {
    if (p->inf) { *o = *q; return; }
    if (q->inf) { *o = *p; return; }
    fp z1z1, z2z2, u1, u2, s1, s2, h, i, j, r, v, t;
    fp_sqr(&z1z1, &p->z);
    fp_sqr(&z2z2, &q->z);
    fp_mul(&u1, &p->x, &z2z2);
    fp_mul(&u2, &q->x, &z1z1);
    fp_mul(&s1, &p->y, &q->z); fp_mul(&s1, &s1, &z2z2);
    fp_mul(&s2, &q->y, &p->z); fp_mul(&s2, &s2, &z1z1);
    if (fp_eq(&u1, &u2)) {
        if (fp_eq(&s1, &s2)) { g1_dbl(o, p); return; }
        g1_set_inf(o);
        return;
    }
    fp_sub(&h, &u2, &u1);
    fp_dbl(&i, &h); fp_sqr(&i, &i);
    fp_mul(&j, &h, &i);
    fp_sub(&r, &s2, &s1); fp_dbl(&r, &r);
    fp_mul(&v, &u1, &i);
    fp_sqr(&o->x, &r);
    fp_sub(&o->x, &o->x, &j);
    fp_sub(&o->x, &o->x, &v);
    fp_sub(&o->x, &o->x, &v);
    fp_sub(&t, &v, &o->x);
    fp_mul(&t, &r, &t);
    fp s1j; fp_mul(&s1j, &s1, &j); fp_dbl(&s1j, &s1j);
    fp_sub(&o->y, &t, &s1j);
    fp_add(&o->z, &p->z, &q->z);
    fp_sqr(&o->z, &o->z);
    fp_sub(&o->z, &o->z, &z1z1);
    fp_sub(&o->z, &o->z, &z2z2);
    fp_mul(&o->z, &o->z, &h);
    o->inf = 0;
}

static void g1_neg(g1p *o, const g1p *p) { *o = *p; fp_neg(&o->y, &p->y); }

/* scalar mul, scalar little-endian limbs */
static void g1_mul(g1p *o, const g1p *p, const uint64_t *e, int n_limbs) {
    g1p acc; g1_set_inf(&acc);
    int started = 0;
    for (int i = n_limbs - 1; i >= 0; i--)
        for (int b = 63; b >= 0; b--) {
            if (started) g1_dbl(&acc, &acc);
            if ((e[i] >> b) & 1) {
                if (!started) { acc = *p; started = 1; }
                else g1_add(&acc, &acc, p);
            }
        }
    *o = acc;
}

static void g1_to_affine(g1a *o, const g1p *p) {
    if (p->inf || fp_is_zero(&p->z)) { o->inf = 1; o->x = FP_ZERO; o->y = FP_ZERO; return; }
    fp zi, zi2, zi3;
    fp_inv(&zi, &p->z);
    fp_sqr(&zi2, &zi);
    fp_mul(&zi3, &zi2, &zi);
    fp_mul(&o->x, &p->x, &zi2);
    fp_mul(&o->y, &p->y, &zi3);
    o->inf = 0;
}

static int g1_on_curve(const g1a *a) {
    if (a->inf) return 1;
    fp l, r;
    fp_sqr(&l, &a->y);
    fp_sqr(&r, &a->x);
    fp_mul(&r, &r, &a->x);
    fp_add(&r, &r, &G1_B_M);
    return fp_eq(&l, &r);
}

static int g1_in_subgroup(const g1a *a) {
    if (a->inf) return 1;
    g1p p, t;
    g1_from_affine(&p, a);
    g1_mul(&t, &p, BLS_ORDER, 4);
    return t.inf || fp_is_zero(&t.z);
}

/* 48-byte compressed G1 -> affine; returns 0 on malformed/off-curve */
static int g1_decompress(g1a *o, const uint8_t in[48]) {
    uint8_t flags = in[0];
    if (!(flags & 0x80)) return 0;            /* must be compressed */
    int infinity = (flags >> 6) & 1;
    int sign = (flags >> 5) & 1;
    uint8_t buf[48];
    memcpy(buf, in, 48);
    buf[0] &= 0x1f;
    if (infinity) {
        for (int i = 0; i < 48; i++) if (buf[i]) return 0;
        if (sign) return 0;
        o->inf = 1; o->x = FP_ZERO; o->y = FP_ZERO;
        return 1;
    }
    fp x, gx, y, ny;
    if (!fp_from_bytes(&x, buf)) return 0;
    fp_sqr(&gx, &x);
    fp_mul(&gx, &gx, &x);
    fp_add(&gx, &gx, &G1_B_M);
    if (!fp_sqrt(&y, &gx)) return 0;
    fp_neg(&ny, &y);
    int y_larger = fp_cmp(&y, &ny) > 0;
    if (y_larger != sign) y = ny;
    o->x = x; o->y = y; o->inf = 0;
    return 1;
}

/* ===================================================================== */
/* G2 (E': y^2 = x^3 + 4(1+u)) — Jacobian                                 */
/* ===================================================================== */

typedef struct { fp2 x, y, z; int inf; } g2p;
typedef struct { fp2 x, y; int inf; } g2a;

static g2a G2_GEN_A;
static fp2 G2_B_M;
static fp2 PSI_CX_M, PSI_CY_M;

static void g2_set_inf(g2p *p) { p->inf = 1; p->x = FP2_ONE; p->y = FP2_ONE; p->z = FP2_ZERO; }

static void g2_from_affine(g2p *o, const g2a *a) {
    if (a->inf) { g2_set_inf(o); return; }
    o->x = a->x; o->y = a->y; o->z = FP2_ONE; o->inf = 0;
}

static void g2_dbl(g2p *o, const g2p *p) {
    if (p->inf || fp2_is_zero(&p->y)) { g2_set_inf(o); return; }
    fp2 a, b, c, d, e, f, t, zz, dc8;
    fp2_sqr(&a, &p->x);
    fp2_sqr(&b, &p->y);
    fp2_sqr(&c, &b);
    fp2_add(&t, &p->x, &b);
    fp2_sqr(&d, &t);
    fp2_sub(&d, &d, &a);
    fp2_sub(&d, &d, &c);
    fp2_dbl(&d, &d);
    fp2_dbl(&e, &a);
    fp2_add(&e, &e, &a);
    fp2_sqr(&f, &e);
    fp2_sub(&o->x, &f, &d);
    fp2_sub(&o->x, &o->x, &d);
    fp2_sub(&t, &d, &o->x);
    fp2_mul(&t, &e, &t);
    fp2_dbl(&dc8, &c); fp2_dbl(&dc8, &dc8); fp2_dbl(&dc8, &dc8);
    fp2_mul(&zz, &p->y, &p->z);
    fp2_sub(&o->y, &t, &dc8);
    fp2_dbl(&o->z, &zz);
    o->inf = 0;
}

static void g2_add(g2p *o, const g2p *p, const g2p *q) {
    if (p->inf) { *o = *q; return; }
    if (q->inf) { *o = *p; return; }
    fp2 z1z1, z2z2, u1, u2, s1, s2, h, i, j, r, v, t, s1j;
    fp2_sqr(&z1z1, &p->z);
    fp2_sqr(&z2z2, &q->z);
    fp2_mul(&u1, &p->x, &z2z2);
    fp2_mul(&u2, &q->x, &z1z1);
    fp2_mul(&s1, &p->y, &q->z); fp2_mul(&s1, &s1, &z2z2);
    fp2_mul(&s2, &q->y, &p->z); fp2_mul(&s2, &s2, &z1z1);
    if (fp2_eq(&u1, &u2)) {
        if (fp2_eq(&s1, &s2)) { g2_dbl(o, p); return; }
        g2_set_inf(o);
        return;
    }
    fp2_sub(&h, &u2, &u1);
    fp2_dbl(&i, &h); fp2_sqr(&i, &i);
    fp2_mul(&j, &h, &i);
    fp2_sub(&r, &s2, &s1); fp2_dbl(&r, &r);
    fp2_mul(&v, &u1, &i);
    fp2_sqr(&o->x, &r);
    fp2_sub(&o->x, &o->x, &j);
    fp2_sub(&o->x, &o->x, &v);
    fp2_sub(&o->x, &o->x, &v);
    fp2_sub(&t, &v, &o->x);
    fp2_mul(&t, &r, &t);
    fp2_mul(&s1j, &s1, &j); fp2_dbl(&s1j, &s1j);
    fp2_sub(&o->y, &t, &s1j);
    fp2_add(&o->z, &p->z, &q->z);
    fp2_sqr(&o->z, &o->z);
    fp2_sub(&o->z, &o->z, &z1z1);
    fp2_sub(&o->z, &o->z, &z2z2);
    fp2_mul(&o->z, &o->z, &h);
    o->inf = 0;
}

static void g2_neg(g2p *o, const g2p *p) { *o = *p; fp2_neg(&o->y, &p->y); }

static void g2_mul_u64(g2p *o, const g2p *p, uint64_t e) {
    g2p acc; g2_set_inf(&acc);
    int started = 0;
    for (int b = 63; b >= 0; b--) {
        if (started) g2_dbl(&acc, &acc);
        if ((e >> b) & 1) {
            if (!started) { acc = *p; started = 1; }
            else g2_add(&acc, &acc, p);
        }
    }
    if (!started) g2_set_inf(o); else *o = acc;
}

static void g2_to_affine(g2a *o, const g2p *p) {
    if (p->inf || fp2_is_zero(&p->z)) { o->inf = 1; o->x = FP2_ZERO; o->y = FP2_ZERO; return; }
    fp2 zi, zi2, zi3;
    fp2_inv(&zi, &p->z);
    fp2_sqr(&zi2, &zi);
    fp2_mul(&zi3, &zi2, &zi);
    fp2_mul(&o->x, &p->x, &zi2);
    fp2_mul(&o->y, &p->y, &zi3);
    o->inf = 0;
}

static int g2_jac_eq(const g2p *p, const g2p *q) {
    int pi = p->inf || fp2_is_zero(&p->z);
    int qi = q->inf || fp2_is_zero(&q->z);
    if (pi || qi) return pi == qi;
    fp2 z1z1, z2z2, a, b, z13, z23;
    fp2_sqr(&z1z1, &p->z);
    fp2_sqr(&z2z2, &q->z);
    fp2_mul(&a, &p->x, &z2z2);
    fp2_mul(&b, &q->x, &z1z1);
    if (!fp2_eq(&a, &b)) return 0;
    fp2_mul(&z13, &z1z1, &p->z);
    fp2_mul(&z23, &z2z2, &q->z);
    fp2_mul(&a, &p->y, &z23);
    fp2_mul(&b, &q->y, &z13);
    return fp2_eq(&a, &b);
}

/* psi (untwist-Frobenius-twist), Jacobian */
static void g2_psi(g2p *o, const g2p *p) {
    fp2_conj(&o->x, &p->x); fp2_mul(&o->x, &o->x, &PSI_CX_M);
    fp2_conj(&o->y, &p->y); fp2_mul(&o->y, &o->y, &PSI_CY_M);
    fp2_conj(&o->z, &p->z);
    o->inf = p->inf;
}

static int g2_on_curve(const g2a *a) {
    if (a->inf) return 1;
    fp2 l, r;
    fp2_sqr(&l, &a->y);
    fp2_sqr(&r, &a->x);
    fp2_mul(&r, &r, &a->x);
    fp2_add(&r, &r, &G2_B_M);
    return fp2_eq(&l, &r);
}

/* Scott's test: Q in G2 iff psi(Q) == [x]Q (x negative: negate) */
static int g2_in_subgroup(const g2a *a) {
    if (a->inf) return 1;
    g2p p, xq, ps;
    g2_from_affine(&p, a);
    g2_mul_u64(&xq, &p, BLS_X_ABS);
    g2_neg(&xq, &xq);
    g2_psi(&ps, &p);
    return g2_jac_eq(&ps, &xq);
}

/* 96-byte compressed G2 -> affine (x.c1 || x.c0 big-endian) */
static int g2_decompress(g2a *o, const uint8_t in[96]) {
    uint8_t flags = in[0];
    if (!(flags & 0x80)) return 0;
    int infinity = (flags >> 6) & 1;
    int sign = (flags >> 5) & 1;
    uint8_t buf[96];
    memcpy(buf, in, 96);
    buf[0] &= 0x1f;
    if (infinity) {
        for (int i = 0; i < 96; i++) if (buf[i]) return 0;
        if (sign) return 0;
        o->inf = 1; o->x = FP2_ZERO; o->y = FP2_ZERO;
        return 1;
    }
    fp2 x, gx, y, ny;
    if (!fp_from_bytes(&x.c1, buf)) return 0;
    if (!fp_from_bytes(&x.c0, buf + 48)) return 0;
    fp2_sqr(&gx, &x);
    fp2_mul(&gx, &gx, &x);
    fp2_add(&gx, &gx, &G2_B_M);
    if (!fp2_sqrt(&y, &gx)) return 0;
    fp2_neg(&ny, &y);
    int y_larger = fp2_lex_gt(&y, &ny);
    if (y_larger != sign) y = ny;
    o->x = x; o->y = y; o->inf = 0;
    return 1;
}

/* ===================================================================== */
/* Pairing: aggregated Miller loop + final exponentiation                 */
/* ===================================================================== */

/* Sparse line element (s0, sv, sv2) occupies Fp12 slots (c0.c0, c1.c1,
 * c1.c2) in the 2-3-2 tower — same derivation as device/pairing.py. */
static void fp12_mul_line(fp12 *f, const fp2 *s0, const fp2 *sv, const fp2 *sv2) {
    fp6 a = f->c0, b = f->c1;
    fp6 al0, bl0, al1, bl1;
    fp2 t, u;

    fp6_mul_fp2(&al0, &a, s0);
    fp6_mul_fp2(&bl0, &b, s0);

    /* b * (sv w^3 + sv2 w^5): in fp6-slot terms the product with
     * (0, sv, sv2) in the v-basis of the OTHER fp6 half:
     * bl1 = b * (sv v + sv2 v^2) where the result lands back shifted. */
    /* bl1_0 = xi*(b1 sv2 + b2 sv); bl1_1 = b0 sv + xi b2 sv2;
       bl1_2 = b0 sv2 + b1 sv */
    fp2_mul(&t, &b.c1, sv2);
    fp2_mul(&u, &b.c2, sv);
    fp2_add(&t, &t, &u);
    fp2_mul_xi(&bl1.c0, &t);
    fp2_mul(&t, &b.c0, sv);
    fp2_mul(&u, &b.c2, sv2);
    fp2_mul_xi(&u, &u);
    fp2_add(&bl1.c1, &t, &u);
    fp2_mul(&t, &b.c0, sv2);
    fp2_mul(&u, &b.c1, sv);
    fp2_add(&bl1.c2, &t, &u);

    fp2_mul(&t, &a.c1, sv2);
    fp2_mul(&u, &a.c2, sv);
    fp2_add(&t, &t, &u);
    fp2_mul_xi(&al1.c0, &t);
    fp2_mul(&t, &a.c0, sv);
    fp2_mul(&u, &a.c2, sv2);
    fp2_mul_xi(&u, &u);
    fp2_add(&al1.c1, &t, &u);
    fp2_mul(&t, &a.c0, sv2);
    fp2_mul(&u, &a.c1, sv);
    fp2_add(&al1.c2, &t, &u);

    /* f = (a + b w)(L0 + L1 w) = (a L0 + v b L1) + (a L1 + b L0) w */
    fp6 vb;
    fp6_mul_v(&vb, &bl1);
    fp6_add(&f->c0, &al0, &vb);
    fp6_add(&f->c1, &al1, &bl0);
}

/* dbl step: T <- 2T, line coefficients at P = (xP, yP) */
static void miller_dbl(g2p *T, fp2 *s0, fp2 *sv, fp2 *sv2, const fp *xP, const fp *yP) {
    fp2 A, B, C, D, E, F, X3, Y3, Z3, Z2, t, z3z2;
    fp2_sqr(&A, &T->x);
    fp2_sqr(&B, &T->y);
    fp2_sqr(&C, &B);
    fp2_add(&t, &T->x, &B);
    fp2_sqr(&D, &t);
    fp2_sub(&D, &D, &A);
    fp2_sub(&D, &D, &C);
    fp2_dbl(&D, &D);
    fp2_dbl(&E, &A); fp2_add(&E, &E, &A);
    fp2_sqr(&F, &E);
    fp2_sub(&X3, &F, &D); fp2_sub(&X3, &X3, &D);
    fp2_sub(&t, &D, &X3);
    fp2_mul(&Y3, &E, &t);
    fp2 c8; fp2_dbl(&c8, &C); fp2_dbl(&c8, &c8); fp2_dbl(&c8, &c8);
    fp2_sub(&Y3, &Y3, &c8);
    fp2_add(&t, &T->y, &T->y);
    fp2_mul(&Z3, &t, &T->z);

    fp2_sqr(&Z2, &T->z);
    fp2_mul(&z3z2, &Z3, &Z2);
    fp2_mul_fp(&t, &z3z2, yP);
    fp2_neg(&t, &t);
    fp2_mul_xi(s0, &t);                  /* s0 = -2YZ^3 yP xi */
    fp2_mul(&t, &E, &T->x);
    fp2_add(sv, &B, &B);
    fp2_sub(sv, sv, &t);                 /* sv = 2Y^2 - 3X^3 */
    fp2_mul(&t, &E, &Z2);
    fp2_mul_fp(sv2, &t, xP);             /* sv2 = 3X^2 Z^2 xP */

    T->x = X3; T->y = Y3; T->z = Z3;
}

/* add step: T <- T + Q (Q affine), line coefficients at P */
static void miller_add(g2p *T, fp2 *s0, fp2 *sv, fp2 *sv2,
                       const g2a *Q, const fp *xP, const fp *yP) {
    fp2 Z2, U2, S2, H, R, HH, HHH, V, X3, Y3, Z3, t, u;
    fp2_sqr(&Z2, &T->z);
    fp2_mul(&U2, &Q->x, &Z2);
    fp2_mul(&t, &T->z, &Z2);
    fp2_mul(&S2, &Q->y, &t);
    fp2_sub(&H, &U2, &T->x);
    fp2_sub(&R, &S2, &T->y);
    fp2_sqr(&HH, &H);
    fp2_mul(&HHH, &H, &HH);
    fp2_mul(&V, &T->x, &HH);
    fp2_sqr(&X3, &R);
    fp2_sub(&X3, &X3, &HHH);
    fp2_sub(&X3, &X3, &V);
    fp2_sub(&X3, &X3, &V);
    fp2_sub(&t, &V, &X3);
    fp2_mul(&Y3, &R, &t);
    fp2_mul(&t, &T->y, &HHH);
    fp2_sub(&Y3, &Y3, &t);
    fp2_mul(&Z3, &T->z, &H);

    fp2_mul_fp(&t, &Z3, yP);
    fp2_neg(&t, &t);
    fp2_mul_xi(s0, &t);                  /* s0 = -HZ yP xi */
    fp2_mul(&t, &Z3, &Q->y);
    fp2_mul(&u, &R, &Q->x);
    fp2_sub(sv, &t, &u);                 /* sv = HZ y2 - R x2 */
    fp2_mul_fp(sv2, &R, xP);             /* sv2 = R xP */

    T->x = X3; T->y = Y3; T->z = Z3;
}

/* Aggregated Miller loop over n pairs; skips pairs with either side at
 * infinity. Result conjugated for the negative parameter. */
static void miller_loop_n(fp12 *f, const g1a *ps, const g2a *qs, int n, g2p *Ts /* scratch n */) {
    *f = FP12_ONE;
    int live = 0;
    for (int i = 0; i < n; i++) {
        if (!ps[i].inf && !qs[i].inf) { g2_from_affine(&Ts[i], &qs[i]); live = 1; }
        else Ts[i].inf = 1;
    }
    if (!live) return;
    int top = 63;
    while (top > 0 && !((BLS_X_ABS >> top) & 1)) top--;
    for (int b = top - 1; b >= 0; b--) {
        fp12_sqr(f, f);
        int bit = (BLS_X_ABS >> b) & 1;
        for (int i = 0; i < n; i++) {
            if (Ts[i].inf) continue;
            fp2 s0, sv, sv2;
            miller_dbl(&Ts[i], &s0, &sv, &sv2, &ps[i].x, &ps[i].y);
            fp12_mul_line(f, &s0, &sv, &sv2);
            if (bit) {
                miller_add(&Ts[i], &s0, &sv, &sv2, &qs[i], &ps[i].x, &ps[i].y);
                fp12_mul_line(f, &s0, &sv, &sv2);
            }
        }
    }
    fp12_conj(f, f); /* negative x */
}

/* final exponentiation, exact (easy part + machine-checked x-chain) */
static void final_exp(fp12 *o, const fp12 *f) {
    fp12 t, inv, a, b, c, u;
    /* easy: f^((p^6-1)(p^2+1)) */
    fp12_conj(&t, f);
    fp12_inv(&inv, f);
    fp12_mul(&t, &t, &inv);
    fp12_frobenius2(&u, &t);
    fp12_mul(&t, &u, &t);
    /* hard: d = (x-1)^2 (x+p)(x^2+p^2-1)/3 + 1  via the chain
     * a = t^((x-1)^2/3); b = a^(x+p); c = b^(x^2+p^2-1); o = c*t.
     * Negative exponents on unitary values via conjugate. */
    fp12_conj_pow_u64(&a, &t, BLS_LAM);            /* t^((x-1)/3), (x-1)<0 */
    fp12_conj_pow_u64(&a, &a, BLS_X_MINUS_1_ABS);  /* ^(x-1) */
    fp12_conj_pow_u64(&b, &a, BLS_X_ABS);          /* a^x */
    fp12_frobenius(&u, &a);
    fp12_mul(&b, &b, &u);                          /* * a^p */
    fp12_conj_pow_u64(&c, &b, BLS_X_ABS);
    fp12_conj_pow_u64(&c, &c, BLS_X_ABS);          /* b^(x^2) */
    fp12_frobenius2(&u, &b);
    fp12_mul(&c, &c, &u);                          /* * b^(p^2) */
    fp12_conj(&u, &b);
    fp12_mul(&c, &c, &u);                          /* * b^-1 */
    fp12_mul(o, &c, &t);                           /* * t */
}

/* ===================================================================== */
/* SHA-256 (compact scalar; hashing is not this library's hot loop)       */
/* ===================================================================== */

static const uint32_t SK[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,0x923f82a4,0xab1c5ed5,
    0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,
    0xe49b69c1,0xefbe4786,0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,0x06ca6351,0x14292967,
    0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,
    0xa2bfe8a1,0xa81a664b,0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,0x5b9cca4f,0x682e6ff3,
    0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2,
};

typedef struct { uint32_t h[8]; uint8_t buf[64]; uint64_t len; size_t fill; } sha256_ctx;

static inline uint32_t ror32(uint32_t x, int r) { return (x >> r) | (x << (32 - r)); }

static void sha256_block(uint32_t h[8], const uint8_t p[64]) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)p[4*i] << 24) | ((uint32_t)p[4*i+1] << 16) | ((uint32_t)p[4*i+2] << 8) | p[4*i+3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ror32(w[i-15], 7) ^ ror32(w[i-15], 18) ^ (w[i-15] >> 3);
        uint32_t s1 = ror32(w[i-2], 17) ^ ror32(w[i-2], 19) ^ (w[i-2] >> 10);
        w[i] = w[i-16] + s0 + w[i-7] + s1;
    }
    uint32_t a=h[0],b=h[1],c=h[2],d=h[3],e=h[4],f=h[5],g=h[6],hh=h[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ror32(e,6) ^ ror32(e,11) ^ ror32(e,25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = hh + S1 + ch + SK[i] + w[i];
        uint32_t S0 = ror32(a,2) ^ ror32(a,13) ^ ror32(a,22);
        uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + mj;
        hh=g; g=f; f=e; e=d+t1; d=c; c=b; b=a; a=t1+t2;
    }
    h[0]+=a; h[1]+=b; h[2]+=c; h[3]+=d; h[4]+=e; h[5]+=f; h[6]+=g; h[7]+=hh;
}

static void sha256_init(sha256_ctx *c) {
    static const uint32_t IV[8] = {0x6a09e667,0xbb67ae85,0x3c6ef372,0xa54ff53a,0x510e527f,0x9b05688c,0x1f83d9ab,0x5be0cd19};
    memcpy(c->h, IV, sizeof IV);
    c->len = 0; c->fill = 0;
}

static void sha256_update(sha256_ctx *c, const uint8_t *p, size_t n) {
    c->len += n;
    while (n) {
        size_t k = 64 - c->fill;
        if (k > n) k = n;
        memcpy(c->buf + c->fill, p, k);
        c->fill += k; p += k; n -= k;
        if (c->fill == 64) { sha256_block(c->h, c->buf); c->fill = 0; }
    }
}

static void sha256_final(sha256_ctx *c, uint8_t out[32]) {
    uint64_t bits = c->len * 8;
    uint8_t pad = 0x80;
    sha256_update(c, &pad, 1);
    uint8_t z = 0;
    while (c->fill != 56) sha256_update(c, &z, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (56 - 8 * i));
    sha256_update(c, lb, 8);
    for (int i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(c->h[i] >> 24);
        out[4*i+1] = (uint8_t)(c->h[i] >> 16);
        out[4*i+2] = (uint8_t)(c->h[i] >> 8);
        out[4*i+3] = (uint8_t)(c->h[i]);
    }
}

/* ===================================================================== */
/* Hash-to-curve G2 (RFC 9380: expand_message_xmd + SSWU + iso3 + h_eff)  */
/* ===================================================================== */

static fp2 ISO_A, ISO_B, ISO_Z;
static fp2 XNUM[4], XDEN[3], YNUM[4], YDEN[4];
static int N_XNUM = 4, N_XDEN = 3, N_YNUM = 4, N_YDEN = 4;

/* fold acc (7 limbs) below 2^384: acc = lo + hi * (2^384 mod p), looped —
 * a single fold leaves residue ~hi/8 which the caller's next *256 shift
 * would outgrow. The limbs of FP_ONE (Montgomery 1) ARE 2^384 mod p. */
static void fold384(uint64_t acc[7]) {
    while (acc[6]) {
        uint64_t hi = acc[6];
        acc[6] = 0;
        u128 c = 0;
        for (int j = 0; j < 6; j++) {
            c += (u128)FP_ONE.l[j] * hi + acc[j];
            acc[j] = (uint64_t)c;
            c >>= 64;
        }
        acc[6] = (uint64_t)c;
    }
}

/* 64 big-endian bytes -> fp (mod p), byte-Horner with fold reduction */
static void fp_from_be64_mod(fp *o, const uint8_t in[64]) {
    /* value = sum b_i 256^i; process high->low: acc = acc*256 + b */
    uint64_t acc[7] = {0};
    for (int i = 0; i < 64; i++) {
        uint64_t carry = 0;
        for (int j = 0; j < 7; j++) {
            uint64_t nv = (acc[j] << 8) | carry;
            carry = acc[j] >> 56;
            acc[j] = nv;
        }
        acc[0] |= in[i];
        fold384(acc);
    }
    fp t;
    for (int i = 0; i < 6; i++) t.l[i] = acc[i];
    while (fp_ge_p(&t)) fp_sub_p(&t);
    fp_mul(o, &t, &FP_R2);
}

static void expand_xmd(uint8_t *out, size_t len_out,
                       const uint8_t *msg, size_t msg_len,
                       const uint8_t *dst, size_t dst_len) {
    uint8_t b0[32], bi[32];
    uint8_t zpad[64] = {0};
    uint8_t lib[2] = {(uint8_t)(len_out >> 8), (uint8_t)len_out};
    uint8_t dstp_tail = (uint8_t)dst_len;
    sha256_ctx c;
    sha256_init(&c);
    sha256_update(&c, zpad, 64);
    sha256_update(&c, msg, msg_len);
    sha256_update(&c, lib, 2);
    uint8_t zero = 0;
    sha256_update(&c, &zero, 1);
    sha256_update(&c, dst, dst_len);
    sha256_update(&c, &dstp_tail, 1);
    sha256_final(&c, b0);

    uint8_t ctr = 1;
    sha256_init(&c);
    sha256_update(&c, b0, 32);
    sha256_update(&c, &ctr, 1);
    sha256_update(&c, dst, dst_len);
    sha256_update(&c, &dstp_tail, 1);
    sha256_final(&c, bi);

    size_t off = 0;
    for (;;) {
        size_t k = len_out - off < 32 ? len_out - off : 32;
        memcpy(out + off, bi, k);
        off += k;
        if (off >= len_out) break;
        uint8_t x[32];
        for (int i = 0; i < 32; i++) x[i] = b0[i] ^ bi[i];
        ctr++;
        sha256_init(&c);
        sha256_update(&c, x, 32);
        sha256_update(&c, &ctr, 1);
        sha256_update(&c, dst, dst_len);
        sha256_update(&c, &dstp_tail, 1);
        sha256_final(&c, bi);
    }
}

static void sswu(fp2 *xo, fp2 *yo, const fp2 *u) {
    fp2 zu2, tv1, x1, gx1, x2, gx2, y, t, u2;
    fp2_sqr(&u2, u);
    fp2_mul(&zu2, &ISO_Z, &u2);
    fp2_sqr(&tv1, &zu2);
    fp2_add(&tv1, &tv1, &zu2);
    if (fp2_is_zero(&tv1)) {
        fp2_mul(&t, &ISO_Z, &ISO_A);
        fp2_inv(&t, &t);
        fp2_mul(&x1, &ISO_B, &t);
    } else {
        fp2_inv(&t, &ISO_A);
        fp2_neg(&t, &t);
        fp2_mul(&t, &t, &ISO_B);
        fp2 inv1;
        fp2_inv(&inv1, &tv1);
        fp2_add(&inv1, &inv1, &FP2_ONE);
        fp2_mul(&x1, &t, &inv1);
    }
    fp2_sqr(&gx1, &x1);
    fp2_add(&gx1, &gx1, &ISO_A);
    fp2_mul(&gx1, &gx1, &x1);
    fp2_add(&gx1, &gx1, &ISO_B);
    if (fp2_sqrt(&y, &gx1)) {
        *xo = x1;
    } else {
        fp2_mul(&x2, &zu2, &x1);
        fp2_sqr(&gx2, &x2);
        fp2_add(&gx2, &gx2, &ISO_A);
        fp2_mul(&gx2, &gx2, &x2);
        fp2_add(&gx2, &gx2, &ISO_B);
        fp2_sqrt(&y, &gx2); /* must succeed */
        *xo = x2;
    }
    if (fp2_sgn0(u) != fp2_sgn0(&y)) fp2_neg(&y, &y);
    *yo = y;
}

static void horner(fp2 *o, const fp2 *coef, int n, const fp2 *x) {
    fp2 acc = FP2_ZERO;
    for (int i = n - 1; i >= 0; i--) {
        fp2_mul(&acc, &acc, x);
        fp2_add(&acc, &acc, &coef[i]);
    }
    *o = acc;
}

static void iso3(g2a *o, const fp2 *x, const fp2 *y) {
    fp2 xn, xd, yn, yd, t;
    horner(&xn, XNUM, N_XNUM, x);
    horner(&xd, XDEN, N_XDEN, x);
    horner(&yn, YNUM, N_YNUM, x);
    horner(&yd, YDEN, N_YDEN, x);
    fp2_inv(&t, &xd);
    fp2_mul(&o->x, &xn, &t);
    fp2_inv(&t, &yd);
    fp2_mul(&o->y, &yn, &t);
    fp2_mul(&o->y, &o->y, y);
    o->inf = 0;
}

/* [x]P for the NEGATIVE parameter x: -( [|x|] P ) */
static void g2_mul_param(g2p *o, const g2p *p) {
    g2_mul_u64(o, p, BLS_X_ABS);
    g2_neg(o, o);
}

static void clear_cofactor(g2p *o, const g2p *p) {
    /* [X^2-X-1]P + [X-1]psi(P) + psi^2([2]P)  (Budroni-Pintore) */
    g2p xp, x2p, part1, part2, part3, t, np;
    g2_mul_param(&xp, p);
    g2_mul_param(&x2p, &xp);
    g2_neg(&np, &xp);
    g2_add(&part1, &x2p, &np);
    g2_neg(&np, (g2p *)p);
    g2_add(&part1, &part1, &np);       /* x2p - xp - p */
    g2_add(&t, &xp, &np);              /* xp - p */
    g2_psi(&part2, &t);
    g2_dbl(&t, p);
    g2_psi(&t, &t);
    g2_psi(&part3, &t);
    g2_add(o, &part1, &part2);
    g2_add(o, o, &part3);
}

static void hash_to_g2(g2a *o, const uint8_t *msg, size_t msg_len,
                       const uint8_t *dst, size_t dst_len) {
    uint8_t uni[256];
    expand_xmd(uni, 256, msg, msg_len, dst, dst_len);
    fp2 u0, u1, x, y;
    fp_from_be64_mod(&u0.c0, uni);
    fp_from_be64_mod(&u0.c1, uni + 64);
    fp_from_be64_mod(&u1.c0, uni + 128);
    fp_from_be64_mod(&u1.c1, uni + 192);
    g2a q0, q1;
    sswu(&x, &y, &u0);
    iso3(&q0, &x, &y);
    sswu(&x, &y, &u1);
    iso3(&q1, &x, &y);
    g2p j0, j1, s, c;
    g2_from_affine(&j0, &q0);
    g2_from_affine(&j1, &q1);
    g2_add(&s, &j0, &j1);
    clear_cofactor(&c, &s);
    g2_to_affine(o, &c);
}

/* ===================================================================== */
/* init + public API                                                      */
/* ===================================================================== */

static int INITED = 0;

static void ensure_init(void) {
    if (INITED) return;
    for (int i = 0; i < 6; i++) { FP_ZERO.l[i] = 0; FP_R2.l[i] = BLS_R2[i]; }
    /* FP_ONE = R mod p = mont(1): raw 1 -> mont via R2 needs mont mul with
     * the not-yet-set FP_ONE? No: mont mul is self-contained. */
    fp one_raw = {{1, 0, 0, 0, 0, 0}};
    fp_mul(&FP_ONE, &one_raw, &FP_R2);
    FP2_ZERO.c0 = FP_ZERO; FP2_ZERO.c1 = FP_ZERO;
    FP2_ONE.c0 = FP_ONE; FP2_ONE.c1 = FP_ZERO;
    memset(&FP12_ONE, 0, sizeof FP12_ONE);
    FP12_ONE.c0.c0 = FP2_ONE;

    fp_from_raw(&G1_GEN.x, G1_GEN_X.l);
    fp_from_raw(&G1_GEN.y, G1_GEN_Y.l);
    G1_GEN.inf = 0;
    fp_from_raw(&G1_B_M, G1_B.l);
    fp2_from_raw(&G2_GEN_A.x, &G2_GEN_X);
    fp2_from_raw(&G2_GEN_A.y, &G2_GEN_Y);
    G2_GEN_A.inf = 0;
    fp2_from_raw(&G2_B_M, &G2_B);
    fp2_from_raw(&PSI_CX_M, &PSI_CX_T);
    fp2_from_raw(&PSI_CY_M, &PSI_CY_T);

    fp2 g;
    fp2_from_raw(&g, &FROB12_C1);
    G1F[0] = FP2_ONE;
    for (int k = 1; k < 6; k++) fp2_mul(&G1F[k], &G1F[k - 1], &g);

    fp2_from_raw(&ISO_A, &ISO3_A_T);
    fp2_from_raw(&ISO_B, &ISO3_B_T);
    fp2_from_raw(&ISO_Z, &ISO3_Z_T);
    for (int i = 0; i < 4; i++) fp2_from_raw(&XNUM[i], &ISO3_XNUM[i]);
    for (int i = 0; i < 3; i++) fp2_from_raw(&XDEN[i], &ISO3_XDEN[i]);
    for (int i = 0; i < 4; i++) fp2_from_raw(&YNUM[i], &ISO3_YNUM[i]);
    for (int i = 0; i < 4; i++) fp2_from_raw(&YDEN[i], &ISO3_YDEN[i]);
    INITED = 1;
}

/* ---- exported surface (ctypes) -------------------------------------- */

/* Decompress + KeyValidate a G1 pubkey: writes x||y (96 raw BE bytes).
 * Returns 1 ok; 0 invalid (off-curve / wrong subgroup / infinity). */
int bls_g1_pubkey_check(const uint8_t in[48], uint8_t out_xy[96]) {
    ensure_init();
    g1a a;
    if (!g1_decompress(&a, in)) return 0;
    if (a.inf) return 0;
    if (!g1_on_curve(&a)) return 0;
    if (!g1_in_subgroup(&a)) return 0;
    fp_to_bytes(out_xy, &a.x);
    fp_to_bytes(out_xy + 48, &a.y);
    return 1;
}

/* hash a message to G2, writing x.c0||x.c1||y.c0||y.c1 (192 raw BE). */
int bls_hash_to_g2(const uint8_t *msg, uint32_t msg_len,
                   const uint8_t *dst, uint32_t dst_len,
                   uint8_t out[192]) {
    ensure_init();
    g2a h;
    hash_to_g2(&h, msg, msg_len, dst, dst_len);
    fp_to_bytes(out, &h.x.c0);
    fp_to_bytes(out + 48, &h.x.c1);
    fp_to_bytes(out + 96, &h.y.c0);
    fp_to_bytes(out + 144, &h.y.c1);
    return 1;
}

/* internal: read an uncompressed raw G1 affine point (x||y, 48+48 BE) */
static int g1_from_xy(g1a *o, const uint8_t in[96]) {
    if (!fp_from_bytes(&o->x, in)) return 0;
    if (!fp_from_bytes(&o->y, in + 48)) return 0;
    o->inf = fp_is_zero(&o->x) && fp_is_zero(&o->y);
    return 1;
}

/* Batch verification (the reference seam, blst.rs:36-119):
 *   sigs:      n_sets * 96 bytes, compressed G2
 *   pks:       sum(pk_counts) * 96 bytes, RAW affine x||y (pre-validated
 *              at admission by bls_g1_pubkey_check — mirrors the
 *              reference's decompress-once ValidatorPubkeyCache)
 *   pk_counts: n_sets u32
 *   msgs:      n_sets * 32 bytes
 *   rands:     n_sets * 8 bytes little-endian, nonzero 64-bit scalars
 *   dst:       domain separation tag for hash-to-curve
 * Returns 1 iff every set verifies. Caller screens the blst edge rules
 * (empty batch / empty set / infinity signature => false) beforehand;
 * this function re-checks what it can see cheaply. */
int bls_verify_signature_sets(uint32_t n_sets,
                              const uint8_t *sigs,
                              const uint8_t *pks,
                              const uint32_t *pk_counts,
                              const uint8_t *msgs,
                              const uint8_t *rands,
                              const uint8_t *dst, uint32_t dst_len) {
    ensure_init();
    if (n_sets == 0) return 0;

    enum { MAXN = 1024, MAXMSG = 1024 };
    if (n_sets > MAXN) {
        /* split recursively: all chunks must pass */
        uint32_t half = n_sets / 2;
        uint64_t pk_off = 0;
        for (uint32_t i = 0; i < half; i++) pk_off += pk_counts[i];
        return bls_verify_signature_sets(half, sigs, pks, pk_counts, msgs, rands, dst, dst_len)
            && bls_verify_signature_sets(n_sets - half, sigs + (uint64_t)half * 96,
                                         pks + pk_off * 96, pk_counts + half,
                                         msgs + (uint64_t)half * 32,
                                         rands + (uint64_t)half * 8, dst, dst_len);
    }

    static __thread g1a g1_sides[MAXN + 1];
    static __thread g2a g2_sides[MAXN + 1];
    static __thread g2p scratch[MAXN + 1];
    /* distinct-message hash cache (linear scan; gossip batches share few
     * distinct AttestationData roots) */
    static __thread uint8_t seen_msg[MAXMSG][32];
    static __thread g2a seen_h[MAXMSG];
    int n_seen = 0;

    g2p sig_acc;
    g2_set_inf(&sig_acc);

    uint64_t pk_off = 0;
    for (uint32_t i = 0; i < n_sets; i++) {
        uint32_t k = pk_counts[i];
        if (k == 0) return 0;

        g2a sig;
        if (!g2_decompress(&sig, sigs + (uint64_t)i * 96)) return 0;
        if (sig.inf) return 0;
        if (!g2_on_curve(&sig)) return 0;
        if (!g2_in_subgroup(&sig)) return 0;

        /* aggregate the set's pubkeys */
        g1p agg;
        g1_set_inf(&agg);
        for (uint32_t j = 0; j < k; j++) {
            g1a pk;
            if (!g1_from_xy(&pk, pks + (pk_off + j) * 96)) return 0;
            if (pk.inf) return 0;
            g1p pkj;
            g1_from_affine(&pkj, &pk);
            g1_add(&agg, &agg, &pkj);
        }
        pk_off += k;
        if (agg.inf || fp_is_zero(&agg.z)) return 0;

        uint64_t r = 0;
        for (int b = 0; b < 8; b++) r |= (uint64_t)rands[i * 8 + b] << (8 * b);
        if (r == 0) return 0;

        /* [r] agg_pk */
        uint64_t rl[1] = {r};
        g1p ra;
        g1_mul(&ra, &agg, rl, 1);
        g1_to_affine(&g1_sides[i], &ra);

        /* sig_acc += [r] sig */
        g2p sj, rs;
        g2_from_affine(&sj, &sig);
        g2_mul_u64(&rs, &sj, r);
        g2_add(&sig_acc, &sig_acc, &rs);

        /* H(m): cached per distinct message */
        const uint8_t *m = msgs + (uint64_t)i * 32;
        int found = -1;
        for (int s = 0; s < n_seen; s++)
            if (memcmp(seen_msg[s], m, 32) == 0) { found = s; break; }
        if (found < 0) {
            if (n_seen >= MAXMSG) return 0;
            memcpy(seen_msg[n_seen], m, 32);
            hash_to_g2(&seen_h[n_seen], m, 32, dst, dst_len);
            found = n_seen++;
        }
        g2_sides[i] = seen_h[found];
    }

    /* last pair: (-g1_gen, sig_acc) */
    g1_sides[n_sets] = G1_GEN;
    fp_neg(&g1_sides[n_sets].y, &G1_GEN.y);
    g2_to_affine(&g2_sides[n_sets], &sig_acc);

    fp12 f, e;
    miller_loop_n(&f, g1_sides, g2_sides, (int)n_sets + 1, scratch);
    final_exp(&e, &f);
    return fp12_is_one(&e);
}

/* aggregate_verify: ONE signature over per-pubkey messages.
 * pks raw affine (n*96), msgs n*32. */
int bls_aggregate_verify(uint32_t n,
                         const uint8_t sig_comp[96],
                         const uint8_t *pks,
                         const uint8_t *msgs,
                         const uint8_t *dst, uint32_t dst_len) {
    ensure_init();
    if (n == 0) return 0;
    enum { MAXN = 1024 };
    if (n > MAXN) return 0;
    static __thread g1a g1_sides[MAXN + 1];
    static __thread g2a g2_sides[MAXN + 1];
    static __thread g2p scratch[MAXN + 1];

    g2a sig;
    if (!g2_decompress(&sig, sig_comp)) return 0;
    if (sig.inf) return 0;
    if (!g2_on_curve(&sig) || !g2_in_subgroup(&sig)) return 0;

    for (uint32_t i = 0; i < n; i++) {
        if (!g1_from_xy(&g1_sides[i], pks + (uint64_t)i * 96)) return 0;
        if (g1_sides[i].inf) return 0;
        hash_to_g2(&g2_sides[i], msgs + (uint64_t)i * 32, 32, dst, dst_len);
    }
    g1_sides[n] = G1_GEN;
    fp_neg(&g1_sides[n].y, &G1_GEN.y);
    g2_sides[n] = sig;

    fp12 f, e;
    miller_loop_n(&f, g1_sides, g2_sides, (int)n + 1, scratch);
    final_exp(&e, &f);
    return fp12_is_one(&e);
}

/* compress a G2 affine point to the 96-byte wire form */
static void g2_compress(uint8_t out[96], const g2a *a) {
    if (a->inf) {
        memset(out, 0, 96);
        out[0] = 0xC0;
        return;
    }
    fp_to_bytes(out, &a->x.c1);
    fp_to_bytes(out + 48, &a->x.c0);
    fp2 ny;
    fp2_neg(&ny, &a->y);
    int larger = fp2_lex_gt(&a->y, &ny);
    out[0] |= 0x80 | (larger ? 0x20 : 0);
}

/* sign: [sk] H(msg) -> compressed G2. sk is 32 big-endian bytes (mod r
 * already enforced by the caller). Bench/test helper — validator signing
 * stays host-side in production, this keeps workload generation fast. */
int bls_sign(const uint8_t sk_be[32], const uint8_t *msg, uint32_t msg_len,
             const uint8_t *dst, uint32_t dst_len, uint8_t out_sig[96]) {
    ensure_init();
    g2a h;
    hash_to_g2(&h, msg, msg_len, dst, dst_len);
    uint64_t e[4] = {0};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            e[i] |= (uint64_t)sk_be[32 - 8 * (i + 1) + (7 - j)] << (8 * j);
    g2p p, s;
    g2_from_affine(&p, &h);
    /* 256-bit double-and-add */
    g2_set_inf(&s);
    int started = 0;
    for (int i = 3; i >= 0; i--)
        for (int b = 63; b >= 0; b--) {
            if (started) g2_dbl(&s, &s);
            if ((e[i] >> b) & 1) {
                if (!started) { s = p; started = 1; }
                else g2_add(&s, &s, &p);
            }
        }
    g2a sa;
    if (!started) { sa.inf = 1; sa.x = FP2_ZERO; sa.y = FP2_ZERO; }
    else g2_to_affine(&sa, &s);
    g2_compress(out_sig, &sa);
    return 1;
}

/* sk -> pubkey: [sk] G1_gen, written as raw affine x||y (96 BE bytes). */
int bls_sk_to_pk(const uint8_t sk_be[32], uint8_t out_xy[96]) {
    ensure_init();
    uint64_t e[4] = {0};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            e[i] |= (uint64_t)sk_be[32 - 8 * (i + 1) + (7 - j)] << (8 * j);
    g1p g, s;
    g1_from_affine(&g, &G1_GEN);
    g1_mul(&s, &g, e, 4);
    g1a a;
    g1_to_affine(&a, &s);
    if (a.inf) return 0;
    fp_to_bytes(out_xy, &a.x);
    fp_to_bytes(out_xy + 48, &a.y);
    return 1;
}

/* debug taps for the hash-to-curve pipeline (used by tests only) */
int bls_dbg_expand(const uint8_t *msg, uint32_t msg_len,
                   const uint8_t *dst, uint32_t dst_len, uint8_t out[256]) {
    ensure_init();
    expand_xmd(out, 256, msg, msg_len, dst, dst_len);
    return 1;
}

int bls_dbg_field(const uint8_t in[64], uint8_t out[48]) {
    ensure_init();
    fp u;
    fp_from_be64_mod(&u, in);
    fp_to_bytes(out, &u);
    return 1;
}

int bls_dbg_sswu(const uint8_t u_raw[96], uint8_t out[192]) {
    ensure_init();
    fp2 u, x, y;
    if (!fp_from_bytes(&u.c0, u_raw)) return 0;
    if (!fp_from_bytes(&u.c1, u_raw + 48)) return 0;
    sswu(&x, &y, &u);
    fp_to_bytes(out, &x.c0);
    fp_to_bytes(out + 48, &x.c1);
    fp_to_bytes(out + 96, &y.c0);
    fp_to_bytes(out + 144, &y.c1);
    return 1;
}

int bls_dbg_iso3(const uint8_t xy_raw[192], uint8_t out[192]) {
    ensure_init();
    fp2 x, y;
    g2a o;
    if (!fp_from_bytes(&x.c0, xy_raw)) return 0;
    if (!fp_from_bytes(&x.c1, xy_raw + 48)) return 0;
    if (!fp_from_bytes(&y.c0, xy_raw + 96)) return 0;
    if (!fp_from_bytes(&y.c1, xy_raw + 144)) return 0;
    iso3(&o, &x, &y);
    fp_to_bytes(out, &o.x.c0);
    fp_to_bytes(out + 48, &o.x.c1);
    fp_to_bytes(out + 96, &o.y.c0);
    fp_to_bytes(out + 144, &o.y.c1);
    return 1;
}

/* Self-test: bilinearity e(2P, Q) == e(P, Q)^2 on the generators, plus a
 * sign/hash sanity loop. Returns 1 on success. */
int bls_selftest(void) {
    ensure_init();
    /* e(G1, G2) should be != 1; e(-G1, G2)*e(G1, G2) == 1 */
    g1a ps[2];
    g2a qs[2];
    g2p scratch[2];
    ps[0] = G1_GEN;
    ps[1] = G1_GEN;
    fp_neg(&ps[1].y, &G1_GEN.y);
    qs[0] = G2_GEN_A;
    qs[1] = G2_GEN_A;
    fp12 f, e;
    miller_loop_n(&f, ps, qs, 2, scratch);
    final_exp(&e, &f);
    if (!fp12_is_one(&e)) return 0;
    /* single pairing must NOT be one */
    miller_loop_n(&f, ps, qs, 1, scratch);
    final_exp(&e, &f);
    if (fp12_is_one(&e)) return 0;
    /* generators on curve + in subgroup */
    if (!g1_on_curve(&G1_GEN) || !g1_in_subgroup(&G1_GEN)) return 0;
    if (!g2_on_curve(&G2_GEN_A) || !g2_in_subgroup(&G2_GEN_A)) return 0;
    return 1;
}

#ifdef __cplusplus
}
#endif
