/* Native Snappy raw-block codec (the wire codec of the gossip/req-resp
 * layer and the .ssz_snappy vector files).
 *
 * The reference links the Rust `snap` crate for its SSZ-snappy codecs
 * (lighthouse_network/src/rpc/codec/ssz_snappy.rs); this is the same
 * algorithm in plain C: greedy 4-byte hash matching within 64 KiB
 * blocks, literal + copy1/copy2 emission. The Python layer keeps a
 * pure-Python fallback (utils/snappy.py) so a missing toolchain degrades
 * to slow-not-broken.
 *
 * Exported ABI (ctypes):
 *   size_t lt_snappy_max_compressed(size_t n);
 *   size_t lt_snappy_compress(const uint8_t* in, size_t n, uint8_t* out);
 *   long   lt_snappy_uncompressed_length(const uint8_t* in, size_t n);
 *   long   lt_snappy_decompress(const uint8_t* in, size_t n,
 *                               uint8_t* out, size_t cap);
 *       -> bytes written, or -1 on malformed input / overflow.
 */

#include <stdint.h>
#include <string.h>
#include <stddef.h>

#define BLOCK_LOG 16
#define BLOCK_SIZE (1u << BLOCK_LOG)
#define HASH_BITS 14
#define HASH_SIZE (1u << HASH_BITS)

static inline uint32_t load32(const uint8_t *p) {
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
}

static inline uint32_t hash32(uint32_t v) {
    return (v * 0x1e35a7bdu) >> (32 - HASH_BITS);
}

static uint8_t *emit_uvarint(uint8_t *out, size_t n) {
    while (n >= 0x80) {
        *out++ = (uint8_t)(n | 0x80);
        n >>= 7;
    }
    *out++ = (uint8_t)n;
    return out;
}

static uint8_t *emit_literal(uint8_t *out, const uint8_t *src, size_t len) {
    if (len == 0) return out;
    size_t n = len - 1;
    if (n < 60) {
        *out++ = (uint8_t)(n << 2);
    } else if (n < (1u << 8)) {
        *out++ = 60 << 2;
        *out++ = (uint8_t)n;
    } else if (n < (1u << 16)) {
        *out++ = 61 << 2;
        *out++ = (uint8_t)n;
        *out++ = (uint8_t)(n >> 8);
    } else if (n < (1u << 24)) {
        *out++ = 62 << 2;
        *out++ = (uint8_t)n;
        *out++ = (uint8_t)(n >> 8);
        *out++ = (uint8_t)(n >> 16);
    } else {
        *out++ = 63 << 2;
        *out++ = (uint8_t)n;
        *out++ = (uint8_t)(n >> 8);
        *out++ = (uint8_t)(n >> 16);
        *out++ = (uint8_t)(n >> 24);
    }
    memcpy(out, src, len);
    return out + len;
}

/* one copy element, 4 <= len <= 64, offset < 65536 */
static uint8_t *emit_copy_one(uint8_t *out, size_t offset, size_t len) {
    if (len >= 4 && len <= 11 && offset < 2048) {
        *out++ = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
        *out++ = (uint8_t)offset;
    } else {
        *out++ = (uint8_t)(2 | ((len - 1) << 2));
        *out++ = (uint8_t)offset;
        *out++ = (uint8_t)(offset >> 8);
    }
    return out;
}

static uint8_t *emit_copy(uint8_t *out, size_t offset, size_t len) {
    /* chunk >64 so every element is legal and the tail stays >= 4 */
    while (len >= 68) {
        out = emit_copy_one(out, offset, 64);
        len -= 64;
    }
    if (len > 64) {
        out = emit_copy_one(out, offset, 60);
        len -= 60;
    }
    return emit_copy_one(out, offset, len);
}

size_t lt_snappy_max_compressed(size_t n) {
    return 32 + n + n / 6;
}

static uint8_t *compress_block(const uint8_t *in, size_t n, uint8_t *out,
                               uint16_t *table) {
    memset(table, 0, HASH_SIZE * sizeof(uint16_t));
    size_t ip = 0, anchor = 0;
    if (n >= 15) {
        size_t ip_limit = n - 4;
        uint32_t skip = 32; /* snappy's literal-run acceleration */
        ip = 1;
        while (ip <= ip_limit) {
            uint32_t v = load32(in + ip);
            uint32_t h = hash32(v);
            size_t cand = table[h];
            table[h] = (uint16_t)ip;
            if (cand < ip && load32(in + cand) == v) {
                out = emit_literal(out, in + anchor, ip - anchor);
                size_t len = 4;
                size_t maxlen = n - ip;
                while (len < maxlen && in[cand + len] == in[ip + len]) len++;
                out = emit_copy(out, ip - cand, len);
                ip += len;
                anchor = ip;
                skip = 32;
                if (ip <= ip_limit && ip >= 2) {
                    /* re-prime the table at the new position - 1 */
                    table[hash32(load32(in + ip - 1))] = (uint16_t)(ip - 1);
                }
            } else {
                ip += (skip++ >> 5);
            }
        }
    }
    return emit_literal(out, in + anchor, n - anchor);
}

size_t lt_snappy_compress(const uint8_t *in, size_t n, uint8_t *out) {
    uint16_t table[HASH_SIZE];
    uint8_t *op = emit_uvarint(out, n);
    size_t pos = 0;
    while (pos < n) {
        size_t blk = n - pos < BLOCK_SIZE ? n - pos : BLOCK_SIZE;
        op = compress_block(in + pos, blk, op, table);
        pos += blk;
    }
    return (size_t)(op - out);
}

static long read_uvarint(const uint8_t *in, size_t n, size_t *pos) {
    size_t out = 0;
    unsigned shift = 0;
    while (1) {
        if (*pos >= n || shift > 63) return -1;
        uint8_t b = in[(*pos)++];
        out |= (size_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) return (long)out;
        shift += 7;
    }
}

long lt_snappy_uncompressed_length(const uint8_t *in, size_t n) {
    size_t pos = 0;
    return read_uvarint(in, n, &pos);
}

long lt_snappy_decompress(const uint8_t *in, size_t n, uint8_t *out,
                          size_t cap) {
    size_t pos = 0;
    long want = read_uvarint(in, n, &pos);
    if (want < 0 || (size_t)want > cap) return -1;
    size_t op = 0;
    while (pos < n) {
        uint8_t tag = in[pos++];
        uint32_t kind = tag & 3;
        if (kind == 0) { /* literal */
            size_t len = (tag >> 2) + 1;
            if (len > 60) {
                unsigned extra = (unsigned)(len - 60);
                if (pos + extra > n) return -1;
                len = 0;
                for (unsigned i = 0; i < extra; i++)
                    len |= (size_t)in[pos + i] << (8 * i);
                len += 1;
                pos += extra;
            }
            if (pos + len > n || op + len > cap) return -1;
            memcpy(out + op, in + pos, len);
            pos += len;
            op += len;
        } else {
            size_t len, offset;
            if (kind == 1) {
                len = ((tag >> 2) & 0x7) + 4;
                if (pos >= n) return -1;
                offset = ((size_t)(tag >> 5) << 8) | in[pos++];
            } else if (kind == 2) {
                len = (tag >> 2) + 1;
                if (pos + 2 > n) return -1;
                offset = (size_t)in[pos] | ((size_t)in[pos + 1] << 8);
                pos += 2;
            } else {
                len = (tag >> 2) + 1;
                if (pos + 4 > n) return -1;
                offset = (size_t)in[pos] | ((size_t)in[pos + 1] << 8) |
                         ((size_t)in[pos + 2] << 16) |
                         ((size_t)in[pos + 3] << 24);
                pos += 4;
            }
            if (offset == 0 || offset > op || op + len > cap) return -1;
            /* byte-wise: copies may overlap (run-length encoding) */
            for (size_t i = 0; i < len; i++) {
                out[op + i] = out[op + i - offset];
            }
            op += len;
        }
    }
    if ((long)op != want) return -1;
    return (long)op;
}
