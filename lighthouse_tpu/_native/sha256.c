/* Batched SHA-256 for the merkleization hot loop.
 *
 * Native analogue of the reference's crypto/eth2_hashing
 * (/root/reference/crypto/eth2_hashing/src/lib.rs:87-177): runtime
 * CPU-feature dispatch between a portable scalar implementation and the
 * x86 SHA-NI extension path. The exported surface is batch-first —
 * `sha256_hash_pairs` hashes n independent 64-byte messages (one merkle
 * tree level) in one call, so Python pays one FFI transition per level
 * instead of one interpreter round-trip per node.
 *
 * Build: cc -O3 -fPIC -shared (the SHA-NI unit is compiled with
 * -msha -msse4.1; it is only ever entered after __builtin_cpu_supports
 * confirms the extension).
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

#if defined(__x86_64__) || defined(_M_X64)
#define HAVE_X86 1
#include <immintrin.h>
#endif

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

static const uint32_t IV[8] = {
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
};

/* The constant second block of every 64-byte message:
 * 0x80, zeros, 64-bit big-endian bit length (512). */
static const uint8_t PAD64[64] = {
    0x80, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 2, 0,
};

/* ------------------------------------------------------------------ */
/* Portable scalar compression                                         */
/* ------------------------------------------------------------------ */

#define ROTR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void compress_scalar(uint32_t st[8], const uint8_t block[64]) {
    uint32_t w[64];
    for (int t = 0; t < 16; t++)
        w[t] = ((uint32_t)block[4 * t] << 24) | ((uint32_t)block[4 * t + 1] << 16) |
               ((uint32_t)block[4 * t + 2] << 8) | block[4 * t + 3];
    for (int t = 16; t < 64; t++) {
        uint32_t s0 = ROTR(w[t - 15], 7) ^ ROTR(w[t - 15], 18) ^ (w[t - 15] >> 3);
        uint32_t s1 = ROTR(w[t - 2], 17) ^ ROTR(w[t - 2], 19) ^ (w[t - 2] >> 10);
        w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
    uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int t = 0; t < 64; t++) {
        uint32_t S1 = ROTR(e, 6) ^ ROTR(e, 11) ^ ROTR(e, 25);
        uint32_t ch = g ^ (e & (f ^ g));
        uint32_t t1 = h + S1 + ch + K[t] + w[t];
        uint32_t S0 = ROTR(a, 2) ^ ROTR(a, 13) ^ ROTR(a, 22);
        uint32_t maj = (a & b) | (c & (a | b));
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* ------------------------------------------------------------------ */
/* SHA-NI compression (x86)                                            */
/* ------------------------------------------------------------------ */

#ifdef HAVE_X86

__attribute__((target("sha,sse4.1")))
static inline __m128i sched_ni(__m128i w0, __m128i w1, __m128i w2, __m128i w3) {
    /* W[t..t+3] from the previous four schedule blocks. */
    __m128i t0 = _mm_sha256msg1_epu32(w0, w1);        /* W[t-16..]+s0(W[t-15..]) */
    __m128i t1 = _mm_alignr_epi8(w3, w2, 4);          /* W[t-7..t-4] */
    t0 = _mm_add_epi32(t0, t1);
    return _mm_sha256msg2_epu32(t0, w3);              /* + s1(W[t-2..]) */
}

__attribute__((target("sha,sse4.1")))
static void compress_ni(uint32_t st[8], const uint8_t *block) {
    const __m128i MASK =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);

    __m128i TMP = _mm_loadu_si128((const __m128i *)&st[0]);     /* DCBA */
    __m128i STATE1 = _mm_loadu_si128((const __m128i *)&st[4]);  /* HGFE */
    TMP = _mm_shuffle_epi32(TMP, 0xB1);                         /* CDAB */
    STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);                   /* EFGH */
    __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);           /* ABEF */
    STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);                /* CDGH */

    const __m128i ABEF_SAVE = STATE0;
    const __m128i CDGH_SAVE = STATE1;

    __m128i w[4];
    __m128i MSG;
    for (int t = 0; t < 16; t++) {
        __m128i cur;
        if (t < 4) {
            cur = _mm_loadu_si128((const __m128i *)(block + 16 * t));
            cur = _mm_shuffle_epi8(cur, MASK);
        } else {
            cur = sched_ni(w[t % 4], w[(t + 1) % 4], w[(t + 2) % 4], w[(t + 3) % 4]);
        }
        w[t % 4] = cur;
        MSG = _mm_add_epi32(cur, _mm_loadu_si128((const __m128i *)&K[4 * t]));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    }

    STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
    STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);

    TMP = _mm_shuffle_epi32(STATE0, 0x1B);                      /* FEBA */
    STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);                   /* DCHG */
    STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);                /* DCBA */
    STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);                   /* HGFE */
    _mm_storeu_si128((__m128i *)&st[0], STATE0);
    _mm_storeu_si128((__m128i *)&st[4], STATE1);
}

static int have_sha_ni(void) {
    static int cached = -1;
    if (cached < 0)
        cached = __builtin_cpu_supports("sha") ? 1 : 0;
    return cached;
}

#else
static int have_sha_ni(void) { return 0; }
static void compress_ni(uint32_t st[8], const uint8_t *block) { (void)st; (void)block; }
#endif

/* ------------------------------------------------------------------ */
/* Exports                                                             */
/* ------------------------------------------------------------------ */

static void store_be(uint8_t out[32], const uint32_t st[8]) {
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)(st[i] >> 24);
        out[4 * i + 1] = (uint8_t)(st[i] >> 16);
        out[4 * i + 2] = (uint8_t)(st[i] >> 8);
        out[4 * i + 3] = (uint8_t)st[i];
    }
}

/* n independent 64-byte messages -> n 32-byte digests. */
void sha256_hash_pairs(const uint8_t *in, uint8_t *out, size_t n) {
    if (have_sha_ni()) {
        for (size_t i = 0; i < n; i++) {
            uint32_t st[8];
            memcpy(st, IV, sizeof st);
            compress_ni(st, in + 64 * i);
            compress_ni(st, PAD64);
            store_be(out + 32 * i, st);
        }
    } else {
        for (size_t i = 0; i < n; i++) {
            uint32_t st[8];
            memcpy(st, IV, sizeof st);
            compress_scalar(st, in + 64 * i);
            compress_scalar(st, PAD64);
            store_be(out + 32 * i, st);
        }
    }
}

/* General SHA-256 (arbitrary length), for non-merkle callers. */
void sha256_oneshot(const uint8_t *in, size_t len, uint8_t *out) {
    uint32_t st[8];
    memcpy(st, IV, sizeof st);
    size_t off = 0;
    void (*comp)(uint32_t *, const uint8_t *) =
        have_sha_ni() ? compress_ni : compress_scalar;
    while (len - off >= 64) {
        comp(st, in + off);
        off += 64;
    }
    uint8_t tail[128];
    size_t rem = len - off;
    memcpy(tail, in + off, rem);
    tail[rem] = 0x80;
    size_t tlen = (rem + 9 <= 64) ? 64 : 128;
    memset(tail + rem + 1, 0, tlen - rem - 1 - 8);
    uint64_t bits = (uint64_t)len * 8;
    for (int i = 0; i < 8; i++)
        tail[tlen - 1 - i] = (uint8_t)(bits >> (8 * i));
    comp(st, tail);
    if (tlen == 128)
        comp(st, tail + 64);
    store_be(out, st);
}

int sha256_has_sha_ni(void) { return have_sha_ni(); }

#ifdef __cplusplus
}
#endif
