"""Native (C) runtime components, built on demand with the system compiler.

The reference links vendored native libraries for its hot loops (blst asm,
ring's SHA-NI — ``crypto/eth2_hashing/Cargo.toml``). Here the native layer
is compiled from checked-in C at first import and loaded via ctypes; every
caller has a pure-Python fallback so a missing toolchain degrades to slow,
not broken.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
from pathlib import Path

_DIR = Path(__file__).resolve().parent


def _compiler() -> str | None:
    for cc in (os.environ.get("CC"), "cc", "gcc", "g++", "clang"):
        if not cc:
            continue
        try:
            subprocess.run([cc, "--version"], capture_output=True, check=True)
            return cc
        except (OSError, subprocess.CalledProcessError):
            continue
    return None


_LOAD_CACHE: dict[str, ctypes.CDLL | None] = {}


def build_and_load(stem: str, extra_flags: tuple[str, ...] = ()) -> ctypes.CDLL | None:
    """Compile ``<stem>.c`` into ``lib<stem>.so`` (if stale) and dlopen it.
    Returns None when no compiler is available or the build fails. The
    outcome — INCLUDING failure — is cached per stem, so hot callers with
    a pure-Python fallback (SecretKey.sign/public_key) never re-probe the
    compiler per call."""
    if stem in _LOAD_CACHE:
        return _LOAD_CACHE[stem]
    _LOAD_CACHE[stem] = out = _build_and_load_uncached(stem, extra_flags)
    return out


def _build_and_load_uncached(
    stem: str, extra_flags: tuple[str, ...] = ()
) -> ctypes.CDLL | None:
    src = _DIR / f"{stem}.c"
    so = _DIR / f"lib{stem}{sysconfig.get_config_var('EXT_SUFFIX') or '.so'}"
    if not src.exists():
        return None
    if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
        cc = _compiler()
        if cc is None:
            return None
        # Compile to a per-pid temp path and os.replace: concurrent
        # importers never dlopen a half-written file.
        tmp = so.with_suffix(f".tmp{os.getpid()}")
        cmd = [cc, "-O3", "-fPIC", "-shared", *extra_flags, str(src), "-o", str(tmp)]
        try:
            subprocess.run(cmd, capture_output=True, check=True)
            os.replace(tmp, so)
        except (OSError, subprocess.CalledProcessError):
            tmp.unlink(missing_ok=True)
            return None
    try:
        return ctypes.CDLL(str(so))
    except OSError:
        return None
