"""Domain / signing-root computation (spec helpers the reference keeps on
``ChainSpec`` — ``consensus/types/src/chain_spec.rs`` ``get_domain``/
``compute_domain`` — and in ``signing_root`` helpers)."""

from __future__ import annotations

from ..ssz import hash_tree_root
from .chain_spec import ChainSpec
from .containers import types_for
from .preset import PRESETS


def _fork_data_root(t, current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return hash_tree_root(
        t.ForkData(
            current_version=current_version,
            genesis_validators_root=genesis_validators_root,
        )
    )


def compute_fork_data_root(
    spec: ChainSpec, current_version: bytes, genesis_validators_root: bytes
) -> bytes:
    t = types_for(PRESETS[spec.preset_base])
    return _fork_data_root(t, current_version, genesis_validators_root)


def compute_fork_digest(
    spec: ChainSpec, current_version: bytes, genesis_validators_root: bytes
) -> bytes:
    return compute_fork_data_root(spec, current_version, genesis_validators_root)[:4]


def compute_domain(
    spec: ChainSpec,
    domain_type: int,
    fork_version: bytes,
    genesis_validators_root: bytes,
) -> bytes:
    root = compute_fork_data_root(spec, fork_version, genesis_validators_root)
    return domain_type.to_bytes(4, "little") + root[:28]


def get_domain(
    spec: ChainSpec,
    state,
    domain_type: int,
    epoch: int | None = None,
) -> bytes:
    """Domain at ``epoch`` using the state's fork (spec ``get_domain``)."""
    preset = PRESETS[spec.preset_base]
    if epoch is None:
        epoch = state.slot // preset.SLOTS_PER_EPOCH
    fork = state.fork
    version = (
        fork.previous_version if epoch < fork.epoch else fork.current_version
    )
    return compute_domain(spec, domain_type, version, state.genesis_validators_root)


def compute_signing_root(tpe, obj, domain: bytes) -> bytes:
    """hash_tree_root(SigningData(object_root, domain)) — the 32-byte
    message every BLS signature in the system actually signs."""
    t = types_for(PRESETS["mainnet"])  # SigningData is preset-independent
    root = hash_tree_root(tpe, obj) if not isinstance(obj, bytes) else obj
    return hash_tree_root(t.SigningData(object_root=root, domain=domain))
