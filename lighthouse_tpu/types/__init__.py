"""Consensus types: presets, runtime chain spec, and per-fork containers
(reference layer: ``consensus/types``, see SURVEY.md §2.3)."""

from .chain_spec import (
    ChainSpec,
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    FAR_FUTURE_EPOCH,
    mainnet_spec,
    minimal_spec,
)
from .containers import FORK_ORDER, fork_at_least, types_for
from .domains import (
    compute_domain,
    compute_fork_data_root,
    compute_fork_digest,
    compute_signing_root,
    get_domain,
)
from .preset import MAINNET, MINIMAL, PRESETS, Preset

__all__ = [
    "ChainSpec",
    "FAR_FUTURE_EPOCH",
    "FORK_ORDER",
    "MAINNET",
    "MINIMAL",
    "PRESETS",
    "Preset",
    "compute_domain",
    "compute_fork_data_root",
    "compute_fork_digest",
    "compute_signing_root",
    "fork_at_least",
    "get_domain",
    "mainnet_spec",
    "minimal_spec",
    "types_for",
    "DOMAIN_AGGREGATE_AND_PROOF",
    "DOMAIN_BEACON_ATTESTER",
    "DOMAIN_BEACON_PROPOSER",
    "DOMAIN_CONTRIBUTION_AND_PROOF",
    "DOMAIN_DEPOSIT",
    "DOMAIN_RANDAO",
    "DOMAIN_SELECTION_PROOF",
    "DOMAIN_SYNC_COMMITTEE",
    "DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF",
    "DOMAIN_VOLUNTARY_EXIT",
]
