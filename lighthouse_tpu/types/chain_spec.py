"""Runtime chain configuration (the reference's ``ChainSpec``,
``consensus/types/src/chain_spec.rs``): fork schedule, domains, genesis
and validator-cycle parameters — the knobs that vary per network at
runtime, as opposed to the compile-time ``Preset`` shape parameters.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

FAR_FUTURE_EPOCH = 2**64 - 1

# Domain types (4-byte little-endian tags).
DOMAIN_BEACON_PROPOSER = 0
DOMAIN_BEACON_ATTESTER = 1
DOMAIN_RANDAO = 2
DOMAIN_DEPOSIT = 3
DOMAIN_VOLUNTARY_EXIT = 4
DOMAIN_SELECTION_PROOF = 5
DOMAIN_AGGREGATE_AND_PROOF = 6
DOMAIN_SYNC_COMMITTEE = 7
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = 8
DOMAIN_CONTRIBUTION_AND_PROOF = 9


@dataclass(frozen=True)
class ChainSpec:
    config_name: str = "mainnet"
    preset_base: str = "mainnet"

    # Transition
    terminal_total_difficulty: int = 58750000000000000000000
    terminal_block_hash: bytes = bytes(32)
    terminal_block_hash_activation_epoch: int = FAR_FUTURE_EPOCH

    # Genesis
    min_genesis_active_validator_count: int = 16384
    min_genesis_time: int = 1606824000
    genesis_fork_version: bytes = bytes(4)
    genesis_delay: int = 604800

    # Fork schedule
    altair_fork_version: bytes = bytes([1, 0, 0, 0])
    altair_fork_epoch: int | None = 74240
    bellatrix_fork_version: bytes = bytes([2, 0, 0, 0])
    bellatrix_fork_epoch: int | None = 144896

    # Time
    seconds_per_slot: int = 12
    seconds_per_eth1_block: int = 14
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    eth1_follow_distance: int = 2048

    # Validator cycle
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16
    ejection_balance: int = 16_000_000_000
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 65536

    # Fork choice
    proposer_score_boost: int | None = 40

    # Deposit contract
    deposit_chain_id: int = 1
    deposit_network_id: int = 1
    deposit_contract_address: bytes = bytes.fromhex(
        "00000000219ab540356cbb839cbe05303d7705fa"
    )

    # -- fork helpers -----------------------------------------------------

    def fork_name_at_epoch(self, epoch: int) -> str:
        if self.bellatrix_fork_epoch is not None and epoch >= self.bellatrix_fork_epoch:
            return "bellatrix"
        if self.altair_fork_epoch is not None and epoch >= self.altair_fork_epoch:
            return "altair"
        return "phase0"

    def fork_version_for(self, fork_name: str) -> bytes:
        return {
            "phase0": self.genesis_fork_version,
            "altair": self.altair_fork_version,
            "bellatrix": self.bellatrix_fork_version,
        }[fork_name]

    def fork_epoch_for(self, fork_name: str) -> int | None:
        return {
            "phase0": 0,
            "altair": self.altair_fork_epoch,
            "bellatrix": self.bellatrix_fork_epoch,
        }[fork_name]

    def fork_version_at_epoch(self, epoch: int) -> bytes:
        return self.fork_version_for(self.fork_name_at_epoch(epoch))

    def to_api_dict(self, preset=None) -> dict:
        """Beacon-API ``/eth/v1/config/spec`` shape: UPPER_SNAKE keys,
        stringified ints, 0x-hex bytes (reference serde of ChainSpec +
        preset into one flat map)."""
        import dataclasses

        out = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            key = f.name.upper()
            if isinstance(v, bytes):
                out[key] = "0x" + v.hex()
            elif isinstance(v, bool):
                out[key] = str(int(v))
            elif v is None:
                continue
            else:
                out[key] = str(v)
        if preset is not None:
            for name in dir(preset):
                if name.isupper():
                    out[name] = str(getattr(preset, name))
        return out


def mainnet_spec() -> ChainSpec:
    return ChainSpec()


def gnosis_spec() -> ChainSpec:
    """Gnosis chain (reference GnosisEthSpec + gnosis network config):
    mainnet preset values with 5-second slots and its own fork schedule."""
    return ChainSpec(
        config_name="gnosis",
        preset_base="mainnet",
        seconds_per_slot=5,
        genesis_fork_version=bytes([0, 0, 0, 0x64]),
        altair_fork_version=bytes([1, 0, 0, 0x64]),
        altair_fork_epoch=512,
        bellatrix_fork_version=bytes([2, 0, 0, 0x64]),
        bellatrix_fork_epoch=385536,
        min_genesis_time=1638968400,
        min_genesis_active_validator_count=4096,
        churn_limit_quotient=4096,
        deposit_chain_id=100,
        deposit_network_id=100,
        seconds_per_eth1_block=6,
        eth1_follow_distance=1024,
    )


def minimal_spec(**overrides) -> ChainSpec:
    """Minimal-preset test spec (forks at genesis unless overridden)."""
    base = ChainSpec(
        config_name="minimal",
        preset_base="minimal",
        min_genesis_active_validator_count=64,
        seconds_per_slot=6,
        genesis_fork_version=bytes([0, 0, 0, 1]),
        altair_fork_version=bytes([1, 0, 0, 1]),
        altair_fork_epoch=None,
        bellatrix_fork_version=bytes([2, 0, 0, 1]),
        bellatrix_fork_epoch=None,
        shard_committee_period=64,
        eth1_follow_distance=16,
        churn_limit_quotient=32,
    )
    return replace(base, **overrides) if overrides else base
