"""Key management: EIP-2333 derivation, EIP-2335 keystores, EIP-2386
wallets, EIP-3076 slashing protection.

Reference: ``crypto/eth2_key_derivation``, ``crypto/eth2_keystore``,
``crypto/eth2_wallet``, ``validator_client/slashing_protection``.
"""

from .derivation import (
    derive_child_sk,
    derive_master_sk,
    derive_sk_at_path,
    hkdf_mod_r,
    parse_path,
    validator_signing_path,
    validator_withdrawal_path,
)
from .keystore import KeystoreError, decrypt, encrypt, load, save
from .slashing_protection import SlashingDatabase, SlashingProtectionError
from .wallet import Wallet, WalletError

__all__ = [
    "KeystoreError",
    "SlashingDatabase",
    "SlashingProtectionError",
    "Wallet",
    "WalletError",
    "decrypt",
    "derive_child_sk",
    "derive_master_sk",
    "derive_sk_at_path",
    "encrypt",
    "hkdf_mod_r",
    "load",
    "parse_path",
    "save",
    "validator_signing_path",
    "validator_withdrawal_path",
]
