"""EIP-2333 BLS key derivation + EIP-2334 paths (reference:
``crypto/eth2_key_derivation`` — ``derived_key.rs``,
``lamport_secret_key.rs``, ``path.rs``).

Tree-KDF: every node key derives 2^32 children via a Lamport-keyed HKDF
construction; validator keys live at EIP-2334 paths
``m/12381/3600/<account>/0/0`` (signing) / ``.../0`` (withdrawal).
"""

from __future__ import annotations

import hashlib
import hmac

from ..crypto.params import R

_SALT0 = b"BLS-SIG-KEYGEN-SALT-"


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_mod_r(ikm: bytes, key_info: bytes = b"") -> int:
    """RFC-style keygen: loop until nonzero mod r (EIP-2333 hkdf_mod_r)."""
    salt = _SALT0
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % R
    return sk


def _ikm_to_lamport_sk(ikm: bytes, salt: bytes) -> list[bytes]:
    okm = _hkdf_expand(_hkdf_extract(salt, ikm), b"", 255 * 32)
    return [okm[i * 32:(i + 1) * 32] for i in range(255)]


def _parent_sk_to_lamport_pk(parent_sk: int, index: int) -> bytes:
    salt = index.to_bytes(4, "big")
    ikm = parent_sk.to_bytes(32, "big")
    not_ikm = bytes(b ^ 0xFF for b in ikm)
    chunks = _ikm_to_lamport_sk(ikm, salt) + _ikm_to_lamport_sk(not_ikm, salt)
    return hashlib.sha256(
        b"".join(hashlib.sha256(c).digest() for c in chunks)
    ).digest()


def derive_master_sk(seed: bytes) -> int:
    if len(seed) < 32:
        raise ValueError("EIP-2333 seed must be >= 32 bytes")
    return hkdf_mod_r(seed)


def derive_child_sk(parent_sk: int, index: int) -> int:
    if not 0 <= index < 2**32:
        raise ValueError("child index out of range")
    return hkdf_mod_r(_parent_sk_to_lamport_pk(parent_sk, index))


def parse_path(path: str) -> list[int]:
    """EIP-2334 path: ``m/12381/3600/<i>/0[/0]``."""
    parts = path.strip().split("/")
    if not parts or parts[0] != "m":
        raise ValueError(f"invalid EIP-2334 path {path!r}")
    out = []
    for p in parts[1:]:
        if not p.isdigit():
            raise ValueError(f"invalid path component {p!r}")
        out.append(int(p))
    return out


def derive_sk_at_path(seed: bytes, path: str) -> int:
    sk = derive_master_sk(seed)
    for index in parse_path(path):
        sk = derive_child_sk(sk, index)
    return sk


def validator_signing_path(account: int) -> str:
    return f"m/12381/3600/{account}/0/0"


def validator_withdrawal_path(account: int) -> str:
    return f"m/12381/3600/{account}/0"
