"""EIP-2335 keystores (reference: ``crypto/eth2_keystore`` —
``keystore.rs``, ``json_keystore/``): password-encrypted BLS secret keys.

crypto modules: kdf = scrypt (default) or pbkdf2-hmac-sha256; checksum =
sha256(dk[16:32] || ciphertext); cipher = aes-128-ctr keyed by dk[:16].
Passwords are NFKD-normalized with C0/C1 control codepoints stripped, per
the EIP (same rule the reference implements).
"""

from __future__ import annotations

import hashlib
import json
import secrets
import unicodedata
import uuid as uuid_mod

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes


class KeystoreError(ValueError):
    pass


def normalize_password(password: str) -> bytes:
    norm = unicodedata.normalize("NFKD", password)
    stripped = "".join(
        c for c in norm
        if not (0x00 <= ord(c) <= 0x1F or 0x7F <= ord(c) <= 0x9F)
    )
    return stripped.encode("utf-8")


def _aes128ctr(key16: bytes, iv16: bytes, data: bytes) -> bytes:
    c = Cipher(algorithms.AES(key16), modes.CTR(iv16)).encryptor()
    return c.update(data) + c.finalize()


def _derive_key(password: bytes, kdf: dict) -> bytes:
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(
            password,
            salt=salt,
            n=params["n"],
            r=params["r"],
            p=params["p"],
            dklen=params["dklen"],
            maxmem=256 * 1024 * 1024,
        )
    if kdf["function"] == "pbkdf2":
        if params.get("prf", "hmac-sha256") != "hmac-sha256":
            raise KeystoreError("unsupported prf")
        return hashlib.pbkdf2_hmac(
            "sha256", password, salt, params["c"], params["dklen"]
        )
    raise KeystoreError(f"unsupported kdf {kdf['function']!r}")


def encrypt(
    secret: bytes,
    password: str,
    path: str = "",
    kdf: str = "scrypt",
    pubkey: bytes | None = None,
    description: str = "",
    kdf_work: int | None = None,
) -> dict:
    """-> EIP-2335 keystore JSON object. ``kdf_work`` overrides the work
    parameter (scrypt n / pbkdf2 c) — tests use small values."""
    pw = normalize_password(password)
    salt = secrets.token_bytes(32)
    if kdf == "scrypt":
        kdf_module = {
            "function": "scrypt",
            "params": {
                "dklen": 32,
                "n": kdf_work or 262144,
                "r": 8,
                "p": 1,
                "salt": salt.hex(),
            },
            "message": "",
        }
    elif kdf == "pbkdf2":
        kdf_module = {
            "function": "pbkdf2",
            "params": {
                "dklen": 32,
                "c": kdf_work or 262144,
                "prf": "hmac-sha256",
                "salt": salt.hex(),
            },
            "message": "",
        }
    else:
        raise KeystoreError(f"unsupported kdf {kdf!r}")

    dk = _derive_key(pw, kdf_module)
    iv = secrets.token_bytes(16)
    ciphertext = _aes128ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()

    return {
        "crypto": {
            "kdf": kdf_module,
            "checksum": {
                "function": "sha256",
                "params": {},
                "message": checksum.hex(),
            },
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": ciphertext.hex(),
            },
        },
        "description": description,
        "pubkey": pubkey.hex() if pubkey else "",
        "path": path,
        "uuid": str(uuid_mod.uuid4()),
        "version": 4,
    }


def decrypt(keystore: dict, password: str) -> bytes:
    if keystore.get("version") != 4:
        raise KeystoreError("unsupported keystore version")
    crypto = keystore["crypto"]
    pw = normalize_password(password)
    dk = _derive_key(pw, crypto["kdf"])
    ciphertext = bytes.fromhex(crypto["cipher"]["message"])
    if crypto["checksum"]["function"] != "sha256":
        raise KeystoreError("unsupported checksum function")
    want = bytes.fromhex(crypto["checksum"]["message"])
    got = hashlib.sha256(dk[16:32] + ciphertext).digest()
    if got != want:
        raise KeystoreError("invalid password (checksum mismatch)")
    if crypto["cipher"]["function"] != "aes-128-ctr":
        raise KeystoreError("unsupported cipher")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return _aes128ctr(dk[:16], iv, ciphertext)


def save(keystore: dict, path) -> None:
    with open(path, "w") as f:
        json.dump(keystore, f, indent=2)


def load(path) -> dict:
    with open(path) as f:
        return json.load(f)
