"""Slashing protection database, EIP-3076 (reference:
``validator_client/slashing_protection/src/slashing_database.rs:35-608``
+ ``interchange.rs``).

SQLite-backed record of every signed block/attestation per validator;
``check_and_insert_*`` enforces, atomically:

* blocks — no double proposal at a slot (same signing root is an
  idempotent re-sign), no proposal at or below the low watermark;
* attestations — source <= target, no double vote for a target epoch, no
  surrounding or surrounded vote (min-max conditions), monotone source.

Interchange (EIP-3076 v5) import/export for migrating between clients.
"""

from __future__ import annotations

import json
import sqlite3
import threading


class SlashingProtectionError(ValueError):
    """Refusing to sign: doing so would be slashable (or unsafe)."""


class SlashingDatabase:
    def __init__(self, path: str = ":memory:", genesis_validators_root: bytes = bytes(32)):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self.genesis_validators_root = genesis_validators_root
        with self._conn:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS validators ("
                " id INTEGER PRIMARY KEY, pubkey BLOB UNIQUE NOT NULL)"
            )
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS signed_blocks ("
                " validator_id INTEGER NOT NULL, slot INTEGER NOT NULL,"
                " signing_root BLOB,"
                " UNIQUE (validator_id, slot))"
            )
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS signed_attestations ("
                " validator_id INTEGER NOT NULL,"
                " source_epoch INTEGER NOT NULL, target_epoch INTEGER NOT NULL,"
                " signing_root BLOB,"
                " UNIQUE (validator_id, target_epoch))"
            )

    # -- registration ----------------------------------------------------

    def register_validator(self, pubkey: bytes) -> int:
        with self._lock, self._conn:
            row = self._conn.execute(
                "SELECT id FROM validators WHERE pubkey=?", (pubkey,)
            ).fetchone()
            if row:
                return row[0]
            cur = self._conn.execute(
                "INSERT INTO validators (pubkey) VALUES (?)", (pubkey,)
            )
            return cur.lastrowid

    def _vid(self, pubkey: bytes) -> int:
        row = self._conn.execute(
            "SELECT id FROM validators WHERE pubkey=?", (pubkey,)
        ).fetchone()
        if not row:
            raise SlashingProtectionError(
                f"unregistered validator {pubkey.hex()[:12]}"
            )
        return row[0]

    # -- blocks ----------------------------------------------------------

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        with self._lock, self._conn:
            vid = self._vid(pubkey)
            row = self._conn.execute(
                "SELECT signing_root FROM signed_blocks"
                " WHERE validator_id=? AND slot=?",
                (vid, slot),
            ).fetchone()
            if row is not None:
                if row[0] == signing_root:
                    return  # idempotent re-sign of the same block
                raise SlashingProtectionError(
                    f"double block proposal at slot {slot}"
                )
            low = self._conn.execute(
                "SELECT MAX(slot) FROM signed_blocks WHERE validator_id=?",
                (vid,),
            ).fetchone()[0]
            if low is not None and slot < low:
                raise SlashingProtectionError(
                    f"block slot {slot} below low watermark {low}"
                )
            self._conn.execute(
                "INSERT INTO signed_blocks (validator_id, slot, signing_root)"
                " VALUES (?,?,?)",
                (vid, slot, signing_root),
            )

    # -- attestations ----------------------------------------------------

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int,
        signing_root: bytes,
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingProtectionError("attestation source > target")
        with self._lock, self._conn:
            vid = self._vid(pubkey)
            row = self._conn.execute(
                "SELECT signing_root FROM signed_attestations"
                " WHERE validator_id=? AND target_epoch=?",
                (vid, target_epoch),
            ).fetchone()
            if row is not None:
                if row[0] == signing_root:
                    return
                raise SlashingProtectionError(
                    f"double vote for target epoch {target_epoch}"
                )
            # surround checks (min-max): new surrounds old / old surrounds new
            surrounds = self._conn.execute(
                "SELECT 1 FROM signed_attestations WHERE validator_id=?"
                " AND source_epoch > ? AND target_epoch < ?",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounds:
                raise SlashingProtectionError(
                    "attestation would surround an existing vote"
                )
            surrounded = self._conn.execute(
                "SELECT 1 FROM signed_attestations WHERE validator_id=?"
                " AND source_epoch < ? AND target_epoch > ?",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounded:
                raise SlashingProtectionError(
                    "attestation would be surrounded by an existing vote"
                )
            # monotone watermarks (EIP-3076 minimal conditions)
            max_source = self._conn.execute(
                "SELECT MAX(source_epoch) FROM signed_attestations"
                " WHERE validator_id=?",
                (vid,),
            ).fetchone()[0]
            if max_source is not None and source_epoch < max_source:
                # allowed by the letter of slashing rules, but EIP-3076
                # importers use max-source as the low watermark; refuse to
                # regress (matches the reference's behaviour)
                raise SlashingProtectionError(
                    f"attestation source {source_epoch} below watermark {max_source}"
                )
            max_target = self._conn.execute(
                "SELECT MAX(target_epoch) FROM signed_attestations"
                " WHERE validator_id=?",
                (vid,),
            ).fetchone()[0]
            if max_target is not None and target_epoch <= max_target:
                raise SlashingProtectionError(
                    f"attestation target {target_epoch} at/below watermark {max_target}"
                )
            self._conn.execute(
                "INSERT INTO signed_attestations"
                " (validator_id, source_epoch, target_epoch, signing_root)"
                " VALUES (?,?,?,?)",
                (vid, source_epoch, target_epoch, signing_root),
            )

    # -- interchange (EIP-3076 v5) ---------------------------------------

    def export_interchange(self) -> dict:
        with self._lock:
            data = []
            for vid, pubkey in self._conn.execute(
                "SELECT id, pubkey FROM validators ORDER BY id"
            ).fetchall():
                blocks = [
                    {
                        "slot": str(slot),
                        **(
                            {"signing_root": "0x" + root.hex()}
                            if root
                            else {}
                        ),
                    }
                    for slot, root in self._conn.execute(
                        "SELECT slot, signing_root FROM signed_blocks"
                        " WHERE validator_id=? ORDER BY slot",
                        (vid,),
                    ).fetchall()
                ]
                atts = [
                    {
                        "source_epoch": str(s),
                        "target_epoch": str(t),
                        **(
                            {"signing_root": "0x" + root.hex()}
                            if root
                            else {}
                        ),
                    }
                    for s, t, root in self._conn.execute(
                        "SELECT source_epoch, target_epoch, signing_root"
                        " FROM signed_attestations WHERE validator_id=?"
                        " ORDER BY target_epoch",
                        (vid,),
                    ).fetchall()
                ]
                data.append(
                    {
                        "pubkey": "0x" + pubkey.hex(),
                        "signed_blocks": blocks,
                        "signed_attestations": atts,
                    }
                )
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x"
                + self.genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, obj: dict) -> None:
        meta = obj["metadata"]
        if meta["interchange_format_version"] != "5":
            raise SlashingProtectionError("unsupported interchange version")
        gvr = bytes.fromhex(meta["genesis_validators_root"][2:])
        if (
            self.genesis_validators_root != bytes(32)
            and gvr != self.genesis_validators_root
        ):
            raise SlashingProtectionError("genesis_validators_root mismatch")
        with self._lock, self._conn:
            for rec in obj["data"]:
                pubkey = bytes.fromhex(rec["pubkey"][2:])
                row = self._conn.execute(
                    "SELECT id FROM validators WHERE pubkey=?", (pubkey,)
                ).fetchone()
                vid = (
                    row[0]
                    if row
                    else self._conn.execute(
                        "INSERT INTO validators (pubkey) VALUES (?)", (pubkey,)
                    ).lastrowid
                )
                for b in rec.get("signed_blocks", []):
                    root = (
                        bytes.fromhex(b["signing_root"][2:])
                        if "signing_root" in b
                        else None
                    )
                    self._conn.execute(
                        "INSERT OR REPLACE INTO signed_blocks"
                        " (validator_id, slot, signing_root) VALUES (?,?,?)",
                        (vid, int(b["slot"]), root),
                    )
                for a in rec.get("signed_attestations", []):
                    root = (
                        bytes.fromhex(a["signing_root"][2:])
                        if "signing_root" in a
                        else None
                    )
                    self._conn.execute(
                        "INSERT OR REPLACE INTO signed_attestations"
                        " (validator_id, source_epoch, target_epoch,"
                        " signing_root) VALUES (?,?,?,?)",
                        (
                            vid,
                            int(a["source_epoch"]),
                            int(a["target_epoch"]),
                            root,
                        ),
                    )

    def export_json(self) -> str:
        return json.dumps(self.export_interchange(), indent=2)

    def import_json(self, s: str) -> None:
        self.import_interchange(json.loads(s))
