"""EIP-2386 hierarchical deterministic wallets (reference:
``crypto/eth2_wallet`` — ``wallet.rs``, ``validator_path.rs``).

A wallet is a password-encrypted seed (same crypto section as an EIP-2335
keystore) plus a ``nextaccount`` counter; validators are derived at
EIP-2334 paths ``m/12381/3600/<account>/0/0``.
"""

from __future__ import annotations

import secrets
import uuid as uuid_mod

from . import keystore as ks
from .derivation import derive_sk_at_path, validator_signing_path, validator_withdrawal_path


class WalletError(ValueError):
    pass


class Wallet:
    def __init__(self, json_obj: dict):
        self.json = json_obj

    # -- creation --------------------------------------------------------

    @classmethod
    def create(
        cls, name: str, password: str, seed: bytes | None = None,
        kdf_work: int | None = None,
    ) -> "Wallet":
        seed = seed or secrets.token_bytes(32)
        enc = ks.encrypt(seed, password, kdf_work=kdf_work)
        obj = {
            "crypto": enc["crypto"],
            "name": name,
            "nextaccount": 0,
            "type": "hierarchical deterministic",
            "uuid": str(uuid_mod.uuid4()),
            "version": 1,
        }
        return cls(obj)

    # -- properties ------------------------------------------------------

    @property
    def name(self) -> str:
        return self.json["name"]

    @property
    def nextaccount(self) -> int:
        return self.json["nextaccount"]

    def decrypt_seed(self, password: str) -> bytes:
        fake_store = {"crypto": self.json["crypto"], "version": 4}
        return ks.decrypt(fake_store, password)

    # -- key derivation --------------------------------------------------

    def next_validator(
        self, wallet_password: str, keystore_password: str,
        kdf_work: int | None = None,
    ) -> tuple[dict, dict]:
        """Derive the next validator's (signing keystore, withdrawal
        keystore) and bump ``nextaccount`` (reference
        ``wallet.rs`` ``next_validator``)."""
        from ..crypto import bls

        seed = self.decrypt_seed(wallet_password)
        account = self.json["nextaccount"]
        out = []
        for path_fn in (validator_signing_path, validator_withdrawal_path):
            path = path_fn(account)
            sk_int = derive_sk_at_path(seed, path)
            sk = bls.SecretKey(sk_int)
            out.append(
                ks.encrypt(
                    sk.serialize(),
                    keystore_password,
                    path=path,
                    pubkey=sk.public_key().serialize(),
                    kdf_work=kdf_work,
                )
            )
        self.json["nextaccount"] = account + 1
        return out[0], out[1]
