"""L4c: eth1 deposit tracking — deposit log + block caches feeding
block production's eth1-data votes and deposit inclusion.

Reference: ``beacon_node/eth1`` (``src/service.rs:393`` caching service)
+ ``beacon_node/genesis`` (genesis from deposit logs).
"""

from .service import DepositLog, Eth1Block, Eth1Service, MockEth1Endpoint

__all__ = ["DepositLog", "Eth1Block", "Eth1Service", "MockEth1Endpoint"]
