"""Eth1 service: polls an eth1 endpoint for deposit-contract logs and
blocks, maintains the deposit Merkle tree, serves eth1-data votes and
deposit proofs for block production (reference:
``beacon_node/eth1/src/service.rs`` + ``deposit_cache.rs``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from ..ssz import hash_tree_root
from ..ssz.sha256 import ZERO_HASHES, hash32_concat
from ..types.containers import types_for

DEPOSIT_TREE_DEPTH = 32


@dataclass
class DepositLog:
    index: int
    pubkey: bytes
    withdrawal_credentials: bytes
    amount: int
    signature: bytes
    block_number: int


@dataclass
class Eth1Block:
    number: int
    hash: bytes
    timestamp: int
    deposit_count: int
    deposit_root: bytes


class DepositTree:
    """Incremental Merkle tree of deposit-data roots with cached levels
    (the deposit contract's tree; proofs for spec ``Deposit.proof``).

    ``levels[d][j]`` is the root of the depth-d subtree over leaves
    [j*2^d, (j+1)*2^d), with missing right children treated as zero
    subtrees. A push updates one rightmost node per level (O(depth));
    a proof reads one sibling per level (O(depth)) — the reference's
    incremental deposit tree has the same costs.

    ``proof(index, count)`` proves against the tree truncated to the
    first ``count`` leaves — deposits that arrived after an eth1-data
    vote must not perturb proofs against that vote's root.
    """

    def __init__(self):
        self.leaves: list[bytes] = []
        self.levels: list[list[bytes]] = [[] for _ in range(DEPOSIT_TREE_DEPTH + 1)]

    def push(self, leaf: bytes) -> None:
        self.leaves.append(leaf)
        self.levels[0].append(leaf)
        idx = len(self.leaves) - 1
        for d in range(1, DEPOSIT_TREE_DEPTH + 1):
            idx //= 2
            below = self.levels[d - 1]
            left = below[2 * idx]
            right = (
                below[2 * idx + 1]
                if 2 * idx + 1 < len(below)
                else ZERO_HASHES[d - 1]
            )
            node = hash32_concat(left, right)
            if idx < len(self.levels[d]):
                self.levels[d][idx] = node
            else:
                self.levels[d].append(node)

    def root(self, count: int | None = None) -> bytes:
        n = len(self.leaves) if count is None else count
        return hash32_concat(
            self._node(DEPOSIT_TREE_DEPTH, 0, n), n.to_bytes(32, "little")
        )

    def _node(self, depth: int, idx: int, count: int) -> bytes:
        """Root of the depth-``depth`` subtree at position ``idx`` with
        only the first ``count`` leaves of the whole tree present."""
        lo = idx << depth
        if lo >= count:
            return ZERO_HASHES[depth]
        if (lo + (1 << depth)) <= count:
            return self.levels[depth][idx]  # fully inside: cached
        if depth == 0:
            return self.levels[0][idx]
        return hash32_concat(
            self._node(depth - 1, 2 * idx, count),
            self._node(depth - 1, 2 * idx + 1, count),
        )

    def proof(self, index: int, count: int | None = None) -> list[bytes]:
        """Branch for leaf ``index`` against root(count)."""
        n = len(self.leaves) if count is None else count
        assert index < n <= len(self.leaves)
        path = []
        idx = index
        for d in range(DEPOSIT_TREE_DEPTH):
            sib = idx ^ 1
            path.append(self._node(d, sib, n))
            idx //= 2
        path.append(n.to_bytes(32, "little"))
        return path


class MockEth1Endpoint:
    """In-process stand-in for an eth1 JSON-RPC node (reference
    ``testing/eth1_test_rig``): hosts deposit logs + canonical blocks."""

    def __init__(self):
        self.logs: list[DepositLog] = []
        self.blocks: list[Eth1Block] = []
        self._tree = DepositTree()
        self._preset_types = None

    def add_deposit(self, pubkey: bytes, withdrawal_credentials: bytes,
                    amount: int, signature: bytes, block_number: int) -> None:
        log = DepositLog(
            index=len(self.logs),
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            amount=amount,
            signature=signature,
            block_number=block_number,
        )
        self.logs.append(log)

    def seal_block(self, number: int, timestamp: int) -> Eth1Block:
        from ..types.preset import MAINNET

        t = types_for(MAINNET)
        tree = DepositTree()
        count = 0
        for log in self.logs:
            if log.block_number <= number:
                dd = t.DepositData(
                    pubkey=log.pubkey,
                    withdrawal_credentials=log.withdrawal_credentials,
                    amount=log.amount,
                    signature=log.signature,
                )
                tree.push(hash_tree_root(dd))
                count += 1
        blk = Eth1Block(
            number=number,
            hash=hash32_concat(number.to_bytes(32, "little"), b"eth1".ljust(32, b"\0")),
            timestamp=timestamp,
            deposit_count=count,
            deposit_root=tree.root(),
        )
        self.blocks.append(blk)
        return blk

    def logs_in_range(self, lo: int, hi: int) -> list[DepositLog]:
        return [l for l in self.logs if lo <= l.block_number <= hi]

    def blocks_by_number(self) -> list[Eth1Block]:
        return sorted(self.blocks, key=lambda b: b.number)


class Eth1Service:
    """Caches deposits + blocks from an endpoint; computes the eth1-data
    vote and deposit inclusions for block production."""

    def __init__(self, endpoint: MockEth1Endpoint, preset, spec):
        self.endpoint = endpoint
        self.preset = preset
        self.spec = spec
        self.t = types_for(preset)
        self._lock = threading.Lock()
        self.deposit_tree = DepositTree()
        self.deposits: list = []  # DepositData in index order
        self.blocks: list[Eth1Block] = []

    def update(self) -> None:
        """One poll round (reference ``Service::update``)."""
        with self._lock:
            known = len(self.deposits)
            for log in self.endpoint.logs:
                if log.index < known:
                    continue
                dd = self.t.DepositData(
                    pubkey=log.pubkey,
                    withdrawal_credentials=log.withdrawal_credentials,
                    amount=log.amount,
                    signature=log.signature,
                )
                self.deposits.append(dd)
                self.deposit_tree.push(hash_tree_root(dd))
            self.blocks = self.endpoint.blocks_by_number()

    def eth1_data_vote(self, state):
        """Follow-distance eth1 data (simplified voting: latest block at
        distance; the reference tallies in-period votes too)."""
        with self._lock:
            if not self.blocks:
                return state.eth1_data
            blk = self.blocks[-1]
            return self.t.Eth1Data(
                deposit_root=blk.deposit_root,
                deposit_count=blk.deposit_count,
                block_hash=blk.hash,
            )

    def deposits_for_block(self, state, max_count: int) -> list:
        """Deposits the state still owes (spec: must include min(max,
        eth1_data.count - eth1_deposit_index) in order, with proofs)."""
        with self._lock:
            voted_count = state.eth1_data.deposit_count
            start = state.eth1_deposit_index
            end = min(voted_count, start + max_count)
            out = []
            for i in range(start, min(end, len(self.deposits))):
                out.append(
                    self.t.Deposit(
                        # proofs against the VOTED deposit count: later
                        # deposits must not invalidate them
                        proof=self.deposit_tree.proof(i, voted_count),
                        data=self.deposits[i],
                    )
                )
            return out
