"""Runtime-selectable BLS execution backends.

The reference selects its backend (``blst`` / ``milagro`` / ``fake_crypto``)
at compile time via cargo features (``crypto/bls/src/lib.rs:8-20``); here the
backend is a runtime choice — ``set_backend("tpu")`` or the
``LIGHTHOUSE_TPU_BLS_BACKEND`` environment variable — because device
availability is a runtime property on TPU hosts (this is where the
reference's north-star ``--bls-backend tpu`` flag lands, see
``lighthouse/environment/src/lib.rs``).

Backend protocol (all points are cpu-oracle affine points; the tpu backend
converts to device tensors internally):

    verify(pk_point, message, sig_point) -> bool
    fast_aggregate_verify(pk_points, message, sig_point) -> bool
    aggregate_verify(pk_points, messages, sig_point) -> bool
    verify_signature_sets([(sig, [pk_points], message32)]) -> bool
        where ``sig`` is a bls.Signature OBJECT (possibly lazy/compressed
        — the tpu backend ships its raw bytes to the device) or a bare
        G2 point; an off-curve lazy signature must yield False, never an
        exception (catch bls.BlsError)
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict

from .cpu import bls as _cpu


class CpuBackend:
    """Pure-Python backend (analogue of the reference's milagro backend)."""

    name = "cpu"

    verify = staticmethod(_cpu.verify)
    fast_aggregate_verify = staticmethod(_cpu.fast_aggregate_verify)
    aggregate_verify = staticmethod(_cpu.aggregate_verify)

    @staticmethod
    def verify_signature_sets(sets) -> bool:
        # materialize lazy signatures; a non-curve x is simply invalid
        from . import bls as _bls

        raw = []
        try:
            for sig, pks, msg in sets:
                point = sig.point if isinstance(sig, _bls.Signature) else sig
                if point is None:
                    return False
                raw.append((point, pks, msg))
        except _bls.BlsError:
            return False
        return _cpu.verify_signature_sets(raw)


class FakeBackend:
    """Always-valid backend for tests that ignore crypto (reference:
    crypto/bls/src/impls/fake_crypto.rs). Keeps the reference's edge
    semantics: an empty batch / empty signing keys still fail."""

    name = "fake"

    @staticmethod
    def verify(pk, message, sig) -> bool:
        return True

    @staticmethod
    def fast_aggregate_verify(pks, message, sig) -> bool:
        return bool(pks)

    @staticmethod
    def aggregate_verify(pks, messages, sig) -> bool:
        return bool(pks) and len(pks) == len(messages)

    @staticmethod
    def verify_signature_sets(sets) -> bool:
        sets = list(sets)
        return bool(sets) and all(pks for _, pks, _ in sets)


def _native_backend():
    from .native import NativeBackend

    return NativeBackend()


_REGISTRY: Dict[str, Callable[[], object]] = {
    "cpu": lambda: CpuBackend(),
    "cpu-native": _native_backend,
    "fake": lambda: FakeBackend(),
}

_lock = threading.Lock()
_active = None
_active_name = None


def register(name: str, factory: Callable[[], object]) -> None:
    _REGISTRY[name] = factory


def set_backend(name: str) -> None:
    global _active, _active_name
    with _lock:
        if name not in _REGISTRY and name == "tpu":
            from . import device  # noqa: F401  (registers "tpu")
        if name not in _REGISTRY:
            raise KeyError(f"unknown BLS backend {name!r}; have {sorted(_REGISTRY)}")
        _active = _REGISTRY[name]()
        _active_name = name


def active():
    global _active, _active_name
    if _active is None:
        with _lock:
            if _active is None:
                name = os.environ.get("LIGHTHOUSE_TPU_BLS_BACKEND", "cpu")
                if name not in _REGISTRY and name == "tpu":
                    # Lazily register the device backend on first request.
                    from . import device  # noqa: F401  (registers "tpu")
                _active = _REGISTRY[name]()
                _active_name = name
    return _active


def active_name() -> str:
    active()
    return _active_name
