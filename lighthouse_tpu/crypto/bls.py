"""Public BLS API: the generic-backend seam.

Mirrors the reference's ``crypto/bls`` generic layer
(``/root/reference/crypto/bls/src/lib.rs:99-140``): wrapper types carry both
serialized bytes and the decompressed point; *all* serious cryptography is
deferred to a runtime-selectable backend (``fake`` / ``cpu`` / ``tpu``),
where the reference selects ``blst``/``milagro``/``fake_crypto`` at compile
time. Deserialization rules follow the reference:

* public keys: 48 bytes, must decompress onto the curve, subgroup-checked,
  infinity rejected (``generic_public_key.rs``);
* signatures: 96 bytes, the all-zero encoding is the valid "empty"
  (infinity) signature (``generic_signature.rs``, ``INFINITY_SIGNATURE``).
"""

from __future__ import annotations

from typing import Optional, Sequence

from . import backend as _backend
from .cpu import bls as _cpu
from .cpu.curve import G1Point, G2Point
from .params import P as P_MOD, DST, PUBLIC_KEY_BYTES, R, SECRET_KEY_BYTES, SIGNATURE_BYTES

INFINITY_SIGNATURE = bytes([0xC0] + [0] * 95)
INFINITY_PUBLIC_KEY = bytes([0xC0] + [0] * 47)


class BlsError(ValueError):
    pass


class PublicKey:
    """A decompressed, subgroup-checked G1 public key."""

    __slots__ = ("point", "_bytes")

    def __init__(self, point: G1Point, raw: Optional[bytes] = None):
        self.point = point
        self._bytes = raw

    @classmethod
    def deserialize(cls, data: bytes) -> "PublicKey":
        if len(data) != PUBLIC_KEY_BYTES:
            raise BlsError(f"invalid pubkey length {len(data)}")
        try:
            point = G1Point.decompress(data)
        except ValueError as e:
            raise BlsError(str(e)) from e
        if point.is_infinity():
            raise BlsError("infinity public key is invalid")
        if not point.in_subgroup():
            raise BlsError("public key not in subgroup")
        return cls(point, bytes(data))

    def serialize(self) -> bytes:
        if self._bytes is None:
            self._bytes = self.point.compress()
        return self._bytes

    def __eq__(self, o):
        return isinstance(o, PublicKey) and self.serialize() == o.serialize()

    def __hash__(self):
        return hash(self.serialize())

    def __repr__(self):
        return f"PublicKey(0x{self.serialize().hex()})"


def parse_compressed_g2_x(data: bytes) -> tuple[int, int, bool]:
    """Structural parse of a compressed G2 encoding -> (x0, x1,
    sign_larger). Validates length, compression flag, range; the on-curve
    check (sqrt) is the caller's business (host decompress or device)."""
    if len(data) != SIGNATURE_BYTES:
        raise BlsError(f"invalid signature length {len(data)}")
    data = bytes(data)
    flags = data[0] >> 5
    if not flags & 0x4:
        raise BlsError("uncompressed G2 encoding not supported")
    if flags & 0x2:
        raise BlsError("infinity encoding has no x")
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P_MOD or x1 >= P_MOD:
        raise BlsError("x out of range")
    return x0, x1, bool(flags & 0x1)


class Signature:
    """A G2 signature; ``point`` is None for the "empty" (infinity)
    encoding.

    Decompression is LAZY: ``deserialize`` performs only the cheap
    structural checks (length, flags, x-range, infinity well-formedness)
    and defers the square root until ``point`` is touched — the TPU
    backend never touches it (G2 decompression runs ON DEVICE,
    ``crypto/device/bls.py``), which removes ~10 ms of host big-int math
    per gossip signature. A non-curve x (sqrt fails) therefore surfaces
    at USE time as BlsError; batch verifiers contain it as a normal
    invalid-signature outcome."""

    __slots__ = ("_point", "_bytes", "_decompressed")

    def __init__(self, point: Optional[G2Point] = None, raw: Optional[bytes] = None):
        self._point = point
        self._bytes = raw
        self._decompressed = point is not None or raw is None

    @property
    def point(self) -> Optional[G2Point]:
        if not self._decompressed:
            if bytes(self._bytes) == INFINITY_SIGNATURE:
                self._point = None
            else:
                try:
                    self._point = G2Point.decompress(self._bytes)
                except ValueError as e:
                    raise BlsError(str(e)) from e
            self._decompressed = True
        return self._point

    @classmethod
    def deserialize(cls, data: bytes) -> "Signature":
        if len(data) != SIGNATURE_BYTES:
            raise BlsError(f"invalid signature length {len(data)}")
        data = bytes(data)
        if (data[0] >> 5) & 0x2:  # infinity must be the canonical encoding
            if data != INFINITY_SIGNATURE:
                raise BlsError("malformed infinity encoding")
            return cls(None, INFINITY_SIGNATURE)
        parse_compressed_g2_x(data)  # structural validation
        return cls(None, data)  # sqrt (on-curve check) deferred

    @classmethod
    def infinity(cls) -> "Signature":
        return cls(None, INFINITY_SIGNATURE)

    def serialize(self) -> bytes:
        if self._bytes is None:
            self._bytes = (
                INFINITY_SIGNATURE if self.point is None else self.point.compress()
            )
        return self._bytes

    def point_or_infinity(self) -> G2Point:
        return G2Point.infinity() if self.point is None else self.point

    def is_infinity(self) -> bool:
        if not self._decompressed and self._bytes is not None:
            return bytes(self._bytes) == INFINITY_SIGNATURE
        return self._point is None or self._point.is_infinity()

    def verify(self, pk: PublicKey, message: bytes) -> bool:
        return _backend.active().verify(pk.point, message, self.point_or_infinity())

    def __eq__(self, o):
        return isinstance(o, Signature) and self.serialize() == o.serialize()

    def __hash__(self):
        return hash(self.serialize())

    def __repr__(self):
        return f"Signature(0x{self.serialize().hex()})"


class AggregateSignature(Signature):
    """A signature accumulating others by point addition (reference:
    generic_aggregate_signature.rs add_assign / add_assign_aggregate)."""

    @classmethod
    def infinity(cls) -> "AggregateSignature":
        return cls(None, INFINITY_SIGNATURE)

    def add_assign(self, other: Signature) -> None:
        if other.point is None:
            return
        if self.point is None:
            self._point = other.point
        else:
            self._point = self.point + other.point
        self._decompressed = True
        self._bytes = None

    def fast_aggregate_verify(self, message: bytes, pks: Sequence[PublicKey]) -> bool:
        if not pks:
            return False
        return _backend.active().fast_aggregate_verify(
            [pk.point for pk in pks], message, self.point_or_infinity()
        )

    def aggregate_verify(
        self, messages: Sequence[bytes], pks: Sequence[PublicKey]
    ) -> bool:
        if not pks or len(pks) != len(messages):
            return False
        return _backend.active().aggregate_verify(
            [pk.point for pk in pks], list(messages), self.point_or_infinity()
        )


class SecretKey:
    __slots__ = ("k",)

    def __init__(self, k: int):
        if not 0 < k < R:
            raise BlsError("secret key out of range")
        self.k = k

    @classmethod
    def deserialize(cls, data: bytes) -> "SecretKey":
        if len(data) != SECRET_KEY_BYTES:
            raise BlsError("invalid secret key length")
        return cls(int.from_bytes(data, "big"))

    def serialize(self) -> bytes:
        return self.k.to_bytes(SECRET_KEY_BYTES, "big")

    def public_key(self) -> PublicKey:
        # Fast path through the C library when a toolchain exists (a
        # pure-Python G1 scalar mul is ~100 ms — it made large interop
        # genesis states take minutes); oracle fallback otherwise.
        try:
            from .cpu.fields import Fq
            from .native import native_sk_to_pk_xy

            x, y = native_sk_to_pk_xy(self.k)
            return PublicKey(G1Point(Fq(x), Fq(y)))
        except Exception:
            return PublicKey(_cpu.sk_to_pk(self.k))

    def sign(self, message: bytes) -> Signature:
        # Same native fast path as public_key(): ~2 ms vs ~200 ms for the
        # oracle's pure-Python hash-to-curve + G2 scalar mul.
        try:
            from .native import native_sign

            return Signature.deserialize(native_sign(self.k, bytes(message)))
        except Exception:
            return Signature(_cpu.sign(self.k, message))


class SignatureSet:
    """A signature over one message by one or more public keys — the unit
    of batch verification (reference: generic_signature_set.rs:61-107).

    ``signing_indices`` optionally carries the validator indices the
    keys were resolved at (state_transition/signature_sets.py threads
    them): the device key table's flush-planner classification uses
    them as a fast static/dynamic pre-filter
    (crypto/device/key_table.py). They are advisory — the backend's
    index resolution is identity-pinned to the host pubkey cache's own
    point objects, so a stale or foreign index can cost a raw-plane
    fallback but never a wrong-key verification."""

    __slots__ = ("signature", "signing_keys", "message", "signing_indices")

    def __init__(
        self,
        signature: Signature,
        signing_keys: Sequence[PublicKey],
        message: bytes,
        signing_indices: "Optional[Sequence[int]]" = None,
    ):
        if len(message) != 32:
            raise BlsError("message must be a 32-byte signing root")
        self.signature = signature
        self.signing_keys = list(signing_keys)
        self.message = bytes(message)
        if signing_indices is not None:
            signing_indices = [int(i) for i in signing_indices]
            if len(signing_indices) != len(self.signing_keys):
                raise BlsError(
                    "signing_indices must match signing_keys one-to-one"
                )
        self.signing_indices = signing_indices

    @classmethod
    def single_pubkey(
        cls, signature: Signature, signing_key: PublicKey, message: bytes,
        signing_index: "Optional[int]" = None,
    ) -> "SignatureSet":
        return cls(
            signature, [signing_key], message,
            None if signing_index is None else [signing_index],
        )

    @classmethod
    def multiple_pubkeys(
        cls, signature: Signature, signing_keys: Sequence[PublicKey],
        message: bytes, signing_indices: "Optional[Sequence[int]]" = None,
    ) -> "SignatureSet":
        return cls(signature, signing_keys, message, signing_indices)

    def verify(self) -> bool:
        """Verify just this set (fast_aggregate_verify)."""
        return AggregateSignature(
            self.signature.point, self.signature.serialize()
        ).fast_aggregate_verify(self.message, self.signing_keys)


def verify_signature_sets(sets: Sequence[SignatureSet]) -> bool:
    """Batch-verify; `True` iff every set verifies (modulo the standard
    2^-64 random-linear-combination soundness).

    Backends receive the SIGNATURE OBJECTS (not decompressed points): the
    tpu backend ships raw compressed bytes to the device and decompresses
    there; the cpu backend materializes points lazily. A signature whose
    x is not on the curve (lazy decompress fails) is an ordinary invalid
    outcome, never an exception."""
    sets = list(sets)
    if not sets:
        return False
    prepared = []
    for s in sets:
        # An "empty" (infinity-encoded) signature fails the whole batch
        # before reaching any backend (blst.rs:77-83).
        if s.signature.is_infinity():
            return False
        prepared.append(
            (s.signature, [pk.point for pk in s.signing_keys], s.message)
        )
    try:
        return _backend.active().verify_signature_sets(prepared)
    except BlsError:
        return False


__all__ = [
    "AggregateSignature",
    "BlsError",
    "DST",
    "INFINITY_SIGNATURE",
    "PublicKey",
    "SecretKey",
    "Signature",
    "SignatureSet",
    "verify_signature_sets",
]
