"""Public BLS API: the generic-backend seam.

Mirrors the reference's ``crypto/bls`` generic layer
(``/root/reference/crypto/bls/src/lib.rs:99-140``): wrapper types carry both
serialized bytes and the decompressed point; *all* serious cryptography is
deferred to a runtime-selectable backend (``fake`` / ``cpu`` / ``tpu``),
where the reference selects ``blst``/``milagro``/``fake_crypto`` at compile
time. Deserialization rules follow the reference:

* public keys: 48 bytes, must decompress onto the curve, subgroup-checked,
  infinity rejected (``generic_public_key.rs``);
* signatures: 96 bytes, the all-zero encoding is the valid "empty"
  (infinity) signature (``generic_signature.rs``, ``INFINITY_SIGNATURE``).
"""

from __future__ import annotations

from typing import Optional, Sequence

from . import backend as _backend
from .cpu import bls as _cpu
from .cpu.curve import G1Point, G2Point
from .params import DST, PUBLIC_KEY_BYTES, R, SECRET_KEY_BYTES, SIGNATURE_BYTES

INFINITY_SIGNATURE = bytes([0xC0] + [0] * 95)
INFINITY_PUBLIC_KEY = bytes([0xC0] + [0] * 47)


class BlsError(ValueError):
    pass


class PublicKey:
    """A decompressed, subgroup-checked G1 public key."""

    __slots__ = ("point", "_bytes")

    def __init__(self, point: G1Point, raw: Optional[bytes] = None):
        self.point = point
        self._bytes = raw

    @classmethod
    def deserialize(cls, data: bytes) -> "PublicKey":
        if len(data) != PUBLIC_KEY_BYTES:
            raise BlsError(f"invalid pubkey length {len(data)}")
        try:
            point = G1Point.decompress(data)
        except ValueError as e:
            raise BlsError(str(e)) from e
        if point.is_infinity():
            raise BlsError("infinity public key is invalid")
        if not point.in_subgroup():
            raise BlsError("public key not in subgroup")
        return cls(point, bytes(data))

    def serialize(self) -> bytes:
        if self._bytes is None:
            self._bytes = self.point.compress()
        return self._bytes

    def __eq__(self, o):
        return isinstance(o, PublicKey) and self.serialize() == o.serialize()

    def __hash__(self):
        return hash(self.serialize())

    def __repr__(self):
        return f"PublicKey(0x{self.serialize().hex()})"


class Signature:
    """A G2 signature; ``point`` is None for the "empty" (infinity) encoding."""

    __slots__ = ("point", "_bytes")

    def __init__(self, point: Optional[G2Point], raw: Optional[bytes] = None):
        self.point = point
        self._bytes = raw

    @classmethod
    def deserialize(cls, data: bytes) -> "Signature":
        if len(data) != SIGNATURE_BYTES:
            raise BlsError(f"invalid signature length {len(data)}")
        if bytes(data) == INFINITY_SIGNATURE:
            return cls(None, INFINITY_SIGNATURE)
        try:
            point = G2Point.decompress(data)
        except ValueError as e:
            raise BlsError(str(e)) from e
        return cls(point, bytes(data))

    @classmethod
    def infinity(cls) -> "Signature":
        return cls(None, INFINITY_SIGNATURE)

    def serialize(self) -> bytes:
        if self._bytes is None:
            self._bytes = (
                INFINITY_SIGNATURE if self.point is None else self.point.compress()
            )
        return self._bytes

    def point_or_infinity(self) -> G2Point:
        return G2Point.infinity() if self.point is None else self.point

    def is_infinity(self) -> bool:
        return self.point is None or self.point.is_infinity()

    def verify(self, pk: PublicKey, message: bytes) -> bool:
        return _backend.active().verify(pk.point, message, self.point_or_infinity())

    def __eq__(self, o):
        return isinstance(o, Signature) and self.serialize() == o.serialize()

    def __hash__(self):
        return hash(self.serialize())

    def __repr__(self):
        return f"Signature(0x{self.serialize().hex()})"


class AggregateSignature(Signature):
    """A signature accumulating others by point addition (reference:
    generic_aggregate_signature.rs add_assign / add_assign_aggregate)."""

    @classmethod
    def infinity(cls) -> "AggregateSignature":
        return cls(None, INFINITY_SIGNATURE)

    def add_assign(self, other: Signature) -> None:
        if other.point is None:
            return
        if self.point is None:
            self.point = other.point
        else:
            self.point = self.point + other.point
        self._bytes = None

    def fast_aggregate_verify(self, message: bytes, pks: Sequence[PublicKey]) -> bool:
        if not pks:
            return False
        return _backend.active().fast_aggregate_verify(
            [pk.point for pk in pks], message, self.point_or_infinity()
        )

    def aggregate_verify(
        self, messages: Sequence[bytes], pks: Sequence[PublicKey]
    ) -> bool:
        if not pks or len(pks) != len(messages):
            return False
        return _backend.active().aggregate_verify(
            [pk.point for pk in pks], list(messages), self.point_or_infinity()
        )


class SecretKey:
    __slots__ = ("k",)

    def __init__(self, k: int):
        if not 0 < k < R:
            raise BlsError("secret key out of range")
        self.k = k

    @classmethod
    def deserialize(cls, data: bytes) -> "SecretKey":
        if len(data) != SECRET_KEY_BYTES:
            raise BlsError("invalid secret key length")
        return cls(int.from_bytes(data, "big"))

    def serialize(self) -> bytes:
        return self.k.to_bytes(SECRET_KEY_BYTES, "big")

    def public_key(self) -> PublicKey:
        return PublicKey(_cpu.sk_to_pk(self.k))

    def sign(self, message: bytes) -> Signature:
        return Signature(_cpu.sign(self.k, message))


class SignatureSet:
    """A signature over one message by one or more public keys — the unit
    of batch verification (reference: generic_signature_set.rs:61-107)."""

    __slots__ = ("signature", "signing_keys", "message")

    def __init__(
        self,
        signature: Signature,
        signing_keys: Sequence[PublicKey],
        message: bytes,
    ):
        if len(message) != 32:
            raise BlsError("message must be a 32-byte signing root")
        self.signature = signature
        self.signing_keys = list(signing_keys)
        self.message = bytes(message)

    @classmethod
    def single_pubkey(
        cls, signature: Signature, signing_key: PublicKey, message: bytes
    ) -> "SignatureSet":
        return cls(signature, [signing_key], message)

    @classmethod
    def multiple_pubkeys(
        cls, signature: Signature, signing_keys: Sequence[PublicKey], message: bytes
    ) -> "SignatureSet":
        return cls(signature, signing_keys, message)

    def verify(self) -> bool:
        """Verify just this set (fast_aggregate_verify)."""
        return AggregateSignature(
            self.signature.point, self.signature.serialize()
        ).fast_aggregate_verify(self.message, self.signing_keys)


def verify_signature_sets(sets: Sequence[SignatureSet]) -> bool:
    """Batch-verify; `True` iff every set verifies (modulo the standard
    2^-64 random-linear-combination soundness)."""
    sets = list(sets)
    if not sets:
        return False
    raw = []
    for s in sets:
        # An "empty" (infinity-encoded) signature fails the whole batch
        # before reaching any backend (blst.rs:77-83).
        if s.signature.point is None:
            return False
        raw.append(
            (s.signature.point, [pk.point for pk in s.signing_keys], s.message)
        )
    return _backend.active().verify_signature_sets(raw)


__all__ = [
    "AggregateSignature",
    "BlsError",
    "DST",
    "INFINITY_SIGNATURE",
    "PublicKey",
    "SecretKey",
    "Signature",
    "SignatureSet",
    "verify_signature_sets",
]
