"""Backend ``"cpu-native"``: the C BLS12-381 verifier (`_native/bls12381.c`).

This is the blst-class CPU baseline demanded by BASELINE.md — the
reference's default backend is blst's assembly implementation
(``/root/reference/crypto/bls/src/impls/blst.rs:36-119``); the pure-Python
``cpu`` backend is an oracle, orders of magnitude too slow to stand in for
it. ``vs_baseline`` in bench.py is computed against THIS backend.

Signatures cross the FFI boundary in their compressed wire form (the C
side decompresses, curve- and subgroup-checks); public keys cross as raw
affine coordinates because they were already decompressed and
KeyValidate'd at admission (``ValidatorPubkeyCache`` — mirroring the
reference's decompress-once rule, ``validator_pubkey_cache.rs:79``).
"""

from __future__ import annotations

import ctypes
import secrets

from .params import DST


class NativeUnavailable(RuntimeError):
    pass


_lib = None
_lib_error: Exception | None = None


def lib() -> ctypes.CDLL:
    global _lib, _lib_error
    if _lib_error is not None:
        raise _lib_error  # build/selftest failure is permanent per process
    if _lib is None:
        try:
            return _load()
        except Exception as e:
            _lib_error = e
            raise
    return _lib


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        from .. import _native

        handle = _native.build_and_load("bls12381")
        if handle is None:
            raise NativeUnavailable(
                "no C compiler / build failed for _native/bls12381.c"
            )
        handle.bls_verify_signature_sets.restype = ctypes.c_int
        handle.bls_aggregate_verify.restype = ctypes.c_int
        handle.bls_g1_pubkey_check.restype = ctypes.c_int
        handle.bls_hash_to_g2.restype = ctypes.c_int
        handle.bls_sign.restype = ctypes.c_int
        handle.bls_sk_to_pk.restype = ctypes.c_int
        handle.bls_selftest.restype = ctypes.c_int
        if handle.bls_selftest() != 1:
            raise NativeUnavailable("bls12381.c selftest failed")
        _lib = handle
    return _lib


def _pk_raw(point) -> bytes:
    """G1 affine oracle point -> 96 raw big-endian bytes (x || y)."""
    return point.x.n.to_bytes(48, "big") + point.y.n.to_bytes(48, "big")


def _sig_compressed(sig) -> bytes | None:
    """Signature object or bare G2 point -> compressed bytes; None for a
    structurally-invalid input (treated as verification failure)."""
    from . import bls as _bls

    if isinstance(sig, _bls.Signature):
        return bytes(sig.serialize())
    try:
        return bytes(sig.compress())
    except Exception:
        return None


def _rand8() -> bytes:
    while True:
        r = secrets.token_bytes(8)
        if any(r):
            return r


def native_sk_to_pk_xy(sk_int: int) -> tuple[int, int]:
    """[sk] g1 as affine (x, y) ints via the C library — used by the
    Python SecretKey.public_key() fast path (a pure-Python G1 scalar mul
    is ~100 ms; this is ~1 ms, which is the difference between a 4096-
    validator interop genesis taking minutes vs seconds)."""
    out = (ctypes.c_uint8 * 96)()
    rc = lib().bls_sk_to_pk(sk_int.to_bytes(32, "big"), out)
    if rc != 1:
        raise NativeUnavailable("bls_sk_to_pk failed")
    raw = bytes(out)
    return int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:], "big")


def native_sign(sk_int: int, signing_root: bytes) -> bytes:
    """[sk] H(root) as compressed bytes via the C library — a fast signer
    for benchmark/test workload generation (~ms instead of the oracle's
    pure-Python hash-to-curve + scalar mul)."""
    out = (ctypes.c_uint8 * 96)()
    rc = lib().bls_sign(
        sk_int.to_bytes(32, "big"), bytes(signing_root), len(signing_root),
        DST, len(DST), out,
    )
    if rc != 1:
        raise NativeUnavailable("bls_sign failed")
    return bytes(out)


class NativeBackend:
    """Runtime backend ``"cpu-native"`` — same protocol as the others
    (see crypto/backend.py docstring)."""

    name = "cpu-native"

    def __init__(self):
        lib()  # build + selftest at selection time, not first verify

    # -- batch verification (the hot path) -------------------------------

    def verify_signature_sets(self, sets) -> bool:
        from . import bls as _bls

        sets = list(sets)
        if not sets:
            return False
        sigs = []
        pk_parts = []
        counts = []
        msgs = []
        try:
            for sig, pks, msg in sets:
                pks = list(pks)
                if not pks:
                    return False
                if isinstance(sig, _bls.Signature) and sig.is_infinity():
                    return False
                comp = _sig_compressed(sig)
                if comp is None:
                    return False
                for pk in pks:
                    if pk.is_infinity():
                        return False
                    pk_parts.append(_pk_raw(pk))
                sigs.append(comp)
                counts.append(len(pks))
                msgs.append(bytes(msg))
        except _bls.BlsError:
            return False
        n = len(sets)
        c_counts = (ctypes.c_uint32 * n)(*counts)
        rands = b"".join(_rand8() for _ in range(n))
        rc = lib().bls_verify_signature_sets(
            n,
            b"".join(sigs),
            b"".join(pk_parts),
            c_counts,
            b"".join(msgs),
            rands,
            DST,
            len(DST),
        )
        return rc == 1

    # -- single-set entry points -----------------------------------------

    def verify(self, pk, message, sig) -> bool:
        if pk.is_infinity():
            return False
        return self.verify_signature_sets([(sig, [pk], message)])

    def fast_aggregate_verify(self, pks, message, sig) -> bool:
        pks = list(pks)
        if not pks:
            return False
        return self.verify_signature_sets([(sig, pks, message)])

    def aggregate_verify(self, pks, messages, sig) -> bool:
        pks, messages = list(pks), list(messages)
        if not pks or len(pks) != len(messages):
            return False
        if any(pk.is_infinity() for pk in pks):
            return False
        comp = _sig_compressed(sig)
        if comp is None:
            return False
        rc = lib().bls_aggregate_verify(
            len(pks),
            comp,
            b"".join(_pk_raw(pk) for pk in pks),
            b"".join(bytes(m) for m in messages),
            DST,
            len(DST),
        )
        return rc == 1
