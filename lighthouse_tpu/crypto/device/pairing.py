"""Batched optimal-ate pairing on BLS12-381, device-side.

Everything is batched over leading dims and branch-free (selects only), so
one jitted graph serves any number of (G1, G2) pairs. The structure is the
TPU-idiomatic version of what blst's ``verify_multiple_aggregate_signatures``
does on CPU (``/root/reference/crypto/bls/src/impls/blst.rs:114-118``):
shared Miller loops, one product, one final exponentiation.

Differences from the host oracle (``crypto/cpu/pairing.py``), which works
affine over Fq12 with per-step inversions:

* G2 points stay on the twist E'(Fp2) in **Jacobian projective** form —
  no inversions inside the loop.
* Line functions are evaluated in **sparse form**. Derivation: untwisting
  ``(x', y') -> (x'/w^2, y'/w^3)`` maps the affine line
  ``l = m*(xP - xT) - (yP - yT)`` to
  ``l = -yP + (m xP) w^-1 + (yT - m xT) w^-3``; scaling by the slope
  denominator (an Fp2 value — final exponentiation kills any Fp2 factor,
  since ``(p^2-1) | (p^12-1)/r``) and by ``xi = w^6`` gives the
  polynomial sparse element ``s0 + s_v w^3 + s_v2 w^5`` with

      dbl step (T=(X,Y,Z) Jacobian):  s0 = -2YZ^3 yP * xi,
          s_v = 2Y^2 - 3X^3,          s_v2 = 3X^2 Z^2 xP
      add step (Q=(x2,y2) affine):    s0 = -HZ yP * xi,
          s_v = HZ y2 - R x2,         s_v2 = R xP
          with H = x2 Z^2 - X, R = y2 Z^3 - Y

  In the 2-3-2 tower, ``w^3 = v w`` and ``w^5 = v^2 w``, so the sparse
  element occupies slots (c0.c0, c1.c1, c1.c2) and multiplies a general
  Fp12 element in 18 Fp2 muls (vs 27 generic).
* The final-exponentiation hard part uses the x-chain
  ``d = (x-1)^2 (x+p) (x^2+p^2-1)/3 + 1`` — machine-verified against
  ``(p^4-p^2+1)/r`` at import — with conjugation standing in for
  inversion on unitary values.
"""

from __future__ import annotations

import contextlib
import os

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..params import P, R, X
from . import curve, fp, fp2, tower

X_ABS = -X  # 0xd201000000010000, the positive BLS parameter


# ---------------------------------------------------------------------------
# Line-evaluation engine seam (ISSUE 16)
#
# ``composed`` emits each Miller-loop step as ~13-15 individual fp2
# dispatches; ``fused`` restructures the same formulas into
# dependency-leveled ``fp2.mul_pairs``/``fp2.sq_batch`` batches (5-6
# dispatches per step), which both shrinks the staged HLO bodies and
# hands whole batches to the fused Fp2 Pallas kernel when that engine is
# active. Identical canonical values either way (differentially pinned).
# ---------------------------------------------------------------------------

IMPL_LINE_COMPOSED = "composed"
IMPL_LINE_FUSED = "fused"

_LINE_IMPLS = (IMPL_LINE_COMPOSED, IMPL_LINE_FUSED)

_active_line_impl = os.environ.get(
    "LIGHTHOUSE_TPU_LINE_IMPL", IMPL_LINE_COMPOSED
)
if _active_line_impl not in _LINE_IMPLS:
    raise KeyError(
        f"LIGHTHOUSE_TPU_LINE_IMPL={_active_line_impl!r} unknown; "
        f"have {sorted(_LINE_IMPLS)}"
    )


def get_line_impl() -> str:
    return _active_line_impl


def set_line_impl(name: str) -> None:
    """Select the line-eval step shape. Trace-time dispatch: callers
    holding jitted programs must call ``device.reset_compiled_state()``
    afterwards (same contract as ``fp.set_impl``)."""
    global _active_line_impl
    if name not in _LINE_IMPLS:
        raise KeyError(
            f"unknown line impl {name!r}; have {sorted(_LINE_IMPLS)}"
        )
    _active_line_impl = name


@contextlib.contextmanager
def line_impl(name: str):
    """Scoped line-impl switch (restores the previous choice)."""
    prev = _active_line_impl
    set_line_impl(name)
    try:
        yield
    finally:
        set_line_impl(prev)


# ---------------------------------------------------------------------------
# Sparse line element: (s0, sv, sv2) occupying Fp12 slots c0.c0, c1.c1, c1.c2
# ---------------------------------------------------------------------------

def mul_by_line(f, s0, sv, sv2):
    """General Fp12 times the sparse line element; the 18 Fp2 products go
    through one batched fp.mul."""
    a, b = tower.c0(f), tower.c1(f)  # Fp6 halves
    a0, a1, a2 = tower.f6_c(a, 0), tower.f6_c(a, 1), tower.f6_c(a, 2)
    b0, b1, b2 = tower.f6_c(b, 0), tower.f6_c(b, 1), tower.f6_c(b, 2)
    xi = fp2.mul_by_u_plus_1

    p = fp2.mul_pairs(
        [
            (a0, s0), (a1, s0), (a2, s0),        # a*L0
            (b0, s0), (b1, s0), (b2, s0),        # b*L0
            (b1, sv2), (b2, sv), (b0, sv), (b2, sv2), (b0, sv2), (b1, sv),  # b*L1
            (a1, sv2), (a2, sv), (a0, sv), (a2, sv2), (a0, sv2), (a1, sv),  # a*L1
        ]
    )
    a_l0 = tower.f6_pack(p[0], p[1], p[2])
    b_l0 = tower.f6_pack(p[3], p[4], p[5])
    bl1 = tower.f6_pack(
        xi(fp2.add(p[6], p[7])), fp2.add(p[8], xi(p[9])), fp2.add(p[10], p[11])
    )
    al1 = tower.f6_pack(
        xi(fp2.add(p[12], p[13])), fp2.add(p[14], xi(p[15])), fp2.add(p[16], p[17])
    )
    return tower.pack(
        tower.f6_add(a_l0, tower.f6_mul_by_v(bl1)),
        tower.f6_add(al1, b_l0),
    )


def _scale_batch(pairs):
    """[(fp2 elem, fp scalar)] -> [elem * scalar] with every component
    product in ONE fp.mul (the fused-step spelling of fp2.scale)."""
    xs = fp2._bstack([x for x, _ in pairs], -3)
    ks = fp2._bstack([k[..., None, :] for _, k in pairs], -3)
    t = fp.mul(xs, ks)
    return [t[..., i, :, :] for i in range(len(pairs))]


def _dbl_step(T, xP, yP):
    """Jacobian doubling of T on E'(Fp2) + sparse line coefficients at
    P = (xP, yP) in G1 affine, under the active line engine. Returns
    (T2, s0, sv, sv2)."""
    if _active_line_impl == IMPL_LINE_FUSED:
        return _dbl_step_fused(T, xP, yP)
    return _dbl_step_composed(T, xP, yP)


def _dbl_step_composed(T, xP, yP):
    Xc, Yc, Zc = T
    A = fp2.sq(Xc)              # X^2
    B = fp2.sq(Yc)              # Y^2
    C = fp2.sq(B)               # Y^4
    D = fp2.sub(fp2.sq(fp2.add(Xc, B)), fp2.add(A, C))
    D = fp2.add(D, D)           # 4XY^2
    E = fp2.add(fp2.add(A, A), A)  # 3X^2
    F = fp2.sq(E)
    X3 = fp2.sub(F, fp2.add(D, D))
    Y3 = fp2.sub(fp2.mul(E, fp2.sub(D, X3)), fp2.mul_small(C, 8))
    Z3 = fp2.mul(fp2.add(Yc, Yc), Zc)  # 2YZ

    Z2 = fp2.sq(Zc)
    # s0 = -2YZ^3 * yP * xi; 2YZ^3 = Z3 * Z2
    z3z2 = fp2.mul(Z3, Z2)
    s0 = fp2.mul_by_u_plus_1(fp2.neg(fp2.scale(z3z2, yP)))
    # sv = 2Y^2 - 3X^3
    sv = fp2.sub(fp2.add(B, B), fp2.mul(E, Xc))
    # sv2 = 3X^2 Z^2 * xP
    sv2 = fp2.scale(fp2.mul(E, Z2), xP)
    return (X3, Y3, Z3), s0, sv, sv2


def _dbl_step_fused(T, xP, yP):
    """Same doubling + line formulas, restructured into dependency-leveled
    batches: 3 squaring/mul batches + 1 product + 1 scale batch."""
    Xc, Yc, Zc = T
    A, B, Z2 = fp2.sq_batch([Xc, Yc, Zc])
    E = fp2.add(fp2.add(A, A), A)  # 3X^2
    C, XB2, F = fp2.sq_batch([B, fp2.add(Xc, B), E])
    D = fp2.sub(XB2, fp2.add(A, C))
    D = fp2.add(D, D)              # 4XY^2
    X3 = fp2.sub(F, fp2.add(D, D))
    EdX, Z3, EX, EZ2 = fp2.mul_pairs(
        [(E, fp2.sub(D, X3)), (fp2.add(Yc, Yc), Zc), (E, Xc), (E, Z2)]
    )
    Y3 = fp2.sub(EdX, fp2.mul_small(C, 8))
    sv = fp2.sub(fp2.add(B, B), EX)          # 2Y^2 - 3X^3
    (z3z2,) = fp2.mul_pairs([(Z3, Z2)])      # 2YZ^3
    s0c, sv2 = _scale_batch([(z3z2, yP), (EZ2, xP)])
    s0 = fp2.mul_by_u_plus_1(fp2.neg(s0c))
    return (X3, Y3, Z3), s0, sv, sv2


# ---------------------------------------------------------------------------
# Miller loop (batched)
# ---------------------------------------------------------------------------

_XBITS = np.array([int(b) for b in bin(X_ABS)[2:]], np.int32)


def miller_loop(g1_aff, g2_aff):
    """f_{|x|,Q}(P) conjugated (negative parameter), batched.

    ``g1_aff = (x, y, inf)`` with x,y fp [..., 32]; ``g2_aff = (x, y, inf)``
    with x,y fp2 [..., 2, 32]. Lanes where either point is at infinity
    yield one (so they do not affect a product of Miller values).
    """
    xP, yP, infP = g1_aff
    xQ, yQ, infQ = g2_aff

    batch = xP.shape[:-1]
    T0 = (xQ, yQ, fp2.ones(batch))
    f0 = jnp.broadcast_to(tower.ones(), (*batch, 2, 3, 2, fp.NL)).astype(jnp.int32)

    def body(carry, bit):
        f, T = carry
        f = tower.sq(f)
        T2, s0, sv, sv2 = _dbl_step(T, xP, yP)
        f = mul_by_line(f, s0, sv, sv2)
        # conditional add-step (bit is traced; both branches computed)
        T3, a0, av, av2 = _add_line(T2, xQ, yQ, xP, yP)
        fa = mul_by_line(f, a0, av, av2)
        take = bit == 1
        f = tower.select(jnp.broadcast_to(take, batch), fa, f)
        T = curve.select(fp2, jnp.broadcast_to(take, batch), T3, T2)
        return (f, T), None

    (f, _), _ = lax.scan(body, (f0, T0), jnp.asarray(_XBITS[1:]))
    # negative x: conjugate
    f = tower.conjugate(f)
    # infinity lanes -> 1
    one = jnp.broadcast_to(tower.ones(), f.shape).astype(jnp.int32)
    return tower.select(infP | infQ, one, f)


def _add_line(T, xQ, yQ, xP, yP):
    """Mixed addition T + Q with sparse line coefficients at P, under the
    active line engine."""
    if _active_line_impl == IMPL_LINE_FUSED:
        return _add_line_fused(T, xQ, yQ, xP, yP)
    return _add_line_composed(T, xQ, yQ, xP, yP)


def _add_line_composed(T, xQ, yQ, xP, yP):
    Xc, Yc, Zc = T
    Z2 = fp2.sq(Zc)
    U2 = fp2.mul(xQ, Z2)
    S2 = fp2.mul(yQ, fp2.mul(Zc, Z2))
    H = fp2.sub(U2, Xc)
    Rr = fp2.sub(S2, Yc)
    HH = fp2.sq(H)
    HHH = fp2.mul(H, HH)
    V = fp2.mul(Xc, HH)
    X3 = fp2.sub(fp2.sub(fp2.sq(Rr), HHH), fp2.add(V, V))
    Y3 = fp2.sub(fp2.mul(Rr, fp2.sub(V, X3)), fp2.mul(Yc, HHH))
    Z3 = fp2.mul(Zc, H)  # = HZ

    s0 = fp2.mul_by_u_plus_1(fp2.neg(fp2.scale(Z3, yP)))
    sv = fp2.sub(fp2.mul(Z3, yQ), fp2.mul(Rr, xQ))
    sv2 = fp2.scale(Rr, xP)
    return (X3, Y3, Z3), s0, sv, sv2


def _add_line_fused(T, xQ, yQ, xP, yP):
    """Same mixed-addition + line formulas in dependency-leveled batches:
    1 squaring + 4 product batches + 1 scale batch."""
    Xc, Yc, Zc = T
    Z2 = fp2.sq(Zc)
    U2, ZZ2 = fp2.mul_pairs([(xQ, Z2), (Zc, Z2)])
    H = fp2.sub(U2, Xc)
    S2, HH = fp2.mul_pairs([(yQ, ZZ2), (H, H)])
    Rr = fp2.sub(S2, Yc)
    HHH, V, R2, Z3 = fp2.mul_pairs(
        [(H, HH), (Xc, HH), (Rr, Rr), (Zc, H)]
    )
    X3 = fp2.sub(fp2.sub(R2, HHH), fp2.add(V, V))
    t = fp2.mul_pairs(
        [(Rr, fp2.sub(V, X3)), (Yc, HHH), (Z3, yQ), (Rr, xQ)]
    )
    Y3 = fp2.sub(t[0], t[1])
    sv = fp2.sub(t[2], t[3])
    s0c, sv2 = _scale_batch([(Z3, yP), (Rr, xP)])
    s0 = fp2.mul_by_u_plus_1(fp2.neg(s0c))
    return (X3, Y3, Z3), s0, sv, sv2


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------

def _exp_pos(f, e: int):
    """f^e for fixed positive e (generic square-and-multiply scan)."""
    return tower.pow_const(f, e)


def _conj_exp(f, e: int):
    """f^e for fixed NEGATIVE e on a unitary f: conj(f^|e|)."""
    return tower.conjugate(_exp_pos(f, -e))


def final_exponentiation(f):
    """f^((p^12-1)/r), batched, exact. Easy part then the machine-checked
    x-chain. Used where the VALUE matters (oracle-parity pairing tests);
    the verification path uses :func:`final_exp_is_one` instead."""
    t = _easy_part(f)
    # Hard: d = (x-1)^2 (x+p) (x^2+p^2-1) / 3 + 1 applied as a chain.
    lam = (X - 1) // 3  # negative
    a = _conj_exp(t, lam)          # t^((x-1)/3)
    a = _conj_exp(a, X - 1)        # t^((x-1)^2/3)
    b = tower.mul(_conj_exp(a, X), tower.frobenius(a))        # a^(x+p)
    c = _conj_exp(_conj_exp(b, X), X)                         # b^(x^2)
    c = tower.mul(c, tower.frobenius_n(b, 2))                 # * b^(p^2)
    c = tower.mul(c, tower.conjugate(b))                      # * b^(-1)
    return tower.mul(c, t)                                    # * t  (the +1)


def _easy_part(f):
    """f^((p^6-1)(p^2+1)) — output is unitary (conj == inverse)."""
    t = tower.mul(tower.conjugate(f), tower.inv(f))
    return tower.mul(tower.frobenius_n(t, 2), t)


# -- compile-light final-exp decision procedure -----------------------------
#
# The verification paths only need "does f^((p^12-1)/r) == 1", so they can
# exponentiate by 3*(hard part) instead (r is prime != 3, so cubing is a
# bijection on the r-torsion): Fuentes-Castaneda's 3h = (x-1)^2 (x+p)
# (x^2+p^2-1) + 3 expands in powers of p to FOUR x-polynomial exponents
#
#   3h = lam0 + lam1 p + lam2 p^2 + lam3 p^3
#   lam0 = (x-1)^2 (x^3-x) + 3,  lam1 = (x-1)^2 (x^2-1),
#   lam2 = (x-1)^2 x,            lam3 = (x-1)^2
#
# evaluated as ONE shared-squaring multi-exponentiation over the Frobenius
# powers t^(p^i) (frobenius = a handful of fp2 muls). The five separate
# square-multiply ladders + glue of the exact chain were ~54k HLO lines of
# the device program; this is one scan with one Fp12 mul per bit.

_LAM = [
    (X - 1) ** 2 * (X**3 - X) + 3,
    (X - 1) ** 2 * (X**2 - 1),
    (X - 1) ** 2 * X,
    (X - 1) ** 2,
]
assert (
    sum(l * P**i for i, l in enumerate(_LAM)) == 3 * (P**4 - P**2 + 1) // R
), "multi-exp hard-part decomposition is wrong"


def _multiexp_bits() -> np.ndarray:
    """Per-step subset indices: bit i of step s selects base i (MSB
    first). int32 [n_steps]."""
    mags = [abs(l) for l in _LAM]
    n = max(m.bit_length() for m in mags)
    idx = np.zeros(n, np.int32)
    for i, m in enumerate(mags):
        for s in range(n):
            bit = (m >> (n - 1 - s)) & 1
            idx[s] |= bit << i
    return idx


_MULTIEXP_IDX = _multiexp_bits()


def final_exp_is_one(f):
    """True iff final_exponentiation(f) == 1, via the 3h multi-exp."""
    t = _easy_part(f)
    bases = [t]
    for _ in range(3):
        bases.append(tower.frobenius(bases[-1]))
    # negative exponents on unitary values: conjugate the base
    bases = [
        tower.conjugate(b) if lam < 0 else b
        for b, lam in zip(bases, _LAM)
    ]
    # subset-product table T[s] = prod_{i in s} bases[i], built by ONE
    # scan over (dst, a, b) steps so the tower.mul body is emitted once
    # (the popcount-level batched version emitted it three times);
    # dependency order: every step's operands are already final.
    shape = f.shape
    one = jnp.broadcast_to(tower.ones(), shape).astype(jnp.int32)
    table = jnp.stack(
        [one, bases[0], bases[1], one, bases[2]]
        + [one] * 3
        + [bases[3]]
        + [one] * 7
    )  # [16, ..., 2,3,2,NL]; composite slots filled by the scan
    steps = jnp.asarray(
        [
            (3, 1, 2), (5, 1, 4), (9, 1, 8), (6, 2, 4), (10, 2, 8),
            (12, 4, 8), (7, 3, 4), (11, 3, 8), (13, 5, 8), (14, 6, 8),
            (15, 7, 8),
        ],
        jnp.int32,
    )

    def build(T, step):
        d, a, b = step[0], step[1], step[2]
        prod = tower.mul(jnp.take(T, a, axis=0), jnp.take(T, b, axis=0))
        return lax.dynamic_update_index_in_dim(T, prod, d, axis=0), None

    table, _ = lax.scan(build, table, steps)

    idx = jnp.asarray(_MULTIEXP_IDX)
    acc0 = jnp.take(table, idx[0], axis=0)

    def body(acc, i):
        acc = tower.sq(acc)
        acc = tower.mul(acc, jnp.take(table, i, axis=0))
        return acc, None

    acc, _ = lax.scan(body, acc0, idx[1:])
    return tower.is_one(acc)


# ---------------------------------------------------------------------------
# Multi-pairing
# ---------------------------------------------------------------------------

def multi_pairing(g1_aff, g2_aff, axis: int = 0):
    """prod_i e(P_i, Q_i) over a batch axis: batched Miller loops, scan
    product, one final exponentiation. Returns an Fp12 element (reduced
    over ``axis``)."""
    f = miller_loop(g1_aff, g2_aff)
    f = curve.tree_reduce(f, axis, tower.mul, tower.ones())
    return final_exponentiation(f)


def multi_pairing_is_one(g1_aff, g2_aff, axis: int = 0):
    """prod_i e(P_i, Q_i) == 1, with the compile-light multi-exp final
    exponentiation — the form every verification program uses."""
    f = miller_loop(g1_aff, g2_aff)
    f = curve.tree_reduce(f, axis, tower.mul, tower.ones())
    return final_exp_is_one(f)


def pairing(g1_aff, g2_aff):
    """e(P, Q), batched elementwise (no reduction)."""
    return final_exponentiation(miller_loop(g1_aff, g2_aff))
