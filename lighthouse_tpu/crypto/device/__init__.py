"""Device (JAX/TPU) BLS12-381 stack.

This package is the TPU-native re-design of the reference's native crypto
backends (``/root/reference/crypto/bls/src/impls/blst.rs`` — x86-64
asm + C): instead of per-core SIMD pairings it evaluates *batches* of
pairings/scalar-muls as data-parallel JAX programs whose batch dimension is
the signature-set dimension of
``verify_signature_sets`` (``blst.rs:36-119``).

Layout: a base-field element is an ``int32[..., 32]`` array of 12-bit limbs
(little-endian); every operation broadcasts over leading batch dimensions,
so the whole tower/curve/pairing stack is batched by construction — no
``vmap`` required. Bounds guaranteeing no int32 overflow are checked by
interval arithmetic at import time (see ``fp.py``).

The base-field multiply — the funnel the entire stack drains into — is
selectable via ``LIGHTHOUSE_TPU_FP_IMPL`` (``toeplitz_int32`` int32/VPU,
``matmul_int8`` int8 limb-split/MXU, ``pallas_int8`` hand-placed kernel;
see ``fp.py`` and docs/DEVICE_CRYPTO.md); fp2/tower/curve/pairing/bls pick
the active engine up transparently at trace time.
"""

from .. import backend as _backend


def _make_tpu_backend():
    from . import bls as _bls

    return _bls.TpuBackend()


_backend.register("tpu", _make_tpu_backend)


def reset_compiled_state() -> None:
    """Drop EVERY compiled device program and the accounting keyed on it
    — the one switch to flip around an ``fp.set_impl`` change (dispatch
    is trace-time, so stale jitted kernels would otherwise survive):

    * ``jax.clear_caches()`` — the jit dispatch caches;
    * ``bls.reset_recompile_tracking()`` — the recompile counter's seen
      signatures (the next dispatches ARE fresh compiles);
    * the compile service's warm-shape registry (when one is attached)
      — rungs that would now recompile must stop routing as warm, and
      the background worker re-warms the configured plan.

    Replaces the manual ``jax.clear_caches()`` +
    ``reset_recompile_tracking()`` pairing call sites used to carry.
    """
    import jax

    from ...compile_service import service as _csvc
    from . import bls as _bls

    jax.clear_caches()
    _bls.reset_recompile_tracking()
    _csvc.invalidate_registry()
