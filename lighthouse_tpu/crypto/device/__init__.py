"""Device (JAX/TPU) BLS12-381 stack.

This package is the TPU-native re-design of the reference's native crypto
backends (``/root/reference/crypto/bls/src/impls/blst.rs`` — x86-64
asm + C): instead of per-core SIMD pairings it evaluates *batches* of
pairings/scalar-muls as data-parallel JAX programs whose batch dimension is
the signature-set dimension of
``verify_signature_sets`` (``blst.rs:36-119``).

Layout: a base-field element is an ``int32[..., 32]`` array of 12-bit limbs
(little-endian); every operation broadcasts over leading batch dimensions,
so the whole tower/curve/pairing stack is batched by construction — no
``vmap`` required. Bounds guaranteeing no int32 overflow are checked by
interval arithmetic at import time (see ``fp.py``).

The base-field multiply — the funnel the entire stack drains into — is
selectable via ``LIGHTHOUSE_TPU_FP_IMPL`` (``toeplitz_int32`` int32/VPU,
``matmul_int8`` int8 limb-split/MXU, ``pallas_int8`` hand-placed kernel;
see ``fp.py`` and docs/DEVICE_CRYPTO.md); fp2/tower/curve/pairing/bls pick
the active engine up transparently at trace time.
"""

from .. import backend as _backend


def _make_tpu_backend():
    from . import bls as _bls

    return _bls.TpuBackend()


_backend.register("tpu", _make_tpu_backend)
