"""Fused Fp2 Pallas kernels: Karatsuba mul and squaring in ONE tile.

The composed :mod:`.fp2` path lowers every Fp2 product as one batched
``fp.mul`` (three Fp lanes) plus separate reduce/add/sub dispatches, and
leaves the Karatsuba recombination to XLA's fusion heuristics. These
kernels state the whole inner loop explicitly instead: the int8 dot
passes, the shift recombination, the column reduction AND the Karatsuba
combine all run inside one Pallas tile, so the product never round-trips
raw columns through HBM between the contraction and the combine.

Selected via ``LIGHTHOUSE_TPU_FP2_IMPL=fused_pallas`` (see ``fp2.py``);
off-TPU the kernels run in interpreter mode, so the full differential
matrix (vs the Python Fq2 oracle and the composed path) covers them on
any host.

Soundness notes (the same machine-checked regime as ``fp.py``):

* Operand sums (``a0+a1`` etc.) are carry-reduced by ``fp.add``/``fp.sub``
  BEFORE ``fp.split_int8`` — the int8 split is only valid for values in
  ``[0, LIMB_MAX]``.
* Inside the kernel, products are first reduced to the relaxed 32-limb
  form (``fp.reduce_cols`` with the full-band profile); the Karatsuba
  subtractions then use the saturated multiple ``fp.SAT`` so every limb
  stays non-negative: ``t0 - t1 == t0 + (SAT - t1) (mod p)`` with exact
  per-column bounds ``LIMB_MAX + SAT_i < 2**31`` asserted at trace time.
* Raw columns are NEVER combined pre-reduction: negative columns would
  break the carry shifts, and ``SAT`` only covers relaxed 32-limb values.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pallas_fp import TILE, _interpret


def _raw_cols(split_shift, xs_ref, bs_ref):
    """Shared contraction: the four int8 dot passes + shift recombination
    -> exact int32 product columns [T, R, NCOLS]."""
    from jax import lax

    def dot(a, b):
        # [T, R, NL] x [T, R, NL, NCOLS] -> [T, R, NCOLS]; int32 acc
        return lax.dot_general(
            a, b, (((2,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.int32,
        )

    xh, xl = xs_ref[0], xs_ref[1]
    bh, bl = bs_ref[0], bs_ref[1]
    return (
        (dot(xh, bh) << (2 * split_shift))
        + ((dot(xh, bl) + dot(xl, bh)) << split_shift)
        + dot(xl, bl)
    )


def _karatsuba_tile_kernel(split_shift, xs_ref, bs_ref, fold_ref, sat_ref,
                           out_ref):
    """One batch tile of the fused Fp2 product.

    xs [2, T, 3, NL] int8, bs [2, T, 3, NL, NCOLS] int8 — per lane the
    three Karatsuba operand rows (a0, a1, a0+a1) x (b0, b1, b0+b1) —
    -> out [T, 2, NL] int32 relaxed Fp2 elements. ``fold_ref``/``sat_ref``
    carry the reduction tables in (kernels cannot capture constants).
    """
    from . import fp

    raw = _raw_cols(split_shift, xs_ref, bs_ref)
    sat = sat_ref[...]
    with fp.fold_table(fold_ref[...]):
        t = fp.reduce_cols(raw, fp.MUL_COL_BOUNDS)   # [T, 3, NL] relaxed
        t0, t1, m = t[:, 0], t[:, 1], t[:, 2]
        # c0 = t0 - t1, c1 = m - t0 - t1, each in ONE reduction via SAT
        c0 = fp.reduce_cols(
            t0 + (sat - t1), [fp.LIMB_MAX + int(v) for v in fp.SAT]
        )
        c1 = fp.reduce_cols(
            m + (2 * sat - t0 - t1),
            [fp.LIMB_MAX + 2 * int(v) for v in fp.SAT],
        )
    out_ref[:] = jnp.stack([c0, c1], axis=1)


def _sq_tile_kernel(split_shift, xs_ref, bs_ref, fold_ref, sat_ref, out_ref):
    """Fused Fp2 squaring tile: rows (a0+a1, a0) x (a0-a1, a1) ->
    (t0, t1) with c0 = t0, c1 = 2 t1. xs [2, T, 2, NL] int8,
    bs [2, T, 2, NL, NCOLS] int8 -> out [T, 2, NL] int32."""
    from . import fp

    raw = _raw_cols(split_shift, xs_ref, bs_ref)
    with fp.fold_table(fold_ref[...]):
        t = fp.reduce_cols(raw, fp.MUL_COL_BOUNDS)   # [T, 2, NL]
        t0, t1 = t[:, 0], t[:, 1]
        c1 = fp.reduce_cols(t1 + t1, [2 * fp.LIMB_MAX] * fp.NL)
    out_ref[:] = jnp.stack([t0, c1], axis=1)


def _run_rows(kernel, xrows, yrows):
    """Shared launch: per-lane operand rows [..., R, NL] (already
    carry-reduced) -> [..., 2, NL] fused Fp2 results."""
    from jax.experimental import pallas as pl

    from . import fp

    nrows = xrows.shape[-2]
    lead = xrows.shape[:-2]
    n = 1
    for d in lead:
        n *= d
    xf = xrows.reshape(n, nrows, fp.NL)
    bf = fp.band_matrix(yrows.reshape(n, nrows, fp.NL))

    pad = (-n) % TILE
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0), (0, 0)))
        bf = jnp.pad(bf, ((0, pad), (0, 0), (0, 0), (0, 0)))
    npad = n + pad

    xs = fp.split_int8(xf)                  # [2, npad, R, NL]
    bs = fp.split_int8(bf)                  # [2, npad, R, NL, NCOLS]

    nfold = fp.FOLD.shape[0]
    out = pl.pallas_call(
        functools.partial(kernel, fp.SPLIT_SHIFT),
        grid=(npad // TILE,),
        in_specs=[
            pl.BlockSpec((2, TILE, nrows, fp.NL), lambda i: (0, i, 0, 0)),
            pl.BlockSpec(
                (2, TILE, nrows, fp.NL, fp.NCOLS), lambda i: (0, i, 0, 0, 0)
            ),
            pl.BlockSpec((nfold, fp.NL), lambda i: (0, 0)),
            pl.BlockSpec((fp.NL,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((TILE, 2, fp.NL), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((npad, 2, fp.NL), jnp.int32),
        interpret=_interpret(),
    )(xs, bs, jnp.asarray(fp.FOLD), jnp.asarray(fp.SAT))
    return out[:n].reshape(*lead, 2, fp.NL)


def mul2(x, y):
    """Fused Fp2 product; same contract as the composed ``fp2.mul``
    (relaxed limbs, identical canonical value)."""
    from . import fp

    x, y = jnp.broadcast_arrays(x, y)
    a0, a1 = x[..., 0, :], x[..., 1, :]
    b0, b1 = y[..., 0, :], y[..., 1, :]
    # the Karatsuba operand sums MUST be carry-reduced before split_int8
    xrows = jnp.stack([a0, a1, fp.add(a0, a1)], axis=-2)
    yrows = jnp.stack([b0, b1, fp.add(b0, b1)], axis=-2)
    return _run_rows(_karatsuba_tile_kernel, xrows, yrows)


def sq2(x):
    """Fused Fp2 squaring via (a0+a1)(a0-a1) | a0*a1."""
    from . import fp

    a0, a1 = x[..., 0, :], x[..., 1, :]
    xrows = jnp.stack([fp.add(a0, a1), a0], axis=-2)
    yrows = jnp.stack([fp.sub(a0, a1), a1], axis=-2)
    return _run_rows(_sq_tile_kernel, xrows, yrows)
