"""Pallas TPU kernel for the int8-decomposed banded-Toeplitz fp product.

This is the hand-placed fallback behind ``FP_IMPL=pallas_int8`` (see
``fp.py``): if XLA's ``dot_general`` lowering of the ``matmul_int8`` path
keeps the int8 contractions on the VPU, this kernel states the placement
explicitly — int8 operand tiles in VMEM, four s8 x s8 -> s32 dot passes
per batch tile, shift-recombined in-register before the columns leave the
kernel. Off-TPU it runs in interpreter mode so the whole differential test
matrix (vs the Python oracle and the int32 path) still covers it.

The kernel computes RAW product columns only; the caller reduces them mod
p through ``fp.reduce_cols`` with the shared full-band bound profile
(``fp.MUL_COL_BOUNDS``) — one reduction engine, machine-checked bounds,
regardless of which engine produced the columns.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Batch lanes per kernel instance. 8 sublanes is the int32 native tile
# height; the int8 operands are padded by Mosaic as needed (the band is
# [NL=32, NCOLS=63] — below the 128-lane tile, acceptable for a stub).
TILE = 8


def _mul_tile_kernel(split_shift: int, xs_ref, bs_ref, out_ref):
    """One batch tile: xs [2, T, NL] int8, bs [2, T, NL, NCOLS] int8 ->
    out [T, NCOLS] int32 raw product columns."""
    from jax import lax

    def dot(a, b):
        # [T, NL] x [T, NL, NCOLS] -> [T, NCOLS], batched over T, int32 acc
        return lax.dot_general(
            a, b, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32,
        )

    xh, xl = xs_ref[0], xs_ref[1]
    bh, bl = bs_ref[0], bs_ref[1]
    hh = dot(xh, bh)
    hl = dot(xh, bl)
    lh = dot(xl, bh)
    ll = dot(xl, bl)
    out_ref[:] = (
        (hh << (2 * split_shift)) + ((hl + lh) << split_shift) + ll
    )


@functools.cache
def _interpret() -> bool:
    # Interpreter mode everywhere but a real TPU: the kernel is then a
    # reference semantics check, not a performance path.
    return jax.default_backend() != "tpu"


def mul_cols_int8(x, y):
    """Raw banded product columns of two fp limb arrays via the Pallas
    kernel; same contract as the dot_general passes in
    ``fp._mul_matmul_int8`` (exact int32 schoolbook columns)."""
    from jax.experimental import pallas as pl

    from . import fp

    x, y = jnp.broadcast_arrays(x, y)
    lead = x.shape[:-1]
    n = 1
    for d in lead:
        n *= d
    xf = x.reshape(n, fp.NL)
    bf = fp.band_matrix(y.reshape(n, fp.NL))

    pad = (-n) % TILE
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
        bf = jnp.pad(bf, ((0, pad), (0, 0), (0, 0)))
    npad = n + pad

    xs = fp.split_int8(xf)                  # [2, npad, NL]
    bs = fp.split_int8(bf)                  # [2, npad, NL, NCOLS]

    cols = pl.pallas_call(
        functools.partial(_mul_tile_kernel, fp.SPLIT_SHIFT),
        grid=(npad // TILE,),
        in_specs=[
            pl.BlockSpec((2, TILE, fp.NL), lambda i: (0, i, 0)),
            pl.BlockSpec((2, TILE, fp.NL, fp.NCOLS), lambda i: (0, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((TILE, fp.NCOLS), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((npad, fp.NCOLS), jnp.int32),
        interpret=_interpret(),
    )(xs, bs)
    return cols[:n].reshape(*lead, fp.NCOLS)
