"""BLS12-381 quadratic extension Fp2 = Fp[u]/(u^2+1) on device.

An Fp2 element is ``int32[..., 2, 32]``: axis -2 stacks (c0, c1), axis -1
is the 12-bit limb axis of :mod:`.fp`. All ops broadcast over leading batch
dims, mirroring the host oracle ``crypto/cpu/fields.Fq2`` (tested for
bit-equality against it). Reference behaviour being reproduced: the Fp2
tower inside blst (``/root/reference/crypto/bls/src/impls/blst.rs`` links
the asm backend).

Every product here drains into :func:`fp.mul` and therefore inherits the
active ``FP_IMPL`` engine (int32 Toeplitz dot / int8 MXU decomposition /
Pallas kernel) without any change at this layer.

This layer has its OWN engine seam on top (ISSUE 16): the default
``composed`` implementation emits the Karatsuba recombination as separate
XLA ops around one batched ``fp.mul``; ``fused_pallas`` hands the whole
product — contraction, reduction and Karatsuba combine — to one Pallas
tile (:mod:`.pallas_fp2`). Select with ``LIGHTHOUSE_TPU_FP2_IMPL`` (env)
or :func:`set_impl` / the :func:`impl` context manager. Dispatch happens
at TRACE time: callers holding jitted programs must call
``device.reset_compiled_state()`` after switching, exactly like the fp
seam.
"""

from __future__ import annotations

import contextlib
import os

import numpy as np

import jax.numpy as jnp
from jax import lax

from . import fp

# Trailing element dims of an fp2 array: (2, NL).
ELEM_NDIM = 2


def pack(c0, c1):
    """Two fp elements [..., 32] -> one fp2 element [..., 2, 32]."""
    return jnp.stack([c0, c1], axis=-2)


def c0(x):
    return x[..., 0, :]


def c1(x):
    return x[..., 1, :]


def const(v0: int, v1: int):
    return pack(fp.const(v0), fp.const(v1))


def zeros(shape=()):
    return jnp.zeros((*shape, 2, fp.NL), jnp.int32)


def ones(shape=()):
    return pack(fp.ones(shape), fp.zeros(shape))


def add(x, y):
    return fp.add(x, y)  # limbwise; fp ops broadcast over the (2,) axis


def sub(x, y):
    return fp.sub(x, y)


def neg(x):
    return fp.neg(x)


def mul_small(x, k: int):
    return fp.mul_small(x, k)


def _bstack(elems, axis):
    """Stack with broadcasting to a common shape (constants vs batches)."""
    shapes = [e.shape for e in elems]
    nd = max(len(s) for s in shapes)
    target = jnp.broadcast_shapes(*[(1,) * (nd - len(s)) + s for s in shapes])
    return jnp.stack([jnp.broadcast_to(e, target) for e in elems], axis=axis)


def _mul_composed(x, y):
    """(a0 + a1 u)(b0 + b1 u) via Karatsuba, with the three Fp products
    stacked into ONE batched fp.mul — the whole tower funnels its
    component products into single big contractions this way (small HLO
    graphs, large batched matmuls: the TPU-native shape of blst's
    tower arithmetic)."""
    a0, a1 = c0(x), c1(x)
    b0, b1 = c0(y), c1(y)
    xs = _bstack([a0, a1, fp.add(a0, a1)], -2)
    ys = _bstack([b0, b1, fp.add(b0, b1)], -2)
    t = fp.mul(xs, ys)
    t0, t1, m = t[..., 0, :], t[..., 1, :], t[..., 2, :]
    return pack(fp.sub(t0, t1), fp.sub(m, fp.add(t0, t1)))


def _mul_fused(x, y):
    from . import pallas_fp2

    x, y = jnp.broadcast_arrays(x, y)
    return pallas_fp2.mul2(x, y)


def mul(x, y):
    """Fp2 product under the active implementation (see module docstring)."""
    return _IMPLS[_active_impl][0](x, y)


def mul_pairs(pairs):
    """[(x_i, y_i)] -> [x_i * y_i] with ALL products in one batched fp.mul.

    The workhorse of the Fp6/Fp12 layers: an Fp12 multiply is 27 Fp2
    products = 81 Fp products = one fp.mul call here.
    """
    xs = _bstack([p[0] for p in pairs], -3)
    ys = _bstack([p[1] for p in pairs], -3)
    out = mul(xs, ys)
    return [out[..., i, :, :] for i in range(len(pairs))]


def sq_batch(elems):
    """[x_i] -> [x_i^2] with ALL squarings in one batched call (the
    squaring sibling of :func:`mul_pairs`; used by the fused line-eval
    steps in pairing.py)."""
    xs = _bstack(elems, -3)
    out = sq(xs)
    return [out[..., i, :, :] for i in range(len(elems))]


def _sq_composed(x):
    """(a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u (one batched fp.mul)."""
    a0, a1 = c0(x), c1(x)
    xs = _bstack([fp.add(a0, a1), a0], -2)
    ys = _bstack([fp.sub(a0, a1), a1], -2)
    t = fp.mul(xs, ys)
    t2 = t[..., 1, :]
    return pack(t[..., 0, :], fp.add(t2, t2))


def _sq_fused(x):
    from . import pallas_fp2

    return pallas_fp2.sq2(x)


def sq(x):
    """Fp2 squaring under the active implementation."""
    return _IMPLS[_active_impl][1](x)


# ---------------------------------------------------------------------------
# Implementation selection (mirrors the fp.mul engine seam)
# ---------------------------------------------------------------------------

IMPL_COMPOSED = "composed"
IMPL_FUSED_PALLAS = "fused_pallas"

_IMPLS = {
    IMPL_COMPOSED: (_mul_composed, _sq_composed),
    IMPL_FUSED_PALLAS: (_mul_fused, _sq_fused),
}

_active_impl = os.environ.get("LIGHTHOUSE_TPU_FP2_IMPL", IMPL_COMPOSED)
if _active_impl not in _IMPLS:
    raise KeyError(
        f"LIGHTHOUSE_TPU_FP2_IMPL={_active_impl!r} unknown; "
        f"have {sorted(_IMPLS)}"
    )


def get_impl() -> str:
    return _active_impl


def set_impl(name: str) -> None:
    """Select the Fp2 implementation. Dispatch happens at TRACE time:
    callers holding jitted programs must call
    ``device.reset_compiled_state()`` afterwards (same contract as
    ``fp.set_impl``)."""
    global _active_impl
    if name not in _IMPLS:
        raise KeyError(f"unknown fp2 impl {name!r}; have {sorted(_IMPLS)}")
    _active_impl = name


@contextlib.contextmanager
def impl(name: str):
    """Scoped implementation switch (restores the previous choice)."""
    prev = _active_impl
    set_impl(name)
    try:
        yield
    finally:
        set_impl(prev)


def conjugate(x):
    return pack(c0(x), fp.neg(c1(x)))


def scale(x, k):
    """Multiply both components by an fp element ``k`` [..., 32]."""
    return pack(fp.mul(c0(x), k), fp.mul(c1(x), k))


def mul_by_u_plus_1(x):
    """Multiply by the sextic non-residue xi = 1 + u:
    (a0 + a1 u)(1 + u) = (a0 - a1) + (a0 + a1) u."""
    a0, a1 = c0(x), c1(x)
    return pack(fp.sub(a0, a1), fp.add(a0, a1))


def inv(x):
    """(a0 - a1 u) / (a0^2 + a1^2); inv(0) = 0 (callers mask)."""
    a0, a1 = c0(x), c1(x)
    s = fp.mul(_bstack([a0, a1], -2), _bstack([a0, a1], -2))
    d = fp.inv(fp.add(s[..., 0, :], s[..., 1, :]))
    t = fp.mul(_bstack([a0, a1], -2), d[..., None, :])
    return pack(t[..., 0, :], fp.neg(t[..., 1, :]))


def canonical(x):
    return fp.canonical(x)


def is_zero(x):
    return jnp.all(canonical(x) == 0, axis=(-1, -2))


def eq(x, y):
    return jnp.all(canonical(x) == canonical(y), axis=(-1, -2))


def select(mask, a, b):
    """mask [...] bool -> elementwise fp2 select."""
    return jnp.where(mask[..., None, None], a, b)


def pow_const(x, e: int):
    """x**e for a fixed Python-int exponent (shared ladder in fp)."""
    return fp.square_multiply(x, e, sq, mul, select)
