"""Device-resident validator pubkey table (ISSUE 10, ROADMAP item 2).

PR 8's data-movement ledger measured the claim this module acts on: G1
pubkeys are 87–94% of all host→device bytes at committee rungs
(COST_MODEL.md bytes-per-set table) and ``bls_device_pubkey_reupload_
ratio`` sits above 0.9 on gossip steady state — every verify re-packs
and re-ships the same ~known validators. The FPGA verification-engine
paper (PAPERS.md, arxiv 2112.02229) keeps precomputed keys resident
next to the verifier core; this is that pattern for the JAX device
backend:

* **One device array, index-keyed** — limb-packed G1 affine rows
  (``int32[cap, 2, NL]``, the exact layout ``curve.pack_g1`` produces
  and ``_stage2_fn`` consumes) uploaded ONCE from the host
  :class:`~lighthouse_tpu.beacon_chain.pubkey_cache.ValidatorPubkeyCache`
  and delta-updated when ``import_new_pubkeys`` admits deposits. Row
  index == validator index, append-only (exits leave their rows
  resident — an exited validator's historical signatures still verify).
  Uploads are CHUNKED (``upload_chunk_rows``) so a 1M-validator table
  never needs one giant host buffer in flight; capacity grows on a
  coarse ladder so the gather program's compile is keyed on a handful
  of shapes, and growth copies the old rows DEVICE-side (no re-upload).
* **Identity pinned to the host cache** — the table resolves a packed
  set's pubkey POINTS through an ``id(point) -> index`` map built only
  from the cache's own immortal point objects (the cache list is
  append-only and the table holds the cache alive, so a hit proves the
  argument IS that exact object). A set built from any other
  state/cache — VC tests, library callers, pre-admission gossip — can
  never silently verify against the wrong key: it misses the map and
  falls back to the raw limb-plane pack.
* **Epoch-stable aggregate-pubkey sums** — committee sets whose index
  tuple repeats (sync-committee periods, identical attestation
  aggregates; the committee cost model arxiv 2302.00418 makes these
  epoch-stable) collapse to a SINGLE table row holding the host-summed
  aggregate point, so a K-wide committee set ships one index and pays
  one K=1 gather lane. Sums are inserted on the SECOND sighting of a
  tuple (``agg_min_repeats``) so one-shot participation subsets never
  pay the host point-add cost. The region is EPOCH-TAGGED (ISSUE 19):
  each entry carries the epoch it serves, entries are retained for two
  epochs (committees reshuffle each epoch but late attestations for
  the prior one still arrive) and evicted per-epoch onto a slot
  free-list as the chain clock advances — the wholesale
  reset-when-full recycle survives only as the last resort when the
  region fills inside a single epoch. :meth:`insert_precomputed` is
  the duty-lookahead entry (``duty_lookahead/``): a committee sum
  computed off the hot path, inserted for a FUTURE epoch, bypassing
  ``agg_min_repeats`` so the committee's first sighting already ships
  K=1 — the reactive path's admission rules are untouched.

The verdict is IDENTICAL by construction: the gathered rows are the
same limb encodings the raw packer ships, and an aggregate row is the
same group element the device's masked K-axis sum produces (a sum that
degenerates to infinity is never cached — it keeps failing through the
device's ``agg_inf_bad`` screen like the raw path).

jax-free at import (the flush planner and the metrics lint import this
module on boxes that must not initialize a backend); every device
operation imports jax lazily. The process-global seam
(:func:`set_table` / :func:`get_active_table`) mirrors the compile
service's: the client builder owns the lifecycle, ``TpuBackend`` and
the flush planner reach the table without plumbing a handle through
every caller.
"""

from __future__ import annotations

import hashlib
import os
import random
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...utils import (
    fault_injection,
    flight_recorder,
    metrics,
    slot_clock,
    slot_ledger,
)

# limbs per field element; pinned == fp.NL by test (this module must not
# import the device fp module, which pulls jax)
NL = 32
G1_ROW_SHAPE = (2, NL)          # affine (x, y) limb rows
G1_ROW_BYTES = 2 * NL * 4       # int32

# Validator-region capacity ladder: the gather program's compile is
# keyed on the table array shape, so capacity moves in coarse steps —
# log-many shapes between genesis and a 1M-validator registry.
CAPACITY_LADDER = (1024, 4096, 16384, 65536, 262144, 1048576)

_ENV_ENABLED = "LIGHTHOUSE_TPU_KEY_TABLE"
_ENV_MAX_AGG = "LIGHTHOUSE_TPU_KEY_TABLE_MAX_AGG"
_ENV_CHUNK = "LIGHTHOUSE_TPU_KEY_TABLE_CHUNK"
# re-sync retry (ISSUE 13): a failed admission-listener delta schedules
# a full-sync retry with capped exponential backoff + jitter instead of
# degrading to raw packs forever (sync always catches the mirror up to
# the whole host cache, so one retry covers any number of missed deltas)
_ENV_RESYNC_BASE = "LIGHTHOUSE_TPU_KEY_TABLE_RESYNC_BASE_S"
_ENV_RESYNC_MAX = "LIGHTHOUSE_TPU_KEY_TABLE_RESYNC_MAX_S"

DEFAULT_MAX_AGGREGATES = 4096
DEFAULT_UPLOAD_CHUNK_ROWS = 65536
DEFAULT_AGG_MIN_REPEATS = 2
DEFAULT_RESYNC_BASE_S = 1.0
DEFAULT_RESYNC_MAX_S = 60.0
# the repeat-counting sketch is bounded too: when it exceeds this many
# distinct tuples it resets wholesale (it only gates INSERTS; losing it
# costs one extra sighting before a tuple collapses again)
_AGG_SEEN_CAP = 65536


def table_capacity(n: int) -> int:
    """Validator-region capacity for ``n`` resident rows: the smallest
    ladder rung covering it (beyond the ladder: next 1M multiple)."""
    for c in CAPACITY_LADDER:
        if n <= c:
            return c
    top = CAPACITY_LADDER[-1]
    return ((n + top - 1) // top) * top


def env_enabled() -> bool:
    return os.environ.get(_ENV_ENABLED, "1") not in ("", "0")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


class KeyTableError(RuntimeError):
    """Host-cache/device-table identity cannot be maintained (gap,
    shrunken cache, invalid row). Raised BEFORE any device mutation —
    sync is all-or-nothing."""


# ---------------------------------------------------------------------------
# Telemetry (families under the existing bls_device_ prefix; documented
# in docs/OBSERVABILITY.md, linted by tests/test_zgate4_metrics_lint.py)
# ---------------------------------------------------------------------------

_ENTRIES = metrics.gauge_vec(
    "bls_device_key_table_entries",
    "rows resident in the device pubkey table, by region (validators = "
    "index-identical mirror of ValidatorPubkeyCache, append-only; "
    "aggregates = cached epoch-stable aggregate-pubkey sums)",
    ("region",),
)
_DEVICE_BYTES = metrics.gauge(
    "bls_device_key_table_device_bytes",
    "device bytes held by the pubkey table array (validator capacity + "
    "aggregate region, limb-packed G1 rows)",
)
_UPLOAD_BYTES = metrics.counter_vec(
    "bls_device_key_table_upload_bytes_total",
    "host→device bytes uploaded into the key table, by reason (startup "
    "= initial mirror, delta = deposit admissions, aggregate = cached "
    "committee sums). Capacity growth copies device-side and uploads "
    "nothing",
    ("reason",),
)
_SETS = metrics.counter_vec(
    "bls_device_key_table_sets_total",
    "signature sets by pubkey-shipping path: indexed = shipped as table "
    "indices (device gather), collapsed = shipped as ONE cached "
    "aggregate-sum index (K=1), raw = table attached but at least one "
    "key not resident, so the whole batch fell back to the G1 limb "
    "plane. hit ratio = (indexed+collapsed) / all",
    ("path",),
)
_RESYNCS = metrics.counter_vec(
    "bls_device_key_table_resyncs_total",
    "full-sync retries after a failed mirror sync (ISSUE 13): "
    "scheduled = a retry timer armed with backoff, ok = a retry "
    "caught the mirror up, error = a retry failed (and re-scheduled) "
    "— a failed admission delta degrades batches to raw packs only "
    "until the retry lands, never forever",
    ("outcome",),
)
_AGG_EVENTS = metrics.counter_vec(
    "bls_device_key_table_agg_events_total",
    "aggregate-sum cache LOOKUP events: hit (cached tuple found — warm "
    "routing may still ship it un-collapsed; sets_total{collapsed} is "
    "the shipping truth), miss (tuple not cached), insert (host sum "
    "computed + row uploaded), precomputed (duty-lookahead pre-insert, "
    "ISSUE 19), evict (entry dropped by two-epoch retention, slot "
    "freed), reset (region recycled wholesale — the same-epoch-full "
    "last resort)",
    ("event",),
)


# ---------------------------------------------------------------------------
# The table
# ---------------------------------------------------------------------------


class DeviceKeyTable:
    """Device mirror of a host pubkey cache (see module docstring).

    ``cache`` needs only a ``pubkeys`` list of ``bls.PublicKey``-shaped
    objects (``.point`` attribute) that is append-only for the table's
    lifetime — the chain's ``ValidatorPubkeyCache`` and the bench's shim
    both qualify. The table holds ``cache`` alive, which is what makes
    the ``id(point)`` identity map sound."""

    def __init__(
        self,
        cache,
        max_aggregates: Optional[int] = None,
        upload_chunk_rows: Optional[int] = None,
        agg_min_repeats: int = DEFAULT_AGG_MIN_REPEATS,
    ):
        self.cache = cache
        if max_aggregates is None:
            try:
                max_aggregates = int(os.environ.get(_ENV_MAX_AGG, ""))
            except ValueError:
                max_aggregates = DEFAULT_MAX_AGGREGATES
        if upload_chunk_rows is None:
            try:
                upload_chunk_rows = int(os.environ.get(_ENV_CHUNK, ""))
            except ValueError:
                upload_chunk_rows = DEFAULT_UPLOAD_CHUNK_ROWS
        self.max_aggregates = max(0, int(max_aggregates))
        self.upload_chunk_rows = max(1, int(upload_chunk_rows))
        self.agg_min_repeats = max(1, int(agg_min_repeats))
        self._lock = threading.Lock()
        # TWO device arrays — REPLICATED per dp-mesh shard (ISSUE 11;
        # dict shard -> array, single key 0 without a mesh): the
        # validator mirror [cap_v, 2, NL] and the small aggregate region
        # [max(1, max_agg), 2, NL]. Separate so an aggregate insert's
        # functional .at.set copies ~1 MB per replica, not the whole
        # (potentially 256 MB) validator table, and so cached sums
        # survive validator-capacity growth (the encoded index cap_v +
        # slot is recomputed against the CURRENT base on every resolve).
        # Replication keeps the all-or-nothing sync contract: one delta
        # admission commits on EVERY replica or none (the new arrays for
        # all shards are fully assembled before any commit).
        self._dev: Dict[int, object] = {}
        self._agg_dev: Dict[int, object] = {}
        self._cap_v = 0                     # validator-region capacity
        self._n = 0                         # validator rows resident
        self._point_ids: Dict[int, int] = {}
        # aggregate-sum region (slots live at index cap_v + slot).
        # Resets are DEFERRED (_agg_reset_pending) to the start of the
        # next resolve_sets call and guarded by a generation counter: a
        # slot handed out earlier in a batch must stay valid until that
        # batch's snapshot is taken — a mid-batch recycle would point an
        # already-encoded index at a different committee's sum.
        self._agg_slots: Dict[bytes, Optional[int]] = {}  # None = never cache
        self._agg_seen: Dict[bytes, int] = {}
        self._agg_next = 0                  # slot high-water mark
        self._agg_resets = 0
        self._agg_gen = 0
        self._agg_reset_pending = False
        # epoch-tagged retention (ISSUE 19): each occupied entry carries
        # the epoch it serves; entries older than two epochs are evicted
        # onto the slot free-list at the chain clock's epoch roll (and
        # on demand when the region fills), replacing the wholesale
        # reset as the steady-state recycler
        self._agg_epochs: Dict[bytes, int] = {}
        self._agg_free: List[int] = []
        self._agg_resident = 0
        self._agg_epoch_seen: Optional[int] = None
        self._agg_evictions = 0
        self._agg_precomputed = 0
        # shadow counters for status() (the health endpoint should not
        # parse the exposition to describe the table)
        self._uploads = {"startup": 0, "delta": 0, "aggregate": 0}
        self._sets = {"indexed": 0, "collapsed": 0, "raw": 0}
        self._agg_hits = 0
        self._agg_inserts = 0
        # re-sync retry state (ISSUE 13): one pending timer at a time,
        # backoff grows with consecutive failures, close() cancels
        self._resync_lock = threading.Lock()
        self._resync_base_s = _env_float(_ENV_RESYNC_BASE, DEFAULT_RESYNC_BASE_S)
        self._resync_max_s = _env_float(_ENV_RESYNC_MAX, DEFAULT_RESYNC_MAX_S)
        self._resync_failures = 0
        self._resync_timer: Optional[threading.Timer] = None
        self._resyncs = {"scheduled": 0, "ok": 0, "error": 0}
        self._closed = False

    # -- mesh replication helpers (ISSUE 11) ------------------------------

    @staticmethod
    def _mesh():
        try:
            from . import mesh as mesh_mod

            return mesh_mod.get_active_mesh()
        except Exception:
            return None

    def _replica_shards(self) -> List[int]:
        """The shard set this table mirrors onto: every mesh shard
        (lost chips included — their replicas are already paid for and
        a restored chip must find its rows), else the single default
        shard 0. Pinned to the FIRST sync's answer so replicas never
        silently change set mid-life."""
        if self._dev:
            return sorted(self._dev)
        mesh = self._mesh()
        if mesh is not None:
            return mesh.all_shards()
        return [0]

    def _device_of(self, shard: int):
        mesh = self._mesh()
        return mesh.device_for(shard) if mesh is not None else None

    def _resolve_shard_locked(self) -> Optional[int]:
        """The replica the CURRENT dispatch thread should gather from:
        the thread-local mesh shard when set (the scheduler's sharded
        sub-batch scope), else the lowest replica. None when that shard
        has no replica — the caller then falls back to the raw pack
        (self-consistent: its planes land on the dispatch device)."""
        try:
            from . import mesh as mesh_mod

            shard = mesh_mod.current_shard()
        except Exception:
            shard = None
        if shard is None:
            return min(self._dev) if self._dev else None
        return shard if shard in self._dev else None

    # -- sync (startup + delta admission) ---------------------------------

    def sync(self, reason: str = "delta") -> int:
        """Mirror host-cache rows [resident, len(cache)) onto the device.
        ALL-OR-NOTHING: rows are validated and packed, and the new device
        array fully assembled, before any table state commits — a gap or
        invalid row raises :class:`KeyTableError` and leaves the table
        exactly as it was. Returns the number of rows added.

        The expensive work — pure-Python limb packing of every new row
        and the host→device upload — runs OUTSIDE the table lock against
        snapshots (same discipline as ``resolve_sets``' EC sums): a
        multi-thousand-validator catch-up delta must not stall every
        verifier thread and the block-import listener behind host
        packing. The commit re-checks the snapshots and retries on the
        (rare: builder + admission listener) concurrent-sync race."""
        # chaos seam (ISSUE 13): an armed `key_table_sync` fault point
        # raises here — before any state is touched, like every real
        # sync failure — and exercises the re-sync retry layer
        fault_injection.fire("key_table_sync")
        shards = self._replica_shards()
        for _attempt in range(16):
            with self._lock:
                n_start = self._n
                cap_start = self._cap_v
                dev_start = dict(self._dev)  # shard -> array snapshot
                pubkeys = list(self.cache.pubkeys)
            n_host = len(pubkeys)
            if n_host < n_start:
                raise KeyTableError(
                    f"host cache shrank to {n_host} rows below the "
                    f"{n_start} resident device rows — the cache contract "
                    f"is append-only"
                )
            if n_host == n_start:
                return 0
            new = pubkeys[n_start:n_host]
            rows, points = self._pack_rows(new, base_index=n_start)
            # build EVERY replica's new array before any commit: the
            # all-or-nothing contract spans the mesh (ISSUE 11) — one
            # delta admission commits on every replica or none. A raise
            # mid-build leaves nothing behind (every write is
            # functional).
            cap_v = table_capacity(n_host)
            new_dev: Dict[int, object] = {}
            grew = False
            for s in shards:
                dev_s, _cap_s, grew_s = self._grown_array(
                    dev_start.get(s), cap_start, n_start, n_host,
                    device=self._device_of(s),
                )
                new_dev[s] = self._write_rows(
                    dev_s, n_start, rows, device=self._device_of(s)
                )
                grew = grew or grew_s
            fresh_agg = None
            if not self._agg_dev:  # first sync only (benign racy read)
                import jax.numpy as jnp

                # max(1, ...): a zero-row array would make the gather's
                # take degenerate; with max_aggregates=0 no aggregate
                # index is ever issued, the row is just dead ballast
                fresh_agg = {}
                for s in shards:
                    dev = self._device_of(s)
                    if dev is not None:
                        import jax

                        with jax.default_device(dev):
                            fresh_agg[s] = jnp.zeros(
                                (max(1, self.max_aggregates),
                                 *G1_ROW_SHAPE), jnp.int32,
                            )
                    else:
                        fresh_agg[s] = jnp.zeros(
                            (max(1, self.max_aggregates), *G1_ROW_SHAPE),
                            jnp.int32,
                        )
            nbytes = int(rows.nbytes) * len(shards)
            with self._lock:
                if self._n != n_start or (
                    shards and self._dev.get(shards[0])
                    is not dev_start.get(shards[0])
                ):
                    continue  # a concurrent sync committed first: redo
                # commit only now, replica dict replaced WHOLESALE (all
                # shards or none). Aggregate rows live in their own
                # arrays and SURVIVE capacity growth — their encoded
                # index (cap_v + slot) is recomputed against the new
                # base on every resolve.
                self._dev = new_dev
                if not self._agg_dev:
                    # fresh_agg is non-None here: _agg_dev only ever
                    # goes empty -> populated, so empty at commit
                    # implies the snapshot read above also saw empty
                    # and built one
                    self._agg_dev = fresh_agg
                self._cap_v = cap_v
                for i, p in enumerate(points):
                    self._point_ids[id(p)] = n_start + i
                added = n_host - n_start
                self._n = n_host
                self._uploads[reason] = (
                    self._uploads.get(reason, 0) + nbytes
                )
                cap_total = sum(
                    int(d.shape[0]) for d in self._dev.values()
                ) + sum(int(a.shape[0]) for a in self._agg_dev.values())
            break
        else:
            raise KeyTableError("sync starved by concurrent syncs")
        _ENTRIES.with_labels("validators").set(self._n)
        _DEVICE_BYTES.set(cap_total * G1_ROW_BYTES)
        _UPLOAD_BYTES.with_labels(reason).inc(nbytes)
        flight_recorder.record(
            "key_table_sync",
            reason=reason,
            added=added,
            resident=self._n,
            capacity=self._cap_v,
            upload_bytes=nbytes,
            replicas=len(shards),
            grew=grew,
        )
        return added

    def _pack_rows(self, new: Sequence, base_index: int):
        """Validate + limb-pack host pubkeys into int32[n, 2, NL] rows.
        Raises before any device state is touched."""
        from . import curve

        points = []
        for off, pk in enumerate(new):
            point = getattr(pk, "point", None)
            if point is None or point.is_infinity():
                raise KeyTableError(
                    f"invalid pubkey at cache index {base_index + off}: "
                    f"{'infinity' if point is not None else 'no point'} — "
                    f"admission must reject it before the device mirror"
                )
            points.append(point)
        rows, inf = curve.pack_g1(points)
        if inf.any():
            raise KeyTableError("infinity row survived packing")
        if rows.shape[1:] != G1_ROW_SHAPE:
            raise KeyTableError(
                f"packed row shape {rows.shape[1:]} != {G1_ROW_SHAPE} — "
                f"fp.NL drifted from key_table.NL"
            )
        return np.ascontiguousarray(rows, np.int32), points

    @staticmethod
    def _on_device(device):
        """``jax.default_device`` scope for one replica's writes (no-op
        when the mesh has no real device object for the shard)."""
        if device is None:
            import contextlib

            return contextlib.nullcontext()
        import jax

        return jax.default_device(device)

    def _grown_array(self, dev_start, cap_start: int, n_start: int,
                     n_host: int, device=None):
        """(device array sized for n_host, cap_v, grew): reuses the
        snapshot array when capacity suffices, else allocates the next
        ladder rung ON ``device`` and copies resident validator rows
        DEVICE-side. Pure function of its snapshots — runs outside the
        lock."""
        import jax.numpy as jnp

        cap_v = table_capacity(n_host)
        if dev_start is not None and cap_v <= cap_start:
            return dev_start, cap_start, False
        with self._on_device(device):
            dev = jnp.zeros((cap_v, *G1_ROW_SHAPE), jnp.int32)
            if dev_start is not None and n_start:
                dev = dev.at[:n_start].set(dev_start[:n_start])
        return dev, cap_v, dev_start is not None

    def _write_rows(self, dev, offset: int, rows: np.ndarray, device=None):
        """Host→device upload of ``rows`` at ``offset`` (onto the
        replica's own device): the transfer is chunked
        (``upload_chunk_rows`` bounds each host→device DMA) but the
        functional table update happens ONCE — each eager ``.at.set``
        copies the whole table array, so a per-chunk update loop would
        pay a full-table device copy per chunk."""
        import jax.numpy as jnp

        with self._on_device(device):
            parts = [
                jnp.asarray(rows[i: i + self.upload_chunk_rows])
                for i in range(0, len(rows), self.upload_chunk_rows)
            ]
            staged = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            return dev.at[offset: offset + len(rows)].set(staged)

    # -- re-sync retry (ISSUE 13) -----------------------------------------

    def sync_or_schedule(self, reason: str = "delta") -> Optional[int]:
        """The admission listener's entry: try the sync; on failure
        schedule a full-sync retry with backoff and return None instead
        of raising into the admission path. The table serves what it
        has meanwhile — non-resident keys fall back to the raw pack,
        verdict-identical, until the retry catches the mirror up."""
        try:
            n = self.sync(reason=reason)
        except Exception as e:
            self._schedule_resync(e)
            return None
        with self._resync_lock:
            self._resync_failures = 0
        return n

    def _schedule_resync(self, error: BaseException) -> None:
        with self._resync_lock:
            if self._closed:
                return
            self._resync_failures += 1
            fails = self._resync_failures
            if self._resync_timer is not None:
                return  # one pending retry at a time; it re-syncs fully
            delay = min(
                self._resync_max_s,
                self._resync_base_s * (2.0 ** (fails - 1)),
            ) * random.uniform(0.5, 1.0)
            t = threading.Timer(delay, self._resync_run)
            t.daemon = True
            self._resync_timer = t
            self._resyncs["scheduled"] += 1
            t.start()
        _RESYNCS.with_labels("scheduled").inc()
        from ...utils import logging as tlog

        tlog.log(
            "warn",
            "key-table sync failed — full-sync retry scheduled",
            failures=fails, delay_s=round(delay, 3),
            error=repr(error)[:120],
        )

    def _resync_run(self) -> None:
        with self._resync_lock:
            self._resync_timer = None
            if self._closed:
                return
        try:
            self.sync(reason="recovery")
        except Exception as e:
            with self._resync_lock:
                self._resyncs["error"] += 1
            _RESYNCS.with_labels("error").inc()
            self._schedule_resync(e)
            return
        with self._resync_lock:
            self._resync_failures = 0
            self._resyncs["ok"] += 1
        _RESYNCS.with_labels("ok").inc()

    def close(self) -> None:
        """Stop the retry machinery (``Client.stop()``): cancel any
        pending re-sync timer and refuse new ones — a stopped client's
        table must not keep syncing in the background."""
        with self._resync_lock:
            self._closed = True
            t = self._resync_timer
            self._resync_timer = None
        if t is not None:
            t.cancel()

    # -- resolution (the static/dynamic packer decision) ------------------

    def index_of_point(self, point) -> Optional[int]:
        """Validator index of ``point`` IF it is the host cache's own
        object (identity, not equality — see module docstring)."""
        return self._point_ids.get(id(point))

    def resolve_sets(self, sets):
        """Resolve prepared ``(sig, [G1Point...], msg)`` triples to table
        indices. Returns ``None`` when ANY pubkey is not table-resident
        (the caller falls back to the raw limb-plane pack — per
        sub-batch, the flush planner keeps mixed flushes split), else
        ``(per_set_index_lists, validator_array, aggregate_array,
        n_collapsed)`` where the two device snapshots are guaranteed to
        contain every returned index. Resolution is TWO-PHASE: every
        set's indices resolve before any aggregate-cache mutation, so a
        batch that falls back raw never pays host sums, row uploads or
        aggregate telemetry for its leading sets.

        Shipping-path accounting (``sets_total{indexed|collapsed}``) is
        the DISPATCHER's job via :meth:`count_shipped` — one commit
        point, once the batch is definitely taking the indexed path.
        Only the ``raw`` fallback is counted here (it is final)."""
        with self._lock:
            if not self._dev:
                return None
            # the replica the CURRENT dispatch shard gathers from
            # (ISSUE 11), resolved FIRST: a shard with no replica falls
            # back raw before any aggregate-cache work (its packed
            # planes then land on its own device consistently), keeping
            # the two-phase no-side-effects-before-fallback discipline
            shard = self._resolve_shard_locked()
            if shard is None:
                n = len(sets)
                self._sets["raw"] += n
                _SETS.with_labels("raw").inc(n)
                return None
            # epoch-tagged retention (ISSUE 19): applied only HERE,
            # before any slot of this batch is handed out, so every
            # slot a batch encodes stays valid until its snapshot below.
            # At an epoch roll, entries older than two epochs move to
            # the free-list; the wholesale reset fires only when the
            # region filled and eviction freed nothing (everything
            # resident is still inside its retention window).
            cur_epoch = slot_clock.get_clock().current_epoch()
            if self._agg_epoch_seen != cur_epoch:
                self._agg_epoch_seen = cur_epoch
                self._evict_stale_locked(cur_epoch, journal=True)
            if self._agg_reset_pending:
                self._agg_reset_pending = False
                if not self._agg_free:
                    if not self._evict_stale_locked(cur_epoch, journal=True):
                        self._reset_aggregates_locked(journal=True)
            resolved: List[List[int]] = []
            for _sig, pks, _msg in sets:
                idxs = []
                for p in pks:
                    i = self._point_ids.get(id(p))
                    if i is None:
                        n = len(sets)
                        self._sets["raw"] += n
                        _SETS.with_labels("raw").inc(n)
                        return None
                    idxs.append(i)
                resolved.append(idxs)
            # the batch is fully resident — NOW consult the aggregate
            # cache: hits take their slot, repeat tuples become insert
            # candidates (sum computed OUTSIDE the lock below). Hits
            # record the RAW slot — encoding against the validator
            # capacity happens in the commit lock, because a concurrent
            # capacity-growing sync() between the two phases moves the
            # region base (slots never move; the base does)
            hits: Dict[int, int] = {}       # set position -> RAW agg slot
            miss_positions: Dict[bytes, List[int]] = {}
            cand_keys: Dict[bytes, list] = {}  # key -> pks, ONE sum per key
            if self.max_aggregates:
                for j, (idxs, (_sig, pks, _msg)) in enumerate(
                    zip(resolved, sets)
                ):
                    if len(idxs) <= 1:
                        continue
                    key = self._agg_key(idxs)
                    slot = self._agg_slots.get(key, -1)
                    if slot is None:
                        continue  # known-uncacheable (sum is infinity)
                    if slot >= 0:
                        self._agg_hits += 1
                        _AGG_EVENTS.with_labels("hit").inc()
                        # chain-time (ISSUE 17): a collapsed K=1 row
                        # served this committee — the numerator of the
                        # per-epoch first-sighting dial
                        slot_ledger.note_committee_sighting("hit")
                        hits[j] = slot
                        continue
                    _AGG_EVENTS.with_labels("miss").inc()
                    # first sighting: the host EC sum territory — the
                    # denominator's other half (first + hits = committee
                    # sightings, conservation-pinned)
                    slot_ledger.note_committee_sighting("first")
                    miss_positions.setdefault(key, []).append(j)
                    if len(self._agg_seen) >= _AGG_SEEN_CAP:
                        self._agg_seen.clear()
                    seen = self._agg_seen.get(key, 0) + 1
                    self._agg_seen[key] = seen
                    if seen >= self.agg_min_repeats:
                        # dedup by key: N repeats of one tuple in one
                        # batch pay ONE host sum, and the slot applies
                        # to every position below
                        cand_keys.setdefault(key, list(pks))
            gen = self._agg_gen
        # host EC summation + packing WITHOUT the lock: a 512-member
        # sync-committee sum is hundreds of pure-Python point adds, and
        # holding the table lock for it would serialize every verifier
        # thread and the admission listener behind host arithmetic
        prepared: List[Tuple[bytes, Optional[np.ndarray]]] = []
        for key, pks in cand_keys.items():
            agg = pks[0]
            for p in pks[1:]:
                agg = agg + p
            if agg.is_infinity():
                # never cache: the raw path fails this set through the
                # device agg_inf_bad screen, and a cached infinity row
                # would instead trip the backend's infinity pre-screen —
                # same verdict, different screen; keep ONE behavior
                prepared.append((key, None))
            else:
                from . import curve

                rows, _inf = curve.pack_g1([agg])
                prepared.append(
                    (key, np.ascontiguousarray(rows, np.int32))
                )
        collapsed = 0
        with self._lock:
            if self._agg_gen != gen:
                # a reset raced this batch: every slot assigned above may
                # have been recycled — ship K indices (correct, just not
                # collapsed) rather than gather someone else's sum
                hits = {}
            else:
                for key, row in prepared:
                    if row is None:
                        self._agg_slots[key] = None
                        continue
                    slot = self._agg_slots.get(key, -1)
                    if slot is None:
                        continue
                    if slot < 0:
                        if self._agg_free:
                            # slots recycled by per-epoch eviction are
                            # reused before the high-water mark grows
                            slot = self._agg_free.pop()
                        elif self._agg_next < self.max_aggregates:
                            slot = self._agg_next
                            self._agg_next += 1
                        else:
                            # bounded region: recycle at the START of
                            # the next batch (see ctor comment) —
                            # eviction first, wholesale reset only if
                            # nothing is stale
                            self._agg_reset_pending = True
                            continue
                        # the insert copies only the SMALL aggregate
                        # arrays (~max_agg rows each), never the
                        # validator table — and writes EVERY replica
                        # under the same lock, so the mesh's aggregate
                        # regions can never disagree on what a slot
                        # holds. The seen count is KEPT: after a region
                        # reset an evicted hot tuple re-inserts on its
                        # very next sighting
                        for s in list(self._agg_dev):
                            self._agg_dev[s] = self._write_rows(
                                self._agg_dev[s], slot, row,
                                device=self._device_of(s),
                            )
                        self._agg_slots[key] = slot
                        self._agg_epochs[key] = cur_epoch
                        self._agg_resident += 1
                        self._agg_inserts += 1
                        # counted PER REPLICA, like sync(): the row
                        # really crossed the boundary once per chip
                        row_bytes = G1_ROW_BYTES * max(
                            1, len(self._agg_dev)
                        )
                        self._uploads["aggregate"] += row_bytes
                        _AGG_EVENTS.with_labels("insert").inc()
                        _UPLOAD_BYTES.with_labels("aggregate").inc(
                            row_bytes
                        )
                        _ENTRIES.with_labels("aggregates").set(
                            self._agg_resident
                        )
                    # slot >= 0 here covers the raced-duplicate-insert
                    # case too: another thread cached the same tuple
                    # between our phases — reuse its row (for EVERY
                    # position of this tuple in the batch)
                    for j in miss_positions.get(key, ()):
                        hits[j] = slot
            # encode against the CURRENT base, inside the same lock the
            # dev/agg snapshots are taken under: a capacity growth
            # between the phases moved the base, and a stale encoding
            # would gather a VALIDATOR row where the aggregate region
            # begins
            for j, slot in hits.items():
                resolved[j] = [self._cap_v + slot]
            collapsed = len(hits)
            # snapshot the phase-1 shard's replica (replica dicts are
            # only ever replaced wholesale, so the key still exists)
            dev = self._dev[shard]
            agg_dev = self._agg_dev.get(shard)
        return resolved, dev, agg_dev, collapsed

    def covers_sets(self, sets) -> bool:
        """jax-free eligibility predicate for the flush planner: would
        :meth:`resolve_sets` succeed for these sets? Accepts
        ``SignatureSet`` objects or ``(sig, pks, msg)`` triples.
        ``signing_indices`` (threaded by state_transition/signature_sets)
        is a fast pre-filter; the identity map is the ground truth
        either way, so a planner misprediction costs padding, never
        correctness."""
        if self._n == 0:
            return False
        for item in sets:
            keys = getattr(item, "signing_keys", None)
            if keys is None and isinstance(item, (tuple, list)) and len(item) == 3:
                keys = item[1]
            if not keys:
                return False
            idxs = getattr(item, "signing_indices", None)
            if idxs is not None and any(
                not 0 <= int(i) < self._n for i in idxs
            ):
                return False
            for pk in keys:
                point = getattr(pk, "point", pk)
                if id(point) not in self._point_ids:
                    return False
        return True

    # -- aggregate-sum cache ----------------------------------------------

    @staticmethod
    def _agg_key(idxs: Sequence[int]) -> bytes:
        # order-insensitive: the sum is commutative, so two aggregates
        # over the same participant set share one row
        h = hashlib.blake2b(digest_size=16)
        for i in sorted(idxs):
            h.update(int(i).to_bytes(8, "little"))
        return h.digest()

    def insert_precomputed(self, idxs, point, epoch: Optional[int] = None) -> str:
        """Duty-lookahead entry (ISSUE 19): pre-insert the aggregate sum
        ``point`` for validator-index tuple ``idxs``, computed OFF the
        hot path, tagged for ``epoch`` (default: the clock's NEXT epoch
        — the shuffle a lookahead walks is deterministic an epoch
        ahead). Bypasses ``agg_min_repeats`` — a lookahead-sourced
        committee's FIRST sighting already ships K=1 — while leaving the
        reactive path's admission rules untouched. Never forces the
        wholesale reset: when the region is full and per-epoch eviction
        frees nothing, the pre-insert is declined (``"full"``) and the
        reactive path keeps owning the recycle policy.

        Returns an outcome string: ``inserted`` | ``exists`` (already
        cached — the retention tag is extended through the target
        epoch) | ``infinity`` (never cached, marked so the device
        ``agg_inf_bad`` screen keeps owning the edge) | ``never_cache``
        (previously marked infinity) | ``full`` | ``unsynced`` (no
        device region yet) | ``disabled``. The caller journals failures
        (``lookahead_insert_failed``) — this method stays jax-free
        until a row is actually written."""
        idxs = [int(i) for i in idxs]
        if self.max_aggregates <= 0 or len(idxs) <= 1:
            return "disabled"
        key = self._agg_key(idxs)
        if point is None or point.is_infinity():
            with self._lock:
                self._agg_slots[key] = None
            return "infinity"
        from . import curve

        rows, inf = curve.pack_g1([point])
        if inf.any():
            return "infinity"
        row = np.ascontiguousarray(rows, np.int32)
        with self._lock:
            if not self._agg_dev:
                return "unsynced"
            cur_epoch = slot_clock.get_clock().current_epoch()
            tag = (cur_epoch + 1) if epoch is None else int(epoch)
            existing = self._agg_slots.get(key, -1)
            if existing is None:
                return "never_cache"
            if existing >= 0:
                # the reactive path cached it first: keep that row but
                # extend retention through the lookahead's target epoch
                self._agg_epochs[key] = max(
                    self._agg_epochs.get(key, tag), tag
                )
                return "exists"
            if self._agg_free:
                slot = self._agg_free.pop()
            elif self._agg_next < self.max_aggregates:
                slot = self._agg_next
                self._agg_next += 1
            else:
                self._evict_stale_locked(cur_epoch, journal=True)
                if not self._agg_free:
                    return "full"
                slot = self._agg_free.pop()
            for s in list(self._agg_dev):
                self._agg_dev[s] = self._write_rows(
                    self._agg_dev[s], slot, row,
                    device=self._device_of(s),
                )
            self._agg_slots[key] = slot
            self._agg_epochs[key] = tag
            self._agg_resident += 1
            self._agg_precomputed += 1
            row_bytes = G1_ROW_BYTES * max(1, len(self._agg_dev))
            self._uploads["aggregate"] += row_bytes
            resident = self._agg_resident
        _AGG_EVENTS.with_labels("precomputed").inc()
        _UPLOAD_BYTES.with_labels("aggregate").inc(row_bytes)
        _ENTRIES.with_labels("aggregates").set(resident)
        return "inserted"

    def _evict_stale_locked(self, cur_epoch: int, journal: bool) -> int:
        """Two-epoch retention (ISSUE 19): drop every entry whose epoch
        tag is two or more epochs behind ``cur_epoch`` — its committee
        reshuffled away and even straggler attestations for it are past
        — returning the slots to the free-list for reuse. ``_agg_seen``
        survives like the wholesale reset's contract; the generation
        bump tells any batch that already took slots to ship K indices
        instead of a recycled row. Returns entries evicted (0 = nothing
        stale, no generation bump)."""
        stale = [
            k for k, e in self._agg_epochs.items() if e + 2 <= cur_epoch
        ]
        if not stale:
            return 0
        dropped_epochs = sorted({self._agg_epochs[k] for k in stale})
        for k in stale:
            slot = self._agg_slots.pop(k, None)
            del self._agg_epochs[k]
            if slot is not None and slot >= 0:
                self._agg_free.append(slot)
        freed = len(stale)
        self._agg_resident = max(0, self._agg_resident - freed)
        self._agg_evictions += freed
        self._agg_gen += 1
        _AGG_EVENTS.with_labels("evict").inc(freed)
        _ENTRIES.with_labels("aggregates").set(self._agg_resident)
        if journal:
            flight_recorder.record(
                "key_table_reset",
                region="aggregates",
                mode="evict_epochs",
                dropped=freed,
                epochs=",".join(str(e) for e in dropped_epochs),
                retained=self._agg_resident,
                current_epoch=cur_epoch,
            )
        return freed

    def _reset_aggregates_locked(self, journal: bool) -> None:
        """Recycle the bounded aggregate region WHOLESALE — since
        ISSUE 19 only the last resort, when the region filled inside a
        single epoch and per-epoch eviction freed nothing. ``_agg_seen``
        survives (it has its own cap) so an evicted hot tuple re-inserts
        on its next sighting; the generation bump tells any batch that
        already took slots to ship K indices instead of a recycled
        row."""
        had = self._agg_resident
        self._agg_slots.clear()
        self._agg_epochs.clear()
        self._agg_free.clear()
        self._agg_next = 0
        self._agg_resident = 0
        self._agg_resets += 1
        self._agg_gen += 1
        _AGG_EVENTS.with_labels("reset").inc()
        _ENTRIES.with_labels("aggregates").set(0)
        if journal:
            flight_recorder.record(
                "key_table_reset", region="aggregates", mode="wholesale",
                dropped=had,
            )

    # -- accounting helpers ------------------------------------------------

    def count_shipped(self, n_indexed: int, n_collapsed: int) -> None:
        """Commit a dispatched batch's final shipping-path accounting —
        called by the dispatcher once the batch is definitely taking
        the indexed path (resolution alone is not shipping)."""
        with self._lock:
            self._sets["indexed"] += int(n_indexed)
            self._sets["collapsed"] += int(n_collapsed)
        if n_indexed:
            _SETS.with_labels("indexed").inc(int(n_indexed))
        if n_collapsed:
            _SETS.with_labels("collapsed").inc(int(n_collapsed))

    def count_raw(self, n_sets: int) -> None:
        """A batch fell back to the raw plane for a reason resolve_sets
        did not see (e.g. non-Signature raw-mode screen)."""
        with self._lock:
            self._sets["raw"] += int(n_sets)
        _SETS.with_labels("raw").inc(int(n_sets))

    def device_arrays(self, shard: Optional[int] = None):
        """(validator array, aggregate array) snapshot for one replica
        — the pair the gather program dispatches against (indices >=
        the validator array's length address the aggregate region).
        ``shard=None`` resolves the current dispatch shard (falling
        back to the lowest replica); ``(None, None)`` when that shard
        has no replica or the table is empty."""
        with self._lock:
            if not self._dev:
                return None, None
            if shard is None:
                s = self._resolve_shard_locked()
                if s is None:
                    s = min(self._dev)
            else:
                s = int(shard)
                if s not in self._dev:
                    return None, None
            return self._dev[s], self._agg_dev.get(s)

    def __len__(self) -> int:
        return self._n

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """One document for the /lighthouse/health ``key_table`` block."""
        with self._lock:
            sets = dict(self._sets)
            shipped = sets["indexed"] + sets["collapsed"]
            total = shipped + sets["raw"]
            cap_total = sum(
                int(d.shape[0]) for d in self._dev.values()
            ) + sum(int(a.shape[0]) for a in self._agg_dev.values())
            return {
                "replicas": sorted(self._dev),
                "validators_resident": self._n,
                "host_cache_len": len(self.cache.pubkeys),
                "validator_capacity": self._cap_v,
                "aggregates_resident": self._agg_resident,
                "aggregate_capacity": self.max_aggregates,
                "aggregate_resets": self._agg_resets,
                "aggregate_hits": self._agg_hits,
                "aggregate_inserts": self._agg_inserts,
                "aggregate_precomputed": self._agg_precomputed,
                "aggregate_evictions": self._agg_evictions,
                "aggregate_free_slots": len(self._agg_free),
                "aggregate_epochs": sorted(
                    set(self._agg_epochs.values())
                ),
                "device_bytes": cap_total * G1_ROW_BYTES,
                "upload_bytes": dict(self._uploads),
                "sets": sets,
                "hit_ratio": round(shipped / total, 4) if total else None,
                "identity_pinned": self._n <= len(self.cache.pubkeys),
                "resyncs": dict(self._resyncs),
                "resync_failures": self._resync_failures,
                "resync_pending": self._resync_timer is not None,
            }


# ---------------------------------------------------------------------------
# Process-global table (the seam bls.TpuBackend and the flush planner
# reach without plumbing a handle; the client builder owns the lifecycle)
# ---------------------------------------------------------------------------

_table_lock = threading.Lock()
_table: Optional[DeviceKeyTable] = None


def set_table(table: Optional[DeviceKeyTable]) -> None:
    global _table
    with _table_lock:
        _table = table


def clear_table(table: Optional[DeviceKeyTable] = None) -> None:
    """Detach the global table (only if it still IS ``table`` when one
    is given — a racing rebuild must not lose its fresh table)."""
    global _table
    with _table_lock:
        if table is None or _table is table:
            _table = None


def get_table() -> Optional[DeviceKeyTable]:
    return _table


def get_active_table() -> Optional[DeviceKeyTable]:
    """The attached table, when it has resident rows to gather from."""
    t = _table
    if t is not None and len(t):
        return t
    return None
