"""BLS12-381 base field Fp on device: 12-bit x 32 limb arithmetic in int32.

Design (TPU-first, not a port of blst's 64-bit asm):

* An Fp element is ``int32[..., 32]``: 32 little-endian limbs of 12 bits.
  381-bit values fit in 384 bits. Leading dims are batch dims; every op
  broadcasts, so the whole stack is batched without ``vmap``.
* 12-bit limbs are chosen so schoolbook products never overflow int32:
  a full-product column is at most ``16 * LIMB_MAX**2 < 2**31``. The TPU
  VPU has no 64-bit multiply-high; 12x12->24-bit products with 32-bit
  accumulation map directly onto int32 vector lanes.
* Multiplication = banded-Toeplitz matmul: gather ``y`` into a
  ``[..., 32, 63]`` band matrix, one batched ``dot_general`` computes all
  63 product columns (2016 MACs — the minimal schoolbook work), then the
  columns are reduced mod p by folding limbs >= 32 through a precomputed
  ``2**(12*i) mod p`` table (another small matmul). No Montgomery form:
  the fold table plays the role blst's Montgomery REDC plays
  (``/root/reference/crypto/bls/src/impls/blst.rs`` links the asm).
* Values are kept *relaxed*: limbs in ``[0, LIMB_MAX]``, value in
  ``[0, 2**384)``-ish, only congruent mod p. ``canonical`` produces the
  unique strict representative for equality/serialization.
* Every reduction plan is derived at trace time by exact interval
  arithmetic on per-limb bounds, asserting that no intermediate can
  overflow int32 — machine-checked, not hand-waved.

Subtraction uses a "saturated" multiple of p (every digit >= LIMB_MAX) so
``x - y + SAT`` is limb-wise non-negative — branch-free and select-free.

Multiplication exists in selectable implementations (``FP_IMPL``):

* ``toeplitz_int32`` — the original banded dot over int32 operands.
  Correct everywhere, but int32 multiplies execute on the TPU VPU
  (~2e12 MAC/s on v5e), which caps the whole verifier well below target
  (``docs/COST_MODEL.md``).
* ``matmul_int8`` — each limb is split into int8-ranged halves
  (``hi = limb >> SPLIT_SHIFT``, ``lo = limb & SPLIT_MASK``) and the
  banded product becomes FOUR int8 x int8 -> int32 ``dot_general``
  passes recombined with shifts — the dtype shape XLA lowers onto the
  MXU systolic array (~4.9e13 MAC/s envelope). Same column values,
  machine-checked to recombine without overflow.
* ``pallas_int8`` — the same int8 decomposition as a hand-placed Pallas
  kernel (``pallas_fp.py``), for when XLA keeps the int8 dots on the
  VPU; interpreted off-TPU, so it stays differential-testable.

Select with ``LIGHTHOUSE_TPU_FP_IMPL`` (env, like the BLS backend flag in
``crypto/backend.py``) or :func:`set_impl` / the :func:`impl` context
manager. NOTE: callers that hold jitted programs must call
``device.reset_compiled_state()`` (crypto/device/__init__.py) after
switching — dispatch happens at trace time, and that helper also resets
recompile tracking and the compile service's warm-shape registry.
"""

from __future__ import annotations

import contextlib
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..params import P

# ---------------------------------------------------------------------------
# Layout constants
# ---------------------------------------------------------------------------

ELEM_NDIM = 1             # trailing element dims of an fp array: (NL,)
W = 12                    # bits per limb
NL = 32                   # limbs per element (384 bits >= 381)
MASK = (1 << W) - 1       # 0xFFF
LIMB_MAX = 8191           # relaxed per-limb bound maintained by reduce_cols
NCOLS = 2 * NL - 1        # full-product column count

# Products are accumulated in int32 over *half* the limbs at a time
# (16 * LIMB_MAX**2 < 2**31); see mul().
assert (NL // 2) * (LIMB_MAX ** 2) < 2 ** 31, "half-conv columns must fit int32"

# ---------------------------------------------------------------------------
# int8 limb split (matmul_int8 / pallas_int8 implementations)
# ---------------------------------------------------------------------------
# A relaxed limb carries up to 13 bits (LIMB_MAX = 8191), so the paper-style
# high-8/low-4 split of a strict 12-bit digit does not fit the SIGNED int8
# operands the MXU consumes natively. The split point is therefore *derived*:
# the smallest shift whose high half fits int8, which lands at hi = limb >> 6
# (<= 127) and lo = limb & 63 — a (7+6)-bit split with identical algebra:
#     x*y = (xh*yh << 2S) + ((xh*yl + xl*yh) << S) + xl*yl,  S = SPLIT_SHIFT
_INT8_MAX = 127
SPLIT_SHIFT = next(
    s for s in range(1, 13) if (LIMB_MAX >> s) <= _INT8_MAX
)
SPLIT_MASK = (1 << SPLIT_SHIFT) - 1
assert (LIMB_MAX >> SPLIT_SHIFT) <= _INT8_MAX and SPLIT_MASK <= _INT8_MAX


# ---------------------------------------------------------------------------
# Host-side packing helpers
# ---------------------------------------------------------------------------

def int_to_limbs(x: int) -> np.ndarray:
    """Python int -> strict little-endian 12-bit limbs, int32[32]."""
    assert 0 <= x < 1 << (W * NL)
    return np.array([(x >> (W * i)) & MASK for i in range(NL)], np.int32)


def limbs_to_int(a) -> int:
    """Limb array (any relaxed representation) -> Python int value."""
    a = np.asarray(a)
    return sum(int(v) << (W * i) for i, v in enumerate(a.reshape(-1).tolist()))


def _digits(x: int, n: int) -> list[int]:
    return [(x >> (W * i)) & MASK for i in range(n)]


# ---------------------------------------------------------------------------
# Module-level tables (numpy; jnp converts on first use)
# ---------------------------------------------------------------------------

# Fold table: FOLD[i] = limbs of 2**(W*(NL+i)) mod p, for high limb NL+i.
_FOLD_HI = 64
FOLD = np.stack(
    [int_to_limbs(pow(1 << W, NL + i, P)) for i in range(_FOLD_HI)]
)  # [64, 32] int32, strict digits

# Banded-Toeplitz gather index/mask for multiplication.
_IDX = np.zeros((NL, NCOLS), np.int32)
_BANDMASK = np.zeros((NL, NCOLS), np.int32)
for _a in range(NL):
    for _c in range(NCOLS):
        _d = _c - _a
        if 0 <= _d < NL:
            _IDX[_a, _c] = _d
            _BANDMASK[_a, _c] = 1

# Saturated multiple of p for branch-free subtraction: SAT digits all in
# [LIMB_MAX, ...], value = m*p. Found by a small search.
def _saturated_multiple() -> tuple[np.ndarray, int]:
    S = sum(1 << (W * i) for i in range(NL))  # all-ones weight sum
    for m in range(10, 64):
        t = m * P - LIMB_MAX * S
        if t < 0:
            continue
        d = _digits(t, NL)
        if sum(v << (W * i) for i, v in enumerate(d)) != t:
            continue  # does not fit in 32 digits
        sat = [LIMB_MAX + v for v in d]
        if max(sat) * 2 < 2 ** 20:  # comfortably small
            return np.array(sat, np.int32), m
    raise AssertionError("no saturated multiple of p found")


SAT, _SAT_M = _saturated_multiple()
assert limbs_to_int(SAT) == _SAT_M * P

# Strict digits of 2**384 - k*p for canonical conditional subtraction.
_CSUB_KS = (8, 4, 2, 1)
CSUB = np.stack([np.array(_digits((1 << (W * NL)) - k * P, NL), np.int32)
                 for k in _CSUB_KS])

ZERO = int_to_limbs(0)
ONE = int_to_limbs(1)


# ---------------------------------------------------------------------------
# Reduction: columns -> relaxed 32-limb representative (mod p)
# ---------------------------------------------------------------------------

def _carry_round(cols, bounds):
    """One parallel carry round; widens by one limb. Exact value preserved."""
    assert all(b < 2 ** 31 for b in bounds), f"int32 overflow risk: {bounds}"
    r = cols & MASK
    c = cols >> W
    pad = [(0, 0)] * (cols.ndim - 1)
    r = jnp.pad(r, pad + [(0, 1)])
    c = jnp.pad(c, pad + [(1, 0)])
    rb = [min(b, MASK) for b in bounds] + [0]
    cb = [0] + [b >> W for b in bounds]
    return r + c, [a + b for a, b in zip(rb, cb)]


# Pallas kernels may not capture traced constants: a kernel that calls
# reduce_cols passes the FOLD table in through a ref and installs it here
# for the duration of its trace (see pallas_fp2.py). None -> the module
# table as usual.
_FOLD_OVERRIDE = None


@contextlib.contextmanager
def fold_table(table):
    """Scoped FOLD-table source override (trace-time, kernel-internal)."""
    global _FOLD_OVERRIDE
    prev = _FOLD_OVERRIDE
    _FOLD_OVERRIDE = table
    try:
        yield
    finally:
        _FOLD_OVERRIDE = prev


def _fold_round(cols, bounds):
    """Fold limbs >= NL through the 2**(12i) mod p table (exact mod p)."""
    n = len(bounds)
    k = n - NL
    assert k > 0
    lo, hi = cols[..., :NL], cols[..., NL:]
    table = (
        jnp.asarray(FOLD[:k]) if _FOLD_OVERRIDE is None
        else _FOLD_OVERRIDE[:k]
    )
    out = lo + jnp.einsum("...h,hl->...l", hi, table,
                          preferred_element_type=jnp.int32)
    ob = [bounds[i] + sum(bounds[NL + h] * int(FOLD[h, i]) for h in range(k))
          for i in range(NL)]
    assert all(b < 2 ** 31 for b in ob), f"fold overflow risk: {ob}"
    return out, ob


def _fold_safe(bounds) -> bool:
    k = len(bounds) - NL
    if k <= 0:
        return False
    return all(
        bounds[i] + sum(bounds[NL + h] * int(FOLD[h, i]) for h in range(k))
        < 2 ** 31
        for i in range(NL)
    )


def reduce_cols(cols, bounds):
    """Reduce arbitrary product columns to the relaxed 32-limb form.

    ``bounds`` is a Python list of exact per-column upper bounds; the
    carry/fold schedule is chosen at trace time and asserts int32 safety
    for every intermediate.
    """
    bounds = list(bounds)
    assert cols.shape[-1] == len(bounds)
    for _ in range(32):
        if len(bounds) == NL and max(bounds) <= LIMB_MAX:
            return cols
        if _fold_safe(bounds):
            cols, bounds = _fold_round(cols, bounds)
        else:
            cols, bounds = _carry_round(cols, bounds)
    raise AssertionError(f"reduction did not converge: {bounds}")


# ---------------------------------------------------------------------------
# Field operations (all broadcast over leading dims)
# ---------------------------------------------------------------------------

_B_IN = [LIMB_MAX] * NL  # invariant bound on any input element


def add(x, y):
    return reduce_cols(x + y, [2 * LIMB_MAX] * NL)


def sub(x, y):
    return reduce_cols(x + (jnp.asarray(SAT) - y),
                       [LIMB_MAX + int(v) for v in SAT])


def neg(x):
    return reduce_cols(jnp.asarray(SAT) - x, [int(v) for v in SAT])


def mul_small(x, k: int):
    """Multiply by a small non-negative Python int (k * LIMB_MAX < 2**31)."""
    assert 0 <= k and k * LIMB_MAX < 2 ** 31
    return reduce_cols(x * k, [k * LIMB_MAX] * NL)


def _overlap(c: int, lo: int, hi: int) -> int:
    """Number of a in [lo, hi) with 0 <= c - a < NL (terms in column c)."""
    return max(0, min(c, hi - 1) - max(lo, c - (NL - 1)) + 1)


_H = NL // 2
_HALF_BOUNDS = [
    [_overlap(c, 0, _H) * LIMB_MAX ** 2 for c in range(NCOLS)],
    [_overlap(c, _H, NL) * LIMB_MAX ** 2 for c in range(NCOLS)],
]

# Exact per-column product bound for the FULL 32-term schoolbook band
# (the int8 decomposition recombines to the exact column value, so the
# full-width profile applies; peak 32 * 8191**2 = 2,146,959,392 < 2**31).
MUL_COL_BOUNDS = [_overlap(c, 0, NL) * LIMB_MAX ** 2 for c in range(NCOLS)]
assert max(MUL_COL_BOUNDS) < 2 ** 31, "full-band columns must fit int32"
# The shifted high-high partial is the largest recombination intermediate;
# machine-check it independently of the exact total.
assert (
    NL * (LIMB_MAX >> SPLIT_SHIFT) ** 2 << (2 * SPLIT_SHIFT)
) < 2 ** 31, "hh<<2S recombination must fit int32"


def band_matrix(y):
    """Gather ``y`` into the ``[..., NL, NCOLS]`` banded-Toeplitz matrix
    shared by every mul implementation."""
    return jnp.take(y, jnp.asarray(_IDX), axis=-1) * jnp.asarray(_BANDMASK)


def _mul_toeplitz_int32(x, y):
    """Banded-Toeplitz schoolbook product, split into two 16-limb dots so
    int32 accumulation cannot overflow at LIMB_MAX; each half gets one
    carry round before the halves are combined and reduced."""
    band = band_matrix(y)
    halves = []
    for i, sl in enumerate((slice(0, _H), slice(_H, NL))):
        cols = jnp.einsum("...a,...ac->...c", x[..., sl], band[..., sl, :],
                          preferred_element_type=jnp.int32)
        halves.append(_carry_round(cols, _HALF_BOUNDS[i]))
    (c0, b0), (c1, b1) = halves
    return reduce_cols(c0 + c1, [a + b for a, b in zip(b0, b1)])


def split_int8(a):
    """Stack the int8-ranged halves of limb array ``a`` on a NEW leading
    axis: ``out[0] = a >> SPLIT_SHIFT`` (<= 127), ``out[1] = a & SPLIT_MASK``
    (<= 63). Valid for any value in [0, LIMB_MAX]."""
    return jnp.stack([a >> SPLIT_SHIFT, a & SPLIT_MASK], axis=0).astype(
        jnp.int8
    )


def recombine_int8_passes(passes):
    """``passes[i, j] = (x half i) . (band half j)`` int32 columns ->
    exact product columns via shifts. Overflow-free by the module-level
    bound asserts (the recombined value equals the int32 schoolbook
    column, peak ``max(MUL_COL_BOUNDS) < 2**31``)."""
    hh, hl = passes[0, 0], passes[0, 1]
    lh, ll = passes[1, 0], passes[1, 1]
    return (
        (hh << (2 * SPLIT_SHIFT)) + ((hl + lh) << SPLIT_SHIFT) + ll
    )


def _mul_matmul_int8(x, y):
    """MXU-decomposed product: both operands split into int8 halves, all
    four half-products computed by ONE stacked ``dot_general`` over int8
    operands with int32 accumulation — the operand dtype XLA lowers to
    MXU matmul passes — then recombined with shifts. No per-half carry
    rounds are needed: the recombined columns carry the exact full-band
    bound profile (``MUL_COL_BOUNDS``) and ``reduce_cols`` derives its
    carry/fold schedule from that, machine-checked as always."""
    xs = split_int8(x)                      # [2, ..., NL] int8
    bs = split_int8(band_matrix(y))         # [2, ..., NL, NCOLS] int8
    passes = jnp.einsum(
        "i...a,j...ac->ij...c", xs, bs, preferred_element_type=jnp.int32
    )
    return reduce_cols(recombine_int8_passes(passes), MUL_COL_BOUNDS)


def _mul_pallas_int8(x, y):
    """The int8 decomposition as a hand-placed Pallas kernel (see
    ``pallas_fp.py``) for when the dot_general lowering refuses to leave
    the VPU; interpreted off-TPU so it stays differential-testable."""
    from . import pallas_fp

    return reduce_cols(pallas_fp.mul_cols_int8(x, y), MUL_COL_BOUNDS)


# ---------------------------------------------------------------------------
# Implementation switch (env-selectable, like crypto/backend.py's backend)
# ---------------------------------------------------------------------------

IMPL_TOEPLITZ_INT32 = "toeplitz_int32"
IMPL_MATMUL_INT8 = "matmul_int8"
IMPL_PALLAS_INT8 = "pallas_int8"

_MUL_IMPLS = {
    IMPL_TOEPLITZ_INT32: _mul_toeplitz_int32,
    IMPL_MATMUL_INT8: _mul_matmul_int8,
    IMPL_PALLAS_INT8: _mul_pallas_int8,
}

_active_impl = os.environ.get("LIGHTHOUSE_TPU_FP_IMPL", IMPL_TOEPLITZ_INT32)
if _active_impl not in _MUL_IMPLS:
    raise KeyError(
        f"LIGHTHOUSE_TPU_FP_IMPL={_active_impl!r} unknown; "
        f"have {sorted(_MUL_IMPLS)}"
    )


def get_impl() -> str:
    return _active_impl


def set_impl(name: str) -> None:
    """Select the fp.mul implementation. Dispatch happens at TRACE time:
    callers holding jitted programs (e.g. device/bls.py's staged pipeline)
    must call ``device.reset_compiled_state()`` afterwards or they keep
    the old kernels (and stale warm-shape routing)."""
    global _active_impl
    if name not in _MUL_IMPLS:
        raise KeyError(f"unknown fp impl {name!r}; have {sorted(_MUL_IMPLS)}")
    _active_impl = name


@contextlib.contextmanager
def impl(name: str):
    """Scoped implementation switch (restores the previous choice)."""
    prev = _active_impl
    set_impl(name)
    try:
        yield
    finally:
        set_impl(prev)


def mul(x, y):
    """Schoolbook product mod p under the active implementation — the
    single funnel every fp2/fp6/fp12/curve/pairing multiply drains into."""
    return _MUL_IMPLS[_active_impl](x, y)


def sq(x):
    return mul(x, x)


# ---------------------------------------------------------------------------
# Canonicalization and predicates
# ---------------------------------------------------------------------------

def _seq_carry(cols):
    """Exact sequential carry over limbs -> (strict digits, carry_out)."""
    x = jnp.moveaxis(cols, -1, 0)

    def body(carry, col):
        s = col + carry
        return s >> W, s & MASK

    carry_out, digits = lax.scan(body, jnp.zeros(x.shape[1:], x.dtype), x)
    return jnp.moveaxis(digits, 0, -1), carry_out


def canonical(x):
    """Unique strict representative in [0, p), digits in [0, 4095]."""
    d, c = _seq_carry(x)
    # Relaxed values are < LIMB_MAX * sum(2^(12i)) < 2.0003 * 2**384, so the
    # first carry-out is at most 2; two fold-and-recarry rounds bring the
    # value strictly below 2**384 (each round: v -> v mod 2**384 + c * (2**384
    # mod p), and 2**384 mod p < 2**381).
    for _ in range(2):
        d = d + c[..., None] * jnp.asarray(FOLD[0])
        d, c = _seq_carry(d)
    # Now x < 2**384 < 16p: conditional cascade subtract 8p, 4p, 2p, p.
    for i in range(len(_CSUB_KS)):
        s, c = _seq_carry(d + jnp.asarray(CSUB[i]))
        d = jnp.where((c == 1)[..., None], s, d)
    return d


def is_zero(x):
    """Boolean [...] mask: value == 0 mod p."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(x, y):
    return jnp.all(canonical(x) == canonical(y), axis=-1)


def select(mask, a, b):
    """mask [...] bool -> elementwise field select."""
    return jnp.where(mask[..., None], a, b)


# ---------------------------------------------------------------------------
# Exponentiation (fixed Python-int exponent) and inversion
# ---------------------------------------------------------------------------

def _bits_msb(e: int) -> np.ndarray:
    return np.array([int(b) for b in bin(e)[2:]], np.int32)


def square_multiply(x, e: int, sq_fn, mul_fn, select_fn):
    """Shared fixed-exponent square-and-multiply ladder (MSB-first scan).

    Serves every pow_const in the device stack (fp/fp2/fp12) — one place
    to fix or re-window the ladder. ``e`` must be >= 1.
    """
    assert e >= 1
    bits = _bits_msb(e)
    if len(bits) == 1:
        return x

    def body(acc, bit):
        acc = sq_fn(acc)
        acc = select_fn(bit == 1, mul_fn(acc, x), acc)
        return acc, None

    acc, _ = lax.scan(body, x, jnp.asarray(bits[1:]))
    return acc


def pow_const(x, e: int):
    """x**e for a fixed exponent, as a scan over its bits (MSB first)."""
    return square_multiply(x, e, sq, mul, select)


def inv(x):
    """Fermat inverse x**(p-2); inv(0) = 0 (callers mask separately)."""
    return pow_const(x, P - 2)


# ---------------------------------------------------------------------------
# Constants / conversion on device
# ---------------------------------------------------------------------------

def const(v: int):
    """Embed a fixed field value (shape [32]; broadcasts against batches)."""
    return jnp.asarray(int_to_limbs(v % P))


def zeros(shape=()):
    return jnp.zeros((*shape, NL), jnp.int32)


def ones(shape=()):
    return jnp.broadcast_to(jnp.asarray(ONE), (*shape, NL)).astype(jnp.int32)
