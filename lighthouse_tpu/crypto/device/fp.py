"""BLS12-381 base field Fp on device: 12-bit x 32 limb arithmetic in int32.

Design (TPU-first, not a port of blst's 64-bit asm):

* An Fp element is ``int32[..., 32]``: 32 little-endian limbs of 12 bits.
  381-bit values fit in 384 bits. Leading dims are batch dims; every op
  broadcasts, so the whole stack is batched without ``vmap``.
* 12-bit limbs are chosen so schoolbook products never overflow int32:
  a full-product column is at most ``16 * LIMB_MAX**2 < 2**31``. The TPU
  VPU has no 64-bit multiply-high; 12x12->24-bit products with 32-bit
  accumulation map directly onto int32 vector lanes.
* Multiplication = banded-Toeplitz matmul: gather ``y`` into a
  ``[..., 32, 63]`` band matrix, one batched ``dot_general`` computes all
  63 product columns (2016 MACs — the minimal schoolbook work), then the
  columns are reduced mod p by folding limbs >= 32 through a precomputed
  ``2**(12*i) mod p`` table (another small matmul). No Montgomery form:
  the fold table plays the role blst's Montgomery REDC plays
  (``/root/reference/crypto/bls/src/impls/blst.rs`` links the asm).
* Values are kept *relaxed*: limbs in ``[0, LIMB_MAX]``, value in
  ``[0, 2**384)``-ish, only congruent mod p. ``canonical`` produces the
  unique strict representative for equality/serialization.
* Every reduction plan is derived at trace time by exact interval
  arithmetic on per-limb bounds, asserting that no intermediate can
  overflow int32 — machine-checked, not hand-waved.

Subtraction uses a "saturated" multiple of p (every digit >= LIMB_MAX) so
``x - y + SAT`` is limb-wise non-negative — branch-free and select-free.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..params import P

# ---------------------------------------------------------------------------
# Layout constants
# ---------------------------------------------------------------------------

ELEM_NDIM = 1             # trailing element dims of an fp array: (NL,)
W = 12                    # bits per limb
NL = 32                   # limbs per element (384 bits >= 381)
MASK = (1 << W) - 1       # 0xFFF
LIMB_MAX = 8191           # relaxed per-limb bound maintained by reduce_cols
NCOLS = 2 * NL - 1        # full-product column count

# Products are accumulated in int32 over *half* the limbs at a time
# (16 * LIMB_MAX**2 < 2**31); see mul().
assert (NL // 2) * (LIMB_MAX ** 2) < 2 ** 31, "half-conv columns must fit int32"


# ---------------------------------------------------------------------------
# Host-side packing helpers
# ---------------------------------------------------------------------------

def int_to_limbs(x: int) -> np.ndarray:
    """Python int -> strict little-endian 12-bit limbs, int32[32]."""
    assert 0 <= x < 1 << (W * NL)
    return np.array([(x >> (W * i)) & MASK for i in range(NL)], np.int32)


def limbs_to_int(a) -> int:
    """Limb array (any relaxed representation) -> Python int value."""
    a = np.asarray(a)
    return sum(int(v) << (W * i) for i, v in enumerate(a.reshape(-1).tolist()))


def _digits(x: int, n: int) -> list[int]:
    return [(x >> (W * i)) & MASK for i in range(n)]


# ---------------------------------------------------------------------------
# Module-level tables (numpy; jnp converts on first use)
# ---------------------------------------------------------------------------

# Fold table: FOLD[i] = limbs of 2**(W*(NL+i)) mod p, for high limb NL+i.
_FOLD_HI = 64
FOLD = np.stack(
    [int_to_limbs(pow(1 << W, NL + i, P)) for i in range(_FOLD_HI)]
)  # [64, 32] int32, strict digits

# Banded-Toeplitz gather index/mask for multiplication.
_IDX = np.zeros((NL, NCOLS), np.int32)
_BANDMASK = np.zeros((NL, NCOLS), np.int32)
for _a in range(NL):
    for _c in range(NCOLS):
        _d = _c - _a
        if 0 <= _d < NL:
            _IDX[_a, _c] = _d
            _BANDMASK[_a, _c] = 1

# Saturated multiple of p for branch-free subtraction: SAT digits all in
# [LIMB_MAX, ...], value = m*p. Found by a small search.
def _saturated_multiple() -> tuple[np.ndarray, int]:
    S = sum(1 << (W * i) for i in range(NL))  # all-ones weight sum
    for m in range(10, 64):
        t = m * P - LIMB_MAX * S
        if t < 0:
            continue
        d = _digits(t, NL)
        if sum(v << (W * i) for i, v in enumerate(d)) != t:
            continue  # does not fit in 32 digits
        sat = [LIMB_MAX + v for v in d]
        if max(sat) * 2 < 2 ** 20:  # comfortably small
            return np.array(sat, np.int32), m
    raise AssertionError("no saturated multiple of p found")


SAT, _SAT_M = _saturated_multiple()
assert limbs_to_int(SAT) == _SAT_M * P

# Strict digits of 2**384 - k*p for canonical conditional subtraction.
_CSUB_KS = (8, 4, 2, 1)
CSUB = np.stack([np.array(_digits((1 << (W * NL)) - k * P, NL), np.int32)
                 for k in _CSUB_KS])

ZERO = int_to_limbs(0)
ONE = int_to_limbs(1)


# ---------------------------------------------------------------------------
# Reduction: columns -> relaxed 32-limb representative (mod p)
# ---------------------------------------------------------------------------

def _carry_round(cols, bounds):
    """One parallel carry round; widens by one limb. Exact value preserved."""
    assert all(b < 2 ** 31 for b in bounds), f"int32 overflow risk: {bounds}"
    r = cols & MASK
    c = cols >> W
    pad = [(0, 0)] * (cols.ndim - 1)
    r = jnp.pad(r, pad + [(0, 1)])
    c = jnp.pad(c, pad + [(1, 0)])
    rb = [min(b, MASK) for b in bounds] + [0]
    cb = [0] + [b >> W for b in bounds]
    return r + c, [a + b for a, b in zip(rb, cb)]


def _fold_round(cols, bounds):
    """Fold limbs >= NL through the 2**(12i) mod p table (exact mod p)."""
    n = len(bounds)
    k = n - NL
    assert k > 0
    lo, hi = cols[..., :NL], cols[..., NL:]
    table = jnp.asarray(FOLD[:k])
    out = lo + jnp.einsum("...h,hl->...l", hi, table,
                          preferred_element_type=jnp.int32)
    ob = [bounds[i] + sum(bounds[NL + h] * int(FOLD[h, i]) for h in range(k))
          for i in range(NL)]
    assert all(b < 2 ** 31 for b in ob), f"fold overflow risk: {ob}"
    return out, ob


def _fold_safe(bounds) -> bool:
    k = len(bounds) - NL
    if k <= 0:
        return False
    return all(
        bounds[i] + sum(bounds[NL + h] * int(FOLD[h, i]) for h in range(k))
        < 2 ** 31
        for i in range(NL)
    )


def reduce_cols(cols, bounds):
    """Reduce arbitrary product columns to the relaxed 32-limb form.

    ``bounds`` is a Python list of exact per-column upper bounds; the
    carry/fold schedule is chosen at trace time and asserts int32 safety
    for every intermediate.
    """
    bounds = list(bounds)
    assert cols.shape[-1] == len(bounds)
    for _ in range(32):
        if len(bounds) == NL and max(bounds) <= LIMB_MAX:
            return cols
        if _fold_safe(bounds):
            cols, bounds = _fold_round(cols, bounds)
        else:
            cols, bounds = _carry_round(cols, bounds)
    raise AssertionError(f"reduction did not converge: {bounds}")


# ---------------------------------------------------------------------------
# Field operations (all broadcast over leading dims)
# ---------------------------------------------------------------------------

_B_IN = [LIMB_MAX] * NL  # invariant bound on any input element


def add(x, y):
    return reduce_cols(x + y, [2 * LIMB_MAX] * NL)


def sub(x, y):
    return reduce_cols(x + (jnp.asarray(SAT) - y),
                       [LIMB_MAX + int(v) for v in SAT])


def neg(x):
    return reduce_cols(jnp.asarray(SAT) - x, [int(v) for v in SAT])


def mul_small(x, k: int):
    """Multiply by a small non-negative Python int (k * LIMB_MAX < 2**31)."""
    assert 0 <= k and k * LIMB_MAX < 2 ** 31
    return reduce_cols(x * k, [k * LIMB_MAX] * NL)


def _overlap(c: int, lo: int, hi: int) -> int:
    """Number of a in [lo, hi) with 0 <= c - a < NL (terms in column c)."""
    return max(0, min(c, hi - 1) - max(lo, c - (NL - 1)) + 1)


_H = NL // 2
_HALF_BOUNDS = [
    [_overlap(c, 0, _H) * LIMB_MAX ** 2 for c in range(NCOLS)],
    [_overlap(c, _H, NL) * LIMB_MAX ** 2 for c in range(NCOLS)],
]


def mul(x, y):
    """Banded-Toeplitz schoolbook product, split into two 16-limb dots so
    int32 accumulation cannot overflow at LIMB_MAX; each half gets one
    carry round before the halves are combined and reduced."""
    band = jnp.take(y, jnp.asarray(_IDX), axis=-1) * jnp.asarray(_BANDMASK)
    halves = []
    for i, sl in enumerate((slice(0, _H), slice(_H, NL))):
        cols = jnp.einsum("...a,...ac->...c", x[..., sl], band[..., sl, :],
                          preferred_element_type=jnp.int32)
        halves.append(_carry_round(cols, _HALF_BOUNDS[i]))
    (c0, b0), (c1, b1) = halves
    return reduce_cols(c0 + c1, [a + b for a, b in zip(b0, b1)])


def sq(x):
    return mul(x, x)


# ---------------------------------------------------------------------------
# Canonicalization and predicates
# ---------------------------------------------------------------------------

def _seq_carry(cols):
    """Exact sequential carry over limbs -> (strict digits, carry_out)."""
    x = jnp.moveaxis(cols, -1, 0)

    def body(carry, col):
        s = col + carry
        return s >> W, s & MASK

    carry_out, digits = lax.scan(body, jnp.zeros(x.shape[1:], x.dtype), x)
    return jnp.moveaxis(digits, 0, -1), carry_out


def canonical(x):
    """Unique strict representative in [0, p), digits in [0, 4095]."""
    d, c = _seq_carry(x)
    # Relaxed values are < LIMB_MAX * sum(2^(12i)) < 2.0003 * 2**384, so the
    # first carry-out is at most 2; two fold-and-recarry rounds bring the
    # value strictly below 2**384 (each round: v -> v mod 2**384 + c * (2**384
    # mod p), and 2**384 mod p < 2**381).
    for _ in range(2):
        d = d + c[..., None] * jnp.asarray(FOLD[0])
        d, c = _seq_carry(d)
    # Now x < 2**384 < 16p: conditional cascade subtract 8p, 4p, 2p, p.
    for i in range(len(_CSUB_KS)):
        s, c = _seq_carry(d + jnp.asarray(CSUB[i]))
        d = jnp.where((c == 1)[..., None], s, d)
    return d


def is_zero(x):
    """Boolean [...] mask: value == 0 mod p."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(x, y):
    return jnp.all(canonical(x) == canonical(y), axis=-1)


def select(mask, a, b):
    """mask [...] bool -> elementwise field select."""
    return jnp.where(mask[..., None], a, b)


# ---------------------------------------------------------------------------
# Exponentiation (fixed Python-int exponent) and inversion
# ---------------------------------------------------------------------------

def _bits_msb(e: int) -> np.ndarray:
    return np.array([int(b) for b in bin(e)[2:]], np.int32)


def square_multiply(x, e: int, sq_fn, mul_fn, select_fn):
    """Shared fixed-exponent square-and-multiply ladder (MSB-first scan).

    Serves every pow_const in the device stack (fp/fp2/fp12) — one place
    to fix or re-window the ladder. ``e`` must be >= 1.
    """
    assert e >= 1
    bits = _bits_msb(e)
    if len(bits) == 1:
        return x

    def body(acc, bit):
        acc = sq_fn(acc)
        acc = select_fn(bit == 1, mul_fn(acc, x), acc)
        return acc, None

    acc, _ = lax.scan(body, x, jnp.asarray(bits[1:]))
    return acc


def pow_const(x, e: int):
    """x**e for a fixed exponent, as a scan over its bits (MSB first)."""
    return square_multiply(x, e, sq, mul, select)


def inv(x):
    """Fermat inverse x**(p-2); inv(0) = 0 (callers mask separately)."""
    return pow_const(x, P - 2)


# ---------------------------------------------------------------------------
# Constants / conversion on device
# ---------------------------------------------------------------------------

def const(v: int):
    """Embed a fixed field value (shape [32]; broadcasts against batches)."""
    return jnp.asarray(int_to_limbs(v % P))


def zeros(shape=()):
    return jnp.zeros((*shape, NL), jnp.int32)


def ones(shape=()):
    return jnp.broadcast_to(jnp.asarray(ONE), (*shape, NL)).astype(jnp.int32)
