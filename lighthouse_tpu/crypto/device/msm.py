"""Windowed multi-scalar multiplication (Pippenger) on device.

``sum_i s_i * P_i`` over G1 with 64-bit scalars — the device half of
batch-verification randomizer sums and of ``operation_pool`` aggregate
precomputation (ISSUE 16; ROADMAP item 3's duty-lookahead caller). The
classic bucket method, restated branch-free for a batch machine:

* scalars split into ``N_WINDOWS`` windows of ``WINDOW_BITS`` bits
  (MSW first);
* bucket sums ``B[w, j] = sum of P_i where digit_w(s_i) == j`` computed
  as ONE masked tree-reduction over the point axis, batched over all
  ``N_WINDOWS x N_BUCKETS`` buckets at once — no scatter, no sort, and
  the reduction scan emits a single group-law body (compile-size first,
  like every reduction in this stack);
* per-window weighted sums ``W_w = sum_j j * B[w, j]`` by the running-sum
  trick (one scan over the bucket axis, highest bucket first);
* the final Horner fold ``acc = 2^WINDOW_BITS * acc + W_w`` over windows.

The complete RCB group law makes every masked/duplicate/infinity lane
safe without branches; infinity inputs simply occupy no bucket. A plain
masked point-sum (``point_sum``) rides along for aggregate callers whose
scalars are all one (operation_pool signature aggregation over G2).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from . import curve, fp, fp2

WINDOW_BITS = 4
N_WINDOWS = 64 // WINDOW_BITS        # 16, MSW first
N_BUCKETS = (1 << WINDOW_BITS) - 1   # 15; digit 0 occupies no bucket


def window_digits(scalars):
    """int32[..., 2] (hi, lo) words of a u64 -> int32[..., N_WINDOWS]
    window digits, most-significant window first."""
    hi = scalars[..., 0].astype(jnp.uint32)
    lo = scalars[..., 1].astype(jnp.uint32)
    mask = (1 << WINDOW_BITS) - 1
    digs = []
    for w in range(N_WINDOWS):
        bit = 64 - (w + 1) * WINDOW_BITS
        word = hi if bit >= 32 else lo
        digs.append(((word >> (bit % 32)) & mask).astype(jnp.int32))
    return jnp.stack(digs, axis=-1)


def _bucket_points(F, proj, digits, n):
    """Masked bucket occupancy: broadcast the projective batch to
    ``[N_WINDOWS, N_BUCKETS, n]`` and select infinity everywhere the
    point's window digit is not the bucket's index."""
    j = jnp.arange(1, N_BUCKETS + 1, dtype=jnp.int32)
    sel = digits.T[:, None, :] == j[None, :, None]   # [W, B, n]
    shape = (N_WINDOWS, N_BUCKETS, n)
    broad = tuple(
        jnp.broadcast_to(c, shape + c.shape[1:]) for c in proj
    )
    inf = curve.infinity(F, shape)
    return curve.select(F, sel, broad, inf)


def msm(F, pt_aff, scalars):
    """Generic windowed MSM over field module ``F``:
    ``pt_aff = (x, y, inf)`` affine batch [n, ...], ``scalars`` int32
    [n, 2] u64 words -> projective result point (batch dims reduced)."""
    x, y, inf = pt_aff
    n = x.shape[0]
    proj = curve.from_affine(F, x, y, inf)
    digits = window_digits(scalars)                  # [n, W]
    masked = _bucket_points(F, proj, digits, n)      # [W, B, n] points
    buckets = curve.sum_points(F, masked, axis=2)    # [W, B] points

    # W_w = sum_j j * B[w, j] via running sums, highest bucket first:
    # run_k = sum_{j >= k} B_j, acc = sum_k run_k.
    rev = tuple(c[:, ::-1] for c in buckets)
    seq = tuple(jnp.moveaxis(c, 1, 0) for c in rev)  # [B, W] scan axis first
    zero = curve.infinity(F, (N_WINDOWS,))

    def bucket_step(carry, bj):
        run, acc = carry
        run = curve.add(F, run, bj)
        acc = curve.add(F, acc, run)
        return (run, acc), None

    (_, windows), _ = lax.scan(bucket_step, (zero, zero), seq)

    # Horner across windows (MSW first): acc = 2^w * acc + W_w.
    def window_step(acc, wp):
        for _ in range(WINDOW_BITS):
            acc = curve.dbl(F, acc)
        return curve.add(F, acc, wp), None

    acc, _ = lax.scan(window_step, curve.infinity(F), windows)
    return acc


def point_sum(F, pt_aff):
    """Masked affine point sum (all-ones scalars): the aggregate-only
    fast path operation_pool's device aggregation uses."""
    x, y, inf = pt_aff
    proj = curve.from_affine(F, x, y, inf)
    return curve.sum_points(F, proj, axis=0)


# ---------------------------------------------------------------------------
# Staged-program bodies (jitted by device/bls.py, warmed via lowering.py)
# ---------------------------------------------------------------------------

def msm_g1_fn(pt_xy, pt_inf, scalars):
    """G1 windowed MSM staged program: pt_xy int32[N, 2, NL] affine,
    pt_inf bool[N], scalars int32[N, 2] -> (xy int32[2, NL] canonical
    affine, inf bool[])."""
    acc = msm(fp, (pt_xy[:, 0], pt_xy[:, 1], pt_inf), scalars)
    ax, ay, ainf = curve.to_affine(fp, acc)
    return jnp.stack([ax, ay], axis=0), ainf


def sum_g2_fn(pt_xy, pt_inf):
    """G2 masked point-sum staged program: pt_xy int32[N, 2, 2, NL]
    affine, pt_inf bool[N] -> (xy int32[2, 2, NL], inf bool[])."""
    acc = point_sum(fp2, (pt_xy[:, 0], pt_xy[:, 1], pt_inf))
    ax, ay, ainf = curve.to_affine(fp2, acc)
    return jnp.stack([ax, ay], axis=0), ainf
