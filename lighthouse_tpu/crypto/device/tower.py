"""Device extension-field tower Fp6 / Fp12 for the BLS12-381 pairing.

Layouts (leading dims are batch dims, broadcast everywhere):

* Fp6  = Fp2[v]/(v^3 - xi), xi = 1+u:  ``int32[..., 3, 2, 32]``
* Fp12 = Fp6[w]/(w^2 - v):             ``int32[..., 2, 3, 2, 32]``

Algorithms mirror the host oracle ``crypto/cpu/fields.{Fq6,Fq12}`` (tested
for bit-equality), expressed over the batched :mod:`.fp2` primitives.
Frobenius constants are computed at import from public curve parameters
(same derivation as the oracle's ``GAMMA6_1/GAMMA6_2/GAMMA12``).

All 27/18/81-lane product stacks funnel into :func:`fp.mul`, so the tower
inherits the active ``FP_IMPL`` contraction engine (int32 VPU dot or the
int8 MXU decomposition) transparently.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..params import P
from ..cpu.fields import GAMMA6_1, GAMMA6_2, GAMMA12
from . import fp, fp2

ELEM_NDIM_6 = 3
ELEM_NDIM_12 = 4


# ---------------------------------------------------------------------------
# Fp6
# ---------------------------------------------------------------------------

def f6_pack(c0, c1, c2):
    return jnp.stack([c0, c1, c2], axis=-3)


def f6_c(x, i):
    return x[..., i, :, :]


def f6_zeros(shape=()):
    return jnp.zeros((*shape, 3, 2, fp.NL), jnp.int32)


def f6_ones(shape=()):
    return f6_pack(fp2.ones(shape), fp2.zeros(shape), fp2.zeros(shape))


def f6_add(x, y):
    return fp.add(x, y)


def f6_sub(x, y):
    return fp.sub(x, y)


def f6_neg(x):
    return fp.neg(x)


def _f6_prod_terms(x, y):
    """The 9 Fp2 operand pairs of a schoolbook Fp6 product."""
    a = [f6_c(x, i) for i in range(3)]
    b = [f6_c(y, i) for i in range(3)]
    return [
        (a[0], b[0]),
        (a[0], b[1]), (a[1], b[0]),
        (a[0], b[2]), (a[1], b[1]), (a[2], b[0]),
        (a[1], b[2]), (a[2], b[1]),
        (a[2], b[2]),
    ]


def _f6_combine(p):
    """Recombine the 9 products with v^3 = xi folding (oracle Fq6.__mul__)."""
    t0 = p[0]
    t1 = fp2.add(p[1], p[2])
    t2 = fp2.add(fp2.add(p[3], p[4]), p[5])
    t3 = fp2.add(p[6], p[7])
    t4 = p[8]
    return f6_pack(
        fp2.add(t0, fp2.mul_by_u_plus_1(t3)),
        fp2.add(t1, fp2.mul_by_u_plus_1(t4)),
        t2,
    )


def f6_mul(x, y):
    """Schoolbook over Fp2; all 9 products in one batched fp.mul."""
    return _f6_combine(fp2.mul_pairs(_f6_prod_terms(x, y)))


def f6_sq(x):
    return f6_mul(x, x)


def f6_scale(x, k):
    """Multiply every Fp2 coefficient by the fp2 element ``k``."""
    p = fp2.mul_pairs([(f6_c(x, i), k) for i in range(3)])
    return f6_pack(*p)


def f6_mul_by_v(x):
    """(c0, c1, c2) -> (xi*c2, c0, c1)."""
    return f6_pack(fp2.mul_by_u_plus_1(f6_c(x, 2)), f6_c(x, 0), f6_c(x, 1))


def f6_inv(x):
    a0, a1, a2 = f6_c(x, 0), f6_c(x, 1), f6_c(x, 2)
    p = fp2.mul_pairs(
        [(a0, a0), (a1, a2), (a2, a2), (a0, a1), (a1, a1), (a0, a2)]
    )
    t0 = fp2.sub(p[0], fp2.mul_by_u_plus_1(p[1]))
    t1 = fp2.sub(fp2.mul_by_u_plus_1(p[2]), p[3])
    t2 = fp2.sub(p[4], p[5])
    q = fp2.mul_pairs([(a0, t0), (a2, t1), (a1, t2)])
    den = fp2.add(q[0], fp2.mul_by_u_plus_1(fp2.add(q[1], q[2])))
    d = fp2.inv(den)
    r = fp2.mul_pairs([(t0, d), (t1, d), (t2, d)])
    return f6_pack(*r)


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------

def pack(c0, c1):
    return jnp.stack([c0, c1], axis=-4)


def c0(x):
    return x[..., 0, :, :, :]


def c1(x):
    return x[..., 1, :, :, :]


def zeros(shape=()):
    return jnp.zeros((*shape, 2, 3, 2, fp.NL), jnp.int32)


def ones(shape=()):
    return pack(f6_ones(shape), f6_zeros(shape))


def add(x, y):
    return fp.add(x, y)


def sub(x, y):
    return fp.sub(x, y)


def neg(x):
    return fp.neg(x)


def mul(x, y):
    """Karatsuba over Fp6: the 3 Fp6 products' 27 Fp2 products go through
    ONE batched fp.mul (81 Fp lanes) — graph-small, matmul-large."""
    a0, a1 = c0(x), c1(x)
    b0, b1 = c0(y), c1(y)
    terms = (
        _f6_prod_terms(a0, b0)
        + _f6_prod_terms(a1, b1)
        + _f6_prod_terms(f6_add(a0, a1), f6_add(b0, b1))
    )
    prods = fp2.mul_pairs(terms)
    t0 = _f6_combine(prods[0:9])
    t1 = _f6_combine(prods[9:18])
    m = _f6_combine(prods[18:27])
    return pack(
        f6_add(t0, f6_mul_by_v(t1)),
        f6_sub(f6_sub(m, t0), t1),
    )


def sq(x):
    """Dedicated squaring: (a + bw)^2 = (a^2 + v b^2) + 2ab w via the
    complex trick — 2 Fp6 products (18 Fp2 products in one batched
    fp.mul) vs 27 for the generic multiply. (A Granger-Scott cyclotomic
    squaring for the final-exp chains is a further planned cut.)"""
    a, b = c0(x), c1(x)
    terms = _f6_prod_terms(a, b) + _f6_prod_terms(
        f6_add(a, b), f6_add(a, f6_mul_by_v(b))
    )
    prods = fp2.mul_pairs(terms)
    t = _f6_combine(prods[0:9])          # ab
    u = _f6_combine(prods[9:18])         # (a+b)(a+vb) = a^2 + v b^2 + ab(1+v)
    c0_ = f6_sub(f6_sub(u, t), f6_mul_by_v(t))
    return pack(c0_, f6_add(t, t))


def conjugate(x):
    """x^(p^6): negate the w component. Inverse of unitary elements."""
    return pack(c0(x), f6_neg(c1(x)))


def inv(x):
    a, b = c0(x), c1(x)
    d = f6_inv(f6_sub(f6_sq(a), f6_mul_by_v(f6_sq(b))))
    return pack(f6_mul(a, d), f6_neg(f6_mul(b, d)))


def select(mask, a, b):
    return jnp.where(mask[..., None, None, None, None], a, b)


def canonical(x):
    return fp.canonical(x)


def is_one(x):
    one = jnp.broadcast_to(ones(), x.shape)
    return jnp.all(canonical(x) == canonical(one), axis=(-1, -2, -3, -4))


def eq(x, y):
    return jnp.all(canonical(x) == canonical(y), axis=(-1, -2, -3, -4))


# Frobenius gamma constants (public, derived from xi = 1+u).
_G6_1 = (GAMMA6_1.c0.n, GAMMA6_1.c1.n)
_G6_2 = (GAMMA6_2.c0.n, GAMMA6_2.c1.n)
_G12 = (GAMMA12.c0.n, GAMMA12.c1.n)


def frobenius(x):
    """x -> x^p (oracle Fq12.frobenius); gamma products in one batch."""
    g61 = fp2.const(*_G6_1)
    g62 = fp2.const(*_G6_2)
    g12 = fp2.const(*_G12)
    a, b = c0(x), c1(x)
    ca = [fp2.conjugate(f6_c(a, i)) for i in range(3)]
    cb = [fp2.conjugate(f6_c(b, i)) for i in range(3)]
    p = fp2.mul_pairs(
        [
            (ca[1], g61), (ca[2], g62),
            (cb[0], g12),
            (cb[1], fp2.mul(g61, g12)), (cb[2], fp2.mul(g62, g12)),
        ]
    )
    return pack(f6_pack(ca[0], p[0], p[1]), f6_pack(p[2], p[3], p[4]))


def frobenius_n(x, n: int):
    for _ in range(n):
        x = frobenius(x)
    return x


def pow_const(x, e: int):
    """x**e for fixed non-negative e; e == 0 -> one. Negative exponents are
    the caller's job (conjugate for unitary elements, inv otherwise)."""
    assert e >= 0
    if e == 0:
        return jnp.broadcast_to(ones(), x.shape).astype(jnp.int32)
    return fp.square_multiply(x, e, sq, mul, select)


def from_fp2(a):
    """Embed an fp2 element into Fp12 (constant coefficient)."""
    shape = a.shape[:-2]
    out = zeros(shape)
    return out.at[..., 0, 0, :, :].set(a)


# ---------------------------------------------------------------------------
# Host packing: oracle Fq6/Fq12 <-> device arrays
# ---------------------------------------------------------------------------

def pack_f12(vals) -> np.ndarray:
    """cpu Fq12 list -> int32[n, 2, 3, 2, 32]."""
    out = []
    for v in vals:
        halves = []
        for h in (v.c0, v.c1):
            coeffs = []
            for c in (h.c0, h.c1, h.c2):
                coeffs.append(
                    np.stack([fp.int_to_limbs(c.c0.n), fp.int_to_limbs(c.c1.n)])
                )
            halves.append(np.stack(coeffs))
        out.append(np.stack(halves))
    return np.stack(out)


def unpack_f12(arr):
    """Device Fp12 array [n, 2, 3, 2, 32] -> list of cpu Fq12."""
    from ..cpu.fields import Fq2, Fq6, Fq12

    arr = np.asarray(canonical(jnp.asarray(arr)))
    out = []
    for v in arr.reshape(-1, 2, 3, 2, fp.NL):
        halves = []
        for h in v:
            halves.append(
                Fq6(
                    *[
                        Fq2.from_ints(
                            fp.limbs_to_int(c[0]) % P, fp.limbs_to_int(c[1]) % P
                        )
                        for c in h
                    ]
                )
            )
        out.append(Fq12(*halves))
    return out
