"""Device extension-field tower Fp6 / Fp12 for the BLS12-381 pairing.

Layouts (leading dims are batch dims, broadcast everywhere):

* Fp6  = Fp2[v]/(v^3 - xi), xi = 1+u:  ``int32[..., 3, 2, 32]``
* Fp12 = Fp6[w]/(w^2 - v):             ``int32[..., 2, 3, 2, 32]``

Algorithms mirror the host oracle ``crypto/cpu/fields.{Fq6,Fq12}`` (tested
for bit-equality), expressed over the batched :mod:`.fp2` primitives.
Frobenius constants are computed at import from public curve parameters
(same derivation as the oracle's ``GAMMA6_1/GAMMA6_2/GAMMA12``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..params import P
from ..cpu.fields import GAMMA6_1, GAMMA6_2, GAMMA12
from . import fp, fp2

ELEM_NDIM_6 = 3
ELEM_NDIM_12 = 4


# ---------------------------------------------------------------------------
# Fp6
# ---------------------------------------------------------------------------

def f6_pack(c0, c1, c2):
    return jnp.stack([c0, c1, c2], axis=-3)


def f6_c(x, i):
    return x[..., i, :, :]


def f6_zeros(shape=()):
    return jnp.zeros((*shape, 3, 2, fp.NL), jnp.int32)


def f6_ones(shape=()):
    return f6_pack(fp2.ones(shape), fp2.zeros(shape), fp2.zeros(shape))


def f6_add(x, y):
    return fp.add(x, y)


def f6_sub(x, y):
    return fp.sub(x, y)


def f6_neg(x):
    return fp.neg(x)


def f6_mul(x, y):
    """Schoolbook over Fp2 with v^3 = xi folding (oracle Fq6.__mul__)."""
    a0, a1, a2 = f6_c(x, 0), f6_c(x, 1), f6_c(x, 2)
    b0, b1, b2 = f6_c(y, 0), f6_c(y, 1), f6_c(y, 2)
    t0 = fp2.mul(a0, b0)
    t1 = fp2.add(fp2.mul(a0, b1), fp2.mul(a1, b0))
    t2 = fp2.add(fp2.add(fp2.mul(a0, b2), fp2.mul(a1, b1)), fp2.mul(a2, b0))
    t3 = fp2.add(fp2.mul(a1, b2), fp2.mul(a2, b1))
    t4 = fp2.mul(a2, b2)
    return f6_pack(
        fp2.add(t0, fp2.mul_by_u_plus_1(t3)),
        fp2.add(t1, fp2.mul_by_u_plus_1(t4)),
        t2,
    )


def f6_sq(x):
    return f6_mul(x, x)


def f6_scale(x, k):
    """Multiply every Fp2 coefficient by the fp2 element ``k``."""
    return f6_pack(
        fp2.mul(f6_c(x, 0), k), fp2.mul(f6_c(x, 1), k), fp2.mul(f6_c(x, 2), k)
    )


def f6_mul_by_v(x):
    """(c0, c1, c2) -> (xi*c2, c0, c1)."""
    return f6_pack(fp2.mul_by_u_plus_1(f6_c(x, 2)), f6_c(x, 0), f6_c(x, 1))


def f6_inv(x):
    c0, c1, c2 = f6_c(x, 0), f6_c(x, 1), f6_c(x, 2)
    t0 = fp2.sub(fp2.sq(c0), fp2.mul_by_u_plus_1(fp2.mul(c1, c2)))
    t1 = fp2.sub(fp2.mul_by_u_plus_1(fp2.sq(c2)), fp2.mul(c0, c1))
    t2 = fp2.sub(fp2.sq(c1), fp2.mul(c0, c2))
    den = fp2.add(
        fp2.mul(c0, t0),
        fp2.mul_by_u_plus_1(fp2.add(fp2.mul(c2, t1), fp2.mul(c1, t2))),
    )
    d = fp2.inv(den)
    return f6_pack(fp2.mul(t0, d), fp2.mul(t1, d), fp2.mul(t2, d))


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------

def pack(c0, c1):
    return jnp.stack([c0, c1], axis=-4)


def c0(x):
    return x[..., 0, :, :, :]


def c1(x):
    return x[..., 1, :, :, :]


def zeros(shape=()):
    return jnp.zeros((*shape, 2, 3, 2, fp.NL), jnp.int32)


def ones(shape=()):
    return pack(f6_ones(shape), f6_zeros(shape))


def add(x, y):
    return fp.add(x, y)


def sub(x, y):
    return fp.sub(x, y)


def neg(x):
    return fp.neg(x)


def mul(x, y):
    a0, a1 = c0(x), c1(x)
    b0, b1 = c0(y), c1(y)
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    # Karatsuba middle: (a0+a1)(b0+b1) - t0 - t1
    m = f6_mul(f6_add(a0, a1), f6_add(b0, b1))
    return pack(
        f6_add(t0, f6_mul_by_v(t1)),
        f6_sub(f6_sub(m, t0), t1),
    )


def sq(x):
    return mul(x, x)


def conjugate(x):
    """x^(p^6): negate the w component. Inverse of unitary elements."""
    return pack(c0(x), f6_neg(c1(x)))


def inv(x):
    a, b = c0(x), c1(x)
    d = f6_inv(f6_sub(f6_sq(a), f6_mul_by_v(f6_sq(b))))
    return pack(f6_mul(a, d), f6_neg(f6_mul(b, d)))


def select(mask, a, b):
    return jnp.where(mask[..., None, None, None, None], a, b)


def canonical(x):
    return fp.canonical(x)


def is_one(x):
    one = jnp.broadcast_to(ones(), x.shape)
    return jnp.all(canonical(x) == canonical(one), axis=(-1, -2, -3, -4))


def eq(x, y):
    return jnp.all(canonical(x) == canonical(y), axis=(-1, -2, -3, -4))


# Frobenius gamma constants (public, derived from xi = 1+u).
_G6_1 = (GAMMA6_1.c0.n, GAMMA6_1.c1.n)
_G6_2 = (GAMMA6_2.c0.n, GAMMA6_2.c1.n)
_G12 = (GAMMA12.c0.n, GAMMA12.c1.n)


def frobenius(x):
    """x -> x^p (oracle Fq12.frobenius)."""
    g61 = fp2.const(*_G6_1)
    g62 = fp2.const(*_G6_2)
    g12 = fp2.const(*_G12)

    def frob6(a):
        return f6_pack(
            fp2.conjugate(f6_c(a, 0)),
            fp2.mul(fp2.conjugate(f6_c(a, 1)), g61),
            fp2.mul(fp2.conjugate(f6_c(a, 2)), g62),
        )

    fa = frob6(c0(x))
    fb = f6_scale(frob6(c1(x)), g12)
    return pack(fa, fb)


def frobenius_n(x, n: int):
    for _ in range(n):
        x = frobenius(x)
    return x


def pow_const(x, e: int):
    """x**e for fixed non-negative e; e == 0 -> one. Negative exponents are
    the caller's job (conjugate for unitary elements, inv otherwise)."""
    assert e >= 0
    if e == 0:
        return jnp.broadcast_to(ones(), x.shape).astype(jnp.int32)
    return fp.square_multiply(x, e, sq, mul, select)


def from_fp2(a):
    """Embed an fp2 element into Fp12 (constant coefficient)."""
    shape = a.shape[:-2]
    out = zeros(shape)
    return out.at[..., 0, 0, :, :].set(a)
