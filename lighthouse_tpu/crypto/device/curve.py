"""Device curve arithmetic for G1/E1(Fp) and G2/E2(Fp2), batched.

Points are Jacobian-coordinate triples ``(X, Y, Z)`` of field elements
(``x = X/Z^2``, ``y = Y/Z^3``; infinity iff ``Z == 0``). Every function is
generic over the field module ``F`` (:mod:`.fp` for G1, :mod:`.fp2` for G2)
— the two modules expose an identical batched API, so one set of formulas
serves both groups, and all ops broadcast over leading batch dims.

Branch-free by construction: the group law computes the generic-add,
doubling, and infinity branches unconditionally and ``select``s per lane —
there is no data-dependent Python control flow, so everything jits
(XLA traces once). Reference behaviour being reproduced: the point
aggregation and scalar muls inside blst's batch verification
(``/root/reference/crypto/bls/src/impls/blst.rs:100-118``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..params import P


def infinity(F, shape=()):
    """The canonical infinity representative (1 : 1 : 0)."""
    return (F.ones(shape), F.ones(shape), F.zeros(shape))


def is_infinity(F, pt):
    return F.is_zero(pt[2])


def neg(F, pt):
    x, y, z = pt
    return (x, F.neg(y), z)


def select(F, mask, a, b):
    return tuple(F.select(mask, ca, cb) for ca, cb in zip(a, b))


def eq(F, p, q):
    """Projective equality: X1 Z2^2 == X2 Z1^2 and Y1 Z2^3 == Y2 Z1^3,
    with infinity equal only to infinity."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1, z2z2 = F.sq(z1), F.sq(z2)
    ex = F.eq(F.mul(x1, z2z2), F.mul(x2, z1z1))
    ey = F.eq(F.mul(y1, F.mul(z2, z2z2)), F.mul(y2, F.mul(z1, z1z1)))
    i1, i2 = is_infinity(F, p), is_infinity(F, q)
    return jnp.where(i1 | i2, i1 == i2, ex & ey)


def dbl(F, pt):
    """Jacobian doubling for a = 0 curves. Safe at infinity and at
    2-torsion (Y == 0): both give Z3 == 0 (infinity)."""
    x, y, z = pt
    a = F.sq(x)
    b = F.sq(y)
    c = F.sq(b)
    d = F.sub(F.sub(F.sq(F.add(x, b)), a), c)
    d = F.add(d, d)
    e = F.add(F.add(a, a), a)
    f = F.sq(e)
    x3 = F.sub(f, F.add(d, d))
    y3 = F.sub(F.mul(e, F.sub(d, x3)), F.mul_small(c, 8))
    z3 = F.mul(F.add(y, y), z)
    return (x3, y3, z3)


def add(F, p, q):
    """Unified Jacobian addition: handles P == Q (doubling), P == -Q
    (infinity) and either operand at infinity, via lane-wise selects."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = F.sq(z1)
    z2z2 = F.sq(z2)
    u1 = F.mul(x1, z2z2)
    u2 = F.mul(x2, z1z1)
    s1 = F.mul(y1, F.mul(z2, z2z2))
    s2 = F.mul(y2, F.mul(z1, z1z1))
    h = F.sub(u2, u1)
    r = F.sub(s2, s1)
    hh = F.sq(h)
    hhh = F.mul(h, hh)
    v = F.mul(u1, hh)
    x3 = F.sub(F.sub(F.sq(r), hhh), F.add(v, v))
    y3 = F.sub(F.mul(r, F.sub(v, x3)), F.mul(s1, hhh))
    z3 = F.mul(F.mul(z1, z2), h)
    out = (x3, y3, z3)

    h_zero = F.is_zero(h)
    r_zero = F.is_zero(r)
    # P == Q (same affine point): use the doubling formula.
    out = select(F, h_zero & r_zero, dbl(F, p), out)
    # P == -Q: infinity. (z3 is already 0 there since h == 0 — but the
    # doubling select above may have overwritten it; re-assert.)
    inf = infinity(F, ())
    inf = tuple(jnp.broadcast_to(c, o.shape) for c, o in zip(inf, out))
    out = select(F, h_zero & ~r_zero, inf, out)
    out = select(F, is_infinity(F, p), q, out)
    out = select(F, is_infinity(F, q), p, out)
    return out


def scalar_mul_bits(F, pt, bits):
    """Variable scalar mul: ``bits`` is int32 [..., n] MSB-first, batched
    alongside the point's batch dims. Double-and-add via ``lax.scan``."""
    nbits = bits.shape[-1]
    bits_t = jnp.moveaxis(bits, -1, 0)
    acc = tuple(
        jnp.broadcast_to(c, o.shape) for c, o in zip(infinity(F), pt)
    )

    def body(acc, bit):
        acc = dbl(F, acc)
        acc = select(F, bit == 1, add(F, acc, pt), acc)
        return acc, None

    acc, _ = lax.scan(body, acc, bits_t, length=nbits)
    return acc


def scalar_mul_const(F, pt, k: int):
    """Fixed Python-int scalar mul (shared bit pattern across the batch)."""
    if k < 0:
        return scalar_mul_const(F, neg(F, pt), -k)
    if k == 0:
        return tuple(
            jnp.broadcast_to(c, o.shape) for c, o in zip(infinity(F), pt)
        )
    bits = np.array([int(b) for b in bin(k)[2:]], np.int32)
    batch = _batch_shape(F, pt[0])
    return scalar_mul_bits(F, pt, jnp.broadcast_to(bits, (*batch, len(bits))))


def to_affine(F, pt):
    """-> (x, y, inf_mask); (0, 0) at infinity (F.inv(0) == 0)."""
    x, y, z = pt
    zi = F.inv(z)
    zi2 = F.sq(zi)
    ax = F.mul(x, zi2)
    ay = F.mul(y, F.mul(zi, zi2))
    return F.canonical(ax), F.canonical(ay), is_infinity(F, pt)


def from_affine(F, x, y, inf_mask=None):
    """Affine coords (+ optional infinity mask) -> Jacobian triple."""
    shape = _batch_shape(F, x)
    z = F.ones(shape)
    if inf_mask is not None:
        z = F.select(inf_mask, F.zeros(shape), z)
        x = F.select(inf_mask, F.ones(shape), x)
        y = F.select(inf_mask, F.ones(shape), y)
    return (x, y, z)


def _batch_shape(F, x):
    """Leading batch dims of a field element array."""
    return x.shape[: x.ndim - F.ELEM_NDIM]


def tree_reduce(x, axis: int, combine, identity):
    """Reduction of a pytree of arrays along ``axis`` via ``lax.scan``.

    Compile-size first: the scan emits ONE ``combine`` body regardless of
    N (an unrolled log-depth tree emitted log2(N) * |combine| HLO — ~90k
    lines of the round-1 program were these unrolled G2 adds, the single
    largest compile-time cost). The sequential chain is cheap at runtime
    because ``combine`` itself stays batched over all non-reduced dims and
    the Miller loop dominates end-to-end by orders of magnitude.
    """
    import jax

    n = jax.tree_util.tree_leaves(x)[0].shape[axis]
    if n == 0:
        return jax.tree_util.tree_map(
            lambda i, c: jnp.broadcast_to(i, _drop_axis_shape(c, axis)).astype(c.dtype),
            identity,
            x,
        )
    xs = jax.tree_util.tree_map(lambda c: jnp.moveaxis(c, axis, 0), x)
    first = jax.tree_util.tree_map(lambda c: c[0], xs)
    rest = jax.tree_util.tree_map(lambda c: c[1:], xs)
    if n == 1:
        return first

    def body(acc, item):
        return combine(acc, item), None

    acc, _ = lax.scan(body, first, rest)
    return acc


def _drop_axis_shape(c, axis):
    shape = list(c.shape)
    del shape[axis]
    return tuple(shape)


def sum_points(F, pt, axis: int = 0):
    """Tree-reduce a batch of points with the unified group law."""
    return tree_reduce(
        pt, axis, lambda a, b: add(F, a, b), infinity(F)
    )


# ---------------------------------------------------------------------------
# Host packing: oracle affine points <-> device arrays
# ---------------------------------------------------------------------------

def pack_g1(points) -> tuple[np.ndarray, np.ndarray]:
    """cpu G1Point list -> (xy int32[n, 2, 32], inf bool[n])."""
    from . import fp as _fp

    points = list(points)
    if not points:
        return np.zeros((0, 2, _fp.NL), np.int32), np.zeros((0,), bool)
    xs, infs = [], []
    for p in points:
        infs.append(p.is_infinity())
        if p.is_infinity():
            xs.append(np.zeros((2, _fp.NL), np.int32))
        else:
            xs.append(np.stack([_fp.int_to_limbs(p.x.n), _fp.int_to_limbs(p.y.n)]))
    return np.stack(xs), np.array(infs)


def pack_g2(points) -> tuple[np.ndarray, np.ndarray]:
    """cpu G2Point list -> (xy int32[n, 2, 2, 32], inf bool[n])."""
    from . import fp as _fp

    points = list(points)
    if not points:
        return np.zeros((0, 2, 2, _fp.NL), np.int32), np.zeros((0,), bool)
    xs, infs = [], []
    for p in points:
        infs.append(p.is_infinity())
        if p.is_infinity():
            xs.append(np.zeros((2, 2, _fp.NL), np.int32))
        else:
            xs.append(
                np.stack(
                    [
                        np.stack([_fp.int_to_limbs(p.x.c0.n), _fp.int_to_limbs(p.x.c1.n)]),
                        np.stack([_fp.int_to_limbs(p.y.c0.n), _fp.int_to_limbs(p.y.c1.n)]),
                    ]
                )
            )
    return np.stack(xs), np.array(infs)


def unpack_g1(xy, inf):
    """Device affine arrays -> list of cpu G1Point (host verification)."""
    from . import fp as _fp
    from ..cpu.curve import G1Point
    from ..cpu.fields import Fq

    xy = np.asarray(xy)
    inf = np.asarray(inf)
    out = []
    for i in range(xy.shape[0]):
        if inf[i]:
            out.append(G1Point.infinity())
        else:
            out.append(
                G1Point(
                    Fq(_fp.limbs_to_int(xy[i, 0]) % P),
                    Fq(_fp.limbs_to_int(xy[i, 1]) % P),
                )
            )
    return out


def unpack_g2(xy, inf):
    from . import fp as _fp
    from ..cpu.curve import G2Point
    from ..cpu.fields import Fq2

    xy = np.asarray(xy)
    inf = np.asarray(inf)
    out = []
    for i in range(xy.shape[0]):
        if inf[i]:
            out.append(G2Point.infinity())
        else:
            out.append(
                G2Point(
                    Fq2.from_ints(
                        _fp.limbs_to_int(xy[i, 0, 0]) % P,
                        _fp.limbs_to_int(xy[i, 0, 1]) % P,
                    ),
                    Fq2.from_ints(
                        _fp.limbs_to_int(xy[i, 1, 0]) % P,
                        _fp.limbs_to_int(xy[i, 1, 1]) % P,
                    ),
                )
            )
    return out
