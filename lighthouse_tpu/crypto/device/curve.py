"""Device curve arithmetic for G1/E1(Fp) and G2/E2(Fp2), batched.

Points are homogeneous projective triples ``(X, Y, Z)`` of field elements
(``x = X/Z``, ``y = Y/Z``; infinity = (0 : 1 : 0), iff ``Z == 0``). Every
function is generic over the field module ``F`` (:mod:`.fp` for G1,
:mod:`.fp2` for G2) — the two modules expose an identical batched API, so
one set of formulas serves both groups, and all ops broadcast over
leading batch dims.

The group law is the Renes–Costello–Batina COMPLETE addition for a = 0
short-Weierstrass curves (eprint 2015/1060, algs. 7/9): one branch-free
formula covers generic add, doubling, P + (-P) and infinity operands.
Completeness requires no rational 2-torsion — both E(Fp) and E'(Fp2)
have odd cofactor times odd r, so y == 0 points do not exist. This
replaced the unified-Jacobian law in round 3: the Jacobian add needed
canonical-form equality tests plus an inlined doubling fallback (~9k HLO
lines per call site, half the device program's compile time); the
complete law needs 12 field muls that batch into TWO fused ``F.mul``
calls (~1.5k lines) and no comparisons at all. Reference behaviour being
reproduced: the point aggregation and scalar muls inside blst's batch
verification (``/root/reference/crypto/bls/src/impls/blst.rs:100-118``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..params import P


def infinity(F, shape=()):
    """The canonical infinity representative (0 : 1 : 0)."""
    return (F.zeros(shape), F.ones(shape), F.zeros(shape))


def is_infinity(F, pt):
    return F.is_zero(pt[2])


def neg(F, pt):
    x, y, z = pt
    return (x, F.neg(y), z)


def select(F, mask, a, b):
    return tuple(F.select(mask, ca, cb) for ca, cb in zip(a, b))


def eq(F, p, q):
    """Projective equality by cross-multiplication: X1 Z2 == X2 Z1 and
    Y1 Z2 == Y2 Z1. Complete including infinity (Z == 0) lanes: a finite
    point never cross-matches an infinity because its Y Z' term differs."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    a, b, c, d = _mul_batch(F, [(x1, z2), (x2, z1), (y1, z2), (y2, z1)])
    return F.eq(a, b) & F.eq(c, d)


def _mul_b3(F, x):
    """Multiply by 3b of the curve over ``F``: 12 on E (b = 4), 12(1+u)
    on the twist E' (b = 4(1+u))."""
    t = F.mul_small(x, 12)
    xi = getattr(F, "mul_by_u_plus_1", None)
    return xi(t) if xi is not None else t


def _mul_batch(F, pairs):
    """One fused F.mul over stacked operand pairs (all pairs must share
    the element/batch shape) — the compile-size and MXU-occupancy lever:
    n products cost one kernel instead of n."""
    xs = jnp.stack([a for a, _ in pairs])
    ys = jnp.stack([b for _, b in pairs])
    out = F.mul(xs, ys)
    return [out[i] for i in range(len(pairs))]


def dbl(F, pt):
    """Complete doubling, RCB alg. 9 (a = 0). Maps (0:1:0) to itself."""
    x, y, z = pt
    t0, t1, t2, xy = _mul_batch(F, [(y, y), (y, z), (z, z), (x, y)])
    z3 = F.add(t0, t0)
    z3 = F.add(z3, z3)
    z3 = F.add(z3, z3)              # 8Y^2
    b3z2 = _mul_b3(F, t2)           # 3b Z^2
    y3 = F.add(t0, b3z2)            # Y^2 + 3b Z^2
    nine = F.add(F.add(b3z2, b3z2), b3z2)  # 9b Z^2
    t0 = F.sub(t0, nine)            # Y^2 - 9b Z^2
    x3, z3_out, y3b, xt = _mul_batch(
        F, [(b3z2, z3), (t1, z3), (t0, y3), (t0, xy)]
    )
    y3 = F.add(x3, y3b)
    x3 = F.add(xt, xt)
    return (x3, y3, z3_out)


def add(F, p, q):
    """COMPLETE addition, RCB alg. 7 (a = 0): valid for every input pair
    including P == Q, P == -Q and infinity — no comparisons, no selects.
    12 general multiplications in two fused batches."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0, t1, t2, t3m, t4m, x3m = _mul_batch(
        F,
        [
            (x1, x2),
            (y1, y2),
            (z1, z2),
            (F.add(x1, y1), F.add(x2, y2)),
            (F.add(y1, z1), F.add(y2, z2)),
            (F.add(x1, z1), F.add(x2, z2)),
        ],
    )
    t3 = F.sub(t3m, F.add(t0, t1))      # X1Y2 + X2Y1
    t4 = F.sub(t4m, F.add(t1, t2))      # Y1Z2 + Y2Z1
    y3 = F.sub(x3m, F.add(t0, t2))      # X1Z2 + X2Z1
    t0 = F.add(F.add(t0, t0), t0)       # 3 X1X2
    t2 = _mul_b3(F, t2)                 # 3b Z1Z2
    z3 = F.add(t1, t2)                  # Y1Y2 + 3b Z1Z2
    t1 = F.sub(t1, t2)                  # Y1Y2 - 3b Z1Z2
    y3 = _mul_b3(F, y3)                 # 3b (X1Z2 + X2Z1)
    x3a, t2b, y3a, t1b, t0c, z3c = _mul_batch(
        F,
        [(t4, y3), (t3, t1), (y3, t0), (t1, z3), (t0, t3), (z3, t4)],
    )
    return (
        F.sub(t2b, x3a),
        F.add(t1b, y3a),
        F.add(z3c, t0c),
    )


def scalar_mul_bits(F, pt, bits):
    """Variable scalar mul: ``bits`` is int32 [..., n] MSB-first, batched
    alongside the point's batch dims. Double-and-add via ``lax.scan``."""
    nbits = bits.shape[-1]
    bits_t = jnp.moveaxis(bits, -1, 0)
    acc = tuple(
        jnp.broadcast_to(c, o.shape) for c, o in zip(infinity(F), pt)
    )

    def body(acc, bit):
        acc = dbl(F, acc)
        acc = select(F, bit == 1, add(F, acc, pt), acc)
        return acc, None

    acc, _ = lax.scan(body, acc, bits_t, length=nbits)
    return acc


def scalar_mul_const(F, pt, k: int):
    """Fixed Python-int scalar mul (shared bit pattern across the batch)."""
    if k < 0:
        return scalar_mul_const(F, neg(F, pt), -k)
    if k == 0:
        return tuple(
            jnp.broadcast_to(c, o.shape) for c, o in zip(infinity(F), pt)
        )
    bits = np.array([int(b) for b in bin(k)[2:]], np.int32)
    batch = _batch_shape(F, pt[0])
    return scalar_mul_bits(F, pt, jnp.broadcast_to(bits, (*batch, len(bits))))


def to_affine(F, pt):
    """-> (x, y, inf_mask); (0, 0) at infinity (F.inv(0) == 0)."""
    x, y, z = pt
    zi = F.inv(z)
    ax, ay = _mul_batch(F, [(x, zi), (y, zi)])
    return F.canonical(ax), F.canonical(ay), is_infinity(F, pt)


def from_affine(F, x, y, inf_mask=None):
    """Affine coords (+ optional infinity mask) -> projective triple
    (infinity lanes become the canonical (0 : 1 : 0))."""
    shape = _batch_shape(F, x)
    z = F.ones(shape)
    if inf_mask is not None:
        z = F.select(inf_mask, F.zeros(shape), z)
        x = F.select(inf_mask, F.zeros(shape), x)
        y = F.select(inf_mask, F.ones(shape), y)
    return (x, y, z)


def _batch_shape(F, x):
    """Leading batch dims of a field element array."""
    return x.shape[: x.ndim - F.ELEM_NDIM]


def tree_reduce(x, axis: int, combine, identity):
    """Reduction of a pytree of arrays along ``axis`` via ``lax.scan``.

    Compile-size first: the scan emits ONE ``combine`` body regardless of
    N (an unrolled log-depth tree emitted log2(N) * |combine| HLO — ~90k
    lines of the round-1 program were these unrolled G2 adds, the single
    largest compile-time cost). The sequential chain is cheap at runtime
    because ``combine`` itself stays batched over all non-reduced dims and
    the Miller loop dominates end-to-end by orders of magnitude.
    """
    import jax

    n = jax.tree_util.tree_leaves(x)[0].shape[axis]
    if n == 0:
        return jax.tree_util.tree_map(
            lambda i, c: jnp.broadcast_to(i, _drop_axis_shape(c, axis)).astype(c.dtype),
            identity,
            x,
        )
    xs = jax.tree_util.tree_map(lambda c: jnp.moveaxis(c, axis, 0), x)
    first = jax.tree_util.tree_map(lambda c: c[0], xs)
    rest = jax.tree_util.tree_map(lambda c: c[1:], xs)
    if n == 1:
        return first

    def body(acc, item):
        return combine(acc, item), None

    acc, _ = lax.scan(body, first, rest)
    return acc


def _drop_axis_shape(c, axis):
    shape = list(c.shape)
    del shape[axis]
    return tuple(shape)


def sum_points(F, pt, axis: int = 0):
    """Tree-reduce a batch of points with the unified group law."""
    return tree_reduce(
        pt, axis, lambda a, b: add(F, a, b), infinity(F)
    )


# ---------------------------------------------------------------------------
# Host packing: oracle affine points <-> device arrays
# ---------------------------------------------------------------------------

def pack_g1(points) -> tuple[np.ndarray, np.ndarray]:
    """cpu G1Point list -> (xy int32[n, 2, 32], inf bool[n])."""
    from . import fp as _fp

    points = list(points)
    if not points:
        return np.zeros((0, 2, _fp.NL), np.int32), np.zeros((0,), bool)
    xs, infs = [], []
    for p in points:
        infs.append(p.is_infinity())
        if p.is_infinity():
            xs.append(np.zeros((2, _fp.NL), np.int32))
        else:
            xs.append(np.stack([_fp.int_to_limbs(p.x.n), _fp.int_to_limbs(p.y.n)]))
    return np.stack(xs), np.array(infs)


def pack_g2(points) -> tuple[np.ndarray, np.ndarray]:
    """cpu G2Point list -> (xy int32[n, 2, 2, 32], inf bool[n])."""
    from . import fp as _fp

    points = list(points)
    if not points:
        return np.zeros((0, 2, 2, _fp.NL), np.int32), np.zeros((0,), bool)
    xs, infs = [], []
    for p in points:
        infs.append(p.is_infinity())
        if p.is_infinity():
            xs.append(np.zeros((2, 2, _fp.NL), np.int32))
        else:
            xs.append(
                np.stack(
                    [
                        np.stack([_fp.int_to_limbs(p.x.c0.n), _fp.int_to_limbs(p.x.c1.n)]),
                        np.stack([_fp.int_to_limbs(p.y.c0.n), _fp.int_to_limbs(p.y.c1.n)]),
                    ]
                )
            )
    return np.stack(xs), np.array(infs)


def unpack_g1(xy, inf):
    """Device affine arrays -> list of cpu G1Point (host verification)."""
    from . import fp as _fp
    from ..cpu.curve import G1Point
    from ..cpu.fields import Fq

    xy = np.asarray(xy)
    inf = np.asarray(inf)
    out = []
    for i in range(xy.shape[0]):
        if inf[i]:
            out.append(G1Point.infinity())
        else:
            out.append(
                G1Point(
                    Fq(_fp.limbs_to_int(xy[i, 0]) % P),
                    Fq(_fp.limbs_to_int(xy[i, 1]) % P),
                )
            )
    return out


def unpack_g2(xy, inf):
    from . import fp as _fp
    from ..cpu.curve import G2Point
    from ..cpu.fields import Fq2

    xy = np.asarray(xy)
    inf = np.asarray(inf)
    out = []
    for i in range(xy.shape[0]):
        if inf[i]:
            out.append(G2Point.infinity())
        else:
            out.append(
                G2Point(
                    Fq2.from_ints(
                        _fp.limbs_to_int(xy[i, 0, 0]) % P,
                        _fp.limbs_to_int(xy[i, 0, 1]) % P,
                    ),
                    Fq2.from_ints(
                        _fp.limbs_to_int(xy[i, 1, 0]) % P,
                        _fp.limbs_to_int(xy[i, 1, 1]) % P,
                    ),
                )
            )
    return out
