"""Batched hash-to-curve for G2 on device (RFC 9380
BLS12381G2_XMD:SHA-256_SSWU_RO_, the suite the reference's blst backend
runs natively — ``/root/reference/crypto/bls/src/impls/blst.rs:14``).

Split of labor:

* host: ``expand_message_xmd`` (native batched SHA-256) + the mod-p
  reduction of the 64-byte uniform chunks — byte wrangling, not FLOPs;
* device (this module): everything algebraic, fully batched and
  branch-free — simplified SWU on the 3-isogenous curve E2', the derived
  3-isogeny back to E2, and Budroni-Pintore psi-based cofactor clearing.

Round 1 did all of this per message in pure Python at ~285 ms/message —
the end-to-end bottleneck (VERDICT "what's weak" #2). Here the whole
message batch moves through a handful of batched Fp2 ops and three scan
ladders.

The Fp2 square root uses the p == 3 (mod 4) extension-field algorithm
(same as the host oracle ``cpu/fields.py`` ``Fq2.sqrt``), evaluated
branch-free over the batch with both SSWU candidates stacked so the two
exponentiation ladders are shared by every candidate of every lane.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import iso3_g2
from ..cpu.fields import Fq, Fq2
from ..cpu.pairing import PSI_CX, PSI_CY
from ..params import ISO3_A, ISO3_B, ISO3_Z, P, X
from . import curve, fp, fp2

# ---------------------------------------------------------------------------
# Constants (host-derived, embedded as device arrays)
# ---------------------------------------------------------------------------

def _fq2(v) -> Fq2:
    return Fq2.from_ints(*v)


_A2 = _fq2(ISO3_A)
_B2 = _fq2(ISO3_B)
_Z2 = _fq2(ISO3_Z)
_NEG_B_DIV_A = (-_B2) * _A2.inverse()
_B_DIV_ZA = _B2 * (_Z2 * _A2).inverse()


def _dc(q: Fq2):
    """Fq2 -> device fp2 constant [2, NL]."""
    return fp2.const(q.c0.n, q.c1.n)


_PSI_CX_D = (PSI_CX.c0.n, PSI_CX.c1.n)
_PSI_CY_D = (PSI_CY.c0.n, PSI_CY.c1.n)

X_ABS = -X


def f2pow(x, e: int):
    """Fp2 fixed-exponent ladder (shared square-and-multiply scan)."""
    return fp.square_multiply(x, e, fp2.sq, fp2.mul, fp2.select)


# ---------------------------------------------------------------------------
# Fp2 primitives for the map
# ---------------------------------------------------------------------------

def sgn0(x):
    """RFC 9380 §4.1 sgn0 for m=2, batched -> int32 [...] in {0,1}."""
    d = fp2.canonical(x)  # [..., 2, NL] strict digits
    c0d, c1d = d[..., 0, :], d[..., 1, :]
    sign0 = c0d[..., 0] & 1
    zero0 = jnp.all(c0d == 0, axis=-1)
    sign1 = c1d[..., 0] & 1
    return jnp.where(zero0, sign1, sign0)


def sqrt(x):
    """Batched Fp2 square root -> (root, is_square). ``root`` is valid
    only where ``is_square``; x == 0 gives (0, True)."""
    a1 = f2pow(x, (P - 3) // 4)
    x0 = fp2.mul(a1, x)
    alpha = fp2.mul(a1, x0)
    neg_one = jnp.broadcast_to(fp2.const(P - 1, 0), alpha.shape).astype(jnp.int32)
    is_neg1 = fp2.eq(alpha, neg_one)
    # alpha == -1: root = u * x0  ((a+bu)*u = -b + au)
    cand1 = fp2.pack(fp.neg(fp2.c1(x0)), fp2.c0(x0))
    b = f2pow(fp2.add(fp2.ones(alpha.shape[:-2]), alpha), (P - 1) // 2)
    cand2 = fp2.mul(b, x0)
    root = fp2.select(is_neg1, cand1, cand2)
    ok = fp2.eq(fp2.sq(root), x)
    return root, ok


# ---------------------------------------------------------------------------
# Simplified SWU on E2' (batched, branch-free)
# ---------------------------------------------------------------------------

def sswu_pre(u):
    """Pre-sqrt half of simplified SWU: u -> (x1, x2, g) where
    ``g = [gx1, gx2]`` stacked on axis -3 awaits ONE sqrt ladder. Split
    out so the caller can merge the sqrt with other square roots in the
    same program (one shared ladder — compile-size lever)."""
    shape = u.shape[:-2]
    Z = jnp.broadcast_to(_dc(_Z2), u.shape).astype(jnp.int32)
    A = jnp.broadcast_to(_dc(_A2), u.shape).astype(jnp.int32)
    B = jnp.broadcast_to(_dc(_B2), u.shape).astype(jnp.int32)

    zu2 = fp2.mul(Z, fp2.sq(u))
    tv1 = fp2.add(fp2.sq(zu2), zu2)
    tv1_inv = fp2.inv(tv1)  # inv(0) == 0
    x1 = fp2.mul(
        jnp.broadcast_to(_dc(_NEG_B_DIV_A), u.shape).astype(jnp.int32),
        fp2.add(fp2.ones(shape), tv1_inv),
    )
    x1 = fp2.select(
        fp2.is_zero(tv1),
        jnp.broadcast_to(_dc(_B_DIV_ZA), u.shape).astype(jnp.int32),
        x1,
    )
    gx1 = fp2.add(fp2.mul(fp2.add(fp2.sq(x1), A), x1), B)
    x2 = fp2.mul(zu2, x1)
    # gx2 = (Z u^2)^3 * gx1 (standard SSWU identity)
    zu2_3 = fp2.mul(fp2.sq(zu2), zu2)
    gx2 = fp2.mul(zu2_3, gx1)
    return x1, x2, jnp.stack([gx1, gx2], axis=-3)


def sswu_post(u, x1, x2, roots, ok):
    """Post-sqrt half: candidate roots -> affine (x, y) with the RFC 9380
    sign rule. ``roots``/``ok`` are sqrt outputs of ``sswu_pre``'s g."""
    is1 = ok[..., 0]
    x = fp2.select(is1, x1, x2)
    y = fp2.select(is1, roots[..., 0, :, :], roots[..., 1, :, :])
    # sign: sgn0(y) must equal sgn0(u)
    flip = sgn0(u) != sgn0(y)
    y = fp2.select(flip, fp2.neg(y), y)
    return x, y


def map_to_curve_sswu(u):
    """u: fp2 [..., 2, NL] -> affine (x, y) on the iso-curve E2'."""
    x1, x2, g = sswu_pre(u)
    roots, ok = sqrt(g)
    return sswu_post(u, x1, x2, roots, ok)


# ---------------------------------------------------------------------------
# 3-isogeny E2' -> E2
# ---------------------------------------------------------------------------

def _iso3_coeff_table() -> np.ndarray:
    """All four isogeny polynomials padded to a common degree and stacked:
    int32 [max_len, 4, 2, NL], highest coefficient first (Horner order).
    Zero-padding the short polynomial at the top degree is exact
    (0*x + c)."""
    import numpy as _np

    polys = [iso3_g2.X_NUM, iso3_g2.X_DEN, iso3_g2.Y_NUM, iso3_g2.Y_DEN]
    n = max(len(p) for p in polys)
    out = _np.zeros((n, 4, 2, fp.NL), _np.int32)
    for j, poly in enumerate(polys):
        padded = list(poly) + [(0, 0)] * (n - len(poly))
        for d, c in enumerate(reversed(padded)):  # MSB-first for Horner
            q = _fq2(c)
            out[d, j, 0] = fp.int_to_limbs(q.c0.n)
            out[d, j, 1] = fp.int_to_limbs(q.c1.n)
    return out


_ISO3_TABLE = _iso3_coeff_table()


def iso3_map(x, y):
    """Derived 3-isogeny (coefficients from ``tools/derive_iso3.py``).
    All four polynomials are evaluated by ONE Horner scan over a stacked
    coefficient table (one fp2.mul body instead of ~11 — compile-size
    lever), and the two denominator inverses share one batched fp2.inv."""
    from jax import lax

    table = jnp.asarray(_ISO3_TABLE)  # [deg, 4, 2, NL]
    x4 = jnp.broadcast_to(
        x[..., None, :, :], (*x.shape[:-2], 4, 2, fp.NL)
    ).astype(jnp.int32)
    acc0 = jnp.broadcast_to(table[0], x4.shape).astype(jnp.int32)

    def body(acc, c):
        return fp2.add(fp2.mul(acc, x4), jnp.broadcast_to(c, x4.shape)), None

    acc, _ = lax.scan(body, acc0, table[1:])
    xn, xd, yn, yd = (acc[..., j, :, :] for j in range(4))
    dens = fp2.inv(jnp.stack([xd, yd], axis=-3))
    x_out = fp2.mul(xn, dens[..., 0, :, :])
    y_out = fp2.mul(fp2.mul(y, yn), dens[..., 1, :, :])
    return x_out, y_out


# ---------------------------------------------------------------------------
# psi endomorphism + Budroni-Pintore cofactor clearing
# ---------------------------------------------------------------------------

def psi_jac(pt):
    """(X, Y, Z) -> (conj(X) CX, conj(Y) CY, conj(Z)) — same derivation as
    the subgroup check's psi (``device/bls.py``)."""
    x, y, z = pt
    return (
        fp2.mul(fp2.conjugate(x), fp2.const(*_PSI_CX_D)),
        fp2.mul(fp2.conjugate(y), fp2.const(*_PSI_CY_D)),
        fp2.conjugate(z),
    )


def clear_cofactor(pt):
    """[X^2-X-1]P + [X-1]psi(P) + psi^2([2]P) (RFC 9380 App. G.3).

    The two [X]-multiplications ([X]P, then [X][X]P) run through ONE
    emitted scalar-mul body via an outer length-2 scan (the inner
    double-and-add scan appears once in HLO — compile-size lever)."""
    from jax import lax

    def round_(carry, _):
        q = curve.scalar_mul_const(fp2, carry, X_ABS)
        q = curve.neg(fp2, q)                    # [X]·, X < 0
        return q, q

    _, qs = lax.scan(round_, pt, None, length=2)
    xp = tuple(c[0] for c in qs)                 # [X]P
    x2p = tuple(c[1] for c in qs)                # [X^2]P
    neg_p = curve.neg(fp2, pt)
    neg_xp = curve.neg(fp2, xp)
    part1 = curve.add(fp2, curve.add(fp2, x2p, neg_xp), neg_p)
    part2 = psi_jac(curve.add(fp2, xp, neg_p))
    part3 = psi_jac(psi_jac(curve.dbl(fp2, pt)))
    return curve.add(fp2, curve.add(fp2, part1, part2), part3)


# ---------------------------------------------------------------------------
# The batched map: u values -> G2 Jacobian points
# ---------------------------------------------------------------------------

def map_to_g2_post(u, x1, x2, roots, ok):
    """Post-sqrt remainder of the RO map: SSWU sign-pick, isogeny, the
    count-axis add, cofactor clearing. ``roots/ok`` are sqrt outputs of
    ``sswu_pre(u)``'s stacked g (callers may have merged that sqrt with
    other square roots in the program)."""
    x, y = sswu_post(u, x1, x2, roots, ok)
    x, y = iso3_map(x, y)
    q = curve.from_affine(fp2, x, y)
    q0 = tuple(c[..., 0, :, :] for c in q)
    q1 = tuple(c[..., 1, :, :] for c in q)
    return clear_cofactor(curve.add(fp2, q0, q1))


def map_to_g2(u):
    """u: fp2 [..., 2 (count), 2, NL] -> G2 Jacobian point [...] — the
    full RO map: two SSWU maps, isogeny, one add, cofactor clearing."""
    x1, x2, g = sswu_pre(u)              # batched over [..., 2]
    roots, ok = sqrt(g)
    return map_to_g2_post(u, x1, x2, roots, ok)


# ---------------------------------------------------------------------------
# Host half: messages -> u limbs (native SHA-256, cheap)
# ---------------------------------------------------------------------------

def messages_to_u(messages, dst: bytes) -> np.ndarray:
    """[m_0..m_{B-1}] -> int32 [B, 2, 2, NL] of hash_to_field outputs."""
    from ..cpu.hash_to_curve import expand_message_xmd

    out = np.zeros((len(messages), 2, 2, fp.NL), np.int32)
    L = 64
    for b, msg in enumerate(messages):
        uniform = expand_message_xmd(msg, dst, 2 * 2 * L)
        for i in range(2):
            for j in range(2):
                off = L * (j + i * 2)
                v = int.from_bytes(uniform[off:off + L], "big") % P
                out[b, i, j] = fp.int_to_limbs(v)
    return out
